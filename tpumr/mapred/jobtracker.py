"""JobMaster — the cluster master daemon.

≈ ``org.apache.hadoop.mapred.JobTracker`` (reference: src/mapred/org/apache/
hadoop/mapred/JobTracker.java, 5405 LoC): job registry + tracker registry +
the heartbeat endpoint. Reproduced contracts:

- heartbeat dedupe by response id: a tracker retrying a lost response gets
  the PREVIOUS actions replayed, never double-assigned work
  (JobTracker.java:3336-3375);
- unknown/expired trackers are told to reinitialize
  (ReinitTrackerAction, :3358);
- scheduler delegation at :3405 → ``TaskScheduler.assign_tasks``;
- TaskReport placement stamping at assign time (:3414-3433) — done inside
  JobInProgress.obtain_new_map_task here;
- tracker liveness by heartbeat lease (ExpireTrackers) → lost trackers'
  running attempts killed and completed map outputs re-queued
  (lostTaskTracker);
- per-tracker fault counting + blacklisting (faultyTrackers, :3330-3333);
- the commit gate: first attempt to ask wins the right to promote its
  output (≈ CommitTaskAction gating, TaskTracker.java:1725-1731).

Structural divergence (by design, SURVEY.md §3.2): no global synchronized
heartbeat monitor around O(jobs×tasks) recomputation — job profiling uses
O(1) running sums and the master lock only guards registries.

Lock decomposition (PR 8 — the reference's single synchronized monitor
is exactly the ~200-tracker wall bench_scale.json measured): the
heartbeat fast path touches the GLOBAL lock briefly or not at all.

- ``self.lock`` (rank ``global``) guards only the job table, commit
  grants, and admin swaps; the job table itself is insert-only, so
  lookups (``self.jobs.get``) are lock-free dict reads under the GIL.
- the tracker registry is striped (``tracker_registry.TrackerRegistry``,
  rank ``trackers``): heartbeats from different trackers never contend
  on registration/status-store, and the response-replay cache
  (``self._last_response``) is read and written lock-free (single-key
  dict ops are GIL-atomic; each tracker's beats are serialized by its
  own ``hb_lock``, so a retry can never interleave with its original).
- the per-task STATUS FOLD, accel-event drain, and fetch-failure
  protocol run under the per-job locks only (``JobInProgress.lock``,
  rank ``job``).
- ``get_map_completion_events`` serves from the append-only
  ``CompletionEventFeed`` with NO lock at all — reducer polls never
  queue behind the fold.
- scheduler entry (``before_heartbeat`` / ``assign_tasks``) runs under
  a dedicated ``sched_lock`` (rank ``scheduler``); the ordering rule —
  scheduler → job, never the reverse — is asserted in debug mode
  (metrics/locks.py).

Each lock class feeds ``jt_lock_wait_seconds{lock=global|trackers|
scheduler}`` (+ hold twins) so the decomposition itself is observable.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from tpumr.ipc.rpc import RpcServer
from tpumr.core import confkeys
from tpumr.mapred.history import JobHistory
from tpumr.mapred.ids import JobID, TaskAttemptID
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.job_in_progress import (JobInProgress, JobState,
                                          normalize_priority)
from tpumr.mapred.scheduler import HybridQueueScheduler, TaskScheduler
from tpumr.mapred.task import TaskState, TaskStatus
from tpumr.utils.reflection import new_instance

#: ≈ InterTrackerProtocol versionID 29 (InterTrackerProtocol.java:75)
PROTOCOL_VERSION = 29

#: method → service keys ≈ MapReducePolicyProvider (reference:
#: security.job.submission / inter.tracker / task.umbilical /
#: admin.operations / refresh.policy .protocol.acl). Unmapped methods
#: default to the job-submission (client) key.
JOBTRACKER_POLICY = {
    "heartbeat": ["security.inter.tracker.protocol.acl"],
    "get_job_conf": ["security.inter.tracker.protocol.acl",
                     "security.job.submission.protocol.acl"],
    "get_job_token": ["security.inter.tracker.protocol.acl"],
    # trackers RELAY the umbilical surface for their children (and call
    # get_job_status in the purge loop), so the inter-tracker identity
    # must reach these too — a restricted umbilical/submission ACL must
    # never break commit grants, completion events, or job purging
    "can_commit": ["security.task.umbilical.protocol.acl",
                   "security.inter.tracker.protocol.acl"],
    "get_map_completion_events": ["security.task.umbilical.protocol.acl",
                                  "security.inter.tracker.protocol.acl",
                                  "security.job.submission.protocol.acl"],
    # pipeline surface: submission-tier for clients; trackers reach the
    # handoff feed (downstream maps resolve upstream reduce partitions)
    # and the purge oracle through their inter-tracker identity
    "get_handoff_completion_events": [
        "security.task.umbilical.protocol.acl",
        "security.inter.tracker.protocol.acl",
        "security.job.submission.protocol.acl"],
    "handoff_purgeable": ["security.inter.tracker.protocol.acl",
                          "security.job.submission.protocol.acl"],
    "get_pipeline_status": ["security.inter.tracker.protocol.acl",
                            "security.job.submission.protocol.acl"],
    "get_job_status": ["security.inter.tracker.protocol.acl",
                       "security.job.submission.protocol.acl"],
    "get_recovered_jobs": ["security.inter.tracker.protocol.acl",
                           "security.job.submission.protocol.acl"],
    "get_job_trace": ["security.inter.tracker.protocol.acl",
                      "security.job.submission.protocol.acl"],
    "refresh_queues": ["security.admin.operations.protocol.acl"],
    "refresh_nodes": ["security.admin.operations.protocol.acl"],
    "refresh_service_acl": ["security.refresh.policy.protocol.acl"],
    "get_protocol_version": ["security.job.submission.protocol.acl",
                             "security.inter.tracker.protocol.acl",
                             "security.task.umbilical.protocol.acl"],
}


class _TrackerInfo:
    def __init__(self, status: dict) -> None:
        self.status = status
        #: wall-clock, for the status surfaces (/json/trackers)
        self.last_seen = time.time()
        #: monotonic twin for the lease DEADLINE — an NTP step on the
        #: master must not mass-expire (or immortalize) trackers
        self.seen_mono = time.monotonic()
        self.failures = 0
        self.blacklisted = False
        #: the heartbeat interval the master last INSTRUCTED this
        #: tracker to keep (adaptive cadence); lag is judged against
        #: the schedule the tracker was actually told to run. None
        #: until the first response (use the configured floor).
        self.interval_s: "float | None" = None
        #: serializes THIS tracker's heartbeat processing end-to-end:
        #: a retry racing its own lost original must fold after it and
        #: hit the replay cache, never double-assign. Different
        #: trackers' beats never touch each other's lock — this is the
        #: bottom rank of the master's lock order, held across the
        #: fold/assign phases while the shard lock is not.
        from tpumr.metrics.locks import (RANK_TRACKER_BEAT,
                                         InstrumentedRLock)
        self.hb_lock = InstrumentedRLock(name="tracker-beat",
                                         rank=RANK_TRACKER_BEAT)
        #: fault charges arrive from OTHER trackers' heartbeats too
        #: (fetch-failure blame), so the counter needs its own tiny
        #: leaf lock now that the global lock no longer covers it
        self._fault_lock = threading.Lock()
        #: attempts the master believes are RUNNING on this tracker —
        #: maintained from launch actions + folded statuses (under
        #: ``hb_lock``) because delta beats may suppress unchanged
        #: RUNNING statuses: the last beat's ``task_statuses`` list is
        #: no longer the full picture, and eviction/kill scans need one
        self.running: "set[str]" = set()

    @property
    def name(self) -> str:
        return self.status["tracker_name"]

    def fold_status(self, status: dict) -> dict:
        """Store one beat's status — reconstructing the full dict first
        when the tracker sent a change-only delta — and stamp the
        lease. Returns the full status the rest of the heartbeat works
        on. Caller holds the registry shard lock."""
        from tpumr.mapred.heartbeat import fold_delta
        status = fold_delta(self.status, status)
        self.status = status
        self.last_seen = time.time()
        self.seen_mono = time.monotonic()
        return status

    def charge_fault(self, limit: int) -> bool:
        """One blacklist fault (failed task / lost shuffle output).
        Returns True when THIS fault newly blacklisted the tracker (the
        master keeps an approximate blacklist count off it)."""
        with self._fault_lock:
            self.failures += 1
            if self.failures >= limit and not self.blacklisted:
                self.blacklisted = True
                return True
            return False


def _profiler_line(snaps: dict, jt_snap: dict, flightrec_on: bool) -> str:
    """One cluster-page paragraph answering "what is the master's CPU
    doing, and is watching it costing anything" — cpu_share by
    subsystem, GIL-delay p99, sampler overhead, and the tracer's
    ring-drop count, off the already-taken metrics snapshot."""
    prof = snaps.get("prof", {})
    shares = []
    for name in sorted(prof):
        if name.startswith("cpu_share|subsystem="):
            v = prof[name]
            if isinstance(v, (int, float)) and v > 0:
                shares.append(
                    f"{name.split('subsystem=', 1)[-1]} {v:.0%}")
    gil = prof.get("gil_delay_seconds", {})
    dropped = jt_snap.get("trace_spans_dropped", 0)
    bits = []
    if shares:
        bits.append("cpu share " + " · ".join(shares))
    if isinstance(gil, dict) and gil.get("count"):
        bits.append(f"gil delay p99 {gil.get('p99', 0):.4g}s")
    ov = prof.get("prof_overhead_share")
    if isinstance(ov, (int, float)):
        bits.append(f"sampler overhead {ov:.2%}")
    bits.append(f"trace spans dropped {dropped:.0f}")
    link = (" · <a href='/flame'>flame</a> / <a href='/stacks'>stacks"
            "</a>" if prof else "")
    link += (" / <a href='/incidents'>incidents</a>"
             if flightrec_on else "")
    if not prof:
        return ("<p class='dim'>profiler off (tpumr.prof.enabled) · "
                f"trace spans dropped {dropped:.0f}</p>")
    return "<p>" + " · ".join(bits) + link + "</p>"


class JobMaster:
    def __init__(self, conf: Any, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.conf = conf
        # the GLOBAL lock — after the PR-8 decomposition it guards only
        # the job table, commit grants, and admin swaps (tracker
        # registry, fold, completion feed, and scheduler each have
        # their own synchronization). Wait/hold distributions bind to
        # jt_lock_wait_seconds{lock=global} once the registry exists.
        from tpumr.metrics.locks import (RANK_GLOBAL, RANK_PIPELINE,
                                         RANK_SCHEDULER,
                                         InstrumentedRLock)
        self.lock = InstrumentedRLock(name="global", rank=RANK_GLOBAL)
        #: scheduler entry (before_heartbeat/assign_tasks) serializes
        #: here, NOT on the global lock; ordering rule: scheduler → job,
        #: never the reverse (asserted in debug mode, metrics/locks.py)
        self.sched_lock = InstrumentedRLock(name="scheduler",
                                            rank=RANK_SCHEDULER)
        #: DAG-engine state lock (rank pipeline, below global: planning
        #: reads member-job state and recording a submission both happen
        #: under it, but every BLOCKING part of stage submission — split
        #: computation, conf hooks, submit_job's history write — runs
        #: outside; advancement lives in the heartbeat's DEFERRED phase
        #: and the expiry loop, never on the fast path
        self._pipe_lock = InstrumentedRLock(name="pipeline",
                                            rank=RANK_PIPELINE)
        #: pipeline table: insert-only like the job table, so the
        #: `if self.pipelines` fast-path guard is a lock-free dict read
        self.pipelines: dict[str, Any] = {}
        self._next_pipe = 0
        #: INSERT-ONLY (jobs are never removed from the table), so
        #: heartbeat-path lookups read it lock-free under the GIL;
        #: writers still serialize on the global lock
        self.jobs: dict[str, JobInProgress] = {}
        from tpumr.mapred.tracker_registry import TrackerRegistry
        self.trackers = TrackerRegistry(
            confkeys.get_int(conf, "tpumr.tracker.registry.shards"))
        #: response-replay cache: read and written LOCK-FREE (single-key
        #: dict get/set are GIL-atomic; same-tracker races are excluded
        #: by _TrackerInfo.hb_lock, and the value is an immutable tuple)
        self._last_response: dict[str, tuple[int, list]] = {}
        self._commit_grants: dict[str, str] = {}   # task_id -> attempt_id
        #: old job id -> resubmitted job id for jobs this master
        #: recovered at startup (restart survival). Insert-only, written
        #: before the RPC server starts — read lock-free everywhere a
        #: job id off the wire may predate the restart (heartbeat folds,
        #: kill scans, commit grants, client status polls).
        self._recovered: dict[str, str] = {}
        self._next_job = 0
        #: running-job-set change counter + the cache it keys (see
        #: jobs_version/running_jobs) — the scheduler's per-pass reads
        self._jobs_version = 0
        self._running_cache: "tuple[int, list]" = (-1, [])
        #: approximate count of blacklisted trackers (num_trackers'
        #: lock-free divisor; the exact set still comes from scans)
        self._blacklisted = 0
        #: TTL cache for devcache_tag_index (monotonic stamp, index) —
        #: the affinity pass asks once per heartbeat, and rescanning the
        #: striped registry for every beat of every tracker would make
        #: the warm-placement hint a fleet-rate O(trackers) tax
        self._devcache_index_cache: "tuple[float, dict]" = (-1.0, {})
        # start-time-in-ms identifier ≈ JobTracker's trackerIdentifier —
        # must differ across restarts or recovered job ids collide with
        # the original's history file. The suffix keeps N shard masters
        # booted in the same millisecond from minting colliding job ids
        # (the cluster component of a JobID is a free string).
        self.cluster_id = (str(int(time.time() * 1000))
                           + str(conf.get("tpumr.cluster.id.suffix") or ""))
        self.expiry_s = conf.get_int("tpumr.tracker.expiry.ms", 10_000) / 1000.0
        self.blacklist_faults = conf.get_int("tpumr.tracker.max.faults", 4)
        sched_cls = conf.get_class("mapred.jobtracker.taskScheduler",
                                   HybridQueueScheduler)
        self.scheduler: TaskScheduler = new_instance(sched_cls, conf)
        self.scheduler.set_manager(self)
        #: does this scheduler override the per-beat observation hook?
        #: The stock schedulers don't — skipping the no-op saves a
        #: sched_lock round trip on every heartbeat of every tracker
        self._sched_observes = (
            type(self.scheduler).before_heartbeat
            is not TaskScheduler.before_heartbeat)
        # per-queue submit/administer ACLs ≈ QueueManager.java +
        # mapred-queue-acls.xml, enforced in submit_job and kill_job
        from tpumr.mapred.queue_manager import QueueManager
        self.queue_manager = QueueManager(conf)
        self.history = JobHistory(conf)
        from tpumr.security import rpc_secret
        self._rpc_secret = rpc_secret(conf)
        # the master's transport is the selector reactor (≈ the
        # reference's NIO Listener/Reader + Handler pool) with the
        # heartbeat fast path served INLINE in the loop: at fleet scale
        # the thread-per-connection transport spent more CPU waking
        # handler threads than handling beats. The inline set must stay
        # short-running and never block on an RPC back to this server;
        # everything else (submit_job's history I/O, admin surface)
        # runs on the reactor's handler pool.
        use_reactor = True
        if hasattr(conf, "get_boolean"):
            use_reactor = conf.get_boolean(
                "tpumr.jobtracker.rpc.reactor", True)
        self._server = RpcServer(self, host=host, port=port,
                                 secret=self._rpc_secret,
                                 reactor=use_reactor,
                                 fast_methods={
                                     "heartbeat",
                                     "get_map_completion_events",
                                     "get_handoff_completion_events",
                                     "get_job_status",
                                     "can_commit",
                                     "get_protocol_version",
                                 })
        # delegation-token liveness (≈ JobTracker's
        # DelegationTokenSecretManager): issued/renewed/canceled here,
        # validated by the RPC layer per request
        from tpumr.security.tokens import TokenStore
        self.token_store = TokenStore(conf)
        self._server.token_store = self.token_store
        # service-level authorization ≈ hadoop-policy.xml (off unless
        # tpumr.security.authorization=true)
        from tpumr.security.authorize import ServiceAuthorizationManager
        self._server.authz = ServiceAuthorizationManager(
            conf, JOBTRACKER_POLICY,
            "security.job.submission.protocol.acl")
        # impersonation rules (hadoop.proxyuser.*) are consulted from
        # the daemon conf; without this, doas frames are rejected
        self._server.proxy_conf = conf
        #: require cryptographically verified identity (user key or
        #: delegation token) for ACL-relevant identity claims — with it
        #: off (default), cluster-secret assertions keep working (the
        #: flat round-3 trust domain, documented in docs/OPERATIONS.md)
        self._require_verified = conf.get_boolean(
            "tpumr.acls.require.verified", False) \
            if hasattr(conf, "get_boolean") else False
        # tracker admission lists ≈ mapred.hosts / mapred.hosts.exclude
        # (JobTracker.hostsReader + DisallowedTaskTrackerException):
        # one hostname per line, re-read by mradmin -refreshNodes
        self._hosts_include, self._hosts_exclude = self._read_hosts_lists()
        self._stop = threading.Event()
        self._expire_thread = threading.Thread(
            target=self._expire_loop, name="expire-trackers", daemon=True)
        # ALL advancement runs on its own thread: stage submission can
        # block on DFS (split listing, output checks, conf hooks), and
        # a wedged submission must stall pipelines — never tracker
        # eviction (the expiry loop) or heartbeats. The heartbeat
        # deferred phase and submit_pipeline just set the wake event.
        self._pipe_wake = threading.Event()
        self._pipe_thread = threading.Thread(
            target=self._pipeline_loop, name="pipeline-advance",
            daemon=True)

        # instrumentation ≈ JobTrackerInstrumentation + JobTrackerMXBean:
        # backend placement is a first-class metric (SURVEY.md §5)
        from tpumr.metrics import MetricsSystem
        self.metrics = MetricsSystem(
            "jobtracker",
            period_s=confkeys.get_int(conf, "tpumr.metrics.period.ms") / 1000)
        self._mreg = self.metrics.new_registry("jobtracker")
        def _locked(fn):
            def sample():
                with self.lock:
                    return fn()
            return sample

        self._mreg.set_gauge("jobs_running",
                             _locked(lambda: len(self.running_jobs())))
        self._mreg.set_gauge("jobs_total", _locked(lambda: len(self.jobs)))
        # tracker gauges read the striped registry; the global lock has
        # no say over trackers since the decomposition
        self._mreg.set_gauge("trackers", lambda: len(self.trackers))
        self._mreg.set_gauge(
            "trackers_blacklisted",
            lambda: sum(1 for t in self.trackers.values()
                        if t.blacklisted))
        self._mreg.set_gauge("slots", self.total_slots)
        # shuffle fault tolerance: map attempts with outstanding
        # (sub-threshold) fetch-failure reports across running jobs —
        # the master-side penalty ledger behind fetch_failures_reported
        # / maps_reexecuted_fetch_failure counters
        self._mreg.set_gauge(
            "fetch_failure_penalty_box",
            _locked(lambda: sum(j.fetch_failure_pending_count()
                                for j in self.jobs.values())))
        # shuffle merge engine, cluster-wide: background in-memory merges
        # and bounded-fan-in passes summed from every job's aggregated
        # framework counters (same names the task pages show per attempt)
        from tpumr.core.counters import TaskCounter

        def _merge_engine_totals() -> dict:
            out: dict[str, int] = {}
            for name in ("SHUFFLE_INMEM_MERGES",
                         "SHUFFLE_INMEM_MERGE_SEGMENTS",
                         "MERGE_PASSES", "MERGE_PASS_SEGMENTS"):
                out[name.lower()] = sum(
                    j.counters.value(TaskCounter.FRAMEWORK_GROUP, name)
                    for j in self.jobs.values())
            return out

        self._mreg.set_gauge("shuffle_merge",
                             _locked(_merge_engine_totals))
        # accelerator fault tolerance: cluster-wide demotion/quarantine
        # visibility (the per-event counters are incremented inline in
        # the heartbeat as the decisions arrive)
        self._mreg.set_gauge(
            "jobs_tpu_quarantined_now",
            _locked(lambda: sum(1 for j in self.jobs.values()
                                if j.tpu_disabled)))
        # DAG engine: running pipelines (table is insert-only; the scan
        # is over a handful of pipelines, not jobs)
        self._mreg.set_gauge(
            "pipelines_running",
            lambda: sum(1 for p in self.pipelines.values()
                        if p.state == "RUNNING"))
        self._mreg.set_gauge("pipelines_total",
                             lambda: len(self.pipelines))
        self._mreg.set_gauge(
            "tpu_devices_quarantined",
            lambda: sum(
                len(t.status.get("quarantined_tpu_devices", []) or [])
                for t in self.trackers.values()))
        # control-plane latency distributions: heartbeat handling wall
        # time (hoisted Histogram object — the heartbeat path must not
        # pay a registry lookup), per-method RPC server latency + wire
        # request sizes (the heartbeat payload-size series is the rpc
        # source's rpc_heartbeat_request_bytes — measured from the frame
        # length the transport already read, never re-serialized), and
        # scheduler decision timing. These are the series the ROADMAP's
        # control-plane scale-out work reads first.
        self._hb_seconds = self._mreg.histogram("heartbeat_seconds")
        self._hb_batch_size = self._mreg.histogram(
            "heartbeat_batch_size")
        # async history backpressure: queue depth + events dropped past
        # the bound — a healthy run keeps the drop counter at exactly 0
        self._mreg.set_gauge("history_queue_depth",
                             self.history.queue_depth)
        self._mreg.set_gauge("history_writes_dropped",
                             lambda: self.history.writes_dropped)
        # master saturation series (the scale harness's read side, all
        # hoisted off the registry lookup path):
        # - lock wait/hold PER DECOMPOSED LOCK CLASS as one labeled
        #   family each (jt_lock_wait_seconds{lock=global|trackers|
        #   scheduler} via the `name|k=v` registry convention) — the
        #   decomposition itself is observable, and "which lock is the
        #   wall now" is one scrape away,
        # - heartbeat phase breakdown (fold = task-status/fetch-failure
        #   folding under the per-job locks, assign = the scheduler
        #   pass, deferred_io = history/finalize I/O, replay =
        #   response-id replays of lost responses) as ONE labeled
        #   family,
        # - per-tracker heartbeat LAG: observed inter-heartbeat gap
        #   minus the configured interval — trackers overrunning their
        #   schedule is the first externally visible saturation symptom,
        # - completion-event feed lag: backlog REMAINING after each
        #   reduce poll was served (a poll that fully catches up
        #   records 0 — the series measures pollers falling behind, not
        #   job width).
        from tpumr.metrics.histogram import COUNTS
        self.lock.bind(
            self._mreg.histogram("jt_lock_wait_seconds|lock=global"),
            self._mreg.histogram("jt_lock_hold_seconds|lock=global"))
        self.sched_lock.bind(
            self._mreg.histogram("jt_lock_wait_seconds|lock=scheduler"),
            self._mreg.histogram("jt_lock_hold_seconds|lock=scheduler"))
        self._pipe_lock.bind(
            self._mreg.histogram("jt_lock_wait_seconds|lock=pipeline"),
            self._mreg.histogram("jt_lock_hold_seconds|lock=pipeline"))
        self.trackers.bind(
            self._mreg.histogram("jt_lock_wait_seconds|lock=trackers"),
            self._mreg.histogram("jt_lock_hold_seconds|lock=trackers"))
        self._hb_phase = {
            phase: self._mreg.histogram(
                f"heartbeat_phase_seconds|phase={phase}")
            for phase in ("fold", "assign", "deferred_io", "replay")}
        self._hb_lag = self._mreg.histogram("heartbeat_lag_seconds")
        self._hb_interval_s = conf.get_int(
            "tpumr.heartbeat.interval.ms", 1000) / 1000.0
        # Master-controlled adaptive heartbeat cadence
        # (≈ mapreduce.jobtracker.heartbeats.in.second / JobTracker.
        # getNextHeartbeatInterval, MAPREDUCE-1906): the master targets
        # an AGGREGATE beat rate and instructs each tracker's next
        # interval in the heartbeat response (`next_interval_ms`), so
        # cadence degrades smoothly with fleet size instead of the whole
        # fleet missing schedule at once past the master's beat-rate
        # capacity. The configured interval is the FLOOR (small fleets
        # see no change); `tpumr.heartbeat.interval.max.ms` bounds the
        # staleness an operator will tolerate (0 = uncapped, like the
        # reference). Off by default (0): existing clusters keep exact
        # fixed-cadence semantics unless an operator opts in with a
        # target rate.
        self._hb_target_rate = conf.get_int(
            "tpumr.heartbeat.beats.per.second", 0)
        self._hb_interval_max_s = conf.get_int(
            "tpumr.heartbeat.interval.max.ms", 0) / 1000.0
        self._mreg.set_gauge(
            "heartbeat_interval_instructed_ms",
            lambda: int(self._instructed_interval_s() * 1000))
        self._event_lag = self._mreg.histogram("completion_event_lag",
                                               COUNTS)
        self._server.metrics = self.metrics.new_registry("rpc")
        self.scheduler.metrics = self.metrics.new_registry("scheduler")
        # speculative attempts in flight, summed over running jobs —
        # each term is a lock-free set len, so the gauge never queues
        # on a job lock from the metrics scrape path
        self.scheduler.metrics.set_gauge(
            "speculative_in_flight",
            lambda: sum(j.speculative_in_flight()
                        for j in self.running_jobs()))
        # heartbeat-aggregated cluster view: trackers piggyback their
        # metrics on heartbeats; one scrape of THIS daemon yields
        # cluster-wide distributions (metrics/cluster.py)
        from tpumr.metrics.cluster import ClusterAggregator
        cluster_reg = self.metrics.new_registry("cluster")
        self.cluster_agg = ClusterAggregator(cluster_reg)
        cluster_reg.set_gauge("trackers_reporting",
                              lambda: len(self.trackers))
        # named to match the trackers' own flattened slot_utilization
        # gauge, so one dashboard query covers the cluster series and
        # the per-host rows (only the source label differs)
        for kind in ("cpu", "tpu", "reduce"):
            cluster_reg.set_gauge(
                f"slot_utilization_{kind}",
                (lambda k: lambda: self._slot_utilization(k))(kind))
        # cluster-wide observed acceleration derived from the MERGED
        # distributions (global means) — per-tracker ratio gauges can't
        # be summed, but merged count/sum histograms aggregate exactly
        _exe = cluster_reg.histogram("tpu_execute_seconds")
        _cpu = cluster_reg.histogram("tpu_cpu_batch_seconds")

        def _cluster_observed_accel() -> float:
            if not _exe.count or not _cpu.count or _exe.sum <= 0:
                return 0.0
            return (_cpu.sum / _cpu.count) / (_exe.sum / _exe.count)

        cluster_reg.set_gauge("tpu_observed_acceleration",
                              _cluster_observed_accel)
        from tpumr.metrics import sinks_from_conf
        for sink in sinks_from_conf(conf):
            self.metrics.add_sink(sink)
        # distributed tracing (core/tracing.py): the tracer always
        # exists (cheap buffer object); spans are recorded ONLY for jobs
        # whose conf enables tracing — jip.trace_root None is the
        # zero-overhead-off fast path on every heartbeat
        from tpumr.core.tracing import (Tracer, trace_dir_from_conf,
                                        trace_enabled)
        self.tracer = Tracer("jobtracker",
                             trace_dir=trace_dir_from_conf(conf))
        self._trace_all = trace_enabled(conf)
        # trace shedding is a loss signal, not a log line: the buffer's
        # shed-oldest counter rides the same scrape as everything else
        self._mreg.set_gauge("trace_spans_dropped",
                             lambda: self.tracer.dropped)
        # master brownout (mapred/brownout.py): None unless
        # tpumr.brownout.enabled. The flight recorder's tick drives it;
        # every deferrable path consults it lock-free. Level + counters
        # ride the scrape so operators see sheds as they happen.
        from tpumr.mapred.brownout import BrownoutController
        self.brownout = BrownoutController.from_conf(conf)
        if self.brownout is not None:
            _b = self.brownout
            self._mreg.set_gauge("brownout_level", lambda: _b.level)
            self._mreg.set_gauge("brownout_step_ups",
                                 lambda: _b.step_ups)
            self._mreg.set_gauge("brownout_step_downs",
                                 lambda: _b.step_downs)
            self._mreg.set_gauge("brownout_events_shed",
                                 lambda: _b.events_shed)
        # scenario lab: the active scenario's name (stamped into the
        # master conf by the scenario runner) annotates incident bundles
        self.scenario_name = str(confkeys.get(
            conf, "tpumr.scenario.name") or "")
        #: per-traffic-class latency histograms keyed (kind, class),
        #: created lazily at first observation; the flight recorder
        #: windows them into online per-class SLO verdicts
        self._class_hists: "dict[tuple[str, str], Any]" = {}
        # continuous profiler + flight recorder (both None unless
        # tpumr.prof.enabled — the recorder alone also comes up under
        # tpumr.brownout.enabled, stacks-less, to drive the brownout):
        # where the master's CPU goes, and an automatic postmortem
        # bundle when an SLO breaches
        from tpumr.metrics.flightrec import FlightRecorder
        from tpumr.metrics.sampler import StackSampler
        self.sampler = StackSampler.from_conf(conf, self.metrics)
        self.flightrec = FlightRecorder.from_conf(conf, self, self.sampler)
        self._http: Any = None
        self._http_port = conf.get_int("mapred.job.tracker.http.port", -1)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "JobMaster":
        # recovery runs BEFORE the RPC server accepts its first frame:
        # a re-joining tracker's heartbeat must find the recovered jobs
        # (and the old→new id aliases) already in place, or its adopted
        # in-flight attempts would be killed as unknown
        if self.conf.get_boolean("mapred.jobtracker.restart.recover", False):
            self._recover_jobs()
            # pipelines recover AFTER jobs: the stage-job alias table
            # (_recovered) must be complete before stage replay maps
            # old ids to the resubmitted jobs
            self._recover_pipelines()
        self._server.start()
        self._expire_thread.start()
        self._pipe_thread.start()
        self.metrics.start()
        if self.sampler is not None:
            self.sampler.start()
        if self.flightrec is not None:
            self.flightrec.start()
        if self._http_port >= 0:
            self._http = self._build_http(self._http_port).start()
        return self

    def _read_hosts_lists(self) -> "tuple[set | None, set]":
        """``mapred.hosts`` / ``mapred.hosts.exclude`` host sets
        (≈ HostsFileReader; include=None admits all)."""
        from tpumr.utils.hostsfile import read_hosts_lists
        return read_hosts_lists(self.conf, "mapred.hosts",
                                "mapred.hosts.exclude")

    def _host_allowed(self, host: str) -> bool:
        if host in self._hosts_exclude:
            return False
        return self._hosts_include is None or host in self._hosts_include

    def refresh_nodes(self, user: str = "") -> dict:
        """≈ AdminOperationsProtocol.refreshNodes (mradmin
        -refreshNodes): re-read the include/exclude files and evict any
        registered tracker that is no longer admitted — its running
        attempts and completed map outputs re-queue like a lost
        tracker's. Admin-gated exactly like refresh_queues."""
        ugi = self._acl_caller(user)
        qm = self.queue_manager
        if qm.acls_enabled and not qm.is_admin(ugi):
            raise PermissionError(
                f"user {ugi.user!r} is not a cluster administrator "
                f"(mapred.cluster.administrators)")
        include, exclude = self._read_hosts_lists()
        with self.lock:
            self._hosts_include, self._hosts_exclude = include, exclude
        evicted = [n for n, t in self.trackers.items()
                   if not self._host_allowed(t.status.get("host", ""))]
        for name in evicted:
            self._evict_tracker(name)
        return {"excluded": sorted(exclude),
                "included": sorted(include) if include is not None else "*",
                "evicted_trackers": sorted(evicted)}

    def _recover_jobs(self) -> None:
        """Restart recovery ≈ RecoveryManager (JobTracker.java:1203):
        resubmit jobs whose history shows a submission but no terminal
        event, then replay their ATTEMPT-level outcome from the event
        log (≈ the reference's RecoveryManager walking each job's
        history file) — completed maps are adopted with their original
        attempt ids and surviving shuffle outputs instead of re-running,
        completed reduces are counted done, and the old→new job id
        mapping is kept for every party still speaking the old id
        (re-joining trackers, in-flight task children, polling clients).
        A recovered output that turns out to be gone re-executes through
        the PR-1 fetch-failure protocol."""
        for ev in self.history.incomplete_jobs():
            old_id = ev["job_id"]
            if ev.get("conf_dropped"):
                # conf keys lost in serialization (in-process classes) —
                # a replay would fail every task; flag instead
                self._mreg.incr("jobs_recovery_failed")
                self.history.task_event(
                    old_id, "JOB_RECOVERY_FAILED",
                    error=f"non-serializable conf keys: "
                          f"{ev['conf_dropped']}")
                continue
            try:
                # _submit_job directly: a recovered PIPELINE STAGE job
                # must keep its pipeline stamps (the public RPC strips
                # them from untrusted direct submissions)
                new_id = self._submit_job(ev["conf"], ev["splits"],
                                          verified=None)
            except Exception as e:  # noqa: BLE001 — recovery is best-effort
                self._mreg.incr("jobs_recovery_failed")
                self.history.task_event(old_id, "JOB_RECOVERY_FAILED",
                                        error=str(e))
                continue
            jip = self.jobs[new_id]
            recovered = 0
            try:
                state = self.history.recovered_attempt_state(old_id)
                recovered = jip.recover_attempts(state, old_id)
            except Exception:  # noqa: BLE001 — attempt replay is an
                pass           # optimization; a failed one just re-runs
            # recovery grace (≈ the reference RecoveryManager waiting
            # for trackers to report back): trackers still RUNNING this
            # job's attempts re-join within a couple of heartbeats —
            # scheduling its tasks before they do would duplicate
            # in-flight work (and break the zero-re-run contract)
            grace_s = self.conf.get_int(
                "mapred.jobtracker.restart.recovery.grace.ms",
                3000) / 1000.0
            if grace_s > 0:
                jip.schedule_hold_until = time.monotonic() + grace_s
            self._recovered[old_id] = new_id
            self.history.job_recovered(old_id, new_id)
            self._mreg.incr("jobs_recovered")
            if recovered:
                self._mreg.incr("attempts_recovered", recovered)
                self.history.task_event(
                    new_id, "JOB_ATTEMPTS_RECOVERED", from_job=old_id,
                    attempts=recovered)
            if jip.state in JobState.TERMINAL:
                # every task had already completed — the crash fell in
                # the completion→finalization window; just finalize
                self._bump_jobs_version()
                self._finalize_job(jip)

    def _resolve_job(self, job_id: str) -> "JobInProgress | None":
        """Job lookup that follows the restart-recovery alias: ids off
        the wire (attempt ids on heartbeats, client polls, commit asks)
        may still name the pre-restart job. Lock-free — both dicts are
        insert-only."""
        jip = self.jobs.get(job_id)
        if jip is None and self._recovered:
            jip = self.jobs.get(self._recovered.get(job_id, ""))
        return jip

    def get_recovered_jobs(self) -> dict:
        """old job id → resubmitted job id for every job this master
        recovered at startup — the client-facing rebinding surface
        (``tpumr job status/trace <old-id>`` and polling JobClients
        follow the mapping instead of reporting the job vanished)."""
        return dict(self._recovered)

    def stop(self) -> None:
        self._stop.set()
        self._pipe_wake.set()   # unblock the advancement thread's wait
        if self.flightrec is not None:
            self.flightrec.stop()
        if self.sampler is not None:
            self.sampler.stop()
        self.metrics.stop()
        self.tracer.flush()
        if self._http is not None:
            self._http.stop()
        self._server.stop()
        # history LAST (after the RPC server can no longer enqueue):
        # the event log must be complete on disk before stop() returns —
        # restart recovery replays it immediately
        self.history.stop()

    @property
    def http_url(self) -> str | None:
        return self._http.url if self._http is not None else None

    def _build_http(self, port: int):
        """Status endpoints ≈ webapps/job JSP dashboards + /jmx."""
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("jobtracker", port=port)
        def cluster_info(q: dict) -> dict:
            with self.lock:
                jobs_running = len(self.running_jobs())
                jobs_total = len(self.jobs)
            return {
                "cluster_id": self.cluster_id,
                "trackers": len(self.trackers),
                "slots": self.total_slots(),
                "jobs_running": jobs_running,
                "jobs_total": jobs_total,
            }

        def jobs_info(q: dict) -> list:
            with self.lock:
                jips = [self.jobs[j] for j in sorted(self.jobs)]
            return [j.status_dict() for j in jips]

        def trackers_info(q: dict) -> list:
            rows = [(n, t.last_seen, t.blacklisted, t.failures, t.status)
                    for n, t in sorted(self.trackers.items())]
            return [{"name": n, "last_seen": seen, "blacklisted": bl,
                     "failures": f, "status": st}
                    for n, seen, bl, f, st in rows]

        srv.add_json("cluster", cluster_info)
        srv.add_json("jobs", jobs_info)
        srv.add_json("job", lambda q: self._job(q["id"]).status_dict(),
                     parameterized=True)
        srv.add_json("counters", lambda q: self.get_counters(q["id"]),
                     parameterized=True)
        srv.add_json("tasks", lambda q: self.get_task_reports(
            q["id"], q.get("kind", "map")), parameterized=True)
        srv.add_json("trackers", trackers_info)
        # registers both /metrics (uniform, scraper-facing) and the
        # long-standing /json/metrics with one handler
        srv.attach_metrics(self.metrics)
        from tpumr.core.configuration import redacted_dict
        srv.add_json("conf", lambda q: redacted_dict(self.conf))

        # distributed tracing: /tracejson?job= serves the merged trace
        # in Chrome trace-event format (chrome://tracing / Perfetto
        # load it directly); /trace?job= renders the swimlane timeline
        from tpumr.core import tracing as _tracing

        def tracejson(q: dict):
            return _tracing.to_chrome_trace(
                self.get_job_trace(q["job"])["spans"])

        srv.add_raw("tracejson", tracejson)
        srv.add_json("trace", lambda q: self.get_job_trace(q["job"]),
                     parameterized=True)

        # continuous profiler: /stacks (collapsed folded-stack text) and
        # /flame (self-contained SVG) when tpumr.prof.enabled; the
        # flight recorder's bundle listing is always registered so the
        # page can say WHY it is empty
        if self.sampler is not None:
            self.sampler.attach_http(srv)

        def incidents_json(q: dict) -> list:
            return (self.flightrec.list_incidents()
                    if self.flightrec is not None else [])

        def incident_raw(q: dict) -> dict:
            if self.flightrec is None:
                raise ValueError(
                    "flight recorder disabled (tpumr.prof.enabled off "
                    "or no incident dir)")
            return self.flightrec.read_incident(q["name"])

        srv.add_json("incidents", incidents_json)
        srv.add_raw("incident", incident_raw)

        # HTML views ≈ webapps/job/{jobtracker,jobdetails,jobtasks}.jsp
        from tpumr.http import (RawHtml, html_escape, html_table,
                                progress_bar)

        def index_page(q: dict) -> str:
            c = cluster_info(q)
            jobs = jobs_info(q)
            rows = []
            for j in jobs:
                jid = j["job_id"]
                state_cls = ("ok" if j["state"] == "SUCCEEDED" else
                             "bad" if j["state"] in ("FAILED", "KILLED")
                             else "dim")
                rows.append([
                    RawHtml(f"<a href='/job?id={html_escape(jid)}'>"
                            f"{html_escape(jid)}</a>"),
                    RawHtml(f"<span class='{state_cls}'>"
                            f"{html_escape(j['state'])}</span>"),
                    progress_bar(j["map_progress"]),
                    progress_bar(j["reduce_progress"]),
                    f"{j['num_maps']}", f"{j['num_reduces']}",
                    f"{j['finished_tpu_maps']}", f"{j['finished_cpu_maps']}",
                    (f"{j['acceleration_factor']:.2f}"
                     if j.get("acceleration_factor") else "—"),
                ])
            slots = c["slots"]
            slots_txt = (" / ".join(f"{k} {v}" for k, v in slots.items())
                         if isinstance(slots, dict) else str(slots))
            snap = self.metrics.snapshot().get("jobtracker", {})
            return (
                f"<h1>JobTracker — cluster {html_escape(self.cluster_id)}"
                f"</h1>"
                f"<p>{c['trackers']} trackers · slots "
                f"{html_escape(slots_txt)} · "
                f"{c['jobs_running']} running / {c['jobs_total']} total "
                f"jobs · <a href='/pipelines'>"
                f"{len(self.pipelines)} pipelines</a></p>"
                f"<p>shuffle fault tolerance: "
                f"{snap.get('fetch_failures_reported', 0):.0f} fetch "
                f"failures reported · "
                f"{snap.get('maps_reexecuted_fetch_failure', 0):.0f} maps "
                f"re-executed · penalty box "
                f"{snap.get('fetch_failure_penalty_box', 0)}</p>"
                f"<p>accelerator fault tolerance: "
                f"{snap.get('tpu_demotions', 0):.0f} TIP demotions · "
                f"{snap.get('jobs_tpu_quarantined_now', 0)} jobs TPU-"
                f"quarantined · {snap.get('tpu_devices_quarantined', 0)} "
                f"devices quarantined · "
                f"{snap.get('tasks_reaped_timeout', 0):.0f} tasks reaped "
                f"(timeout)</p>"
                f"<h2>Jobs</h2>"
                + html_table(
                    ["job", "state", "maps", "reduces", "#maps",
                     "#reduces", "tpu maps", "cpu maps", "accel"], rows))

        def job_page(q: dict) -> str:
            jid = q.get("id", "")
            jip = self._job(jid)
            st = jip.status_dict()
            parts = [f"<h1>Job {html_escape(jid)}</h1>",
                     f"<p>state <b>{html_escape(st['state'])}</b>"
                     + (f" — {html_escape(st['error'])}"
                        if st.get("error") else "") + "</p>",
                     # stage jobs link back to their pipeline
                     (f"<p>pipeline <a href='/pipeline?id="
                      f"{html_escape(st['pipeline'])}'>"
                      f"{html_escape(st['pipeline'])}</a> · stage "
                      f"{html_escape(st['pipeline_node'])} · round "
                      f"{st['pipeline_round']}</p>"
                      if st.get("pipeline") else ""),
                     "<p>map ", progress_bar(st["map_progress"]),
                     " reduce ", progress_bar(st["reduce_progress"]),
                     "</p>",
                     f"<p>TPU maps {st['finished_tpu_maps']} · CPU maps "
                     f"{st['finished_cpu_maps']} · mean map time "
                     f"tpu {st['tpu_map_mean_time']:.3f}s / "
                     f"cpu {st['cpu_map_mean_time']:.3f}s</p>",
                     # assignment-order placement (T=tpu, c=cpu): the
                     # convergence curve at a glance — optional
                     # scheduling shows as a c→T flip mid-string
                     (f"<p>placement <code>"
                      f"{html_escape(st['placement_seq'][-512:])}"
                      f"</code></p>" if st.get("placement_seq") else "")]
            for kind in ("map", "reduce"):
                reports = self.get_task_reports(jid, kind)
                rows = []
                for t in reports:
                    backend = ("—" if kind == "reduce"
                               else f"tpu:{t['tpu_device_id']}"
                               if t["run_on_tpu"] else "cpu")
                    runtime = (t["finish_time"] - t["start_time"]
                               if t["finish_time"] and t["start_time"]
                               else 0.0)
                    rows.append([
                        t["task_id"], t["state"],
                        progress_bar(t["progress"]), backend,
                        f"{runtime:.2f}s" if runtime else "—",
                        t["successful_attempt"] or "—",
                    ])
                parts.append(f"<h2>{kind} tasks ({len(rows)})</h2>")
                parts.append(html_table(
                    ["task", "state", "progress", "backend", "runtime",
                     "attempt"], rows))
            counters = self.get_counters(jid)
            crows = [[g, n, f"{v}"]
                     for g, cs in sorted(counters.items())
                     for n, v in sorted(cs.items())]
            parts.append("<h2>Counters</h2>")
            parts.append(html_table(["group", "counter", "value"], crows))
            if jip.trace_id:
                parts.append(
                    f"<p><a href='/trace?job={html_escape(jid)}'>span "
                    f"timeline</a> · <a href='/tracejson?job="
                    f"{html_escape(jid)}'>chrome trace json</a></p>")
            return "".join(parts)

        def trace_page(q: dict) -> str:
            jid = q["job"]
            t = self.get_job_trace(jid)
            if not t["spans"]:
                return (f"<h1>Trace {html_escape(jid)}</h1>"
                        f"<p class='dim'>{html_escape(t.get('error') or 'no spans yet')}</p>")
            cp = _tracing.critical_path(t["spans"])
            crit_rows = [[p["name"], p["role"], p["backend"] or "—",
                          f"{p['duration_s']:.4f}s",
                          f"{p['self_s']:.4f}s",
                          f"{p['contribution_pct']:.1f}%"]
                         for p in cp["path"]]
            return (
                f"<h1>Trace {html_escape(jid)}</h1>"
                f"<p>{len(t['spans'])} spans · makespan "
                f"{cp['makespan_s']:.3f}s · <a href='/tracejson?job="
                f"{html_escape(jid)}'>chrome trace json</a> (load in "
                f"chrome://tracing or Perfetto)</p>"
                + RawHtml(_tracing.swimlane_svg(t["spans"]))
                + "<h2>Critical path</h2>"
                + html_table(["span", "role", "backend", "duration",
                              "self", "contribution"], crit_rows))

        def trackers_page(q: dict) -> str:
            import time as _time
            rows = []
            for t in trackers_info(q):
                st = t["status"] or {}
                quarantined = set(
                    st.get("quarantined_tpu_devices", []) or [])
                # ✖ = quarantined by the device-health monitor (the slot
                # vanished from the advertised pool until a probe passes)
                devices = "".join(
                    "✖" if i in quarantined else "●" if free else "○"
                    for i, free in enumerate(
                        st.get("available_tpu_devices", [])))
                state = ("<span class='bad'>blacklisted</span>"
                         if t["blacklisted"] else
                         "<span class='ok'>healthy</span>"
                         if st.get("healthy", True) else
                         "<span class='bad'>unhealthy</span>")
                # the NodeHealthChecker's ERROR reason — previously
                # invisible cluster-wide (satellite)
                report = st.get("health_report", "")
                rows.append([
                    t["name"],
                    st.get("host", "?"),
                    f"{st.get('count_cpu_map_tasks', 0)}"
                    f"/{st.get('max_cpu_map_slots', 0)}",
                    f"{st.get('count_tpu_map_tasks', 0)}"
                    f"/{st.get('max_tpu_map_slots', 0)}",
                    f"{st.get('count_reduce_tasks', 0)}"
                    f"/{st.get('max_reduce_slots', 0)}",
                    devices,
                    # display ages off the wall stamp kept for status
                    # surfaces (seen_mono owns the lease deadline)
                    f"{max(0.0, _time.time() - t['last_seen']):.1f}s ago",  # tpulint: disable=clock-arith
                    RawHtml(state + (f" — {html_escape(report)}"
                                     if report else "")),
                ])
            return "<h1>Trackers</h1>" + html_table(
                ["tracker", "host", "cpu slots", "tpu slots",
                 "reduce slots", "tpu devices (●=free ✖=quarantined)",
                 "last heartbeat", "state / health report"], rows)

        def cluster_page(q: dict) -> str:
            """Heartbeat-aggregated cluster view: what one scrape of the
            master knows about the whole cluster — slot utilization,
            merged tracker distributions (shuffle fetch, TPU stage/
            execute, tracker RPC), and per-tracker gauge rows."""
            import time as _time
            util = {k: self._slot_utilization(k)
                    for k in ("cpu", "tpu", "reduce")}
            # wall display ages, as on the trackers page
            hb_ages = {n: max(0.0, _time.time() - t.last_seen)  # tpulint: disable=clock-arith
                       for n, t in self.trackers.items()}
            n_trackers = len(hb_ages)
            snaps = self.metrics.snapshot()
            snap = snaps.get("cluster", {})
            jt_snap = snaps.get("jobtracker", {})
            hb = jt_snap.get("heartbeat_seconds", {})
            # per-lock wait/hold of the decomposed master locks — the
            # "which lock is the wall now" table (lock=global|trackers|
            # scheduler via the labeled-family convention)
            lock_rows = []
            for name in sorted(jt_snap):
                if not name.startswith("jt_lock_wait_seconds|"):
                    continue
                which = name.split("lock=", 1)[-1]
                w = jt_snap[name]
                h = jt_snap.get(
                    f"jt_lock_hold_seconds|lock={which}", {})
                lock_rows.append([
                    which, f"{w.get('count', 0):.0f}",
                    f"{w.get('p99', 0):.4g}", f"{w.get('max', 0):.4g}",
                    f"{h.get('p99', 0):.4g}", f"{h.get('max', 0):.4g}"])
            rows, hist_rows = [], []
            for name in sorted(snap):
                v = snap[name]
                if isinstance(v, dict) and "p99" in v:
                    hist_rows.append([
                        name, f"{v['count']:.0f}",
                        f"{v['p50']:.4g}", f"{v['p95']:.4g}",
                        f"{v['p99']:.4g}", f"{v['max']:.4g}"])
                elif isinstance(v, (int, float)):
                    rows.append([name, f"{v:.4g}"])
            parts = [
                "<h1>Cluster</h1>",
                f"<p>{n_trackers} trackers reporting · slot utilization "
                + " · ".join(f"{k} {v:.0%}" for k, v in util.items())
                + (f" · heartbeat p99 {hb.get('p99', 0):.4g}s over "
                   f"{hb.get('count', 0):.0f} beats" if hb else "")
                + "</p>",
                _profiler_line(snaps, jt_snap,
                               self.flightrec is not None),
                "<h2>Master locks (wait vs hold)</h2>",
                html_table(["lock", "acquires", "wait p99", "wait max",
                            "hold p99", "hold max"], lock_rows)
                if lock_rows else "<p class='dim'>none yet</p>",
                "<h2>Merged distributions</h2>",
                html_table(["metric", "count", "p50", "p95", "p99",
                            "max"], hist_rows)
                if hist_rows else "<p class='dim'>none yet</p>",
                "<h2>Merged counters / gauges</h2>",
                html_table(["metric", "value"], rows)
                if rows else "<p class='dim'>none yet</p>",
            ]
            gauge_rows = self.cluster_agg.gauge_rows()
            if gauge_rows:
                keys = sorted({k for g in gauge_rows.values() for k in g})
                parts.append("<h2>Per-tracker gauges</h2>")
                # last-heartbeat age leads each row: merged gauges alone
                # made a wedged tracker look healthy (its last-reported
                # numbers persist) until eviction — staleness is the
                # signal that says whether the row is even current
                parts.append(html_table(
                    ["tracker", "last heartbeat"] + keys,
                    [[t,
                      (f"{hb_ages[t]:.1f}s ago" if t in hb_ages
                       else "evicted")]
                     + [f"{gauge_rows[t].get(k, 0):.4g}" for k in keys]
                     for t in sorted(gauge_rows)]))
            return "".join(parts)

        # pipeline surfaces: /json/pipelines (+/json/pipeline?id=) for
        # tooling, /pipelines + /pipeline?id= for operators, and the
        # merged end-to-end trace of a traced pipeline
        def pipelines_page(q: dict) -> str:
            with self._pipe_lock:
                rows_src = [self.pipelines[p].status_dict()
                            for p in sorted(self.pipelines)]
            rows = []
            for p in rows_src:
                state_cls = ("ok" if p["state"] == "SUCCEEDED" else
                             "bad" if p["state"] in ("FAILED", "KILLED")
                             else "dim")
                done = sum(1 for n in p["nodes"].values()
                           if n["state"] == "SUCCEEDED")
                rows.append([
                    RawHtml(f"<a href='/pipeline?id="
                            f"{html_escape(p['pipeline_id'])}'>"
                            f"{html_escape(p['pipeline_id'])}</a>"),
                    html_escape(p.get("name", "") or "—"),
                    RawHtml(f"<span class='{state_cls}'>"
                            f"{html_escape(p['state'])}</span>"),
                    f"{done}/{len(p['nodes'])}",
                ])
            return ("<h1>Pipelines</h1>"
                    + (html_table(["pipeline", "name", "state",
                                   "stages done"], rows)
                       if rows else "<p class='dim'>none</p>"))

        def pipeline_page(q: dict) -> str:
            pid = q.get("id", "")
            st = self.get_pipeline_status(pid)
            rows = []
            for nid in sorted(st["nodes"]):
                n = st["nodes"][nid]
                state_cls = ("ok" if n["state"] == "SUCCEEDED" else
                             "bad" if n["state"] == "FAILED"
                             else "dim")
                jid = n.get("job_id", "")
                rows.append([
                    html_escape(nid),
                    RawHtml(f"<span class='{state_cls}'>"
                            f"{html_escape(n['state'])}</span>"),
                    (RawHtml(f"<a href='/job?id={html_escape(jid)}'>"
                             f"{html_escape(jid)}</a>") if jid else "—"),
                    f"{n.get('rounds_run', 0)}",
                    html_escape(n.get("output_dir", "") or "—"),
                    html_escape(n.get("error", "") or ""),
                ])
            pip = self.pipelines.get(pid)
            trace_link = (
                f"<p><a href='/pipelinetrace?id={html_escape(pid)}'>"
                f"end-to-end trace json</a> (chrome://tracing / "
                f"Perfetto)</p>"
                if pip is not None and pip.trace_id else "")
            return (
                f"<h1>Pipeline {html_escape(pid)}"
                + (f" — {html_escape(st.get('name', ''))}"
                   if st.get("name") else "") + "</h1>"
                + f"<p>state <b>{html_escape(st['state'])}</b>"
                + (f" — {html_escape(st['error'])}"
                   if st.get("error") else "") + "</p>"
                + html_table(["stage", "state", "job", "rounds",
                              "output", "error"], rows)
                + trace_link)

        def pipelinetrace(q: dict):
            return _tracing.to_chrome_trace(
                self.get_pipeline_trace(q["id"])["spans"])

        srv.add_json("pipelines", lambda q: self.list_pipelines())
        srv.add_json("pipeline",
                     lambda q: self.get_pipeline_status(q["id"]),
                     parameterized=True)
        srv.add_raw("pipelinetrace", pipelinetrace)
        srv.add_page("pipelines", pipelines_page)
        def incidents_page(q: dict) -> str:
            if self.flightrec is None:
                return ("<h1>Incidents</h1><p class='dim'>flight "
                        "recorder disabled — set tpumr.prof.enabled "
                        "and an incident dir (tpumr.prof.incident.dir "
                        "or tpumr.history.dir)</p>")
            import time as _time
            rows = []
            for r in self.flightrec.list_incidents():
                reason = " · ".join(
                    f"{b.get('metric', '?')} p99 "
                    f"{b.get('p99_s', 0):.3f}s > {b.get('slo_s', 0):.3f}s"
                    for b in r.get("reason", []))
                rows.append([
                    RawHtml(f"<a href='/incident?name="
                            f"{html_escape(r['name'])}'>"
                            f"{html_escape(r['name'])}</a>"),
                    (_time.strftime("%Y-%m-%d %H:%M:%S",
                                    _time.localtime(r["ts"]))
                     if r.get("ts") else "?"),
                    html_escape(reason),
                    f"{r.get('bytes', 0)}",
                ])
            return ("<h1>Incidents</h1>"
                    "<p>SLO-breach snapshots written by the flight "
                    "recorder (folded stacks + lock table + rpc/"
                    "heartbeat state + recent spans)</p>"
                    + (html_table(["bundle", "written", "reason",
                                   "bytes"], rows)
                       if rows else "<p class='dim'>none — the "
                       "heartbeat p99 has stayed under the SLO</p>"))

        srv.add_page("incidents", incidents_page)
        srv.add_page("pipeline", pipeline_page, parameterized=True)
        srv.add_page("index", index_page)
        srv.add_page("job", job_page, parameterized=True)
        srv.add_page("trace", trace_page, parameterized=True)
        srv.add_page("trackers", trackers_page)
        srv.add_page("cluster", cluster_page)
        return srv

    @property
    def address(self) -> tuple[str, int]:
        return self._server.address

    # ------------------------------------------------------------ SPI seams

    def jobs_version(self) -> int:
        """Monotone-ish counter bumped whenever the running-job set (or
        a job's priority) changes — the scheduler's FIFO-order cache key.
        Bumps are plain int increments (a lost race just means one
        extra re-sort or one pass on a stale order; obtain re-checks job
        state under the job lock, so staleness is never incorrect)."""
        return self._jobs_version

    def _bump_jobs_version(self) -> None:
        self._jobs_version += 1

    def running_jobs(self) -> list[JobInProgress]:
        # version-cached: the scheduler asks once per assign pass, and
        # rebuilding (under the global lock) per pass was measurable at
        # fleet heartbeat rates. Rebuilt only when the version moved.
        ver = self._jobs_version
        cached_ver, cached = self._running_cache
        if cached_ver == ver:
            return cached
        with self.lock:
            jobs = [j for j in self.jobs.values()
                    if j.state == JobState.RUNNING]
        self._running_cache = (ver, jobs)
        return jobs

    def num_trackers(self) -> int:
        # lock-free approximation for the scheduler's per-pass divisor:
        # per-stripe dict lens (GIL-atomic) minus the blacklist counter.
        # The exact blacklisted set still comes from the full scan on
        # the metrics/status paths; mid-pass the scheduler must never
        # queue on (or take, the ordering rule forbids it) the global
        # lock — and at 400+ trackers even the striped values() walk
        # per pass was a measurable share of assign time.
        return max(1, self.trackers.approx_len() - self._blacklisted)

    def total_slots(self) -> dict:
        out = {"cpu": 0, "tpu": 0, "reduce": 0}
        for t in self.trackers.values():
            out["cpu"] += t.status.get("max_cpu_map_slots", 0)
            out["tpu"] += t.status.get("max_tpu_map_slots", 0)
            out["reduce"] += t.status.get("max_reduce_slots", 0)
        return out

    def devcache_tag_index(self) -> "dict[str, set[str]]":
        """Devcache tag → names of live trackers holding it warm, from
        the trackers' piggybacked ``devcache_tags`` inventories (their
        last folded heartbeat statuses). The scheduler's affinity pass
        reads this once per heartbeat; a short monotonic TTL keeps the
        striped-registry walk off the fleet-rate fast path — staleness
        of a fraction of a beat only costs one cold placement, never
        correctness (placement is a hint, execution works anywhere)."""
        now = time.monotonic()
        stamp, cached = self._devcache_index_cache
        if now - stamp < 0.5:
            return cached
        index: "dict[str, set[str]]" = {}
        for t in self.trackers.values():
            tags = t.status.get("devcache_tags")
            if not tags:
                continue
            name = t.name
            for tag in tags:
                index.setdefault(str(tag), set()).add(name)
        self._devcache_index_cache = (now, index)
        return index

    _SLOT_KEYS = {"cpu": ("count_cpu_map_tasks", "max_cpu_map_slots"),
                  "tpu": ("count_tpu_map_tasks", "max_tpu_map_slots"),
                  "reduce": ("count_reduce_tasks", "max_reduce_slots")}

    def _slot_utilization(self, kind: str) -> float:
        """Cluster-wide busy fraction of one slot pool, from the
        trackers' last heartbeat statuses (registry-striped reads).
        0.0 with no slots of the kind — a present-but-zero series beats
        a missing one for dashboards on heterogeneous clusters."""
        busy_key, max_key = self._SLOT_KEYS[kind]
        busy = total = 0
        for t in self.trackers.values():
            busy += int(t.status.get(busy_key, 0))
            total += int(t.status.get(max_key, 0))
        return busy / total if total else 0.0

    # ------------------------------------------------------------ RPC: jobs

    def get_protocol_version(self) -> int:
        return PROTOCOL_VERSION

    def _acl_caller(self, asserted: str):
        """UGI for an ACL decision. Order: a cryptographically VERIFIED
        rpc identity (user key / delegation token) wins outright; else
        the asserted simple-auth name — unless the cluster demands
        verified identities (tpumr.acls.require.verified), in which case
        unverified assertions count as anonymous. A missing identity is
        always anonymous, never the daemon's own (administrator) user."""
        from tpumr.ipc.rpc import current_rpc_user, current_rpc_verified
        from tpumr.security import UserGroupInformation, server_side_ugi
        if current_rpc_verified():
            return server_side_ugi(str(current_rpc_user()), self.conf)
        if self._require_verified and self.queue_manager.acls_enabled:
            return UserGroupInformation("anonymous", [])
        if asserted:
            return server_side_ugi(asserted, self.conf)
        return UserGroupInformation("anonymous", [])

    def submit_job(self, conf_dict: dict, splits: list) -> str:
        from tpumr.ipc.rpc import current_rpc_user, current_rpc_verified
        # the pipeline stamps are the ENGINE's to set (via _submit_job
        # directly): a direct submission claiming a live pipeline's id
        # would adopt its FIFO anchor (queue-jumping every job since),
        # merge foreign spans into its trace, and ride its handoff
        # purge lifetime — strip them at the RPC door
        for key in ("tpumr.pipeline.id", "tpumr.pipeline.node",
                    "tpumr.pipeline.round"):
            conf_dict.pop(key, None)
        verified = str(current_rpc_user()) if current_rpc_verified() \
            else None
        return self._submit_job(conf_dict, splits, verified)

    def _submit_job(self, conf_dict: dict, splits: list,
                    verified: "str | None") -> str:
        """Submission core. ``verified`` is the cryptographically
        authenticated caller, or None — pipeline STAGE submissions pass
        None explicitly: they run on whatever thread advanced the
        pipeline (usually a heartbeat handler, whose rpc identity is
        the TRACKER's), and the owner-binding check already happened
        once at submit_pipeline against the pipeline's submitter."""
        # submit-time queue validation + ACL (≈ JobTracker.submitJob →
        # QueueManager.hasAccess(SUBMIT_JOB)): rejected jobs never enter
        # any scheduler queue
        from tpumr.mapred.queue_manager import DEFAULT_QUEUE, JOB_QUEUE_KEY
        queue = str(conf_dict.get(JOB_QUEUE_KEY, DEFAULT_QUEUE)
                    or DEFAULT_QUEUE)
        user = str(conf_dict.get("user.name", "") or "")
        if verified is not None:
            # the job OWNER is the authenticated caller (the reference
            # binds owner to the RPC UGI): a verified carol cannot
            # submit a job owned by alice
            if user and user != verified:
                raise PermissionError(
                    f"authenticated user {verified!r} cannot submit a "
                    f"job owned by {user!r}")
            user = conf_dict["user.name"] = verified
        self.queue_manager.check_submit(queue, self._acl_caller(user))
        with self.lock:
            self._next_job += 1
            job_id = JobID(self.cluster_id, self._next_job)
        # distributed tracing: one trace per job, id = the job id (file
        # names + grep both read naturally). Minted BEFORE JobInProgress
        # construction so jip.conf carries it to every tracker
        # (get_job_conf) and child process (the task file).
        from tpumr.core.tracing import (ENABLED_KEY, SAMPLE_KEY,
                                        TRACE_ID_KEY, trace_dir_from_conf,
                                        trace_enabled, trace_sample_rate)
        want_trace = self._trace_all or trace_enabled(conf_dict)
        if want_trace:
            # per-job head sampling (tpumr.trace.sample, default 1.0):
            # decided ONCE here — a sampled-out job is simply untraced
            # everywhere (no id minted into its conf), so a cluster can
            # keep tracing on while span volume stays proportional to
            # the sample rate, not the job count. The job conf's rate
            # wins; the master conf supplies the cluster default.
            import random as _random
            rate = trace_sample_rate(
                conf_dict if SAMPLE_KEY in conf_dict else self.conf)
            if self.brownout is not None \
                    and self.brownout.sheds("trace"):
                # brownout level 1+: new jobs go untraced regardless of
                # the configured rate — span buffers and journal I/O
                # are the cheapest deferrable cost on the master
                rate = 0.0
            if rate < 1.0 and _random.random() >= rate:
                want_trace = False
                conf_dict.pop(TRACE_ID_KEY, None)
                self._mreg.incr("traces_sampled_out")
        # the owning pipeline, when this is a stage submission: the
        # stage job anchors its scheduler order and its trace to it
        pipe = self.pipelines.get(
            str(conf_dict.get("tpumr.pipeline.id") or ""))
        pipe_id = str(conf_dict.get("tpumr.pipeline.id") or "")
        if want_trace:
            if pipe is not None and pipe.trace_id:
                # per-STAGE spans live under one pipeline root: every
                # stage job of a traced pipeline shares the pipeline's
                # trace id (one file, one swimlane end-to-end)
                conf_dict[TRACE_ID_KEY] = pipe.trace_id
            elif pipe_id and str(conf_dict.get(TRACE_ID_KEY)
                                 or "") == pipe_id:
                # restart recovery resubmitting a pipeline-traced
                # stage BEFORE _recover_pipelines rebuilt the table
                # (jobs recover first, by design): the journaled conf
                # already carries the pipeline's trace id — keep it,
                # so the merged trace spans both masters
                pass
            else:
                # overwrite, never setdefault: a clone-and-rerun of a
                # finished job's conf carries the OLD job's trace id,
                # which would merge two jobs' spans into one file
                conf_dict[TRACE_ID_KEY] = str(job_id)
            # master-conf-only tracing must still reach trackers and
            # children — they build their tracers from the JOB conf
            conf_dict[ENABLED_KEY] = True
            # ONE authoritative sink for the whole trace: the master's
            # dir when it has one, else the job conf's — stamped into
            # the job conf so trackers/children write exactly where
            # get_job_trace will read
            sink = self.tracer.trace_dir or trace_dir_from_conf(conf_dict)
            if sink:
                conf_dict["tpumr.trace.dir"] = sink
        # JobInProgress construction resolves split racks (may exec the
        # topology script) — built outside the master lock
        jip = JobInProgress(job_id, conf_dict, splits)
        if self.brownout is not None \
                and self.brownout.sheds("speculation"):
            # jobs born while the master is shedding start with
            # speculation paused; released on step-down with the rest
            jip.speculation_hold = True
        if jip.traffic_class:
            self._mreg.incr(
                f"class_jobs_submitted|class={jip.traffic_class}")
        if pipe is not None:
            # FIFO anchor: every stage of one pipeline sorts at the
            # PIPELINE's submit time, so a late stage never queues
            # behind independent jobs submitted mid-pipeline
            jip.sched_anchor = pipe.start_time
        if jip.trace_id:
            if not self.tracer.trace_dir:
                self.tracer.trace_dir = trace_dir_from_conf(conf_dict)
            jip.trace_root = self.tracer.start_span(
                "job", jip.trace_id,
                parent=(pipe.trace_root if pipe is not None else None),
                job_id=str(job_id),
                job_name=str(conf_dict.get("mapred.job.name", "")))
            self.tracer.instant(
                "job:submit", jip.trace_id, parent=jip.trace_root,
                num_maps=len(splits),
                num_reduces=int(conf_dict.get("mapred.reduce.tasks", 1)))
        # per-job shuffle/umbilical token ≈ the reference's JobToken
        # (JobTokenSecretManager): task children get THIS, never the
        # cluster secret, so a task can only reach its own job's
        # umbilical + map outputs
        import secrets as _secrets
        jip.job_token = _secrets.token_bytes(32)
        with self.lock:
            self.jobs[str(job_id)] = jip
            self._mreg.incr("jobs_submitted")
            self._bump_jobs_version()
        # history write (serializes conf + splits) outside the master lock
        self.history.job_submitted(jip)
        return str(job_id)

    # -------------------------------------------------- RPC: tokens

    def get_delegation_token(self, renewer: str = "") -> dict:
        """Issue a delegation token for the CALLER's identity
        (≈ JobTracker.getDelegationToken): a verified user gets their
        own token; a cluster-secret caller (operator tooling) gets one
        for its asserted identity. Token-authenticated callers are
        refused — tokens must not mint successors. The wire dict is the
        client credential (tpumr.rpc.token.file)."""
        from tpumr.security.tokens import issue_for_caller
        wire = issue_for_caller(self.token_store, self._rpc_secret,
                                renewer)
        self._mreg.incr("tokens_issued")
        return wire

    def renew_delegation_token(self, wire: dict) -> float:
        """≈ renewDelegationToken: owner/renewer extends the tracked
        expiry by one renew interval (capped at max lifetime)."""
        from tpumr.ipc.rpc import current_rpc_user
        from tpumr.security.tokens import verify_wire
        tok = verify_wire(self._rpc_secret, wire)
        return self.token_store.renew(tok, str(current_rpc_user() or ""))

    def cancel_delegation_token(self, wire: dict) -> bool:
        """≈ cancelDelegationToken: kills the token immediately."""
        from tpumr.ipc.rpc import current_rpc_user
        from tpumr.security.tokens import verify_wire
        tok = verify_wire(self._rpc_secret, wire)
        self.token_store.cancel(tok, str(current_rpc_user() or ""))
        return True

    def list_jobs(self) -> list[str]:
        """All known job ids ≈ JobSubmissionProtocol.jobsToComplete +
        getAllJobs (bin/hadoop job -list)."""
        with self.lock:
            return sorted(self.jobs)

    def get_queue_info(self) -> "list[dict]":
        """Per-queue summary ≈ ``bin/hadoop queue -list`` (JobClient.
        getQueues → JobQueueInfo): name, ACL specs, and job counts
        attributed by each job's ``mapred.job.queue.name``."""
        from tpumr.mapred.queue_manager import DEFAULT_QUEUE, JOB_QUEUE_KEY
        qm = self.queue_manager
        with self.lock:
            per_queue: dict[str, dict] = {}
            for jip in self.jobs.values():
                q = str(jip.conf.get(JOB_QUEUE_KEY, DEFAULT_QUEUE)
                        or DEFAULT_QUEUE)
                c = per_queue.setdefault(q, {"running": 0, "total": 0})
                c["total"] += 1
                # a terminal-but-unfinalized job still counts as
                # running — get_job_status masks that window as RUNNING
                # and the two surfaces must agree about the same job
                if (jip.status_dict()["state"] not in JobState.TERMINAL
                        or not jip.finalized.is_set()):
                    c["running"] += 1
        out = []
        for q in qm.queues():
            counts = per_queue.get(q, {"running": 0, "total": 0})
            out.append({
                "queue": q,
                "acl_submit_job": qm.acl_spec(q, "submit-job"),
                "acl_administer_jobs": qm.acl_spec(q, "administer-jobs"),
                "acls_enabled": qm.acls_enabled,
                "running_jobs": counts["running"],
                "total_jobs": counts["total"],
            })
        return out

    def get_queue_jobs(self, queue: str) -> "list[str]":
        """Job ids submitted to one queue (``queue -info Q -showJobs``)."""
        from tpumr.mapred.queue_manager import DEFAULT_QUEUE, JOB_QUEUE_KEY
        with self.lock:
            return sorted(
                jid for jid, jip in self.jobs.items()
                if str(jip.conf.get(JOB_QUEUE_KEY, DEFAULT_QUEUE)
                       or DEFAULT_QUEUE) == queue)

    def get_queue_acls(self, user: str = "") -> "list[dict]":
        """The CALLER's operations per queue ≈ JobClient.
        getQueueAclsForCurrentUser (``queue -showacls``). Identity
        resolution matches submit/kill: verified rpc identity wins,
        else the asserted name (anonymous under require.verified)."""
        return self.queue_manager.operations_for(self._acl_caller(user))

    def refresh_queues(self, user: str = "") -> "list[str]":
        """Re-read queue names + ACLs without a restart ≈
        AdminOperationsProtocol.refreshQueues (``mradmin``). Gated on
        cluster administrators whenever ACLs are enforced; with ACLs
        off the cluster is open by definition and any caller may
        refresh (same trust stance as every other open-cluster op).
        Raises (so the CLI reports it) if the configured ACL file is
        unreadable — a failed refresh must never half-apply."""
        from tpumr.mapred.queue_manager import QueueManager
        ugi = self._acl_caller(user)
        qm = self.queue_manager
        if qm.acls_enabled and not qm.is_admin(ugi):
            raise PermissionError(
                f"user {ugi.user!r} is not a cluster administrator "
                f"(mapred.cluster.administrators)")
        fresh = QueueManager(self.conf)   # re-reads mapred.queue.acls.file
        with self.lock:
            self.queue_manager = fresh
        return fresh.queues()

    def refresh_service_acl(self) -> dict:
        """≈ RefreshAuthorizationPolicyProtocol.refreshServiceAcl
        (mradmin -refreshServiceAcl) — authorized by
        security.refresh.policy.protocol.acl; refuses when service
        authorization is off, like the reference."""
        from tpumr.security.authorize import ServiceAuthorizationManager
        if self._server.authz is None or not self._server.authz.enabled:
            raise PermissionError(
                "service authorization is disabled "
                "(tpumr.security.authorization)")
        fresh = ServiceAuthorizationManager(
            self.conf, JOBTRACKER_POLICY,
            "security.job.submission.protocol.acl")
        self._server.authz = fresh
        return fresh.acl_specs()

    def _job_acl_allows(self, jip: JobInProgress, op: str, ugi) -> bool:
        """The JobACLsManager ladder (reference src/mapred/.../
        JobACLsManager.java + ACLsManager.checkAccess): owner, cluster
        administrators / queue administer ACL, then the job's own
        ``mapreduce.job.acl-<op>-job`` list — which defaults to ""
        (nobody beyond the above), the reference's closed default."""
        from tpumr.mapred.queue_manager import (DEFAULT_QUEUE,
                                                JOB_QUEUE_KEY,
                                                AccessControlList)
        owner = str(jip.conf.get("user.name", ""))
        if ugi.user == owner:
            return True
        queue = str(jip.conf.get(JOB_QUEUE_KEY, DEFAULT_QUEUE)
                    or DEFAULT_QUEUE)
        if self.queue_manager.has_access(queue, "administer-jobs", ugi):
            return True                  # cluster admins included here
        spec = str(jip.conf.get(f"mapreduce.job.acl-{op}-job", "") or "")
        return AccessControlList(spec).allows(ugi)

    def _check_job_op(self, jip: JobInProgress, op: str) -> None:
        """Job-level VIEW/MODIFY gate for the PERSONAL-CREDENTIAL tier:
        a verified user-key/token caller must pass the JobACLsManager
        ladder. Cluster-secret callers — daemons above all: trackers
        localize job confs and proxy completion events through their
        service client — are the infrastructure tier of the documented
        flat trust domain and are NOT gated here (a secret holder could
        read the history files directly; gating them would only break
        the trackers the moment an operator locks the queue ACLs down).
        The reference draws the same line with service-level
        authorization (hadoop-policy.xml) vs job ACLs."""
        if not self.queue_manager.acls_enabled:
            return
        from tpumr.ipc.rpc import current_rpc_user, current_rpc_verified
        if not current_rpc_verified():
            return
        from tpumr.security import server_side_ugi
        ugi = server_side_ugi(str(current_rpc_user()), self.conf)
        if not self._job_acl_allows(jip, op, ugi):
            owner = str(jip.conf.get("user.name", ""))
            raise PermissionError(
                f"user {ugi.user!r} cannot {op} job {jip.job_id} "
                f"(owner {owner!r}; mapreduce.job.acl-{op}-job)")

    def get_job_status(self, job_id: str) -> dict:
        try:
            jip = self._job(job_id)
        except KeyError:
            # restart survival for FINISHED work too: a job that
            # completed before the crash lives only in history — serve
            # its terminal status from there (≈ the reference's retired
            # jobs) instead of telling a polling client it vanished
            st = self._retired_status(job_id)
            if st is None:
                raise
            return st
        self._check_job_op(jip, "view")
        d = jip.status_dict()
        if d["state"] in JobState.TERMINAL and not jip.finalized.is_set():
            # commit/abort still in flight — don't let a polling client
            # read the output dir before it's promoted
            d["state"] = JobState.RUNNING
        return d

    def _retired_status(self, job_id: str) -> "dict | None":
        """History-backed terminal status, following at most a few
        hops of ``JOB_RECOVERED`` chains from masters before the last
        restart (each hop either lands on a live job or on that
        incarnation's terminal history)."""
        for _ in range(8):
            st = self.history.retired_job_status(job_id)
            if st is None:
                return None
            successor = st.pop("recovered_as", None)
            if not successor:
                # the same job-view ACL ladder the live path enforces,
                # against the submit-time conf history retained — a job
                # must not become world-readable by finishing + restart
                from types import SimpleNamespace
                self._check_job_op(
                    SimpleNamespace(conf=st.pop("_acl_conf", {}) or {},
                                    job_id=job_id), "view")
                return st
            jip = self._resolve_job(successor)
            if jip is not None:
                return self.get_job_status(str(jip.job_id))
            job_id = successor
        return None

    def get_counters(self, job_id: str) -> dict:
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        return jip.counters.to_dict()

    def get_task_reports(self, job_id: str, kind: str = "map") -> list:
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        tips = jip.maps if kind == "map" else jip.reduces
        return [{
            "task_id": str(t.task_id), "state": t.report.state,
            "progress": t.report.progress,
            "start_time": t.report.start_time,
            "finish_time": t.report.finish_time,
            "run_on_tpu": t.report.run_on_tpu,
            "tpu_device_id": t.report.tpu_device_id,
            "successful_attempt": t.report.successful_attempt,
        } for t in tips]

    def set_job_priority(self, job_id: str, priority: str,
                         user: str = "") -> str:
        """≈ JobTracker.setJobPriority (hadoop job -set-priority): the
        MODIFY ladder gates it exactly like kill_job (owner / queue
        admin / cluster admin / acl-modify-job); the FIFO queue re-sorts
        on the next heartbeat. Returns the canonical priority set."""
        jip = self._job(job_id)
        p = normalize_priority(priority)   # raises on unknown names
        ugi = self._acl_caller(user)
        if self.queue_manager.acls_enabled and \
                not self._job_acl_allows(jip, "modify", ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot administer job {jip.job_id}")
        with jip.lock:
            jip.priority = p
            # NOTE: restart survival is handled by the
            # JOB_PRIORITY_CHANGED replay in history.incomplete_jobs()
            # — recovery resubmits the conf serialized at submit time,
            # so mutating jip.conf here could never reach it
        self._bump_jobs_version()   # the FIFO-order cache re-sorts
        self.history.task_event(str(jip.job_id), "JOB_PRIORITY_CHANGED",
                                priority=p, by=ugi.user)
        return p

    def kill_task(self, attempt_id: str, should_fail: bool = False,
                  user: str = "") -> bool:
        """≈ JobTracker.killTask(taskid, shouldFail) — `tpumr job
        -kill-task` / `-fail-task`. Modify-ACL gated like kill_job. The
        tracker running the attempt receives a kill action on its next
        heartbeat; with ``should_fail`` the terminal report counts
        toward the task's attempt limit."""
        try:
            job_id = str(TaskAttemptID.parse(attempt_id).task.job)
        except (ValueError, KeyError, IndexError):
            return False     # malformed id: nothing to kill, not a crash
        jip = self._job(job_id)
        ugi = self._acl_caller(user)
        if self.queue_manager.acls_enabled and \
                not self._job_acl_allows(jip, "modify", ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot administer job {jip.job_id}")
        ok = jip.request_attempt_kill(attempt_id, fail=should_fail)
        if ok:
            self.history.task_event(
                job_id, "TASK_KILL_REQUESTED", attempt_id=attempt_id,
                should_fail=should_fail, by=ugi.user)
        return ok

    def get_attempt_ids(self, job_id: str, kind: str = "map",
                        state: str = "running") -> "list[str]":
        """≈ `job -list-attempt-ids JOB_ID map|reduce STATE`: attempt
        ids of one task type filtered by state (running/completed)."""
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        if kind not in ("map", "reduce") \
                or state.lower() not in ("running", "completed"):
            # a typo must be an error, not the OTHER listing with rc=0
            raise ValueError(
                f"kind must be map|reduce and state running|completed "
                f"(got {kind!r}, {state!r})")
        want_running = state.lower() == "running"
        out = []
        with jip.lock:
            tips = jip.maps if kind == "map" else jip.reduces
            for tip in tips:
                for aid, st in tip.attempts.items():
                    if want_running and st.state == TaskState.RUNNING:
                        out.append(aid)
                    elif not want_running \
                            and st.state == TaskState.SUCCEEDED:
                        out.append(aid)
        return sorted(out)

    def get_active_trackers(self) -> "list[str]":
        """≈ `job -list-active-trackers` (ClusterStatus tracker names).
        Unhealthy-but-heartbeating trackers are annotated with their
        NodeHealthChecker ERROR reason — the cause used to be visible
        only on the node itself."""
        out = []
        with self.lock:
            for n in sorted(self.trackers):
                t = self.trackers[n]
                if t.blacklisted:
                    continue
                st = t.status or {}
                if st.get("healthy", True):
                    out.append(n)
                else:
                    reason = st.get("health_report", "") or "unhealthy"
                    out.append(f"{n}\tUNHEALTHY: {reason}")
        return out

    def get_blacklisted_trackers(self) -> "list[str]":
        """≈ `job -list-blacklisted-trackers`."""
        with self.lock:
            return sorted(n for n, t in self.trackers.items()
                          if t.blacklisted)

    def kill_job(self, job_id: str, user: str = "") -> bool:
        jip = self._job(job_id)
        # job-level ACL (≈ JobTracker.killJob → ADMINISTER_JOBS check):
        # owner always may; others need the queue's administer ACL.
        # ``user`` is the caller's asserted simple-auth identity, like
        # the reference's non-Kerberos UGI over the wire. A caller that
        # sends NO identity is treated as an anonymous nobody — never as
        # the daemon's own (usually administrator) identity, which would
        # turn the old 1-arg call signature into an ACL bypass.
        from tpumr.mapred.queue_manager import DEFAULT_QUEUE, JOB_QUEUE_KEY
        queue = str(jip.conf.get(JOB_QUEUE_KEY, DEFAULT_QUEUE)
                    or DEFAULT_QUEUE)
        owner = str(jip.conf.get("user.name", ""))
        ugi = self._acl_caller(user)
        # one MODIFY ladder (owner / queue admin / cluster admin / the
        # job's acl-modify-job list) shared with the view gate — the
        # asserted-identity handling above (anonymous for missing
        # names) is kill_job's long-standing contract
        if self.queue_manager.acls_enabled and \
                not self._job_acl_allows(jip, "modify", ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot administer job {jip.job_id} "
                f"in queue {queue!r} (owner {owner!r})")
        # kill() no-ops if a concurrent heartbeat already made it terminal
        if not jip.kill():  # ≈ JobTracker.killJob: no-op on finished jobs
            return False
        self._bump_jobs_version()
        self._finalize_job(jip)
        return True

    def _finalize_job(self, jip: JobInProgress) -> None:
        """Job-level output commit/abort + history. The reference runs this
        as a cleanup TASK on a tracker (getSetupAndCleanupTasks,
        JobTracker.java:3398); master-side finalization is a deliberate
        simplification — the output FS is shared, the work is two renames.
        Idempotent: the first caller claims it under jip.lock; later
        callers (kill_job racing a heartbeat-deferred finalize) return."""
        with jip.lock:
            if jip.finalize_started:
                return
            jip.finalize_started = True
        root = jip.trace_root
        fin_span = self.tracer.start_span(
            "job:finalize", jip.trace_id, parent=root) \
            if root is not None else None
        try:
            from tpumr.mapred.output_formats import FileOutputCommitter
            conf = JobConf()
            for k, v in jip.conf.items():
                conf.set(k, v)
            if conf.get("mapred.output.dir"):
                committer = FileOutputCommitter(conf)
                if jip.state == JobState.SUCCEEDED:
                    committer.commit_job()
                else:
                    committer.abort_job()
        except Exception as e:  # noqa: BLE001
            jip.error = jip.error or f"job finalization failed: {e}"
        try:
            self.history.job_finished(jip)
            self._mreg.incr(f"jobs_{jip.state.lower()}")
            if jip.traffic_class:
                # scenario lab: submit→complete latency by traffic
                # class — successful runs only (a fast failure must
                # not flatter the completion SLO), failures counted
                if jip.state == JobState.SUCCEEDED:
                    self._class_observe(
                        "complete", jip.traffic_class,
                        time.monotonic() - jip.submit_mono)
                else:
                    self._mreg.incr(f"class_jobs_failed|class="
                                    f"{jip.traffic_class}")
            # per-job stats rollup (metrics-<jobid>.json next to the
            # history log): counters + latency percentiles + the
            # TPU/CPU task-time split — what `tpumr job stats` prints
            # and what a future affinity/critical-path scheduler reads
            try:
                self.history.write_job_metrics(jip)
            except Exception:  # noqa: BLE001 — the rollup is auxiliary;
                pass           # its I/O must not fail job finalization
        finally:
            if root is not None:
                # the root span closes with the job and every master
                # span hits disk BEFORE clients can observe the terminal
                # state — a trace pulled right after completion is whole
                if fin_span is not None:
                    self.tracer.finish(fin_span.set(state=jip.state))
                jip.trace_root = None
                self.tracer.finish(root.set(state=jip.state,
                                            error=jip.error or ""))
                self.tracer.flush()
            # even when history I/O fails the job must become observable
            # as finished — a stuck RUNNING mask would hang clients
            jip.finalized.set()

    def get_map_completion_events(self, job_id: str, from_index: int = 0,
                                  max_events: int = 10_000) -> list:
        jip = self._job(job_id)
        self._check_job_op(jip, "view")   # own task children pass by scope
        # LOCK-FREE: the feed is append-only (CompletionEventFeed), so
        # reducer polls never queue behind the status fold appending
        # under the job lock — at fleet scale these polls outnumber
        # heartbeats and used to serialize on the same locks
        events, pending = jip.completion_events.read(int(from_index),
                                                     int(max_events))
        # completion-event feed lag: the backlog REMAINING after this
        # poll was served (0 = fully caught up). A growing distribution
        # means pollers can't drain the feed — they fall behind the map
        # completion rate, or can't get through a saturated master. The
        # volume a poll catches up on fine is deliberately NOT counted:
        # that grows with job width, not with saturation.
        self._event_lag.observe(pending)
        return events

    def get_job_conf(self, job_id: str) -> dict:
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        return dict(jip.conf)

    def get_job_trace(self, job_id: str) -> dict:
        """Merged distributed trace of one traced job: every daemon's
        flushed span files under the trace dir plus the master's own
        buffer, as raw span dicts (the CLI/HTTP layers convert to Chrome
        trace-event format / compute the critical path)."""
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        from tpumr.core import tracing
        if not jip.trace_id:
            return {"trace_id": "", "spans": [],
                    "error": f"job {job_id} was not traced "
                             f"(set tpumr.trace.enabled=true at submit)"}
        self.tracer.flush()
        # read from the JOB's stamped sink (submit_job made it the
        # authoritative dir every daemon writes to), falling back to the
        # master's own — writers and readers must resolve one place
        read_dir = tracing.trace_dir_from_conf(jip.conf) \
            or self.tracer.trace_dir
        spans = tracing.read_trace_files(read_dir, jip.trace_id) \
            if read_dir else []
        root = jip.trace_root
        if root is not None:
            # still running: ship the open root (end = now) so partial
            # traces anchor correctly in viewers
            d = root.to_dict()
            d["end"] = time.time()
            d["attributes"] = {**d["attributes"], "in_flight": True}
            spans.append(d)
        return {"trace_id": jip.trace_id, "spans": spans}

    def get_job_token(self, job_id: str) -> bytes:
        """Per-job token for trackers localizing the job (cluster-secret
        callers only — the RPC layer rejects token-scoped frames at the
        master, so a task child can never mint or read tokens)."""
        return getattr(self._job(job_id), "job_token", b"") or b""

    def _job(self, job_id: str) -> JobInProgress:
        # lock-free: the job table is insert-only and dict reads are
        # GIL-atomic — completion-event polls and status RPCs must not
        # queue on the global lock just to look up their job. Follows
        # the restart-recovery alias: a pre-restart id serves the
        # resubmitted job (status_dict carries the NEW id, so clients
        # can rebind).
        jip = self._resolve_job(job_id)
        if jip is None:
            raise KeyError(f"unknown job {job_id}")
        return jip

    # --------------------------------------------------- RPC: pipelines

    def submit_pipeline(self, graph_dict: dict) -> str:
        """Admit one validated :class:`~tpumr.pipeline.graph.JobGraph`
        atomically: the whole DAG lands in one RPC, the master owns
        every stage submission from here (split computation included) —
        an N-stage chain costs one client round trip instead of N
        submit/poll/resubmit cycles. Source stages submit before this
        returns, so the client's first status poll already sees them."""
        from tpumr.ipc.rpc import current_rpc_user, current_rpc_verified
        from tpumr.mapred.queue_manager import DEFAULT_QUEUE, JOB_QUEUE_KEY
        from tpumr.pipeline.graph import JobGraph
        from tpumr.pipeline.pipeline_in_progress import PipelineInProgress
        graph = JobGraph.from_dict(dict(graph_dict or {}))
        graph.validate()   # clients lie — reject before admitting
        # ...and they leak: strip client-local credentials server-side
        # too — the graph goes VERBATIM into the history journal and
        # every stage job conf (the submit path's _wire_conf stance)
        from tpumr.mapred.job_client import scrub_credentials
        graph.conf = scrub_credentials(graph.conf)
        for n in graph.nodes.values():
            n["conf"] = scrub_credentials(n["conf"])
        user = str(graph.conf.get("user.name", "") or "")
        if current_rpc_verified():
            verified = str(current_rpc_user())
            if user and user != verified:
                raise PermissionError(
                    f"authenticated user {verified!r} cannot submit a "
                    f"pipeline owned by {user!r}")
            user = graph.conf["user.name"] = verified
        # one submit-ACL check per distinct stage queue, up front — a
        # stage the submitter may not queue must fail the WHOLE graph
        # now, not strand a half-run pipeline later
        ugi = self._acl_caller(user)
        queues = {str(n["conf"].get(JOB_QUEUE_KEY,
                                    graph.conf.get(JOB_QUEUE_KEY,
                                                   DEFAULT_QUEUE))
                      or DEFAULT_QUEUE)
                  for n in graph.nodes.values()}
        for q in sorted(queues):
            self.queue_manager.check_submit(q, ugi)
        # conf hooks execute IN THIS PROCESS at stage submit: only
        # operator-allowlisted module prefixes may run (mapper/reducer
        # names resolve on trackers; this is the one seam where a
        # client string executes in the master itself)
        allowed = [s.strip() for s in str(confkeys.get(
            self.conf, "tpumr.pipeline.conf.hooks.allowed")
            or "").split(",") if s.strip()]
        for nid, n in graph.nodes.items():
            hook = n.get("conf_hook")
            if hook and not any(str(hook).startswith(p)
                                for p in allowed):
                raise PermissionError(
                    f"node {nid!r}: conf_hook {hook!r} is not under "
                    f"an allowed prefix ({', '.join(allowed)}) — "
                    f"hooks run in the master; extend "
                    f"tpumr.pipeline.conf.hooks.allowed to admit it")
        with self.lock:
            self._next_pipe += 1
            pid = f"pipe_{self.cluster_id}_{self._next_pipe:04d}"
        pip = PipelineInProgress(pid, graph, user=user)
        # distributed tracing: ONE root for the whole pipeline; stage
        # jobs share its trace id and parent their job roots to it, so
        # /pipelinetrace renders submit→stage→stage end-to-end
        from tpumr.core.tracing import (ENABLED_KEY, TRACE_ID_KEY,
                                        trace_dir_from_conf,
                                        trace_enabled)
        if self._trace_all or trace_enabled(graph.conf):
            pip.trace_id = pid
            graph.conf[TRACE_ID_KEY] = pid
            graph.conf[ENABLED_KEY] = True
            sink = self.tracer.trace_dir or trace_dir_from_conf(graph.conf)
            if sink:
                graph.conf["tpumr.trace.dir"] = sink
                if not self.tracer.trace_dir:
                    self.tracer.trace_dir = sink
            pip.trace_root = self.tracer.start_span(
                "pipeline", pid, pipeline_id=pid,
                pipeline_name=graph.name, nodes=len(graph.nodes))
        with self._pipe_lock:
            self.pipelines[pid] = pip
        self._mreg.incr("pipelines_submitted")
        # full graph into the journal BEFORE any stage submits: restart
        # recovery replays submission order (≈ job_submitted's stance)
        self.history.task_event(pid, "PIPELINE_SUBMITTED",
                                pipeline_id=pid, user=user,
                                graph=graph.to_dict())
        self._advance_pipeline(pip)
        return pid

    def get_pipeline_status(self, pipeline_id: str) -> dict:
        pip = self.pipelines.get(pipeline_id)
        if pip is None:
            raise KeyError(f"unknown pipeline {pipeline_id}")
        with self._pipe_lock:
            return pip.status_dict()

    def list_pipelines(self) -> "list[dict]":
        with self._pipe_lock:
            return [self.pipelines[pid].status_dict()
                    for pid in sorted(self.pipelines)]

    def kill_pipeline(self, pipeline_id: str, user: str = "") -> bool:
        """Kill the pipeline and every in-flight stage job. MODIFY
        gate: the pipeline's submitter, or a cluster/queue
        administrator (same ladder kill_job walks, at pipeline
        granularity)."""
        pip = self.pipelines.get(pipeline_id)
        if pip is None:
            raise KeyError(f"unknown pipeline {pipeline_id}")
        ugi = self._acl_caller(user)
        qm = self.queue_manager
        if qm.acls_enabled and ugi.user != pip.user \
                and not qm.is_admin(ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot kill pipeline {pipeline_id} "
                f"(owner {pip.user!r})")
        with self._pipe_lock:
            was_terminal = pip.state in ("SUCCEEDED", "FAILED",
                                         "KILLED")
            victims = pip.kill()
        for jid in victims:
            jip = self.jobs.get(jid)
            if jip is not None and jip.kill():
                self._bump_jobs_version()
                self._finalize_job(jip)
        self._finish_pipeline(pip)
        # ≈ kill_job's contract: False for an already-finished target
        return not was_terminal

    def get_handoff_completion_events(self, job_id: str,
                                      from_index: int = 0,
                                      max_events: int = 10_000) -> list:
        """Streamed-handoff announcements of one upstream stage job —
        the completion-event protocol verbatim, second feed: LOCK-FREE
        cursor reads off the append-only ``handoff_events``, OBSOLETE
        tombstones for withdrawn copies, alias-following lookups for
        pre-restart stage ids."""
        jip = self._job(job_id)
        self._check_job_op(jip, "view")
        events, _pending = jip.handoff_events.read(int(from_index),
                                                   int(max_events))
        return events

    def handoff_purgeable(self, job_id: str) -> bool:
        """May a tracker drop its streamed-handoff copies for
        ``job_id``? Only once the OWNING PIPELINE is over — a finished
        upstream stage keeps serving live downstream stages (job
        cleanup must not eat the intermediates mid-pipeline). Unknown
        jobs (recovery off, alias horizon passed) are purgeable: the
        committed DFS artifact is the fallback truth either way."""
        jip = self._resolve_job(job_id)
        if jip is not None:
            if jip.state not in JobState.TERMINAL:
                return False
            pid = str(jip.conf.get("tpumr.pipeline.id") or "")
        else:
            st = self.history.retired_job_status(job_id)
            if st is None:
                return True
            pid = str((st.get("_acl_conf") or {})
                      .get("tpumr.pipeline.id", "") or "")
        if not pid:
            return True
        pip = self.pipelines.get(pid)
        return pip is None or pip.state in ("SUCCEEDED", "FAILED",
                                            "KILLED")

    def get_pipeline_trace(self, pipeline_id: str) -> dict:
        """The merged end-to-end trace of a traced pipeline: every
        stage job's spans plus the pipeline root, one file (they share
        the pipeline's trace id)."""
        pip = self.pipelines.get(pipeline_id)
        if pip is None:
            raise KeyError(f"unknown pipeline {pipeline_id}")
        from tpumr.core import tracing
        if not pip.trace_id:
            return {"trace_id": "", "spans": [],
                    "error": f"pipeline {pipeline_id} was not traced"}
        self.tracer.flush()
        read_dir = self.tracer.trace_dir \
            or tracing.trace_dir_from_conf(pip.graph.conf)
        spans = tracing.read_trace_files(read_dir, pip.trace_id) \
            if read_dir else []
        root = pip.trace_root
        if root is not None:
            d = root.to_dict()
            d["end"] = time.time()
            d["attributes"] = {**d["attributes"], "in_flight": True}
            spans.append(d)
        return {"trace_id": pip.trace_id, "spans": spans}

    # ------------------------------------------------ pipeline engine

    def _advance_pipelines(self) -> None:
        """One advancement sweep over the running pipelines. Called
        from the heartbeat's DEFERRED phase and the expiry loop — the
        caller holds NO locks; each pipeline's plan/record transitions
        take the pipeline lock briefly, all I/O runs between."""
        for pip in list(self.pipelines.values()):
            if pip.state == "RUNNING":
                self._advance_pipeline(pip)

    def _advance_pipeline(self, pip: Any) -> None:
        # bounded: each iteration either submits stages, resolves
        # history-only stage outcomes, or stops; a loop node chains
        # rounds one fold per iteration
        for _ in range(len(pip.nodes) * 4 + 8):
            with self._pipe_lock:
                plans, unresolved = pip.plan_locked(self)
            if not plans and not unresolved:
                break
            for nid, rnd in plans:
                self._submit_stage(pip, nid, rnd)
            if unresolved:
                # stage jobs only history remembers (finished before a
                # restart): the file reads happen HERE, outside the
                # pipeline lock; verdicts feed back under it
                verdicts = [(nid, pip._retired_state(self, jid))
                            for nid, jid in unresolved]
                with self._pipe_lock:
                    for nid, st in verdicts:
                        pip.apply_retired(nid, st)
                if all(st == "RUNNING" for _, st in verdicts) \
                        and not plans:
                    break   # nothing actionable yet — next beat retries
        if pip.state in ("SUCCEEDED", "FAILED", "KILLED"):
            self._finish_pipeline(pip)

    def _finish_pipeline(self, pip: Any) -> None:
        """Terminal bookkeeping, exactly once (idempotent claim under
        the pipeline lock; the I/O runs outside it). A FAILED pipeline
        kills its still-running sibling stages — half a diamond must
        not burn slots for a join that can never run."""
        with self._pipe_lock:
            if getattr(pip, "finished_recorded", False):
                return
            pip.finished_recorded = True
            victims = []
            if pip.state in ("FAILED", "KILLED"):
                for n in pip.nodes.values():
                    if n.state == "RUNNING":
                        # settle the sibling observably: advancement
                        # stops on terminal pipelines, nothing would
                        # ever fold this node again
                        if n.job_id:
                            victims.append(n.job_id)
                        n.state = "FAILED"
                        n.error = n.error or "killed with pipeline"
        for jid in victims:
            jip = self.jobs.get(jid)
            if jip is not None and jip.kill():
                self._bump_jobs_version()
                self._finalize_job(jip)
        self._mreg.incr(f"pipelines_{pip.state.lower()}")
        self.history.task_event(
            pip.pipeline_id, "PIPELINE_FINISHED", state=pip.state,
            error=pip.error,
            wall_time=(pip.finish_time or time.time()) - pip.start_time,
            nodes={nid: n.state for nid, n in pip.nodes.items()})
        root = pip.trace_root
        if root is not None:
            pip.trace_root = None
            self.tracer.finish(root.set(state=pip.state,
                                        error=pip.error or ""))
            self.tracer.flush()

    def _submit_stage(self, pip: Any, nid: str, rnd: int) -> None:
        """Build and submit one stage job (NO pipeline lock held: conf
        hooks, split computation, and the submission's history write
        all block). The node was marked SUBMITTING under the lock, so
        concurrent advances cannot double-submit."""
        import json as _json
        node = pip.nodes[nid]
        graph = pip.graph
        try:
            conf = node.round_conf(graph.conf, rnd)
            conf.setdefault("user.name", pip.user)
            conf["tpumr.pipeline.id"] = pip.pipeline_id
            conf["tpumr.pipeline.node"] = nid
            conf["tpumr.pipeline.round"] = rnd
            conf.setdefault(
                "mapred.job.name",
                f"{graph.name or pip.pipeline_id}:{nid}"
                + (f"@r{rnd}" if node.is_loop else ""))
            if any(e["stream"] for e in graph.downstreams(nid)):
                conf["tpumr.pipeline.stream.handoff"] = True
            ins = graph.upstreams(nid)
            ups = {e["src"]: pip.nodes[e["src"]] for e in ins}
            ups_info = {src: {"job_id": up.job_id,
                              "output_dir": up.output_dir,
                              "num_reduces": up.num_reduces}
                        for src, up in ups.items()}
            handoff_splits = None
            if ins and all(e["stream"] for e in ins):
                # streamed input: one map per upstream reduce
                # partition, fetched over the shuffle wire — splits are
                # built HERE, no DFS listing, no client round trip
                from tpumr.pipeline.handoff import build_handoff_splits
                conf["mapred.input.format.class"] = \
                    "tpumr.pipeline.handoff.PipelineHandoffInputFormat"
                conf["tpumr.pipeline.handoff.upstream"] = _json.dumps(
                    sorted({i["job_id"] for i in ups_info.values()}))
                handoff_splits = []
                for src in sorted(ups):
                    up = ups[src]
                    serving = self._handoff_serving(up.job_id)
                    handoff_splits.extend(build_handoff_splits(
                        up.job_id, up.num_reduces, up.output_dir,
                        serving))
            elif ins and not str(conf.get("mapred.input.dir") or ""):
                # dfs wiring: the committed upstream output dirs
                conf["mapred.input.dir"] = ",".join(
                    ups_info[src]["output_dir"] for src in sorted(ups))
            hook = node.spec.get("conf_hook")
            if hook:
                # a FUNCTION by dotted name (resolve_class insists on
                # classes): the master-side prep seam for work that
                # needs upstream output to exist (partition sampling)
                import importlib
                mod_name, _, attr = str(hook).rpartition(".")
                getattr(importlib.import_module(mod_name),
                        attr)(conf, ups_info)
            if handoff_splits is not None:
                splits_wire = [s.to_dict() for s in handoff_splits]
            else:
                # the client's submission prep, master-side — the ONE
                # shared helper (job_client.build_submission), so the
                # client and pipeline submit paths can never drift
                # (this is the latency the sequential chain pays per
                # stage)
                from tpumr.mapred.job_client import build_submission
                jc = JobConf()
                for k, v in conf.items():
                    jc.set(k, v)
                conf, splits_wire = build_submission(jc)
            job_id = self._submit_job(conf, splits_wire, verified=None)
            jip = self.jobs[job_id]
            out_dir = str(conf.get("mapred.output.dir") or "")
            with self._pipe_lock:
                accepted = pip.record_submitted(nid, rnd, job_id,
                                                out_dir,
                                                jip.num_reduces)
            if not accepted:
                # the pipeline was killed/failed while this submission
                # was in flight — reap the just-submitted job now, or
                # nothing ever would (advancement stops on terminal
                # pipelines)
                if jip.kill():
                    self._bump_jobs_version()
                    self._finalize_job(jip)
            self._mreg.incr("pipeline_stages_submitted")
            self.history.task_event(
                pip.pipeline_id, "PIPELINE_STAGE_SUBMITTED", node=nid,
                round=rnd, stage_job_id=job_id, output_dir=out_dir,
                num_reduces=jip.num_reduces)
            if pip.trace_root is not None:
                self.tracer.instant(
                    "pipeline:stage_submit", pip.trace_id,
                    parent=pip.trace_root, node=nid, round=rnd,
                    job_id=job_id)
        except Exception as e:  # noqa: BLE001 — a stage that cannot
            # submit fails the pipeline observably, never silently
            with self._pipe_lock:
                pip.record_submit_failed(
                    nid, f"{type(e).__name__}: {e}")
            self._mreg.incr("pipeline_stage_submit_failed")
            self.history.task_event(
                pip.pipeline_id, "PIPELINE_STAGE_SUBMIT_FAILED",
                node=nid, round=rnd, error=f"{type(e).__name__}: {e}")

    def _handoff_serving(self, job_id: str) -> "dict[int, str]":
        """partition -> serving shuffle_addr of one upstream stage's
        already-committed handoff copies (locality hints for the
        downstream splits; lock-free feed iteration)."""
        jip = self._resolve_job(job_id)
        if jip is None:
            return {}
        return {e["map_index"]: e["shuffle_addr"]
                for e in jip.handoff_events
                if e.get("status") == "SUCCEEDED"}

    def _recover_pipelines(self) -> None:
        """Restart recovery for in-flight pipelines: replay each
        journal's graph + stage submissions, following the job-recovery
        alias for stage jobs the restart resubmitted. Completed
        upstream stages are adopted terminal from history — a master
        kill mid-pipeline must never re-run finished stages."""
        from tpumr.pipeline.pipeline_in_progress import PipelineInProgress
        for rec in self.history.incomplete_pipelines():
            pid = rec["pipeline_id"]
            try:
                pip = PipelineInProgress.from_recovery(
                    pid, rec["graph"], rec["stages"], self,
                    user=rec.get("user", ""))
            except Exception as e:  # noqa: BLE001 — recovery is
                self._mreg.incr("pipelines_recovery_failed")  # best-
                self.history.task_event(                      # effort
                    pid, "PIPELINE_RECOVERY_FAILED", error=str(e))
                continue
            with self._pipe_lock:
                self.pipelines[pid] = pip
            self._mreg.incr("pipelines_recovered")
            self.history.task_event(pid, "PIPELINE_RECOVERED",
                                    pipeline_id=pid)

    # ------------------------------------------------------------ RPC: commit

    def can_commit(self, task_id: str, attempt_id: str) -> bool:
        """First asker wins (≈ the single CommitTaskAction per task). Grants
        are revoked when the granted attempt fails or its tracker is lost,
        so re-runs can commit. An attempt the master already settled
        terminally is refused outright: a reaped zombie thread asking
        AFTER its FAILED status was folded (and any prior grant revoked)
        must not capture a fresh grant it would hold forever, denying
        every re-run."""
        jip = None
        try:
            job_id = str(TaskAttemptID.parse(attempt_id).task.job)
        except (ValueError, IndexError):
            pass   # unparseable id: no job to consult, legacy grant path
        else:
            jip = self._resolve_job(job_id)   # lock-free lookup
        if jip is not None:
            with jip.lock:
                tip = jip._tip_of_attempt(attempt_id)
                st = tip.attempts.get(attempt_id) if tip is not None \
                    else None
                if st is not None and st.state in TaskState.TERMINAL:
                    return False
        with self.lock:
            granted = self._commit_grants.setdefault(task_id, attempt_id)
            return granted == attempt_id

    def _revoke_commit(self, task_id: str, attempt_id: str) -> None:
        with self.lock:
            if self._commit_grants.get(task_id) == attempt_id:
                del self._commit_grants[task_id]

    # ------------------------------------------------------------ RPC: heartbeat

    def _instructed_interval_s(self) -> float:
        """The heartbeat interval the master currently asks trackers to
        keep: ``max(floor, fleet_size / target_rate)``, optionally
        capped. Lock-free (``approx_len``) — called per beat under the
        tracker's ``hb_lock``, the bottom of the lock order, where no
        shard stripe may be taken."""
        rate = self._hb_target_rate
        if rate <= 0:
            s = self._hb_interval_s
        else:
            s = max(self._hb_interval_s,
                    self.trackers.approx_len() / rate)
            if self._hb_interval_max_s > 0:
                # a floor above the cap means the operator pinned the
                # cadence — the floor wins (adaptation never speeds
                # beats up)
                s = min(s, max(self._hb_interval_max_s,
                               self._hb_interval_s))
        if self.brownout is not None:
            # brownout level 2+: stretch the instructed cadence toward
            # the adaptive max — the whole fleet beats slower and the
            # fold/assign path breathes (lock-free, one int read)
            s = self.brownout.stretch_interval(
                s, max(self._hb_interval_max_s, self._hb_interval_s))
        return s

    def _class_observe(self, kind: str, cls: str,
                       seconds: float) -> None:
        """Per-traffic-class latency fold (scenario lab):
        ``class_assign_seconds`` / ``class_complete_seconds`` labeled
        by class. Get-or-create is registry-locked and idempotent; the
        local dict probe keeps repeat observations allocation-free."""
        h = self._class_hists.get((kind, cls))
        if h is None:
            h = self._mreg.histogram(
                f"class_{kind}_seconds|class={cls}")
            self._class_hists[(kind, cls)] = h
        h.observe(max(0.0, seconds))

    def brownout_tick(self, pressure: bool) -> None:
        """One flight-recorder tick's pressure bit → the brownout state
        machine, plus the side effects a level change implies (the
        speculation hold is per-job state, flipped here on transitions
        so the scheduler's lock-free prechecks see it)."""
        b = self.brownout
        if b is None:
            return
        was_holding = b.sheds("speculation")
        b.on_tick(pressure)
        holding = b.sheds("speculation")
        if was_holding != holding:
            for jip in list(self.jobs.values()):
                jip.speculation_hold = holding

    def heartbeat(self, status: dict, initial_contact: bool,
                  ask_for_new_task: bool, response_id: int) -> dict:
        name = status["tracker_name"]
        self._mreg.incr("heartbeats")
        t0 = time.monotonic()
        from tpumr.utils.fi import fires
        if fires("jt.heartbeat.slow", self.conf):
            # BEHAVIORAL observability seam: handling crawls for
            # tpumr.fi.jt.heartbeat.slow.ms, breaching the windowed
            # heartbeat p99 SLO — the flight recorder's forcing function
            time.sleep(confkeys.get_int(
                self.conf, "tpumr.fi.jt.heartbeat.slow.ms") / 1000.0)
        # the tracker's PR-2 heartbeat span context (shipped only when
        # the tracker traces its daemon loop): master-side phase work
        # records as sub-spans on that same trace, so one swimlane shows
        # where a slow heartbeat's time went. Popped so the stored
        # tracker status never carries it.
        hb_trace = status.pop("trace", None)
        # history appends + job finalization are file I/O — deferred past
        # all locks so disk latency never serializes the control plane;
        # task events flush BEFORE finalization so the per-job log
        # stays causally ordered (TASK_* precede JOB_FINISHED)
        deferred_events: list[tuple[str, str, dict]] = []
        deferred_final: list[JobInProgress] = []
        try:
            return self._heartbeat(status, initial_contact,
                                   ask_for_new_task, response_id,
                                   name, deferred_events,
                                   deferred_final, hb_trace, t0)
        finally:
            t_io = time.monotonic()
            t_io_wall = time.time()
            for job_id, event, fields in deferred_events:
                try:
                    self.history.task_event(job_id, event, **fields)
                except Exception:  # noqa: BLE001 — history I/O best-effort
                    pass
            for jip in deferred_final:
                try:
                    self._finalize_job(jip)
                except Exception:  # noqa: BLE001
                    jip.error = jip.error or "finalization failed"
                    jip.finalized.set()
            if deferred_events or deferred_final:
                self._hb_phase["deferred_io"].observe(
                    time.monotonic() - t_io)
                self._phase_span(hb_trace, "heartbeat:deferred_io",
                                 t_io_wall,
                                 events=len(deferred_events),
                                 finalized=len(deferred_final))
            if self.pipelines:
                # DAG advancement must NEVER run on a heartbeat
                # handler thread (stage submission blocks on DFS
                # listings and conf hooks — it would silence this
                # tracker's beats): the deferred phase just WAKES the
                # dedicated pipeline-advance thread, which picks the
                # fold's consequences up within microseconds. The
                # guard is a lock-free dict-truthiness read, so
                # pipeline-less clusters pay nothing here.
                self._pipe_wake.set()
            # handling latency INCLUDING the deferred history/finalize
            # I/O: that work serializes this handler thread (and with it
            # this tracker's next heartbeat), so it is part of the
            # latency an operator must see
            self._hb_seconds.observe(time.monotonic() - t0)

    def heartbeat_batch(self, beats: list) -> list:
        """Many co-located trackers' beats in ONE RPC (satellite of the
        sharded-master work: the syscall + dispatch overhead of a
        round-trip per tracker was the measured single-process wall,
        not the fold itself). Each member is ``[status,
        initial_contact, ask_for_new_task, response_id]`` and is folded
        through the normal :meth:`heartbeat` path — the per-tracker
        replay cache, hb_lock, delta decode, and deferred phase all
        apply PER MEMBER, so a resent batch replays stored actions
        instead of double-folding any tracker. Members fail
        independently: a bad member yields ``{"error": ...}`` in its
        slot and the rest of the batch proceeds. Deliberately NOT a
        reactor fast method — a batch does real work and belongs on
        the handler pool."""
        self._mreg.incr("heartbeat_batches")
        self._hb_batch_size.observe(len(beats))
        out = []
        for member in beats:
            try:
                status, initial_contact, ask, response_id = member
                out.append(self.heartbeat(status, bool(initial_contact),
                                          bool(ask), int(response_id)))
            except Exception as e:  # noqa: BLE001 — member-isolated
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out

    def shard_snapshot(self) -> dict:
        """One coordinator poll's worth of this shard's state: the full
        typed metrics snapshot (the coordinator folds counter deltas
        reset-safely, so a respawned shard's counters restarting at zero
        don't go negative), per-class latency histograms, and this
        shard's own CPU shares from the always-on profiler — the
        per-shard ``cpu_share`` columns the scale bench commits come
        straight from here. Handler-pool method like any slow RPC."""
        return {
            "cluster_id": self.cluster_id,
            "trackers": len(self.trackers),
            "metrics": self.metrics.typed_snapshot(),
            "class_hists": {f"{kind}|{cls}": h.typed()
                            for (kind, cls), h
                            in list(self._class_hists.items())},
            "rpc_inflight_peak": self._server.inflight_peak(),
            "cpu_shares": (self.sampler.subsystem_shares()
                           if self.sampler is not None else None),
        }

    def _phase_span(self, hb_trace: "dict | None", name: str,
                    start_wall: float, **attrs: Any) -> None:
        """Record one already-elapsed heartbeat phase as a sub-span of
        the tracker's heartbeat span (no-op when the tracker didn't ship
        trace context — the zero-overhead-off contract)."""
        if hb_trace is None:
            return
        s = self.tracer.start_span(name, hb_trace.get("trace_id", ""),
                                   parent=hb_trace, **attrs)
        s.start = start_wall
        self.tracer.finish(s)

    def _heartbeat(self, status: dict, initial_contact: bool,
                   ask_for_new_task: bool, response_id: int,
                   name: str, deferred_events: list,
                   deferred_final: list,
                   hb_trace: "dict | None" = None,
                   t0: float = 0.0) -> dict:
        # ---- phase: registry — the ONLY synchronization here is the
        # tracker registry's shard stripe; the global lock is never
        # taken on the heartbeat fast path
        is_delta = bool(status.get("delta"))
        adopted = False
        restarted_info: "_TrackerInfo | None" = None
        shard_lock, shard = self.trackers.shard_of(name)
        with shard_lock:
            info = shard.get(name)
            # host screening first (≈ DisallowedTaskTrackerException)
            # whenever the beat names its host — excluded trackers get
            # "disallowed", never "reinit". A delta that omits the host
            # is screened against the stored status; an UNKNOWN delta
            # can't be screened here and is asked for a full re-send
            # (which gets screened).
            host = status.get("host") if "host" in status \
                or not status.get("delta") \
                else info.status.get("host", "") if info is not None \
                else None
            host_ok = host is None or self._host_allowed(host or "")
            if not host_ok:
                registered = info is not None
            elif info is None and is_delta:
                # no baseline to apply this delta to (master restarted,
                # or the tracker was evicted): ask for a FULL status.
                # Unlike the old blanket reinit, nothing is killed — the
                # full beat that follows is adopted below, in-flight
                # tasks and all.
                return {"response_id": response_id, "actions":
                        [{"type": "resend_full"}]}
            elif info is not None and initial_contact and not is_delta:
                # full INITIAL-contact beat from a tracker this master
                # already knows: the tracker PROCESS restarted under
                # its old name (cold re-registration — crash + rejoin
                # faster than the expiry sweep), or its registration
                # response was lost and this is the re-send. Either
                # way the OLD incarnation's believed-running attempts
                # never ran to completion there, and its replay-cache
                # entry would feed the new process a response meant
                # for the dead one. Swap in a fresh registration here;
                # the stale work is requeued below, outside the shard
                # lock (≈ JobTracker.java's lostTaskTracker on a known
                # tracker's initialContact).
                restarted_info = info
                status.pop("delta", None)
                info = shard[name] = _TrackerInfo(status)
            elif info is not None:
                if not initial_contact:
                    # heartbeat LAG: how far past its scheduled interval
                    # this tracker's beat arrived — judged against the
                    # interval the master last INSTRUCTED it to keep
                    # (adaptive cadence), not the configured floor.
                    # Climbing lag p99 with flat handling latency =
                    # trackers (or the network/handler pool) can't keep
                    # schedule — the first saturation tell. Observed for
                    # replayed beats too.
                    gap = time.monotonic() - info.seen_mono
                    self._hb_lag.observe(max(
                        0.0,
                        gap - (info.interval_s or self._hb_interval_s)))
                # delta beats reconstruct against the stored status
                # (heartbeat.py); full beats replace it wholesale
                status = info.fold_status(status)
            else:
                # full status from an unknown tracker: a true initial
                # contact registers; a NON-initial full beat is a
                # RE-JOIN (this master restarted, or the tracker was
                # expired while partitioned away) — register it and
                # ADOPT its in-flight work in the fold below instead of
                # answering reinit (which would kill healthy tasks)
                adopted = not initial_contact
                status.pop("delta", None)
                info = shard[name] = _TrackerInfo(status)
        if not host_ok:
            # ≈ DisallowedTaskTrackerException: the tracker's host is
            # excluded (or absent from a configured include list) —
            # refuse it; the NodeRunner shuts itself down on this
            if registered:
                self._evict_tracker(name)
            return {"response_id": response_id, "actions":
                    [{"type": "disallowed"}]}

        if restarted_info is not None:
            self._requeue_restarted(name, restarted_info, status)

        # ---- per-tracker serialization: one beat of one tracker at a
        # time. A retry racing its own lost original folds after it and
        # hits the replay cache — it can never double-assign. Trackers
        # never contend here (rank tracker-beat, bottom of the order).
        with info.hb_lock:
            # eviction (expiry/exclusion) may have raced the registry
            # phase above: it pops the entry, then requeues the running
            # set under THIS lock. A beat that loses that race must not
            # fold/assign onto the orphaned info — work assigned there
            # would never be requeued (pre-decomposition the global
            # lock made evict-vs-beat atomic). GIL-atomic dict read;
            # `is` distinguishes a concurrent fresh re-registration.
            # The tracker re-ships a full status and is adopted on its
            # next beat — no reinit, nothing killed.
            if shard.get(name) is not info:
                return {"response_id": response_id, "actions":
                        [{"type": "resend_full"}]}
            return self._heartbeat_fold_and_assign(
                status, info, initial_contact, ask_for_new_task,
                response_id, name, deferred_events, deferred_final,
                hb_trace, t0, is_delta, adopted)

    def _heartbeat_fold_and_assign(self, status: dict, info: _TrackerInfo,
                                   initial_contact: bool,
                                   ask_for_new_task: bool,
                                   response_id: int, name: str,
                                   deferred_events: list,
                                   deferred_final: list,
                                   hb_trace: "dict | None",
                                   t0: float,
                                   is_delta: bool = False,
                                   adopted: bool = False) -> dict:
        """Fold + replay-check + assign for one beat (caller holds the
        tracker's ``hb_lock`` and NOTHING else — every acquisition below
        is rank-ascending: scheduler → global → trackers → job).
        ``adopted`` marks a re-join beat (full status from a tracker
        this master doesn't know): RUNNING attempts are bound to their
        (possibly recovered) TIPs; attempts no live job will claim are
        killed INDIVIDUALLY, never via blanket reinit."""
        t_fold = time.monotonic()
        t_fold_wall = time.time() if hb_trace is not None else 0.0
        # fold the piggybacked tracker metrics into the cluster
        # registry — cumulative state, so replayed heartbeats are
        # idempotent (no seq protocol needed, unlike task statuses);
        # delta beats omit an UNCHANGED piggyback entirely, so idle
        # trackers skip this merge altogether
        self.cluster_agg.merge(name, status.get("metrics"))

        # Fold in task statuses FIRST — even when this turns out to be a
        # replayed heartbeat. The tracker drops terminal statuses after
        # any delivered response, so a completion carried on a retry
        # would otherwise be lost forever. Each status folds under ITS
        # job's lock only; the job table read is lock-free
        # (insert-only dict under the GIL).
        shuffle_addr = status.get("shuffle_addr") or \
            f"{status.get('host', '')}:{status.get('shuffle_port', 0)}"
        statuses = status.get("task_statuses") or []
        if not is_delta:
            # a FULL beat's status list is the tracker's complete
            # running set (delta beats may suppress unchanged RUNNING
            # statuses — they only ever add/remove incrementally below)
            info.running = {sd["attempt_id"] for sd in statuses
                            if sd.get("state") == TaskState.RUNNING}
        # group by job: a beat's statuses overwhelmingly belong to few
        # jobs, and taking each job's lock ONCE per beat (not once per
        # status) halves the lock round trips on the fold fast path
        by_job: "dict[str, list] | None" = None
        if statuses:
            by_job = {}
            for sd in statuses:
                ts = TaskStatus.from_dict(sd)
                aid = str(ts.attempt_id)
                if ts.state == TaskState.RUNNING:
                    info.running.add(aid)
                elif ts.state in TaskState.TERMINAL:
                    info.running.discard(aid)
                by_job.setdefault(str(ts.attempt_id.task.job),
                                  []).append(ts)
        #: attempts a re-join beat carried that no live job adopted —
        #: killed individually in THIS response
        adopt_kills: "list[str]" = []
        attempts_adopted = 0
        for job_id, group in (by_job or {}).items():
            jip = self._resolve_job(job_id)
            if jip is None:
                if adopted:
                    # the job died with the old master (or recovery is
                    # off / failed): these survivors have no home
                    for ts in group:
                        if ts.state not in TaskState.TERMINAL:
                            adopt_kills.append(str(ts.attempt_id))
                            info.running.discard(str(ts.attempt_id))
                continue
            revoke: "list[tuple[str, str]]" = []
            with jip.lock:
                before = jip.state
                for ts in group:
                    aid = str(ts.attempt_id)
                    if adopted and ts.state not in TaskState.TERMINAL:
                        # bind the in-flight attempt to its TIP (the
                        # recovered job's, or this job's after an
                        # eviction re-join) — any non-terminal state
                        # counts as in flight; rejects are zombies,
                        # their task already succeeded elsewhere
                        if jip.adopt_running_attempt(ts):
                            attempts_adopted += 1
                        else:
                            adopt_kills.append(aid)
                            info.running.discard(aid)
                            continue
                    jip.update_task_status(ts, shuffle_addr)
                    if ts.state in TaskState.TERMINAL \
                            and aid not in jip.history_logged:
                        # replayed heartbeats re-deliver terminal
                        # statuses; log each attempt's outcome once
                        jip.history_logged.add(aid)
                        if ts.state == TaskState.FAILED \
                                and ts.failure_class == "timeout":
                            # a tracker reaped this attempt for progress
                            # silence (counted once per attempt — this
                            # dedup block — because a lost response
                            # replays the same terminal status); the
                            # FAILED fold below also charges the tracker
                            # a blacklist fault, like any task failure
                            from tpumr.core.counters import JobCounter
                            self._mreg.incr("tasks_reaped_timeout")
                            jip.counters.incr(
                                JobCounter.GROUP,
                                JobCounter.TASKS_REAPED_TIMEOUT)
                        event = {TaskState.SUCCEEDED: "TASK_FINISHED",
                                 TaskState.KILLED: "TASK_KILLED"}.get(
                            ts.state, "TASK_FAILED")
                        deferred_events.append((str(jip.job_id), event,
                                                dict(
                            attempt_id=aid, is_map=ts.is_map,
                            run_on_tpu=ts.run_on_tpu,
                            tpu_device_id=ts.tpu_device_id,
                            runtime=ts.runtime, tracker=name,
                            # where a successful map's output is served
                            # from — restart recovery re-feeds it into
                            # the resubmitted job's completion events.
                            # Streamed-handoff stages record it for
                            # REDUCES too: recovery re-announces the
                            # surviving handoff copies to downstream
                            # pipeline stages
                            shuffle_addr=(shuffle_addr
                                          if (ts.is_map
                                              or jip.stream_handoff)
                                          and ts.state
                                          == TaskState.SUCCEEDED
                                          else ""),
                            # per-attempt counters make the history
                            # file self-sufficient for post-hoc
                            # diagnosis (tools.vaidya) ≈ the reference
                            # history's COUNTERS field
                            counters=ts.counters or {})))
                    if ts.state in (TaskState.FAILED, TaskState.KILLED):
                        # a dead attempt must not keep the commit
                        # grant — otherwise its re-run is denied commit
                        # and output is silently lost (revoked after
                        # the job lock drops: global < job in the rank
                        # order, so the grant table must not be touched
                        # while a job lock is held)
                        revoke.append((str(ts.attempt_id.task), aid))
                    if ts.state == "FAILED":
                        if info.charge_fault(self.blacklist_faults):
                            self._blacklisted += 1
                job_done = (before == JobState.RUNNING
                            and jip.state in JobState.TERMINAL)
            if jip.has_accel_events():
                self._drain_accel_events(jip, str(jip.job_id), name,
                                         deferred_events)
            for task_id, aid in revoke:
                self._revoke_commit(task_id, aid)
            if job_done:
                self._bump_jobs_version()
                deferred_final.append(jip)
        if adopted:
            # the re-join itself is the observable event (acceptance:
            # trackers survive a master restart without reinit)
            self._mreg.incr("trackers_adopted")
            if attempts_adopted:
                self._mreg.incr("attempts_adopted", attempts_adopted)

        # Fetch-failure reports (the "too many fetch failures"
        # protocol): reducers on this tracker found a completed
        # map's output unfetchable while its tracker still
        # heartbeats. Folded BEFORE replay detection for the same
        # reason as task statuses: the tracker only drops reports
        # once a response is delivered, so a retried heartbeat
        # re-carries them (distinct-reducer counting makes the
        # re-delivery harmless).
        for ff in status.get("fetch_failures") or []:
            self._fetch_failure(ff, deferred_events, deferred_final)
        self._hb_phase["fold"].observe(time.monotonic() - t_fold)
        self._phase_span(
            hb_trace, "heartbeat:fold", t_fold_wall,
            statuses=len(statuses))

        # Normal case: the tracker echoes the response id we last sent
        # (last[0] == response_id). A MISMATCH means our response was
        # lost in flight — replay the stored actions rather than
        # assigning duplicate work (JobTracker.java:3336-3375). The
        # cache read is lock-free (GIL-atomic dict get of an immutable
        # tuple; hb_lock excludes same-tracker writers).
        last = self._last_response.get(name)
        if last is not None and last[0] != response_id \
                and not initial_contact:
            # replayed beats observe the phase + lag series uniformly
            # (lag landed in the registry phase above) — distinguishable
            # from first-delivery beats by the phase=replay label
            self._hb_phase["replay"].observe(
                time.monotonic() - (t0 or t_fold))
            self._phase_span(hb_trace, "heartbeat:replay",
                             time.time() if hb_trace is not None else 0.0,
                             response_id=last[0])
            # a tracker whose response was lost still needs the cadence
            # instruction — replays re-carry the CURRENT interval
            nxt = self._instructed_interval_s()
            info.interval_s = nxt
            return {"response_id": last[0], "actions": last[1],
                    "next_interval_ms": int(nxt * 1000 + 0.5)}

        actions: list[dict] = []
        if adopted:
            # individually kill the survivors no job would claim, and
            # teach the tracker any job id rebindings (it re-keys the
            # recovered jobs' served map outputs so NEW-id reducers can
            # fetch outputs produced under the OLD id)
            for aid in adopt_kills:
                actions.append({"type": "kill_task", "attempt_id": aid})
            for old, new in self._recovered.items():
                actions.append({"type": "recover_job",
                                "old": old, "new": new})
        # scheduler observation hook BEFORE the kill scan and
        # independent of free slots: a saturated cluster (no tracker
        # ever asks for work) is exactly when fair-share preemption
        # must still run, and marks made here produce kill actions in
        # THIS response for victims on this tracker. Skipped entirely
        # for schedulers that don't override the hook — no reason to
        # serialize every beat on the scheduler lock for a no-op.
        if self._sched_observes:
            with self.sched_lock:
                try:
                    self.scheduler.before_heartbeat(status)
                except Exception:  # noqa: BLE001 — observation must not
                    pass           # break heartbeats
        # kill actions: tasks of dead jobs + marked attempts
        # (speculative-race losers, preemptions, operator kills) — over
        # the tracker's BELIEVED running set (delta beats may suppress
        # an unchanged RUNNING status, and a speculative loser whose
        # progress report was suppressed must still die). The whole scan
        # is lock-free: job state and the kill-mark set are plain reads
        # (marks are maintained at the points where an attempt becomes
        # a kill candidate — job_in_progress._kill_marked)
        for aid in list(info.running):
            # attempt_<cluster>_<nnnn>_... → job_<cluster>_<nnnn>
            # (sliced, not parsed: this runs per running attempt per
            # beat and TaskAttemptID.parse was profiling-visible).
            # Alias-resolved: adopted pre-restart attempts must still
            # be killable when their (recovered) job dies.
            parts = aid.split("_", 3)
            jip = self._resolve_job(f"job_{parts[1]}_{parts[2]}")
            if jip is None:
                continue
            if jip.state in JobState.TERMINAL or jip.kill_marked(aid):
                actions.append({"type": "kill_task", "attempt_id": aid})

        want_task = (ask_for_new_task and not info.blacklisted
                     and status.get("healthy", True))
        if want_task and not self.sched_lock.acquire(blocking=False):
            # TRY-lock, never queue: with thousands of asking trackers,
            # beats waiting in line for the one-at-a-time scheduler
            # pass were the post-decomposition wall (sched-lock wait
            # p99 tracked heartbeat p99 exactly like the old global
            # lock did). A beat that loses the race simply assigns
            # nothing — the tracker re-asks next interval, and
            # assignment throughput is bounded by pass cost, not by
            # contention. Counted so a hot scheduler is visible.
            self._mreg.incr("assign_skipped_busy")
        elif want_task:
            t_assign = time.monotonic()
            t_assign_wall = time.time() if hb_trace is not None else 0.0
            try:
                assigned = self.scheduler.assign_tasks(status)
            finally:
                self.sched_lock.release()
            for task in assigned:
                if not task.is_map:
                    self._mreg.incr("reduces_launched")
                elif task.run_on_tpu:
                    self._mreg.incr("maps_launched_tpu")
                else:
                    self._mreg.incr("maps_launched_cpu")
                tjip = self.jobs.get(str(task.attempt_id.task.job))
                if tjip is not None and tjip.first_assign_mono is None:
                    # first assignment for this job — the scheduling-
                    # responsiveness half of the per-class SLO (the
                    # assign pass is serialized by sched_lock, so the
                    # None check can't race itself)
                    tjip.first_assign_mono = time.monotonic()
                    if tjip.traffic_class:
                        self._class_observe(
                            "assign", tjip.traffic_class,
                            tjip.first_assign_mono - tjip.submit_mono)
                if tjip is not None and tjip.trace_root is not None:
                    # scheduling decision span; its context rides the
                    # launch action so the tracker/child parent their
                    # spans to it (submit→schedule→launch→run chain)
                    sched = self.tracer.instant(
                        "schedule", tjip.trace_id,
                        parent=tjip.trace_root,
                        backend=("tpu" if task.run_on_tpu else "cpu")
                        if task.is_map else "cpu",
                        attempt_id=str(task.attempt_id), tracker=name)
                    task.trace = {"trace_id": tjip.trace_id,
                                  "span_id": sched.span_id}
                # the believed-running set learns launches immediately:
                # a launched-but-never-yet-reported attempt must still
                # be requeued if this tracker is lost, and killed if
                # its job dies before the first status arrives
                info.running.add(str(task.attempt_id))
                actions.append({"type": "launch",
                                "job_id": str(task.attempt_id.task.job),
                                "task": task.to_dict()})
                # assignment-time event: gives the history timeline
                # true start stamps + placement (≈ JobHistory
                # Task.START_TIME; rendered by the history server's
                # /jobtasks view, the TaskGraphServlet role). Display-
                # only — the history server derives a start stamp when
                # it's absent — so brownout level 3 sheds the append
                # and its deferred file I/O.
                if self.brownout is not None \
                        and self.brownout.sheds("history"):
                    self.brownout.events_shed += 1
                else:
                    deferred_events.append((
                        str(task.attempt_id.task.job), "TASK_STARTED",
                        dict(attempt_id=str(task.attempt_id),
                             is_map=task.is_map,
                             run_on_tpu=task.run_on_tpu,
                             tpu_device_id=task.tpu_device_id,
                             tracker=name)))
            # the scheduler pass plus per-assignment bookkeeping —
            # observed only when the pass actually ran, so the
            # distribution isn't drowned by no-ask heartbeats
            self._hb_phase["assign"].observe(
                time.monotonic() - t_assign)
            self._phase_span(hb_trace, "heartbeat:assign",
                             t_assign_wall)

        response_id += 1
        self._last_response[name] = (response_id, actions)
        # adaptive cadence: every response tells the tracker when to
        # come back (TaskTracker honors HeartbeatResponse's interval in
        # the reference; ours is the same contract)
        nxt = self._instructed_interval_s()
        info.interval_s = nxt
        return {"response_id": response_id, "actions": actions,
                "next_interval_ms": int(nxt * 1000 + 0.5)}

    def _drain_accel_events(self, jip: JobInProgress, job_id: str,
                            tracker: str, deferred_events: list) -> None:
        """Demotion/quarantine decisions made inside update_task_status:
        meter them, history-log them, and drop trace instants on the job
        timeline (takes only the job lock; history I/O is deferred)."""
        for ev in jip.drain_accel_events():
            kind = ev.pop("kind")
            ev["tracker"] = tracker
            if kind == "tip_demoted":
                self._mreg.incr("tpu_demotions")
                deferred_events.append((job_id, "TIP_TPU_DEMOTED", ev))
                instant = "tpu:demote_tip"
            else:
                self._mreg.incr("jobs_tpu_quarantined")
                deferred_events.append((job_id, "JOB_TPU_QUARANTINED", ev))
                instant = "tpu:job_quarantine"
            if jip.trace_root is not None:
                self.tracer.instant(instant, jip.trace_id,
                                    parent=jip.trace_root, **ev)

    def _fetch_failure(self, ff: dict, deferred_events: list,
                       deferred_final: list) -> None:
        """Apply one reducer fetch-failure report (job-lock work only —
        the global lock is touched just to revoke the burned attempt's
        commit grant). The job counts distinct reporting reducers; once
        it withdraws the map output the master-side effects land here:
        the burned attempt's commit grant is revoked (the re-run must be
        able to commit), a fault is charged to the tracker that SERVED
        the lost output — a lame-but-heartbeating shuffle server walks
        toward blacklisting exactly like a task-failing tracker — and
        the re-execution is metered + history-logged."""
        map_attempt = str(ff.get("map_attempt", ""))
        reduce_attempt = str(ff.get("reduce_attempt", ""))
        try:
            task_id = TaskAttemptID.parse(map_attempt).task
        except (ValueError, IndexError):
            return
        jip = self._resolve_job(str(task_id.job))
        if jip is None:
            return
        before = jip.state
        res = jip.fetch_failure_notification(map_attempt, reduce_attempt)
        if res is None:
            return   # stale (already withdrawn) — not a counted report
        self._mreg.incr("fetch_failures_reported")
        if jip.trace_root is not None:
            # per-map fetch-failure recovery on the job timeline: report
            # marks are sub-threshold; a withdrawal is the re-execution
            # decision itself
            self.tracer.instant(
                "fetch_failure:withdraw" if res["withdrawn"]
                else "fetch_failure:report",
                jip.trace_id, parent=jip.trace_root,
                map_attempt=map_attempt, reduce_attempt=reduce_attempt,
                reports=res.get("reports", 0),
                reexecuted=res["reexecuted"])
        if res["withdrawn"]:
            self._revoke_commit(str(task_id), map_attempt)
            if res["reexecuted"]:
                self._mreg.incr("maps_reexecuted_fetch_failure")
            addr = res.get("shuffle_addr", "")
            info = self._tracker_by_shuffle_addr(addr)
            if info is not None and \
                    info.charge_fault(self.blacklist_faults):
                self._blacklisted += 1
            deferred_events.append((str(task_id.job), "MAP_OUTPUT_LOST",
                                    dict(attempt_id=map_attempt,
                                         shuffle_addr=addr,
                                         reports=res.get("reports", 0),
                                         reexecuted=res["reexecuted"])))
        if before == JobState.RUNNING and jip.state in JobState.TERMINAL:
            self._bump_jobs_version()
            deferred_final.append(jip)

    def _tracker_by_shuffle_addr(self, addr: str) -> "_TrackerInfo | None":
        """The registered tracker serving map outputs at ``addr``
        (registry-striped scan)."""
        if not addr:
            return None
        for info in self.trackers.values():
            st = info.status
            a = st.get("shuffle_addr") or \
                f"{st.get('host', '')}:{st.get('shuffle_port', 0)}"
            if a == addr:
                return info
        return None

    # ------------------------------------------------------------ expiry

    def _evict_tracker(self, name: str) -> None:
        """Remove one tracker and re-queue everything it owned (running
        attempts AND completed maps whose outputs lived there) —
        ≈ JobTracker.lostTaskTracker. Takes the registry shard lock for
        the pop only; the requeue work runs under per-job locks (a slow
        eviction must not stall other trackers' heartbeats)."""
        info = self.trackers.pop(name)
        if info is None:
            return
        if info.blacklisted:
            self._blacklisted = max(0, self._blacklisted - 1)
        self._last_response.pop(name, None)
        self.cluster_agg.forget(name)
        # the BELIEVED running set, not the last beat's status list: a
        # delta beat may have suppressed (rate-limited) an unchanged
        # RUNNING status, and a launched-but-never-reported attempt
        # only exists here. Snapshot under the tracker's hb_lock: an
        # in-flight beat that won the lock first finishes its
        # fold/assign and its launches land in the snapshot; one that
        # loses sees the popped registry entry and aborts with reinit
        # (the membership re-check in _heartbeat) — either way nothing
        # can be assigned to this tracker after the snapshot.
        with info.hb_lock:
            attempts = list(info.running) or \
                [sd["attempt_id"] for sd in
                 info.status.get("task_statuses", [])]
        addr = (f"{info.status.get('host', '')}:"
                f"{info.status.get('shuffle_port', 0)}")
        self._requeue_tracker_work(attempts, addr)

    def _requeue_restarted(self, name: str, old: "_TrackerInfo",
                           status: dict) -> None:
        """Cold re-registration cleanup (caller just swapped the
        registry entry; holds no locks): requeue what the OLD
        incarnation owned — minus any attempt the new status still
        carries, per the wire contract, though a cold process never
        carries one — and drop its replay-cache entry so a stale
        response id can never replay the dead process's actions into
        the new one."""
        self._mreg.incr("trackers_restarted")
        self._last_response.pop(name, None)
        carried = {sd.get("attempt_id")
                   for sd in status.get("task_statuses", [])}
        with old.hb_lock:
            attempts = [a for a in old.running if a not in carried]
        addr = (f"{old.status.get('host', '')}:"
                f"{old.status.get('shuffle_port', 0)}")
        self._requeue_tracker_work(attempts, addr)

    def _requeue_tracker_work(self, attempts: "list[str]",
                              addr: str) -> None:
        """Requeue a dead tracker incarnation's work: running attempts
        back to pending, completed map outputs it served withdrawn,
        streamed-handoff announcements tombstoned, commit grants
        revoked. Per-job locks only — shared by eviction and cold
        re-registration."""
        for jip in list(self.jobs.values()):
            with jip.lock:
                # OBSOLETE entries are tombstones of already-withdrawn
                # outputs — only live events name outputs this tracker
                # still owed the shuffle
                owned = [e["attempt_id"]
                         for e in jip.completion_events
                         if e["shuffle_addr"] == addr
                         and e.get("status") != "OBSOLETE"]
            withdrawn = jip.requeue_lost_attempts(attempts + owned)
            for aid in withdrawn:
                # journal the withdrawal: restart recovery replays the
                # history file and must not adopt outputs this master
                # already declared gone with their tracker
                self.history.task_event(
                    str(jip.job_id), "MAP_OUTPUT_LOST", attempt_id=aid,
                    shuffle_addr=addr, reason="tracker_lost")
            # streamed-handoff copies this tracker served die with it:
            # tombstone their announcements (downstream readers evict
            # the location and fall back to the committed part files —
            # the PR-1 withdrawal dialect, one feed over)
            lost_handoff = jip.withdraw_handoff_at(addr)
            if lost_handoff:
                self._mreg.incr("handoff_outputs_lost", lost_handoff)
                self.history.task_event(
                    str(jip.job_id), "HANDOFF_OUTPUT_LOST",
                    shuffle_addr=addr, partitions=lost_handoff,
                    reason="tracker_lost")
        for aid in attempts:
            self._revoke_commit(str(TaskAttemptID.parse(aid).task), aid)

    def _expire_loop(self) -> None:
        while not self._stop.wait(min(1.0, self.expiry_s / 3)):
            now = time.monotonic()
            self.token_store.purge_expired()
            lost = [n for n, t in self.trackers.items()
                    if now - t.seen_mono > self.expiry_s]
            for name in lost:
                self._evict_tracker(name)

    def _pipeline_loop(self) -> None:
        """THE advancement thread: woken by heartbeat folds (the
        deferred phase sets the event when a pipeline may have moved)
        with a 500ms poll backstop for quiet clusters — e.g.
        resubmitting stages right after a restart while the fleet
        re-joins. Isolated here so blocking stage-submission I/O can
        never wedge eviction or heartbeats."""
        while not self._stop.is_set():
            self._pipe_wake.wait(0.5)
            self._pipe_wake.clear()
            if self._stop.is_set():
                return
            if self.pipelines:
                try:
                    self._advance_pipelines()
                except Exception:  # noqa: BLE001
                    self._mreg.incr("pipeline_advance_errors")
