"""Task model: the unit of scheduled work, with accelerator placement.

≈ ``org.apache.hadoop.mapred.{Task,TaskStatus,TaskReport}``. The fields that
define the reference's GPU delta are carried 1:1 as TPU fields:

- ``Task.runOnGPU`` / ``Task.GPUDeviceId`` (mapred/Task.java:169-170,
  serialized :438-439/:464-465) → :attr:`Task.run_on_tpu` /
  :attr:`Task.tpu_device_id` — set by the scheduler at assign time, shipped
  to the node runner, and used to select the map runner
  (mapred/MapTask.java:433-438).
- ``TaskStatus`` GPU fields (mapred/TaskStatus.java:66-67,390-395) →
  :class:`TaskStatus` — reported in every heartbeat so the master can
  attribute runtimes per backend (the hybrid scheduler's profiling input).
- ``TaskReport`` GPU fields (mapred/TaskReport.java:49,102-114), stamped by
  the JobTracker at assign time (mapred/JobTracker.java:3414-3433).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from tpumr.mapred.ids import TaskAttemptID, TaskID


class TaskState:
    UNASSIGNED = "UNASSIGNED"
    RUNNING = "RUNNING"
    COMMIT_PENDING = "COMMIT_PENDING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"

    TERMINAL = {SUCCEEDED, FAILED, KILLED}


class TaskPhase:
    STARTING = "STARTING"
    MAP = "MAP"
    SHUFFLE = "SHUFFLE"
    SORT = "SORT"
    REDUCE = "REDUCE"
    CLEANUP = "CLEANUP"


@dataclass
class Task:
    """A scheduled task attempt, shipped master → node runner."""

    attempt_id: TaskAttemptID
    partition: int                 # map: split index; reduce: partition index
    num_reduces: int = 1
    split: dict | None = None      # InputSplit.to_dict() for maps
    num_maps: int = 0              # for reduces: how many map outputs to fetch
    # --- accelerator placement (≈ Task.java:169-170) ---
    run_on_tpu: bool = False
    tpu_device_id: int = -1
    #: declared memory demand (mapred.job.{map,reduce}.memory.mb), stamped
    #: at assign time so the tracker can report available memory without a
    #: conf lookup — feeds the capacity scheduler's memory matching
    memory_mb: int = 0
    #: distributed-tracing context ({trace_id, span_id} of the master's
    #: scheduling span), stamped at assign time for traced jobs only —
    #: the tracker and child parent their spans to it (core/tracing.py).
    #: None for untraced jobs: the zero-overhead-off contract.
    trace: dict | None = None

    @property
    def is_map(self) -> bool:
        return self.attempt_id.task.is_map

    @property
    def task_id(self) -> TaskID:
        return self.attempt_id.task

    def to_dict(self) -> dict[str, Any]:
        return {
            "attempt_id": str(self.attempt_id),
            "partition": self.partition,
            "num_reduces": self.num_reduces,
            "split": self.split,
            "num_maps": self.num_maps,
            "run_on_tpu": self.run_on_tpu,
            "tpu_device_id": self.tpu_device_id,
            "memory_mb": self.memory_mb,
            "trace": self.trace,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Task":
        return cls(attempt_id=TaskAttemptID.parse(d["attempt_id"]),
                   partition=d["partition"], num_reduces=d["num_reduces"],
                   split=d.get("split"), num_maps=d.get("num_maps", 0),
                   run_on_tpu=d.get("run_on_tpu", False),
                   tpu_device_id=d.get("tpu_device_id", -1),
                   memory_mb=d.get("memory_mb", 0),
                   trace=d.get("trace"))


@dataclass
class TaskStatus:
    """Per-attempt status, carried in heartbeats (≈ TaskStatus.java with the
    GPU fields of :66-67 and factory overloads :475-491)."""

    attempt_id: TaskAttemptID
    is_map: bool = True
    state: str = TaskState.RUNNING
    progress: float = 0.0
    phase: str = TaskPhase.STARTING
    start_time: float = field(default_factory=time.time)
    finish_time: float = 0.0
    diagnostics: str = ""
    counters: dict = field(default_factory=dict)
    # --- accelerator placement ---
    run_on_tpu: bool = False
    tpu_device_id: int = -1

    @property
    def runtime(self) -> float:
        """Wall-clock seconds (finish-start) — the hybrid scheduler's
        profiling signal (JobInProgress.getCPU/GPUMapTaskMeanTime inputs,
        mapred/JobInProgress.java:527-565)."""
        end = self.finish_time or time.time()
        return max(0.0, end - self.start_time)

    def to_dict(self) -> dict[str, Any]:
        d = dict(self.__dict__)
        d["attempt_id"] = str(self.attempt_id)
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TaskStatus":
        d = dict(d)
        d["attempt_id"] = TaskAttemptID.parse(d["attempt_id"])
        return cls(**d)


@dataclass
class TaskReport:
    """Client-visible per-task report (≈ TaskReport.java:49,102-114 — the
    JobTracker stamps TPU placement at assign time,
    JobTracker.java:3414-3433 'NEW BLOCK')."""

    task_id: TaskID
    state: str = TaskState.UNASSIGNED
    progress: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    successful_attempt: str = ""
    diagnostics: list[str] = field(default_factory=list)
    run_on_tpu: bool = False
    tpu_device_id: int = -1
