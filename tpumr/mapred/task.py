"""Task model: the unit of scheduled work, with accelerator placement.

≈ ``org.apache.hadoop.mapred.{Task,TaskStatus,TaskReport}``. The fields that
define the reference's GPU delta are carried 1:1 as TPU fields:

- ``Task.runOnGPU`` / ``Task.GPUDeviceId`` (mapred/Task.java:169-170,
  serialized :438-439/:464-465) → :attr:`Task.run_on_tpu` /
  :attr:`Task.tpu_device_id` — set by the scheduler at assign time, shipped
  to the node runner, and used to select the map runner
  (mapred/MapTask.java:433-438).
- ``TaskStatus`` GPU fields (mapred/TaskStatus.java:66-67,390-395) →
  :class:`TaskStatus` — reported in every heartbeat so the master can
  attribute runtimes per backend (the hybrid scheduler's profiling input).
- ``TaskReport`` GPU fields (mapred/TaskReport.java:49,102-114), stamped by
  the JobTracker at assign time (mapred/JobTracker.java:3414-3433).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from tpumr.mapred.ids import TaskAttemptID, TaskID


class TaskState:
    UNASSIGNED = "UNASSIGNED"
    RUNNING = "RUNNING"
    COMMIT_PENDING = "COMMIT_PENDING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"

    TERMINAL = {SUCCEEDED, FAILED, KILLED}


class TaskPhase:
    STARTING = "STARTING"
    MAP = "MAP"
    SHUFFLE = "SHUFFLE"
    SORT = "SORT"
    REDUCE = "REDUCE"
    CLEANUP = "CLEANUP"


class FailureClass:
    """Why an attempt failed — the accelerator-fault-tolerance signal.

    The reference retries every failure identically (mapred.map.max.
    attempts) and often re-lands the retry on the same backend; the
    hybrid dispatch plane needs to know WHETHER the failure indicts the
    accelerator (demote the TIP to CPU, quarantine the device) or the
    user code (burn attempts as usual). Derived at the failure site
    (tpu_runner / child / the tracker's reaper) and carried on
    TaskStatus through heartbeats into JobInProgress._on_failure."""

    DEVICE = "device"      # the accelerator runtime/device misbehaved
    COMPILE = "compile"    # XLA/kernel compilation failed
    OOM = "oom"            # memory exhaustion (host RSS or device HBM)
    USER = "user"          # user code raised — backend is innocent
    TIMEOUT = "timeout"    # reaped: stopped reporting progress

    #: classes that indict the accelerator path (drive TPU→CPU demotion
    #: and job-level TPU quarantine); OOM is excluded — a split too big
    #: for HBM usually OOMs the host spill path too
    ACCELERATOR = {DEVICE, COMPILE}


def tag_failure(exc: BaseException, failure_class: str) -> BaseException:
    """Stamp ``failure_class`` on an exception at its site (first stamp
    wins). Best-effort: exotic exceptions with __slots__ just stay
    unclassified and fall through to the heuristics."""
    if not getattr(exc, "failure_class", ""):
        try:
            exc.failure_class = failure_class
        except (AttributeError, TypeError):
            pass
    return exc


def classify_exception(exc: BaseException) -> str:
    """Generic (site-less) classification at the settle points: an
    explicit site tag wins; memory exhaustion is recognized by type or
    by the XLA RESOURCE_EXHAUSTED wording; everything else is user
    code's fault."""
    fc = getattr(exc, "failure_class", "")
    if fc:
        return str(fc)
    if isinstance(exc, MemoryError):
        return FailureClass.OOM
    text = f"{type(exc).__name__}: {exc}".lower()
    if "resource_exhausted" in text or "out of memory" in text \
            or "hbm" in text and "exhaust" in text:
        return FailureClass.OOM
    return FailureClass.USER


def classify_accelerator_exception(exc: BaseException,
                                   compile_cold: bool = False) -> str:
    """Classification inside the TPU runner (the stage and execute
    sites). Compile failures surface as execute-time errors under JAX's
    lazy compilation, so a COLD dispatch whose error text mentions
    compilation/lowering is classed ``compile``; errors raised by the
    jax/jaxlib/XLA stack are ``device``; anything else is user code
    that happened to run on an accelerator slot."""
    fc = getattr(exc, "failure_class", "")
    if fc:
        return str(fc)
    generic = classify_exception(exc)
    if generic == FailureClass.OOM:
        return generic
    text = f"{type(exc).__name__}: {exc}".lower()
    if compile_cold and ("compil" in text or "lowering" in text
                         or "unsupported" in text):
        return FailureClass.COMPILE
    # top-level package match, not a prefix: jaxtyping/jax_md etc. are
    # user-code stacks whose bugs must not indict the device
    mod = (type(exc).__module__ or "").split(".")[0]
    if mod in ("jax", "jaxlib") or "xla" in text:
        return FailureClass.DEVICE
    return FailureClass.USER


@dataclass
class Task:
    """A scheduled task attempt, shipped master → node runner."""

    attempt_id: TaskAttemptID
    partition: int                 # map: split index; reduce: partition index
    num_reduces: int = 1
    split: dict | None = None      # InputSplit.to_dict() for maps
    num_maps: int = 0              # for reduces: how many map outputs to fetch
    # --- accelerator placement (≈ Task.java:169-170) ---
    run_on_tpu: bool = False
    tpu_device_id: int = -1
    #: declared memory demand (mapred.job.{map,reduce}.memory.mb), stamped
    #: at assign time so the tracker can report available memory without a
    #: conf lookup — feeds the capacity scheduler's memory matching
    memory_mb: int = 0
    #: distributed-tracing context ({trace_id, span_id} of the master's
    #: scheduling span), stamped at assign time for traced jobs only —
    #: the tracker and child parent their spans to it (core/tracing.py).
    #: None for untraced jobs: the zero-overhead-off contract.
    trace: dict | None = None

    @property
    def is_map(self) -> bool:
        return self.attempt_id.task.is_map

    @property
    def task_id(self) -> TaskID:
        return self.attempt_id.task

    def to_dict(self) -> dict[str, Any]:
        return {
            "attempt_id": str(self.attempt_id),
            "partition": self.partition,
            "num_reduces": self.num_reduces,
            "split": self.split,
            "num_maps": self.num_maps,
            "run_on_tpu": self.run_on_tpu,
            "tpu_device_id": self.tpu_device_id,
            "memory_mb": self.memory_mb,
            "trace": self.trace,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Task":
        return cls(attempt_id=TaskAttemptID.parse(d["attempt_id"]),
                   partition=d["partition"], num_reduces=d["num_reduces"],
                   split=d.get("split"), num_maps=d.get("num_maps", 0),
                   run_on_tpu=d.get("run_on_tpu", False),
                   tpu_device_id=d.get("tpu_device_id", -1),
                   memory_mb=d.get("memory_mb", 0),
                   trace=d.get("trace"))


@dataclass
class TaskStatus:
    """Per-attempt status, carried in heartbeats (≈ TaskStatus.java with the
    GPU fields of :66-67 and factory overloads :475-491)."""

    attempt_id: TaskAttemptID
    is_map: bool = True
    state: str = TaskState.RUNNING
    progress: float = 0.0
    phase: str = TaskPhase.STARTING
    start_time: float = field(default_factory=time.time)
    finish_time: float = 0.0
    diagnostics: str = ""
    counters: dict = field(default_factory=dict)
    # --- accelerator placement ---
    run_on_tpu: bool = False
    tpu_device_id: int = -1
    #: why a FAILED attempt failed (FailureClass.*; "" = unclassified) —
    #: the demotion/quarantine/reaping signal, heartbeat-carried
    failure_class: str = ""
    #: total map-output bytes (sum of partition part lengths), stamped at
    #: the success settle sites — rides completion events so reduces can
    #: order their fetch queues largest-first (size-aware shuffle)
    output_bytes: int = 0

    @property
    def runtime(self) -> float:
        """Wall-clock seconds (finish-start) — the hybrid scheduler's
        profiling signal (JobInProgress.getCPU/GPUMapTaskMeanTime inputs,
        mapred/JobInProgress.java:527-565)."""
        end = self.finish_time or time.time()
        return max(0.0, end - self.start_time)

    def to_dict(self) -> dict[str, Any]:
        d = dict(self.__dict__)
        d["attempt_id"] = str(self.attempt_id)
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TaskStatus":
        d = dict(d)
        d["attempt_id"] = TaskAttemptID.parse(d["attempt_id"])
        return cls(**d)


@dataclass
class TaskReport:
    """Client-visible per-task report (≈ TaskReport.java:49,102-114 — the
    JobTracker stamps TPU placement at assign time,
    JobTracker.java:3414-3433 'NEW BLOCK')."""

    task_id: TaskID
    state: str = TaskState.UNASSIGNED
    progress: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    successful_attempt: str = ""
    diagnostics: list[str] = field(default_factory=list)
    run_on_tpu: bool = False
    tpu_device_id: int = -1
