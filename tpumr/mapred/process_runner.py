"""Process-isolated task execution — the tracker side.

≈ ``TaskRunner`` + ``JvmManager`` + the ``TaskController`` SPI (reference:
src/mapred/org/apache/hadoop/mapred/TaskRunner.java:252 child cmdline,
JvmManager.java:322-413 spawn/reap, TaskController.java DefaultTaskController
vs setuid LinuxTaskController): builds the child command line, optionally
routes the launch through the native setuid ``task-controller`` binary
(native/task-controller/), watches the process, and settles the attempt's
final status if the child died without reporting over the umbilical.

Enabled per job or per tracker with ``tpumr.task.isolation=process``; the
default stays in-process threads (tasktracker.py module docstring — TPU
tasks and device-shuffle gang reduces always stay in-process because they
must share the tracker's JAX runtime and HBM split cache). A crashing
(segfault / os._exit / OOM-killed) child then costs one task attempt, not
the tracker — the reference's whole reason for child JVMs.

Launch-path contracts:

- the child runs from a per-attempt sandbox dir (the same dir the
  in-process path uses for spills), so the tracker can serve the map
  output files after the child exits;
- a bootstrap script with the tracker's ``sys.path`` baked in is execed
  instead of ``-m``, because the task-controller clears the environment
  (including PYTHONPATH) before exec;
- the task file (conf + task + umbilical address + RPC secret) is written
  0600 into the sandbox — the single file the setuid controller validates;
- memory limits (``mapred.task.limit.maxrss.mb``) are enforced by the
  shared TaskMemoryManager against the child pid — process kills, as the
  reference's TaskMemoryManagerThread does, not cooperative checks.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any

from tpumr.io.writable import serialize
from tpumr.mapred.task import Task, TaskState, TaskStatus

_BOOT_TEMPLATE = """\
import sys
sys.path[:0] = {path!r}
from tpumr.mapred.child import main
sys.exit(main([{task_file!r}]))
"""


def build_child_command(runner: Any, task_dir: str, task_file: str,
                        log_path: str) -> "list[str]":
    """Child argv; routed through the task-controller when the TRACKER
    conf names one (the job conf is untrusted for launcher selection —
    reference: LinuxTaskController reads its binary path from the tracker,
    never the job)."""
    boot = os.path.join(task_dir, "child_boot.py")
    with open(boot, "w", encoding="utf-8") as f:
        f.write(_BOOT_TEMPLATE.format(path=list(sys.path),
                                      task_file=task_file))
    cmd = [sys.executable, boot]
    tc = runner.conf.get("mapred.task.tracker.task-controller")
    if tc:
        import getpass
        user = runner.conf.get("tpumr.task.user") or getpass.getuser()
        cmd = [tc, user, task_dir, log_path] + cmd
    return cmd


def run_task_in_process(runner: Any, job_id: str, task: Task,
                        status: TaskStatus, conf: Any) -> None:
    """Spawn + babysit one isolated attempt. The child reports its own
    terminal state over the umbilical; this function only (a) relays
    kill requests as process kills, (b) applies memory-limit kills, and
    (c) declares FAILED when the child exits without having reported."""
    aid = str(task.attempt_id)
    task_dir = os.path.join(runner.local_root, job_id, aid)
    os.makedirs(task_dir, exist_ok=True)

    task_file = os.path.join(task_dir, "task.bin")
    # the child gets the per-JOB token, never the cluster secret (≈ the
    # reference's jobToken file in the attempt dir): a compromised task
    # can only reach its own job's umbilical + shuffle surface
    if runner._rpc_secret:
        child_secret, child_scope = runner._job_token(job_id), job_id
    else:
        child_secret, child_scope = b"", None  # unauthenticated cluster
    conf_dict = conf.to_dict()
    if conf.get_boolean("tpumr.task.strip.cluster.secret", False):
        # hardening opt-in: the child's umbilical/shuffle traffic signs
        # with the job token either way, but the cluster secret ALSO
        # rides the job conf (tasks reading tdfs:// authenticate to the
        # dfs daemons with it — full child credential isolation needs
        # delegation tokens, a documented non-goal). Deployments whose
        # tasks don't touch tdfs directly can strip it.
        from tpumr.core.configuration import is_sensitive_key
        conf_dict = {k: v for k, v in conf_dict.items()
                     if not is_sensitive_key(k)}
    payload = serialize({
        "job_id": job_id,
        "task": task.to_dict(),
        "conf": conf_dict,
        "tracker_host": runner.bind_host,
        "tracker_port": runner.shuffle_port,
        "secret": child_secret,
        "scope": child_scope,
    })
    fd = os.open(task_file, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(payload)

    # the child's stdout/stderr goes STRAIGHT into the retained userlogs
    # tree (≈ userlogs + TaskLogServlet): the sandbox dir is purged the
    # moment the job finishes — a post-exit copy from it would race that
    # cleanup and lose exactly the logs someone wants to read
    logs_dir = os.path.join(runner.local_root, "userlogs", job_id, aid)
    os.makedirs(logs_dir, exist_ok=True)
    log_path = os.path.join(logs_dir, "child.log")
    cmd = build_child_command(runner, task_dir, task_file, log_path)
    open(log_path, "ab").close()
    _prepare_sandbox_for_user(runner, task_dir, logs_dir)

    mem_killed = []
    with open(log_path, "ab") as log_f:
        proc = subprocess.Popen(cmd, cwd=task_dir, stdout=log_f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)

    limit_mb = conf.get_int("mapred.task.limit.maxrss.mb", 0)
    manager = None
    if limit_mb > 0:
        from tpumr.mapred.node_health import GLOBAL_MEMORY_MANAGER
        manager = GLOBAL_MEMORY_MANAGER

        def mem_kill(_aid: str) -> None:
            mem_killed.append(_aid)
            _kill_tree(proc)

        manager.register(aid, proc.pid, limit_mb * 1024 * 1024, mem_kill)

    try:
        while proc.poll() is None:
            with runner.lock:
                wants_kill = aid in runner._kill_requested
            if wants_kill:
                _kill_tree(proc)
                break
            time.sleep(0.1)
        proc.wait()
    finally:
        if manager is not None:
            manager.unregister(aid)

    # settle: the child normally set a terminal state via umbilical_done/
    # umbilical_fail; if it vanished first (segfault, os._exit, SIGKILL),
    # the attempt is decided here. A reaper-settled (timeout) attempt is
    # already terminal — the early return keeps its failure_class.
    from tpumr.mapred.task import FailureClass
    with runner.lock:
        if status.state in TaskState.TERMINAL:
            return
        status.finish_time = time.time()
        if mem_killed:
            status.state = TaskState.FAILED
            status.failure_class = FailureClass.OOM
            status.diagnostics = (
                f"killed by memory manager: RSS exceeded {limit_mb} MB "
                f"(mapred.task.limit.maxrss.mb)")
        elif aid in runner._kill_requested:
            status.state = TaskState.KILLED
            status.diagnostics = "child killed on tracker request"
        else:
            status.state = TaskState.FAILED
            # a crash without a report is user code's doing (segfault,
            # os._exit) — possibly the OOM killer's, recognizable by rc
            status.failure_class = (FailureClass.OOM
                                    if proc.returncode == -9 else
                                    FailureClass.USER)
            status.diagnostics = (
                f"child exited rc={proc.returncode} without reporting\n"
                + _tail(log_path))


def _prepare_sandbox_for_user(runner: Any, task_dir: str,
                              logs_dir: "str | None" = None) -> None:
    """When launching through the setuid task-controller as root, hand the
    attempt sandbox (and its userlogs dir — the controller redirects the
    child's stdio there after the privilege drop) to the task user before
    exec — the controller refuses a task dir the target user does not
    own. This is the role of the reference controller's INITIALIZE_TASK
    command (the tracker-side Localizer chowns task dirs through it).
    Parent dirs get traverse-only bits so the child can reach its sandbox
    but not list sibling jobs."""
    tc = runner.conf.get("mapred.task.tracker.task-controller")
    if not tc or os.geteuid() != 0:
        return
    import getpass
    import pwd
    user = runner.conf.get("tpumr.task.user") or getpass.getuser()
    try:
        pw = pwd.getpwnam(user)
    except KeyError:
        return
    if pw.pw_uid == os.geteuid():
        return
    os.chmod(runner.local_root, 0o711)
    os.chmod(os.path.dirname(task_dir), 0o711)
    roots = [task_dir]
    if logs_dir is not None:
        os.chmod(os.path.dirname(logs_dir), 0o711)          # userlogs/<job>
        os.chmod(os.path.dirname(os.path.dirname(logs_dir)), 0o711)
        roots.append(logs_dir)
    for top in roots:
        for root, dirs, files in os.walk(top):
            os.chown(root, pw.pw_uid, pw.pw_gid)
            for name in files:
                os.chown(os.path.join(root, name), pw.pw_uid, pw.pw_gid)


def _kill_tree(proc: "subprocess.Popen[bytes]") -> None:
    """Kill the child's whole session (it may have spawned pipes/streaming
    grandchildren — reference kills the process TREE via the controller)."""
    import signal
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def _tail(path: str, max_bytes: int = 4096) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""
