"""tpumr — a TPU-native distributed MapReduce framework.

A ground-up re-design of the capabilities of ``millecker/hadoop-1.0.3-gpu``
(Apache Hadoop 1.0.3 + Shirahata et al. hybrid CPU/GPU map-task scheduling)
for TPU hardware:

- the Java control plane (JobTracker/TaskTracker/heartbeats) becomes a Python
  control plane with the same contracts (dual slot pools, profiling-driven
  hybrid scheduler, pluggable scheduler SPI, counters/history);
- the C++/CUDA "pipes" per-record socket data path becomes an in-process
  JAX/XLA/Pallas map runner that stages whole InputSplits into HBM;
- host-level TCP shuffle keeps a host path, plus an on-device bucketed
  all-to-all over ICI for kernel-mapped jobs.

Package layout (≈ reference layers, SURVEY.md §1):

- ``tpumr.core``     — config, counters, progress, metrics (≈ L1 common)
- ``tpumr.io``       — record serialization, SequenceFile/IFile (≈ L1 io)
- ``tpumr.fs``       — FileSystem SPI: local, in-memory, DFS-lite (≈ L1/L3)
- ``tpumr.ipc``      — framed RPC, versioned protocols (≈ L2)
- ``tpumr.parallel`` — mesh, collectives, device shuffle (new: ICI data plane)
- ``tpumr.ops``      — Pallas/JAX map kernels (replaces user CUDA binaries)
- ``tpumr.mapred``   — job/task runtime, schedulers, trackers (≈ L4-L7)
- ``tpumr.models``   — example jobs: wordcount, pi, kmeans, terasort… (≈ L8)
- ``tpumr.utils``    — reflection, shell, net topology helpers
"""

__version__ = "0.1.0"

VERSION_STRING = "1.0.3-tpu"  # ≈ build.xml:31 version 1.0.3-gpu
