"""Example programs + driver.

≈ the reference's ``src/examples/org/apache/hadoop/examples`` tree with its
``ExampleDriver`` (ExampleDriver.java): a name→program registry the CLI
dispatches to (``tpumr examples <name> <args>``). Each program is a
function ``main(argv: list[str]) -> int``.
"""

from __future__ import annotations

import sys
from typing import Callable

_PROGRAMS: dict[str, tuple[Callable[[list[str]], int], str]] = {}


def register(name: str, description: str):
    def deco(fn):
        _PROGRAMS[name] = (fn, description)
        return fn
    return deco


def programs() -> dict[str, str]:
    _load_all()
    return {k: v[1] for k, v in sorted(_PROGRAMS.items())}


def _load_all() -> None:
    # import for registration side effects
    from tpumr.examples import basic  # noqa: F401
    from tpumr.examples import join  # noqa: F401
    from tpumr.examples import random_writer  # noqa: F401
    from tpumr.examples import secondary_sort  # noqa: F401
    from tpumr.examples import sleep  # noqa: F401
    from tpumr.examples import sort  # noqa: F401
    from tpumr.examples import terasort  # noqa: F401


def main(argv: list[str]) -> int:
    """≈ ExampleDriver.main: dispatch by program name."""
    _load_all()
    if not argv or argv[0] in ("-h", "--help", "help"):
        print("Valid program names are:", file=sys.stderr)
        for name, desc in programs().items():
            print(f"  {name}: {desc}", file=sys.stderr)
        return 0 if argv else 255
    name, *rest = argv
    if name not in _PROGRAMS:
        print(f"Unknown program '{name}'", file=sys.stderr)
        for prog, desc in programs().items():
            print(f"  {prog}: {desc}", file=sys.stderr)
        return 255
    return _PROGRAMS[name][0](rest)
