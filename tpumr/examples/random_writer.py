"""RandomWriter — bulk random SequenceFile generation.

≈ ``src/examples/org/apache/hadoop/examples/RandomWriter.java``: map-only
job, each map writes ~``bytes_per_map`` of random key/value records to its
own output file (the standard input generator for the Sort benchmark).
"""

from __future__ import annotations

import argparse

import numpy as np

from tpumr.examples import register
from tpumr.fs import get_filesystem
from tpumr.mapred.api import Mapper
from tpumr.mapred.input_formats import NLineInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.output_formats import SequenceFileOutputFormat


class RandomWriteMapper(Mapper):
    """Input record "<seed> <total_bytes>": emits random-sized random
    records until total_bytes is reached (key 10-1000 bytes, value
    0-10000 bytes ≈ RandomWriter defaults)."""

    def configure(self, conf) -> None:
        self._min_key = conf.get_int("tpumr.randomwriter.min.key", 10)
        self._max_key = conf.get_int("tpumr.randomwriter.max.key", 100)
        self._min_val = conf.get_int("tpumr.randomwriter.min.value", 0)
        self._max_val = conf.get_int("tpumr.randomwriter.max.value", 1000)

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        seed, total = (int(x) for x in s.split())
        rng = np.random.default_rng(seed)
        written = 0
        while written < total:
            klen = int(rng.integers(self._min_key, self._max_key + 1))
            vlen = int(rng.integers(self._min_val, self._max_val + 1))
            kb = rng.integers(0, 256, size=klen, dtype=np.uint8).tobytes()
            vb = rng.integers(0, 256, size=vlen, dtype=np.uint8).tobytes()
            output.collect(kb, vb)
            written += klen + vlen


@register("randomwriter", "each map writes random SequenceFile records")
def randomwriter(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples randomwriter")
    ap.add_argument("output")
    ap.add_argument("-m", "--maps", type=int, default=2)
    ap.add_argument("--bytes-per-map", type=int, default=1 << 20)
    args = ap.parse_args(argv)
    out = args.output.rstrip("/")
    inp = f"{out}.rw-in/maps.txt"
    get_filesystem(inp).write_bytes(
        inp, "".join(f"{1234 + m} {args.bytes_per_map}\n"
                     for m in range(args.maps)).encode())
    conf = JobConf()
    conf.set_job_name("random-writer")
    conf.set_input_paths(inp)
    conf.set_output_path(out)
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", 1)
    conf.set_mapper_class(RandomWriteMapper)
    conf.set_output_format(SequenceFileOutputFormat)
    conf.set_num_reduce_tasks(0)
    ok = run_job(conf).successful
    get_filesystem(out).delete(f"{out}.rw-in", recursive=True)
    return 0 if ok else 1
