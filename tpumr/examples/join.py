"""Join — reduce-side join of two datasets on a shared key.

≈ the reference's join examples (``src/examples/.../Join.java`` wires the
map-side CompositeInputFormat; ``src/contrib/data_join`` is the generic
reduce-side tagged join). This implements the reduce-side form: mappers
tag each record with its source, the reducer crosses the tagged groups —
the semantics users of either reference path rely on.
"""

from __future__ import annotations

import argparse

from tpumr.examples import register
from tpumr.mapred.api import Mapper, Reducer
from tpumr.mapred.input_formats import TextInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf


class TaggedJoinMapper(Mapper):
    """Line "<key><TAB>L|payload" or "<key><TAB>R|payload" → (key,
    (side, payload)). The side marker is in-band in each record; an
    unmarked record is treated as left."""

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        k, _, rest = s.partition("\t")
        if not rest:
            return
        side, _, payload = rest.partition("|")
        if side not in ("L", "R"):
            side, payload = "L", rest
        output.collect(k, (side, payload))


class InnerJoinReducer(Reducer):
    def reduce(self, key, values, output, reporter):
        left, right = [], []
        for side, payload in values:
            (left if side == "L" else right).append(payload)
        for l in left:
            for r in right:
                output.collect(key, f"{l}\t{r}")


class OuterJoinReducer(Reducer):
    def reduce(self, key, values, output, reporter):
        left, right = [], []
        for side, payload in values:
            (left if side == "L" else right).append(payload)
        for l in left or [""]:
            for r in right or [""]:
                output.collect(key, f"{l}\t{r}")


@register("join", "reduce-side join of two tab-keyed text datasets")
def join(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples join")
    ap.add_argument("left", help="text input: key<TAB>L|payload")
    ap.add_argument("right", help="text input: key<TAB>R|payload")
    ap.add_argument("output")
    ap.add_argument("--outer", action="store_true")
    ap.add_argument("-r", "--reduces", type=int, default=1)
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("join")
    conf.set_input_paths(args.left, args.right)
    conf.set_output_path(args.output)
    conf.set_input_format(TextInputFormat)
    conf.set_mapper_class(TaggedJoinMapper)
    conf.set_reducer_class(OuterJoinReducer if args.outer
                           else InnerJoinReducer)
    conf.set_num_reduce_tasks(args.reduces)
    return 0 if run_job(conf).successful else 1
