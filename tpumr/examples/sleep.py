"""SleepJob — a do-nothing job for exercising the scheduler.

≈ ``src/examples/org/apache/hadoop/examples/SleepJob.java``: N maps and R
reduces that just sleep — the tool the reference community used to test
slot accounting, speculative execution, and scheduler behavior. Here it
also doubles as a hybrid-scheduler probe: with ``--tpu`` the job registers
the no-op device kernel so both slot pools are exercised.
"""

from __future__ import annotations

import argparse
import time
from typing import Iterable

from tpumr.examples import register
from tpumr.mapred.api import Mapper, Reducer
from tpumr.mapred.input_formats import NLineInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf
from tpumr.fs import get_filesystem
from tpumr.ops.registry import KernelMapper, register_kernel


class SleepMapper(Mapper):
    def configure(self, conf) -> None:
        self._ms = conf.get_int("tpumr.sleep.map.ms", 100)
        # hang mode (the reaper's manual test dummy): map index
        # tpumr.sleep.hang.map stops reporting progress mid-map — forever
        # — on its first tpumr.sleep.hang.attempts attempts, so the
        # tracker's mapred.task.timeout reaper must fail it and the
        # re-run (a later attempt) completes the job
        self._hang_map = conf.get_int("tpumr.sleep.hang.map", -1)
        self._hang_attempts = conf.get_int("tpumr.sleep.hang.attempts", 1)
        self._partition = conf.get_int("tpumr.task.partition", -1)
        aid = conf.get("tpumr.task.attempt.id", "")
        try:
            from tpumr.mapred.ids import TaskAttemptID
            self._attempt_no = TaskAttemptID.parse(aid).attempt
        except (ValueError, IndexError):
            self._attempt_no = 0

    def map(self, key, value, output, reporter):
        if (self._partition == self._hang_map
                and self._attempt_no < self._hang_attempts):
            # silent forever: no progress, no status, no counters — but
            # keep polling the kill flag so an in-process reap can
            # actually free the thread (isolated children get SIGKILL)
            while True:
                reporter.raise_if_aborted()
                time.sleep(0.05)
        # sleep in slices polling the kill flag — the model for how any
        # long single-record mapper stays preemptible (record-loop mappers
        # get the poll for free in the framework's reader)
        deadline = time.monotonic() + self._ms / 1000.0
        while time.monotonic() < deadline:
            reporter.raise_if_aborted()
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
        output.collect(0, 0)


class SleepKernel(KernelMapper):
    name = "sleep"
    cpu_mapper_class = SleepMapper

    def map_batch(self, batch, conf, task) -> Iterable[tuple]:
        time.sleep(conf.get_int("tpumr.sleep.map.ms", 100) / 1000.0)
        yield 0, 0


register_kernel(SleepKernel())


class SleepReducer(Reducer):
    def configure(self, conf) -> None:
        self._ms = conf.get_int("tpumr.sleep.reduce.ms", 100)

    def reduce(self, key, values, output, reporter):
        for _ in values:
            pass
        time.sleep(self._ms / 1000.0)


@register("sleep", "N sleeping maps + R sleeping reduces (scheduler probe)")
def sleep(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples sleep")
    ap.add_argument("-m", "--maps", type=int, default=4)
    ap.add_argument("-r", "--reduces", type=int, default=1)
    ap.add_argument("--map-ms", type=int, default=100)
    ap.add_argument("--reduce-ms", type=int, default=100)
    ap.add_argument("--tpu", action="store_true",
                    help="register the device kernel (hybrid-scheduler probe)")
    ap.add_argument("--hang-map", type=int, default=-1, metavar="IDX",
                    help="map IDX stops reporting progress mid-map "
                         "(reaper probe: mapred.task.timeout must fail "
                         "it; the retry completes)")
    ap.add_argument("--hang-attempts", type=int, default=1,
                    help="how many of the hang map's attempts hang "
                         "(default 1: the re-run succeeds)")
    ap.add_argument("--work", default="mem:///tmp/sleep")
    args = ap.parse_args(argv)
    inp = f"{args.work.rstrip('/')}/in.txt"
    fs = get_filesystem(inp)
    fs.write_bytes(inp, b"".join(b"%d\n" % i for i in range(args.maps)))
    conf = JobConf()
    conf.set_job_name("sleep")
    conf.set_input_paths(inp)
    conf.set_output_path(f"{args.work.rstrip('/')}/out")
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", 1)
    conf.set("tpumr.sleep.map.ms", args.map_ms)
    conf.set("tpumr.sleep.reduce.ms", args.reduce_ms)
    if args.hang_map >= 0:
        conf.set("tpumr.sleep.hang.map", args.hang_map)
        conf.set("tpumr.sleep.hang.attempts", args.hang_attempts)
    conf.set_mapper_class(SleepMapper)
    if args.tpu:
        conf.set_map_kernel("sleep")
    conf.set_reducer_class(SleepReducer)
    conf.set_num_reduce_tasks(args.reduces)
    return 0 if run_job(conf).successful else 1
