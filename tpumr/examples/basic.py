"""Core example programs: wordcount, grep, pi, kmeans, matmul.

≈ WordCount.java (69 LoC), Grep.java, PiEstimator.java (353 LoC) in the
reference's ``src/examples``, plus the K-Means / matrix-multiply GPU jobs
the Shirahata work ran through pipes (not in the reference tree —
SURVEY.md §2.1 end note). Every program is TPU-wired by default (a device
kernel + a CPU fallback mapper, so the hybrid scheduler has both backends
to profile) — unlike the reference, where only pipes jobs could use the
accelerator.
"""

from __future__ import annotations

import argparse
import io
import sys

import numpy as np

from tpumr.examples import register
from tpumr.fs import get_filesystem
from tpumr.mapred.api import Reducer
from tpumr.mapred.input_formats import (DenseInputFormat, NLineInputFormat,
                                        TextInputFormat)
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf


class LongSumReducer(Reducer):
    """≈ mapred/lib/LongSumReducer.java."""

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))


class CentroidReducer(Reducer):
    """Averages (partial_sum, count) pairs into the new centroid."""

    def reduce(self, key, values, output, reporter):
        total, n = None, 0
        for s, c in values:
            s = np.asarray(s, dtype=np.float64)
            total = s if total is None else total + s
            n += int(c)
        output.collect(key, (total / max(1, n)).tolist())


def _common(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("-r", "--reduces", type=int, default=1)
    ap.add_argument("--cpu-only", action="store_true",
                    help="drop the device kernel (CPU mapper only)")
    ap.add_argument("-D", dest="defs", action="append", default=[],
                    metavar="k=v")


def _apply(conf: JobConf, args: argparse.Namespace) -> None:
    conf.set_num_reduce_tasks(args.reduces)
    for kv in args.defs:
        k, _, v = kv.partition("=")
        conf.set(k.strip(), v.strip())
    if not args.cpu_only:
        conf.set("tpumr.local.run.on.tpu", True)


def save_npy(fs, path: str, arr: np.ndarray) -> None:
    buf = io.BytesIO()
    np.save(buf, arr)
    fs.write_bytes(path, buf.getvalue())


def load_npy(fs, path: str) -> np.ndarray:
    return np.load(io.BytesIO(fs.read_bytes(path)))


def load_npy_rows(fs, path: str, k: int) -> np.ndarray:
    """First ``k`` rows via a ranged read — the driver must not pull the
    full (possibly 100M-point) array just to seed centroids."""
    from tpumr.mapred.input_formats import read_npy_header
    with fs.open(path) as f:
        shape, dtype, data_start = read_npy_header(f)
        n_rows = min(k, shape[0])
        row_bytes = int(np.prod(shape[1:], dtype=np.int64)) * dtype.itemsize
        f.seek(data_start)
        raw = f.read(n_rows * row_bytes)
    return np.frombuffer(raw, dtype=dtype).reshape((n_rows,) + shape[1:])


@register("wordcount", "count words in the input files")
def wordcount(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples wordcount")
    ap.add_argument("input")
    ap.add_argument("output")
    _common(ap)
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("wordcount")
    conf.set_input_paths(*args.input.split(","))
    conf.set_output_path(args.output)
    from tpumr.ops.wordcount import WordCountCpuMapper
    if args.cpu_only:
        conf.set_input_format(TextInputFormat)
        conf.set_mapper_class(WordCountCpuMapper)
    else:
        # whitespace tokenization doesn't need per-line records — the
        # raw-buffer format skips the line machinery entirely
        from tpumr.mapred.input_formats import RawTextInputFormat
        conf.set_input_format(RawTextInputFormat)
        conf.set_map_kernel("wordcount")
    conf.set_reducer_class(LongSumReducer)
    conf.set_combiner_class(LongSumReducer)
    _apply(conf, args)
    return 0 if run_job(conf).successful else 1


@register("grep", "count matches of a regex in the input files")
def grep(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples grep")
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("regex")
    ap.add_argument("group", nargs="?", type=int, default=0)
    _common(ap)
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("grep")
    conf.set_input_paths(*args.input.split(","))
    conf.set_output_path(args.output)
    conf.set_input_format(TextInputFormat)
    conf.set("tpumr.grep.pattern", args.regex)
    conf.set("tpumr.grep.group", args.group)
    from tpumr.ops.grep import GrepCpuMapper
    if args.cpu_only:
        conf.set_mapper_class(GrepCpuMapper)
    else:
        conf.set_map_kernel("grep")
    conf.set_reducer_class(LongSumReducer)
    conf.set_combiner_class(LongSumReducer)
    _apply(conf, args)
    return 0 if run_job(conf).successful else 1


@register("pi", "estimate pi by Monte-Carlo sampling on device")
def pi(argv: list[str]) -> int:
    """≈ PiEstimator.java: one map per sample block; here each map's whole
    block is drawn and reduced on device (pi-sampler kernel)."""
    ap = argparse.ArgumentParser(prog="tpumr examples pi")
    ap.add_argument("n_maps", type=int)
    ap.add_argument("n_samples", type=int, help="samples per map")
    ap.add_argument("--work", default="mem:///tmp/pi",
                    help="scratch URI for job input/output")
    _common(ap)
    args = ap.parse_args(argv)
    fs = get_filesystem(args.work)
    inp = f"{args.work.rstrip('/')}/in.txt"
    out = f"{args.work.rstrip('/')}/out"
    lines = "".join(f"{1000 + i} {args.n_samples}\n"
                    for i in range(args.n_maps))
    fs.write_bytes(inp, lines.encode())
    conf = JobConf()
    conf.set_job_name("pi")
    conf.set_input_paths(inp)
    conf.set_output_path(out)
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", 1)
    from tpumr.ops.pi import PiCpuMapper
    if args.cpu_only:
        conf.set_mapper_class(PiCpuMapper)
    else:
        conf.set_map_kernel("pi-sampler")
    conf.set_reducer_class(LongSumReducer)
    _apply(conf, args)
    result = run_job(conf)
    if not result.successful:
        return 1
    counts = dict(_read_pairs(fs, out))
    inside, total = int(counts["inside"]), int(counts["total"])
    est = 4.0 * inside / max(1, total)
    print(f"Estimated value of Pi is {est}")
    return 0


def _read_pairs(fs, out_dir: str):
    for st in fs.list_files(out_dir):
        if st.path.name.startswith("part"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, _, v = line.partition("\t")
                yield k, v


@register("kmeans", "iterative K-Means clustering (the north-star job)")
def kmeans(argv: list[str]) -> int:
    """Iterative driver: each round is one MapReduce job (assign on device,
    centroid average in reduce), rewriting the centroid file — the workload
    of the Shirahata hybrid-scheduling evaluation."""
    ap = argparse.ArgumentParser(prog="tpumr examples kmeans")
    ap.add_argument("points", help=".npy of shape (n, d)")
    ap.add_argument("output", help="output directory URI")
    ap.add_argument("-k", type=int, default=8)
    ap.add_argument("-i", "--iterations", type=int, default=5)
    ap.add_argument("--split-rows", type=int, default=1 << 17)
    _common(ap)
    args = ap.parse_args(argv)
    from tpumr.ops.kmeans import clear_centroid_cache
    fs = get_filesystem(args.output)
    out = args.output.rstrip("/")
    cent_path = f"{out}/centroids.npy"
    seeds = load_npy_rows(get_filesystem(args.points), args.points, args.k)
    save_npy(fs, cent_path, seeds.astype(np.float32))
    centroids = None
    for it in range(args.iterations):
        clear_centroid_cache()
        conf = JobConf()
        conf.set_job_name(f"kmeans-iter-{it}")
        conf.set_input_paths(args.points)
        conf.set_output_path(f"{out}/iter{it}")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", args.split_rows)
        conf.set("tpumr.kmeans.centroids", cent_path)
        from tpumr.ops.kmeans import KMeansCpuMapper
        # the kernel is set in BOTH modes: CPU slots run its vectorized
        # map_batch_cpu (CpuBatchMapRunner); --cpu-only just withholds the
        # device. The per-record mapper stays as the opt-out fallback
        # (-D tpumr.cpu.batch.map=false).
        conf.set_map_kernel("kmeans-assign")
        conf.set_mapper_class(KMeansCpuMapper)
        conf.set_reducer_class(CentroidReducer)
        _apply(conf, args)
        if not run_job(conf).successful:
            return 1
        import ast
        centroids = load_npy(fs, cent_path).copy()
        for key, val in _read_pairs(fs, f"{out}/iter{it}"):
            centroids[int(key)] = np.asarray(ast.literal_eval(val),
                                             dtype=np.float32)
        save_npy(fs, cent_path, centroids)
    print(f"Final centroids written to {cent_path}")
    if centroids is not None:
        np.savetxt(sys.stdout, centroids, fmt="%.4f")
    return 0


@register("matmul", "blocked dense matrix multiply A @ B")
def matmul(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples matmul")
    ap.add_argument("a", help=".npy for A (n, k)")
    ap.add_argument("b", help=".npy for B (k, m)")
    ap.add_argument("output")
    ap.add_argument("--split-rows", type=int, default=1 << 14)
    _common(ap)
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("matmul")
    conf.set_input_paths(args.a)
    conf.set_output_path(args.output)
    conf.set_input_format(DenseInputFormat)
    conf.set("tpumr.dense.split.rows", args.split_rows)
    conf.set("tpumr.matmul.b", args.b)
    from tpumr.ops.matmul import MatmulCpuMapper
    if args.cpu_only:
        conf.set_mapper_class(MatmulCpuMapper)
    else:
        conf.set_map_kernel("matmul-block")
    conf.set_num_reduce_tasks(0)
    _apply(conf, args)
    return 0 if run_job(conf).successful else 1
