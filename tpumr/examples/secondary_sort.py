"""SecondarySort — value ordering inside a reduce group.

≈ ``src/examples/org/apache/hadoop/examples/SecondarySort.java``: the map
key is the composite ``(first, second)``; partitioning and reduce grouping
use only ``first`` (FirstPartitioner + FirstGroupingComparator), while the
sort comparator orders the full pair — so each reduce group sees its
values with ``second`` ascending.
"""

from __future__ import annotations

import argparse
import zlib

from tpumr.examples import register
from tpumr.io.writable import serialize
from tpumr.mapred.api import Mapper, Partitioner, Reducer
from tpumr.mapred.input_formats import TextInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf


class FirstPartitioner(Partitioner):
    """≈ SecondarySort.FirstPartitioner: hash only the natural key."""

    def get_partition(self, key, value, num_partitions):
        return zlib.crc32(serialize(key[0])) % num_partitions


class FirstGroupingComparator:
    """Groups composite keys by their first element (the grouping-comparator
    seam, JobConf.set_output_value_grouping_comparator)."""

    def sort_key(self, kbytes: bytes):
        from tpumr.io.writable import deserialize
        return deserialize(kbytes)[0]


class PairMapper(Mapper):
    """Line "<first> <second>" → key (first, second), value second."""

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        parts = s.split()
        if len(parts) >= 2:
            first, second = int(parts[0]), int(parts[1])
            output.collect((first, second), second)


class SortedValuesReducer(Reducer):
    """Emits (first, [seconds in ascending order]) — the secondary-sort
    guarantee made visible in the output."""

    def reduce(self, key, values, output, reporter):
        output.collect(key[0], list(values))


@register("secondarysort", "sort values within reduce groups")
def secondarysort(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples secondarysort")
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("-r", "--reduces", type=int, default=1)
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("secondarysort")
    conf.set_input_paths(*args.input.split(","))
    conf.set_output_path(args.output)
    conf.set_input_format(TextInputFormat)
    conf.set_mapper_class(PairMapper)
    conf.set_reducer_class(SortedValuesReducer)
    conf.set_partitioner_class(FirstPartitioner)
    conf.set_output_value_grouping_comparator(FirstGroupingComparator)
    conf.set_num_reduce_tasks(args.reduces)
    return 0 if run_job(conf).successful else 1
