"""TeraGen / TeraSort / TeraValidate.

≈ the reference's ``src/examples/org/apache/hadoop/examples/terasort/``
(TeraGen.java, TeraSort.java, TeraValidate.java): 100-byte records — a
10-byte key plus a 90-byte payload — generated deterministically, globally
sorted via sampled range partitioning (the reference's TeraSort samples in
TeraInputFormat and range-partitions with a trie; here the shared
TotalOrderPartitioner does the bisect), then validated for global order.

Records live in SequenceFiles (the framework's splittable container)
rather than the reference's fixed-width text lines; keys are raw ``bytes``
so byte-lexicographic order — the RawComparator fast path, fixed-width
keys being the device-sortable case called out in SURVEY.md §7 — is the
sort order.
"""

from __future__ import annotations

import argparse

import numpy as np

from tpumr.examples import register
from tpumr.fs import get_filesystem
from tpumr.mapred.api import (IdentityReducer, Mapper, RawComparator,
                              Reducer)
from tpumr.mapred.input_formats import (NLineInputFormat,
                                        SequenceFileInputFormat)
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.output_formats import SequenceFileOutputFormat
from tpumr.mapred.total_order import (TotalOrderPartitioner, sample_input,
                                      write_partition_file)

KEY_LEN = 10
VALUE_LEN = 90
_PRINTABLE_LO, _PRINTABLE_HI = 0x20, 0x7E  # ' '..'~' ≈ TeraGen key alphabet


def gen_records(row_start: int, n: int) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized record block: (n, 10) key bytes + (n, 90) value bytes,
    deterministic in the absolute row number (≈ TeraGen's seeded
    RandomGenerator — one whole block per call, no per-record loop)."""
    rng = np.random.default_rng(0xC0FFEE ^ row_start)
    keys = rng.integers(_PRINTABLE_LO, _PRINTABLE_HI + 1,
                        size=(n, KEY_LEN), dtype=np.uint8)
    values = np.full((n, VALUE_LEN), ord("."), dtype=np.uint8)
    # row-id prefix "rrrrrrrrrr" ≈ TeraGen's row field, all rows at once
    if row_start + n > 10 ** 10:
        raise ValueError("row id exceeds the 10-digit row field")
    rows = row_start + np.arange(n, dtype=np.int64)
    divs = 10 ** np.arange(9, -1, -1, dtype=np.int64)
    values[:, :10] = (rows[:, None] // divs % 10 + ord("0")).astype(np.uint8)
    return keys, values


class TeraGenMapper(Mapper):
    """Input record: ``"<row_start> <num_rows>"``; emits the block —
    in bulk when the collector supports fixed-width rows (map-only jobs
    writing SequenceFiles do: Writer.append_fixed_rows)."""

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        row_start, n = (int(x) for x in s.split())
        keys, values = gen_records(row_start, n)
        bulk = getattr(output, "collect_fixed_rows", None)
        if bulk is not None:
            bulk(np.concatenate([keys, values], axis=1), KEY_LEN)
            return
        for i in range(n):
            output.collect(keys[i].tobytes(), values[i].tobytes())


class TeraSortMapper(Mapper):
    """Identity — the sort happens in the framework's sort/merge path."""

    identity_map = True  # lets device-shuffle maps move records in bulk

    def map(self, key, value, output, reporter):
        output.collect(key, value)


class TeraValidateMapper(Mapper):
    """Per-split order check; emits (split-ordinal, (first, last, errors))
    at close so the single reducer can check cross-part boundaries.
    The part index rides on the key so reduce order == file order."""

    def configure(self, conf) -> None:
        self._first: bytes | None = None
        self._last: bytes | None = None
        self._errors = 0
        self._out = None
        self._ordinal = max(0, conf.get_int("tpumr.task.partition", -1))

    def map(self, key, value, output, reporter):
        self._out = output
        if self._first is None:
            self._first = key
        elif key < self._last:
            self._errors += 1
        self._last = key

    def map_record_batch(self, batch, output, reporter) -> None:
        """Host-vectorized split check (map_task._host_batch_fast_path):
        consecutive-key comparison over the whole split at numpy speed —
        exact Python-bytes ordering (full-width compare on zero-padded
        keys, true length as the tiebreak on equal content)."""
        n = batch.num_records
        if n == 0:
            return
        self._out = output
        klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
        self._first = batch.key(0)
        self._last = batch.key(n - 1)
        if n > 1:
            width = int(klens.max())
            if width == 0:          # all keys empty: equal content, no
                self._errors = 0    # inversions possible
                return
            keys, _ = batch.padded_keys(width)
            a = keys[:-1].astype(np.int16)
            b = keys[1:].astype(np.int16)
            diff = b - a
            nz = diff != 0
            has = nz.any(axis=1)
            first_col = nz.argmax(axis=1)
            at_first = diff[np.arange(n - 1), first_col]
            inverted = (has & (at_first < 0)) | \
                (~has & (klens[1:] < klens[:-1]))
            self._errors = int(inverted.sum())

    def close(self) -> None:
        if self._out is not None and self._first is not None:
            self._out.collect(self._ordinal,
                              (self._first, self._last, self._errors))


class TeraValidateReducer(Reducer):
    """One group per split, keys ascending = file order; checks boundaries."""

    def __init__(self) -> None:
        self._prev_last: bytes | None = None
        self._bad = 0

    def reduce(self, key, values, output, reporter):
        for first, last, errors in values:
            if errors:
                self._bad += errors
                output.collect("misordered-in-part", errors)
            if self._prev_last is not None and first < self._prev_last:
                self._bad += 1
                output.collect("misordered-across-parts", 1)
            self._prev_last = last

    def close(self) -> None:
        pass


@register("teragen", "generate 100-byte terasort records")
def teragen(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples teragen")
    ap.add_argument("num_rows", type=int)
    ap.add_argument("output")
    ap.add_argument("-m", "--maps", type=int, default=2)
    args = ap.parse_args(argv)
    out = args.output.rstrip("/")
    fs = get_filesystem(out)
    inp = f"{out}.teragen-in/rows.txt"
    per = args.num_rows // args.maps
    lines, start = [], 0
    for m in range(args.maps):
        n = per + (args.num_rows - per * args.maps if m == args.maps - 1
                   else 0)
        lines.append(f"{start} {n}\n")
        start += n
    get_filesystem(inp).write_bytes(inp, "".join(lines).encode())
    conf = JobConf()
    conf.set_job_name("teragen")
    conf.set_input_paths(inp)
    conf.set_output_path(out)
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", 1)
    conf.set_mapper_class(TeraGenMapper)
    conf.set_output_format(SequenceFileOutputFormat)
    conf.set_num_reduce_tasks(0)
    ok = run_job(conf).successful
    fs.delete(f"{out}.teragen-in", recursive=True)
    return 0 if ok else 1


@register("terasort", "globally sort terasort records")
def terasort(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples terasort")
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("-r", "--reduces", type=int, default=2)
    ap.add_argument("--device-shuffle", action="store_true",
                    help="shuffle+sort on the device mesh (ICI all_to_all "
                         "+ per-device sort) instead of the host path")
    args = ap.parse_args(argv)
    conf = make_terasort_conf(args.input, args.output, args.reduces,
                              device_shuffle=args.device_shuffle)
    return 0 if run_job(conf).successful else 1


def make_terasort_conf(input_path: str, output_path: str, reduces: int,
                       device_shuffle: bool = False) -> JobConf:
    """Terasort job conf (shared with benchmarks/tests): sampled range
    partitioning; optionally the device-shuffled reduce — terasort's
    fixed-width 10+90 records are the canonical device-sortable layout."""
    conf = JobConf()
    conf.set_job_name("terasort")
    conf.set_input_paths(input_path)
    conf.set_output_path(output_path)
    conf.set_input_format(SequenceFileInputFormat)
    conf.set_mapper_class(TeraSortMapper)
    conf.set_reducer_class(IdentityReducer)
    conf.set_output_format(SequenceFileOutputFormat)
    conf.set_output_key_comparator_class(RawComparator)
    conf.set_num_reduce_tasks(reduces)
    samples = sample_input(conf, num_samples=1000)
    write_partition_file(conf, output_path.rstrip("/") + ".partitions",
                         samples, reduces)
    conf.set_partitioner_class(TotalOrderPartitioner)
    if device_shuffle:
        conf.set_device_shuffle(KEY_LEN, VALUE_LEN)
    return conf


def pipeline_sort_hook(conf: dict, upstreams: dict) -> None:
    """``conf_hook`` for a PIPELINE sort stage (teragen → sort →
    validate as one graph): the range-partition sampling that
    ``terasort()`` runs client-side between jobs needs the teragen
    output to EXIST, so in a pipeline it runs master-side, right before
    the sort stage submits — its input dir is already wired to the
    upstream's committed output."""
    jc = JobConf()
    for k, v in conf.items():
        jc.set(k, v)
    reduces = int(conf.get("mapred.reduce.tasks", 1) or 1)
    samples = sample_input(jc, num_samples=1000)
    part_path = str(conf["mapred.output.dir"]).rstrip("/") \
        + ".partitions"
    write_partition_file(jc, part_path, samples, reduces)
    for k, v in jc:
        conf[k] = v   # PARTITION_PATH_KEY and friends
    conf["mapred.partitioner.class"] = \
        "tpumr.mapred.total_order.TotalOrderPartitioner"


@register("teravalidate", "validate that terasort output is globally sorted")
def teravalidate(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples teravalidate")
    ap.add_argument("input", help="terasort output directory")
    ap.add_argument("output")
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("teravalidate")
    parts = sorted(
        str(st.path) for st in get_filesystem(args.input)
        .list_files(args.input) if st.path.name.startswith("part"))
    conf.set_input_paths(*parts)
    conf.set_output_path(args.output)
    conf.set_input_format(SequenceFileInputFormat)
    conf.set("mapred.min.split.size", 1 << 60)  # one split per part file
    conf.set_mapper_class(TeraValidateMapper)
    conf.set_reducer_class(TeraValidateReducer)
    conf.set_num_reduce_tasks(1)
    if not run_job(conf).successful:
        return 1
    fs = get_filesystem(args.output)
    bad = [line for st in fs.list_files(args.output)
           if st.path.name.startswith("part")
           for line in fs.read_bytes(st.path).decode().splitlines()]
    if bad:
        print("VALIDATION FAILED:")
        for b in bad:
            print(" ", b)
        return 1
    print("Output is globally sorted.")
    return 0
