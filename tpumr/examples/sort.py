"""Sort — the generic sort benchmark job.

≈ ``src/examples/org/apache/hadoop/examples/Sort.java``: identity map +
identity reduce over SequenceFile records; with ``--total-order`` the
sampled range partitioner makes the output globally sorted (the reference
wires lib/InputSampler + TotalOrderPartitioner the same way).
"""

from __future__ import annotations

import argparse

from tpumr.examples import register
from tpumr.mapred.api import IdentityMapper, IdentityReducer
from tpumr.mapred.input_formats import SequenceFileInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.output_formats import SequenceFileOutputFormat
from tpumr.mapred.total_order import (TotalOrderPartitioner, sample_input,
                                      write_partition_file)


@register("sort", "sort SequenceFile records (identity map/reduce)")
def sort(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr examples sort")
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("-r", "--reduces", type=int, default=2)
    ap.add_argument("--total-order", action="store_true",
                    help="globally sort via sampled range partitioning")
    args = ap.parse_args(argv)
    conf = JobConf()
    conf.set_job_name("sorter")
    conf.set_input_paths(*args.input.split(","))
    conf.set_output_path(args.output)
    conf.set_input_format(SequenceFileInputFormat)
    conf.set_output_format(SequenceFileOutputFormat)
    conf.set_mapper_class(IdentityMapper)
    conf.set_reducer_class(IdentityReducer)
    conf.set_num_reduce_tasks(args.reduces)
    if args.total_order:
        samples = sample_input(conf, num_samples=1000)
        write_partition_file(conf, args.output.rstrip("/") + ".partitions",
                             samples, args.reduces)
        conf.set_partitioner_class(TotalOrderPartitioner)
    return 0 if run_job(conf).successful else 1
