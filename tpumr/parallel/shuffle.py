"""Device shuffle — MapReduce's all-to-all on ICI.

The reference's shuffle is R parallel HTTP fetch streams per reduce
(ReduceTask.java:659 MapOutputCopier ↔ TaskTracker.java:4050
MapOutputServlet) with a RAM budget (ShuffleRamManager, :1080). On a mesh,
the same repartition-by-key is ONE collective: every device buckets its
records by destination, pads buckets to a static capacity (XLA needs static
shapes — SURVEY.md §7 'Shuffle on TPU' hard part), and a single
``lax.all_to_all`` exchanges them over ICI. Records that exceed a bucket's
capacity are counted, not silently dropped — the caller retries with a
bigger capacity or falls back to the host shuffle path (the reference's
disk-spill fallback role).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


@dataclass
class ShuffleResult:
    """Per-device view after the exchange (leading dim = this device's
    received slots)."""
    values: Any          # [n_dev * capacity, ...] received records
    valid: Any           # [n_dev * capacity] bool mask
    overflow: Any        # int — TOTAL records dropped across all senders
    keys: Any = None     # [n_dev * capacity] routing keys if requested


def _bucket_local(values, dest, n_dev: int, capacity: int, keys=None):
    """Scatter local records into a [n_dev, capacity, ...] send buffer."""
    n = dest.shape[0]
    order = jnp.argsort(dest, stable=True)
    sdest = dest[order]
    svals = values[order]
    # index of each record within its destination bucket: position minus the
    # index of the bucket's first record (searchsorted on the sorted dests)
    first = jnp.searchsorted(sdest, sdest, side="left")
    slot = jnp.arange(n) - first
    # a record is droppable (counted in overflow) if its bucket is full OR
    # its destination is out of range — jitted scatters silently drop/wrap
    # out-of-bounds indices, which would violate the "counted, not silently
    # dropped" contract
    dest_ok = (sdest >= 0) & (sdest < n_dev)
    ok = (slot < capacity) & dest_ok
    overflow = jnp.sum(~ok).astype(jnp.int32)
    # overflow records scatter into a sacrificial extra slot (capacity) that
    # is sliced off — clipping them into slot capacity-1 would overwrite the
    # legitimate record there; invalid dests are rerouted to bucket 0's
    # sacrificial slot
    sdest = jnp.where(dest_ok, sdest, 0)
    slot_c = jnp.where(ok, jnp.minimum(slot, capacity), capacity)
    send = jnp.zeros((n_dev, capacity + 1) + values.shape[1:], values.dtype)
    send = send.at[sdest, slot_c].set(svals)[:, :capacity]
    mask = jnp.zeros((n_dev, capacity + 1), jnp.bool_).at[sdest, slot_c] \
        .set(ok)[:, :capacity]
    out = [send, mask, overflow]
    if keys is not None:
        skeys = keys[order]
        kbuf = jnp.zeros((n_dev, capacity + 1), keys.dtype).at[sdest, slot_c] \
            .set(skeys)[:, :capacity]
        out.append(kbuf)
    return out


import functools


@functools.lru_cache(maxsize=64)
def make_shuffle(mesh: Mesh, capacity: int, axis_name: str = "data",
                 with_keys: bool = False):
    """Build the jitted SPMD shuffle. Inputs per device shard:
    ``values [n_local, ...]``, ``dest [n_local] int32`` (destination device),
    optionally ``keys [n_local]`` routing keys carried alongside."""
    n_dev = mesh.shape[axis_name]

    in_specs = (P(axis_name), P(axis_name)) + ((P(axis_name),) if with_keys else ())
    out_specs = (P(axis_name), P(axis_name), P(axis_name)) + \
        ((P(axis_name),) if with_keys else ())

    @partial(jax.shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    def _shuffle(values, dest, *maybe_keys):
        keys = maybe_keys[0] if maybe_keys else None
        parts = _bucket_local(values, dest, n_dev, capacity, keys)
        send, mask, overflow = parts[0], parts[1], parts[2]
        recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
        rmask = lax.all_to_all(mask, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)
        flat_vals = recv.reshape((n_dev * capacity,) + recv.shape[2:])
        flat_mask = rmask.reshape(n_dev * capacity)
        outs = [flat_vals, flat_mask, overflow.reshape(1)]
        if keys is not None:
            kbuf = parts[3]
            rkeys = lax.all_to_all(kbuf, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
            outs.append(rkeys.reshape(n_dev * capacity))
        return tuple(outs)

    return jax.jit(_shuffle)


def shuffle_dense(mesh: Mesh, values, dest, capacity: int | None = None,
                  axis_name: str = "data", keys=None) -> ShuffleResult:
    """One-call shuffle of globally-sharded arrays. ``values``/``dest`` are
    sharded over ``axis_name`` (n divisible by mesh size). ``capacity`` is
    per-(src,dst) bucket slots; default 2× the balanced load."""
    n_dev = mesh.shape[axis_name]
    n = values.shape[0]
    if n % n_dev:
        raise ValueError(f"global length {n} not divisible by mesh size {n_dev}")
    local_n = n // n_dev
    if capacity is None:
        capacity = max(1, int(2 * local_n / n_dev))
    fn = make_shuffle(mesh, capacity, axis_name, with_keys=keys is not None)
    args = (values, dest) + ((keys,) if keys is not None else ())
    out = fn(*args)
    res = ShuffleResult(values=out[0], valid=out[1],
                        overflow=np.asarray(out[2]).sum())
    if keys is not None:
        res.keys = out[3]
    return res
