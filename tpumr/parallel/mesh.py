"""Mesh construction + sharding helpers.

The TPU analog of the reference's cluster topology (NetworkTopology rack
awareness, src/core/org/apache/hadoop/net/): where Hadoop places tasks near
HDFS blocks, the device layer places array shards over a
``jax.sharding.Mesh`` and lets XLA insert collectives over ICI/DCN.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def local_device_count() -> int:
    return len(jax.local_devices())


def make_mesh(n_devices: int | None = None,
              axis_names: Sequence[str] = ("data",),
              shape: Sequence[int] | None = None,
              devices: list | None = None) -> Mesh:
    """Build a mesh over the first ``n_devices`` devices (default: all).
    ``shape`` reshapes devices over multiple named axes, e.g.
    shape=(4, 2), axis_names=('data', 'model')."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),)
    total = int(np.prod(shape))
    if total > len(devs):
        raise ValueError(f"mesh shape {shape} needs {total} devices, "
                         f"have {len(devs)}")
    arr = np.array(devs[:total], dtype=object).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def shard_over(mesh: Mesh, array, axis_name: str = "data", dim: int = 0):
    """Place an array sharded along ``dim`` over mesh axis ``axis_name``
    (≈ distributing input splits across trackers). Pads are the caller's
    job — the leading dim must divide evenly."""
    spec = [None] * np.ndim(array)
    spec[dim] = axis_name
    return jax.device_put(array, NamedSharding(mesh, P(*spec)))


def replicate(mesh: Mesh, array):
    """Replicate across the mesh (≈ DistributedCache side files: centroids,
    the B matrix, broadcast job conf)."""
    return jax.device_put(array, NamedSharding(mesh, P()))


def pad_to_multiple(array: np.ndarray, multiple: int, axis: int = 0,
                    fill=0) -> tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple; returns (padded, original_length)."""
    n = array.shape[axis]
    target = -(-n // multiple) * multiple
    if target == n:
        return array, n
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, target - n)
    return np.pad(array, widths, constant_values=fill), n
