"""Multi-host (DCN) bring-up for the device data plane.

The reference scales across hosts with its TCP control plane plus
HTTP shuffle (SURVEY.md §5 'distributed communication backend'); the TPU
analog keeps the host RPC control plane (tpumr.ipc) and moves the data
plane onto XLA collectives, which ride ICI within a slice and DCN across
slices once every participating process has joined one
``jax.distributed`` job. This module is that bring-up: resolve the
coordinator + process identity from job conf (or the TPU pod
environment), initialize exactly once, and hand back the GLOBAL mesh
that makes ``tpumr.parallel`` collectives span hosts.

Conf keys (env fallbacks in parentheses — the standard JAX ones):

- ``tpumr.distributed.coordinator``   host:port of process 0
  (JAX_COORDINATOR_ADDRESS)
- ``tpumr.distributed.num.processes`` world size (JAX_NUM_PROCESSES)
- ``tpumr.distributed.process.id``    this process's rank (JAX_PROCESS_ID)

On a Cloud TPU pod slice all three resolve automatically from the TPU
metadata and ``initialize()`` may be called with no configuration at
all — ``ensure_initialized`` passes through whatever is known.

Single-host (or unset) configurations are a no-op: ``global_mesh`` then
equals the local mesh, so every caller can use this module
unconditionally.
"""

from __future__ import annotations

import threading
from typing import Any

_lock = threading.Lock()
_initialized = False


def distributed_spec(conf: Any = None) -> "dict | None":
    """The (coordinator, num_processes, process_id) triple from conf/env,
    or None when nothing multi-host is configured."""
    import os

    def get(key: str, env: str) -> "str | None":
        v = conf.get(key) if conf is not None else None
        return str(v) if v not in (None, "") else os.environ.get(env)

    coord = get("tpumr.distributed.coordinator", "JAX_COORDINATOR_ADDRESS")
    nproc = get("tpumr.distributed.num.processes", "JAX_NUM_PROCESSES")
    pid = get("tpumr.distributed.process.id", "JAX_PROCESS_ID")
    if coord is None and nproc is None and pid is None:
        return None
    spec: dict = {}
    if coord is not None:
        spec["coordinator_address"] = coord
    if nproc is not None:
        spec["num_processes"] = int(nproc)
    if pid is not None:
        spec["process_id"] = int(pid)
    return spec


def ensure_initialized(conf: Any = None) -> bool:
    """Join the jax.distributed job exactly once per process. Returns
    True when running multi-host (after a successful join), False for
    the single-host no-op. Idempotent and thread-safe; raising callers
    see the real jax.distributed error (mis-set ranks must fail loudly,
    not degrade to a wrong-sized mesh)."""
    global _initialized
    with _lock:
        if _initialized:
            return True
        spec = distributed_spec(conf)
        if spec is None:
            return False
        import jax
        jax.distributed.initialize(**spec)
        _initialized = True
        return True


def global_mesh(conf: Any = None, axis_names=("data",), shape=None):
    """The mesh over EVERY chip of the (possibly multi-host) job: the
    object that makes ``tpumr.parallel`` collectives (psum, all_to_all,
    ring permute) span DCN. Falls back to the local mesh on single-host
    setups, so callers need no branches."""
    import jax

    from tpumr.parallel.mesh import make_mesh
    ensure_initialized(conf)
    return make_mesh(axis_names=axis_names, shape=shape,
                     devices=list(jax.devices()))


def process_info() -> "tuple[int, int]":
    """(process_index, process_count) of this host in the job."""
    import jax
    return jax.process_index(), jax.process_count()
