"""Record-axis (sequence) parallel map + ring primitives.

The reference has no sequence dimension (SURVEY.md §5 'Long-context': its
scaling knobs are split size and NLineInputFormat). The TPU framework's
equivalent axis — documented as new design, not a port — is sharding one
huge InputSplit across chips along the record axis and running the map
kernel under shard_map, with ring (ppermute) transfers for anything that
needs neighbor context: the same mechanics ring attention uses for long
sequences, applied to record streams.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def sequence_parallel_map(mesh: Mesh, fn: Callable[[Any], Any],
                          axis_name: str = "data") -> Callable:
    """Jitted SPMD map: each chip applies ``fn`` to its record shard, output
    stays sharded (embarrassingly parallel — zero communication). The
    device-native form of 'one InputSplit per tracker slot'."""

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axis_name),
             out_specs=P(axis_name))
    def step(shard):
        return fn(shard)

    return jax.jit(step)


def ring_pass(mesh: Mesh, axis_name: str = "data") -> Callable:
    """Jitted one-hop ring rotation of shards (chip i's shard moves to chip
    i+1). Building block for ring-structured scans over the record axis."""

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axis_name),
             out_specs=P(axis_name))
    def step(shard):
        n = lax.axis_size(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lax.ppermute(shard, axis_name, perm)

    return jax.jit(step)


def ring_scan_map(mesh: Mesh,
                  fn: Callable[[Any, Any, Any], Any],
                  axis_name: str = "data") -> Callable:
    """Ring-structured full pass: every chip sees every shard once, combining
    with ``fn(state, visiting_shard, hop_index)``. After n_dev hops each chip
    has folded the ENTIRE record axis into its state while only ever holding
    one remote shard — the constant-memory access pattern of ring attention
    (SNIPPETS/PAPERS: ring collective pattern), here for record streams
    (global top-k, streaming joins, windowed aggregation).
    """

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(axis_name), P(axis_name)), out_specs=P(axis_name))
    def step(init_state, my_shard):
        n = lax.axis_size(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(carry, hop):
            state, visiting = carry
            state = fn(state, visiting, hop)
            visiting = lax.ppermute(visiting, axis_name, perm)
            return (state, visiting), None

        (state, _), _ = lax.scan(body, (init_state, my_shard),
                                 jnp.arange(n))
        return state

    return jax.jit(step)
