"""Device global sort: range-partition → ICI all-to-all → per-device sort.

This is the device data plane of the framework's *device-shuffled reduce*
(`tpumr.mapred.device_shuffle`): the role the reference implements as R
parallel HTTP fetch streams + k-way disk merges (ReduceTask.java:659
ReduceCopier ↔ TaskTracker.java:4050 MapOutputServlet, merge :399-409)
becomes three XLA programs over a mesh:

1. ``compute_dest`` — every record's destination range from sampled key
   splitters (≈ TotalOrderPartitioner's bisect, vectorized on device);
2. ``shuffle_dense`` (tpumr.parallel.shuffle) — ONE ``lax.all_to_all``
   moves every record to the device that owns its range;
3. ``sort_local_shards`` — each device lexsorts what it received.

Keys are fixed-width byte strings (the device-sortable case called out in
SURVEY.md §7: terasort's 10-byte keys); they are packed into big-endian
uint32 columns so lexicographic byte order == multi-column numeric order,
avoiding any dependence on 64-bit ints (jax_enable_x64 stays off).
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def num_key_columns(klen: int) -> int:
    return -(-klen // 4)


def key_columns(records, klen: int):
    """[n, >=klen] uint8 → [n, ceil(klen/4)] uint32, big-endian packed.
    Trailing bytes of the last column are zero-padded (a constant suffix
    shared by every record, so order is preserved). Works under jit and on
    host numpy alike."""
    xp = jnp if isinstance(records, jax.Array) else np
    ncols = num_key_columns(klen)
    n = records.shape[0]
    padded = xp.zeros((n, ncols * 4), dtype=xp.uint8)
    if isinstance(records, jax.Array):
        padded = padded.at[:, :klen].set(records[:, :klen])
    else:
        padded[:, :klen] = records[:, :klen]
    b = padded.reshape(n, ncols, 4).astype(xp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def _lex_gt(key_cols, splitter_cols):
    """[n, c] > [c] lexicographically → [n] bool (key strictly greater)."""
    ncols = key_cols.shape[1]
    xp = jnp if isinstance(key_cols, jax.Array) else np
    gt = xp.zeros(key_cols.shape[0], dtype=bool)
    eq = xp.ones(key_cols.shape[0], dtype=bool)
    for c in range(ncols):
        gt = gt | (eq & (key_cols[:, c] > splitter_cols[c]))
        eq = eq & (key_cols[:, c] == splitter_cols[c])
    return gt


def compute_dest(key_cols, splitter_cols):
    """Destination range per record: ``sum_j (key > splitter_j)`` — matches
    the host TotalOrderPartitioner convention (keys equal to a cut stay in
    the lower range). ``splitter_cols`` is [r-1, c]; loop is unrolled (r is
    the reduce count, small) so memory stays O(n)."""
    xp = jnp if isinstance(key_cols, jax.Array) else np
    dest = xp.zeros(key_cols.shape[0], dtype=xp.int32)
    for j in range(splitter_cols.shape[0]):
        dest = dest + _lex_gt(key_cols, splitter_cols[j]).astype(xp.int32)
    return dest


@functools.lru_cache(maxsize=32)
def _make_dest_fn(mesh: Mesh, klen: int, splitters_key: bytes,
                  ranges_per_dev: int, axis_name: str):
    splitters = np.frombuffer(splitters_key, dtype=np.uint8).reshape(-1, klen)
    splitter_cols = key_columns(splitters, klen) if len(splitters) else \
        np.zeros((0, num_key_columns(klen)), np.uint32)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axis_name),
             out_specs=P(axis_name))
    def _dest(records):
        cols = key_columns(records, klen)
        rng = compute_dest(cols, jnp.asarray(splitter_cols))
        return rng // ranges_per_dev

    return jax.jit(_dest)


def make_dest_fn(mesh: Mesh, klen: int, splitters: np.ndarray,
                 ranges_per_dev: int, axis_name: str = "data"):
    """Jitted SPMD map records→destination *device* (range // ranges_per_dev).
    ``splitters`` is [r-1, klen] uint8 (may be empty for r == 1)."""
    return _make_dest_fn(mesh, klen, splitters.astype(np.uint8).tobytes(),
                         ranges_per_dev, axis_name)


@functools.lru_cache(maxsize=32)
def _make_sort_fn(mesh: Mesh, klen: int, axis_name: str):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axis_name), P(axis_name)),
             out_specs=(P(axis_name), P(axis_name)))
    def _sort(records, valid):
        cols = key_columns(records, klen)
        # lexsort: LAST key is primary → (least-significant col … col0,
        # invalid-last) so each device's shard comes back valid-records-
        # first in ascending key order
        keys = tuple(cols[:, c] for c in range(cols.shape[1] - 1, -1, -1))
        order = jnp.lexsort(keys + (~valid,))
        return jnp.take(records, order, axis=0), jnp.take(valid, order)

    return jax.jit(_sort)


def make_sort_fn(mesh: Mesh, klen: int, axis_name: str = "data"):
    """Jitted SPMD per-device sort of received records by their leading
    ``klen`` key bytes; invalid (padding) slots sort to the end of each
    device's shard."""
    return _make_sort_fn(mesh, klen, axis_name)


@functools.lru_cache(maxsize=8)
def _argsort_keys(ncols: int):
    """Jitted stable argsort of [n, ncols] uint32 key columns (ascending
    lexicographic, column 0 most significant)."""
    @jax.jit
    def _argsort(cols):
        keys = tuple(cols[:, c] for c in range(ncols - 1, -1, -1))
        return jnp.lexsort(keys)

    return _argsort


def device_partition_sort(mesh: Mesh, records: np.ndarray, klen: int,
                          splitters: np.ndarray, num_ranges: int,
                          capacity: int | None = None,
                          max_retries: int = 2,
                          axis_name: str = "data"):
    """Full device path: records [N, w] uint8 (first ``klen`` bytes = the
    sort key) → per-device key-sorted rows. ``records`` is padded internally
    to a mesh-size multiple; a trailing validity byte distinguishes real
    rows from padding after the exchange.

    Returns ``(shards, total_capacity_overflowed)`` where ``shards`` is a
    list of ``n_dev`` numpy arrays (device d's received rows, key-sorted,
    padding removed) or ``None`` when every retry overflowed (caller falls
    back to the host path — the reference's disk-spill role,
    ReduceTask.java:1080 ShuffleRamManager budget semantics).
    """
    from tpumr.parallel.mesh import shard_over
    from tpumr.parallel.shuffle import shuffle_dense

    n_dev = mesh.shape[axis_name]
    n0, w = records.shape
    ranges_per_dev = -(-num_ranges // n_dev)

    if n_dev == 1:
        # single-device mesh: the all-to-all exchange is the identity, so
        # only the SORT KEYS visit the device — upload [n, ceil(klen/4)]
        # uint32 columns, argsort there, download the [n] permutation,
        # and gather the full rows on the host. On a tunneled chip this
        # cuts the transfer from 2 x n x w bytes (rows up + sorted rows
        # down) to ~n x (4 x cols + 4) bytes; the value payload never
        # crosses the wire.
        if n0 == 0:
            return [records.copy()], 0
        kcols = key_columns(records, klen)
        # pad to the next power of two with all-FF sentinel keys so the
        # jitted argsort compiles once per size BUCKET, not per exact n
        # (XLA recompiles per shape; a variadic 2M-row sort compile is
        # tens of seconds on a tunneled chip). lexsort is stable, so pad
        # rows (indices >= n0) land after real rows even on all-FF keys.
        n_pad = 1 << max(4, (n0 - 1).bit_length())
        if n_pad != n0:
            padded = np.full((n_pad, kcols.shape[1]), 0xFFFFFFFF, np.uint32)
            padded[:n0] = kcols
            kcols = padded
        order = np.asarray(_argsort_keys(kcols.shape[1])(kcols))
        if n_pad != n0:
            order = order[order < n0]
        return [records[order]], 0

    # trailing validity byte + pad rows (zeros → marked invalid) so the
    # leading dim divides the mesh; pads route to device 0 and are masked
    # out on the host after the sort
    n = -(-n0 // n_dev) * n_dev
    ext = np.zeros((n, w + 1), dtype=np.uint8)
    ext[:n0, :w] = records
    ext[:n0, w] = 1

    sharded = shard_over(mesh, ext, axis_name)
    dest = make_dest_fn(mesh, klen, splitters, ranges_per_dev,
                        axis_name)(sharded)

    if capacity is None:
        # balanced per-(src,dst) load with 2x headroom for sampling skew;
        # the receive side is only the ACTIVE destination devices (when
        # num_ranges < mesh size, fewer devices share the whole load —
        # dividing by n_dev² would systematically overflow)
        active = max(1, -(-num_ranges // ranges_per_dev))
        capacity = max(16, int(2 * n / (n_dev * active)))
    overflowed = 0
    for _attempt in range(max_retries + 1):
        res = shuffle_dense(mesh, sharded, dest, capacity=capacity,
                            axis_name=axis_name)
        if int(res.overflow) == 0:
            break
        overflowed = int(res.overflow)
        capacity *= 2
    else:
        return None, overflowed

    sorted_recs, sorted_valid = make_sort_fn(mesh, klen, axis_name)(
        res.values, res.valid)
    host_recs = np.asarray(sorted_recs)
    host_valid = np.asarray(sorted_valid)
    per_dev = host_recs.shape[0] // n_dev
    shards = []
    for d in range(n_dev):
        lo, hi = d * per_dev, (d + 1) * per_dev
        rows = host_recs[lo:hi]
        # mask-filter (order-preserving): drop unfilled slots AND padding
        mask = host_valid[lo:hi] & (rows[:, w] == 1)
        shards.append(rows[mask][:, :w])
    return shards, overflowed
