"""Collective wrappers under shard_map.

≈ the reference's three TCP transports (SURVEY.md §5 'Distributed
communication backend') re-based onto XLA collectives: aggregation that rode
the HTTP shuffle + reduce now rides ``psum``/``reduce_scatter``; side-file
broadcast rides ``all_gather``; neighbor pipelines ride ``ppermute``. These
are thin, named-axis-explicit wrappers so runtime code doesn't import lax
directly and tests can exercise every collective on the CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def psum(x, axis_name: str = "data"):
    return lax.psum(x, axis_name)

def pmean(x, axis_name: str = "data"):
    return lax.pmean(x, axis_name)

def pmax(x, axis_name: str = "data"):
    return lax.pmax(x, axis_name)

def all_gather(x, axis_name: str = "data", axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

def reduce_scatter(x, axis_name: str = "data", scatter_dim: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim,
                            tiled=True)

def all_to_all(x, axis_name: str = "data", split_axis: int = 0,
               concat_axis: int = 0):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis)

def ppermute_ring(x, axis_name: str = "data", shift: int = 1):
    """Rotate shards around the ring by ``shift`` (ICI neighbor transfer)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)

def axis_index(axis_name: str = "data"):
    return lax.axis_index(axis_name)

def axis_size(axis_name: str = "data"):
    return lax.axis_size(axis_name)


def map_reduce(mesh: Mesh, local_fn: Callable[[Any], Any],
               axis_name: str = "data", in_dim: int = 0) -> Callable:
    """Build a jitted SPMD map+all-reduce: each device applies ``local_fn``
    to its shard and the pytree of results is summed over the mesh — the
    device-native form of map → combine → reduce for commutative aggregation
    (K-Means partial sums, counters, histograms). Every device returns the
    full reduced result (replicated out-spec)."""
    in_spec = P(*([axis_name] if in_dim == 0 else
                  [None] * in_dim + [axis_name]))

    @partial(jax.shard_map, mesh=mesh, in_specs=(in_spec,), out_specs=P())
    def step(shard):
        return jax.tree.map(lambda v: lax.psum(v, axis_name),
                            local_fn(shard))

    return jax.jit(step)
