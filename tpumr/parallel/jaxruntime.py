"""Process-level JAX runtime configuration.

The reference amortizes task start-up with JVM reuse (JvmManager.java:322
reapJvm); the TPU-native equivalent of that cost is XLA compilation — a
fresh worker process otherwise pays every kernel/sort compile again (the
device-shuffle sort alone is tens of seconds on a tunneled chip). The
persistent compilation cache makes compiles durable ACROSS processes:
first worker populates, every later worker (or restart, or next job) hits
disk instead of the compiler.

Conf keys:

- ``tpumr.jax.cache.dir``: cache directory. Default
  ``~/.cache/tpumr/jax-cache`` (per-user, NOT world-writable tmp — a
  shared cache dir would let any local user poison compiled programs).
  Set to ``none`` to disable.
- ``tpumr.jax.cache.min.compile.secs``: only persist compiles that took
  at least this long (default 0.5s — skips trivial host-callback jits,
  keeps every kernel/sort compile that matters).
"""

from __future__ import annotations

import os
import threading
from typing import Any

_lock = threading.Lock()
_configured = False


def configure_persistent_cache(conf: Any = None) -> "str | None":
    """Idempotently point JAX at the persistent compilation cache; first
    caller in the process wins. Returns the cache dir (None = disabled).
    Cheap after the first call — safe on every device-path entry."""
    global _configured
    if _configured:
        import jax
        return jax.config.jax_compilation_cache_dir
    with _lock:
        if _configured:
            import jax
            return jax.config.jax_compilation_cache_dir
        path = None
        if conf is not None:
            path = conf.get("tpumr.jax.cache.dir")
        if path is None:
            path = os.environ.get("TPUMR_JAX_CACHE_DIR")
        if path is None:
            path = os.path.join(os.path.expanduser("~"), ".cache", "tpumr",
                                "jax-cache")
        if str(path).lower() in ("", "none", "off", "disabled"):
            _configured = True
            return None
        import jax
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", str(path))
            min_secs = 0.5
            if conf is not None:
                min_secs = conf.get_float(
                    "tpumr.jax.cache.min.compile.secs", 0.5)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              min_secs)
        except Exception:  # noqa: BLE001 — cache is an optimization only
            _configured = True
            return None
        _configured = True
        return str(path)


def _reset_for_tests() -> None:
    global _configured
    with _lock:
        _configured = False
