"""Device-mesh parallelism — the ICI/DCN data plane.

The reference's distributed data plane is host-level TCP (SURVEY.md §5:
Hadoop IPC, HTTP shuffle servlet TaskTracker.java:4050 ↔ ReduceCopier
fetchers ReduceTask.java:659, DN→DN streaming). The TPU rebuild keeps a host
RPC control plane (tpumr.ipc) but moves the data plane onto XLA collectives
over the chip interconnect:

- ``mesh``        — jax.sharding.Mesh construction + sharding helpers
- ``collectives`` — psum/all_gather/all_to_all/reduce_scatter/ppermute
  wrappers under shard_map
- ``shuffle``     — the MapReduce shuffle as a bucketed/padded on-device
  all-to-all (static shapes for XLA; overflow detected and surfaced)
- ``seqmap``      — record-axis (sequence) parallel map + ring primitives

Map each reference parallelism strategy (SURVEY.md §2.5) to a mesh concept:
input-split data parallelism → sharding over the 'data' axis; partition
parallelism (shuffle) → all_to_all over ICI; heterogeneous CPU/GPU → the
hybrid scheduler (tpumr.mapred.scheduler) + these device paths.
"""

from tpumr.parallel.mesh import (
    make_mesh, shard_over, replicate, local_device_count,
)
from tpumr.parallel.multihost import ensure_initialized, global_mesh
from tpumr.parallel.shuffle import shuffle_dense, ShuffleResult
from tpumr.parallel.seqmap import sequence_parallel_map, ring_pass

__all__ = [
    "make_mesh", "shard_over", "replicate", "local_device_count",
    "shuffle_dense", "ShuffleResult", "sequence_parallel_map", "ring_pass",
    "ensure_initialized", "global_mesh",
]
