"""Host-level RPC — the control plane transport.

≈ Hadoop IPC (reference: src/core/org/apache/hadoop/ipc/ — NIO reactor
``Server.java`` :279 Listener/:320 Reader/:1350 Handler pool/:583 Responder,
connection-cached ``Client.java``, dynamic-proxy ``RPC.java:203,355``).
Re-designed, not translated: a threaded TCP server with length-prefixed
frames carrying the framework's own typed binary codec (so ndarrays/bytes
ride RPC natively — no JSON detours), a connection-cached thread-safe
client, and duck-typed proxies. The versioned-protocol handshake is kept:
proxies check ``get_protocol_version`` against the expected version at
creation (≈ VersionedProtocol, InterTrackerProtocol versionID 29,
InterTrackerProtocol.java:75).

Data-plane traffic does NOT go through here on TPU paths — that's
tpumr.parallel (ICI collectives); this carries heartbeats, submissions,
umbilical status and the host-shuffle fallback.
"""

from __future__ import annotations

import hmac
import os
import selectors
import socket
import socketserver
import struct
import threading
import time
import traceback
from collections import deque
from typing import Any

from tpumr.io.writable import deserialize, serialize

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


class RpcError(RuntimeError):
    """Remote exception surfaced locally (≈ RemoteException)."""


class RpcAuthError(RpcError):
    """Request failed HMAC verification (≈ SASL auth failure)."""


#: signed-timestamp freshness window (seconds)
AUTH_WINDOW_S = 300.0

#: caller identity of the RPC being served on THIS handler thread —
#: simple-auth semantics (asserted by the client, covered by the HMAC
#: signature when auth is on, but any secret holder may assert any name —
#: exactly the reference's non-Kerberos trust model). None outside an RPC
#: dispatch, i.e. for a daemon's own in-process calls.
_current_user = threading.local()


def current_rpc_user() -> "str | None":
    """User asserted by the RPC currently being dispatched (None when not
    inside a dispatch — the callee is acting as the daemon itself)."""
    return getattr(_current_user, "user", None)


def current_rpc_scope() -> "str | None":
    """Token scope of the RPC currently being dispatched: None for
    cluster-secret (daemon) callers, the job id for callers signed with a
    per-job token (≈ the reference's JobToken identity — task children
    hold only their job's token, never the service secret). Only
    meaningful when the server authenticates."""
    return getattr(_current_user, "scope", None)


def current_rpc_real_user() -> "str | None":
    """The REAL (credentialed) caller behind an impersonated request
    (≈ UGI.getRealUser) — None when the request is not proxied."""
    return getattr(_current_user, "real", None)


def current_rpc_verified() -> bool:
    """True when the RPC being dispatched proved its user identity
    cryptographically — signed with the caller's personal user key or a
    live delegation token (tpumr/security/tokens.py) — rather than
    asserting a name under the shared cluster secret. The difference the
    round-3 verdict called out: ACLs over verified identities
    authenticate USERS; over assertions they authenticate secrets."""
    return bool(getattr(_current_user, "verified", False))


def _sign(secret: bytes, req: dict, port: int, nonce: str) -> str:
    """HMAC-SHA256 over the canonical request identity+payload+timestamp,
    bound to the serving connection via the server's per-connection nonce
    (≈ the reference's DIGEST SASL challenge, SaslRpcServer — SURVEY.md
    §2.2). Replay defenses: the nonce ties every frame to one connection
    of one daemon (a frame captured on the way to datanode A cannot be
    replayed to datanode B, or to A over a new connection), the timestamp
    must be fresh, and the server tracks a per-client high-water request
    id within the connection's lifetime. The token scope is part of the
    canon so a scoped frame cannot be re-labeled."""
    base = [req.get("cid"), req.get("id"), req.get("method"),
            list(req.get("params", [])), req.get("ts"), port,
            nonce, req.get("user"), req.get("scope")]
    if req.get("doas") is not None:
        # appended ONLY when impersonating, so non-doas signers (incl.
        # the native libtdfs client, which builds the 9-element canon)
        # stay wire-compatible. Still tamper-proof in both directions:
        # the serialized list length differs, so adding doas to an
        # unsigned-for-doas frame — or stripping it from a signed one —
        # changes the canon and breaks the HMAC.
        base.append(req["doas"])
    return hmac.new(secret, serialize(base), "sha256").hexdigest()


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class _FrameReader:
    """Buffered frame reads for one connection: the naive path paid two
    ``recv`` syscalls per frame (4-byte length, then payload); at
    thousands of heartbeats/second on the master those syscalls are a
    measurable share of the per-beat budget. One reader per connection,
    single-threaded by construction (the client serializes calls on its
    lock; the server runs one handler thread per connection)."""

    __slots__ = ("_sock", "_buf")

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = bytearray()

    def _fill(self, n: int) -> None:
        buf = self._buf
        while len(buf) < n:
            chunk = self._sock.recv(max(65536, n - len(buf)))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)

    def frame_with_len(self) -> "tuple[Any, int]":
        self._fill(4)
        (length,) = _LEN.unpack_from(self._buf)
        if length > MAX_FRAME:
            raise RpcError(f"frame too large: {length}")
        end = 4 + length
        self._fill(end)
        payload = bytes(self._buf[4:end])
        del self._buf[:end]
        return deserialize(payload), length

    def frame(self) -> Any:
        return self.frame_with_len()[0]


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = serialize(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame_with_len(sock: socket.socket) -> "tuple[Any, int]":
    (length,) = _LEN.unpack(_read_exact(sock, 4))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return deserialize(_read_exact(sock, length)), length


def _recv_frame(sock: socket.socket) -> Any:
    return _recv_frame_with_len(sock)[0]


class _ConnCtx:
    """Per-connection serving state shared by both transports (the
    thread-per-connection handler and the reactor): the auth nonce, the
    adopted client id, and the endpoints the signature canon / proxy
    rules need (resolved once per connection, not per frame)."""

    __slots__ = ("nonce", "cid", "port", "peer")

    def __init__(self, port: int, peer: str = "", nonce: str = "") -> None:
        self.nonce = nonce
        self.port = port
        self.peer = peer
        # connection-adopted client id: unsecured clients send their cid
        # on the FIRST request of a connection only (it's ~35 bytes of
        # serialize/deserialize on every frame otherwise — measurable at
        # fleet heartbeat rates); later frames inherit it here. Secured
        # clients keep sending it per frame (the signature canon binds
        # it), so the auth path is unchanged.
        self.cid: Any = None


class _Handler(socketserver.BaseRequestHandler):
    def setup(self) -> None:
        self.server.track_connection(self.request)  # type: ignore[attr-defined]

    def finish(self) -> None:
        self.server.untrack_connection(self.request)  # type: ignore[attr-defined]

    def handle(self) -> None:
        rpc: RpcServer = self.server.rpc  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            ctx = _ConnCtx(port=sock.getsockname()[1],
                           peer=sock.getpeername()[0])
        except OSError:
            return
        if rpc.secret is not None:
            # authenticated servers open with a one-shot connection nonce
            # the client must fold into every signature (≈ SASL challenge)
            import secrets as _secrets
            ctx.nonce = _secrets.token_hex(16)
            try:
                _send_frame(sock, {"hello": 1, "nonce": ctx.nonce})
            except OSError:
                return
        reader = _FrameReader(sock)
        try:
            while True:
                req, req_len = reader.frame_with_len()
                _send_frame(sock, rpc.serve_request(ctx, req, req_len))
        except (ConnectionError, OSError):
            return


class _ThreadingServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class _Reactor:
    """Selector-loop transport: every connection served from ONE thread
    (≈ the reference's NIO reactor — Server.java:279 Listener/:320
    Reader), with methods on the owning server's ``fast_methods``
    allowlist executed INLINE in the loop and everything else handed to
    a small handler pool (≈ the Handler pool, Server.java:1350).

    Why it exists: the thread-per-connection transport costs a
    many-hundred-tracker master two thread handoffs per heartbeat and
    N mostly-idle handler threads churning the scheduler. At fleet
    heartbeat rates the reactor thread stays hot — a ready frame is
    usually served without a single context switch on the server.

    The inline contract: a fast-path handler must be short and must
    never block on anything that needs another RPC to THIS server to
    resolve (it would deadlock the loop). The master's heartbeat fold /
    event-feed reads qualify; submit_job's history I/O does not —
    that's what the pool is for. Response sends are blocking with the
    connection's socket timeout: control-plane responses are small
    (a stuck peer times out and is dropped rather than wedging the
    loop — the reference's async Responder exists for big payloads,
    which this surface doesn't carry)."""

    #: handler-pool width for non-fast methods (the reference default
    #: was 10 Handler threads; dfs.namenode.handler.count etc.)
    POOL_SIZE = 8

    #: max pooled requests in flight (running + queued). Past this the
    #: reactor answers "server busy" IMMEDIATELY instead of queueing —
    #: bounded backpressure: an unbounded executor queue under overload
    #: turns into unbounded memory plus minutes-stale responses, and
    #: the caller's own timeout/retry policy is the right place to
    #: absorb the pushback. Fast-path methods never queue here.
    POOL_BACKLOG = 64

    def __init__(self, rpc: "RpcServer", host: str, port: int) -> None:
        self.rpc = rpc
        self._pool_inflight = 0
        self._pool_lock = threading.Lock()
        #: high-water mark of frames a single connection had in flight
        #: at once (the one being served + those queued behind it) —
        #: >1 proves a client actually pipelined requests instead of
        #: ping-ponging one per round trip
        self.pipeline_depth_peak = 1
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(512)
        self._listen.setblocking(False)
        self._port = self._listen.getsockname()[1]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        # wake pipe: stop() must interrupt a parked select() promptly
        self._rpipe, self._wpipe = os.pipe()
        self._sel.register(self._rpipe, selectors.EVENT_READ, "wake")
        self._pool: "Any | None" = None
        self._stopping = threading.Event()
        self._thread: "threading.Thread | None" = None

    @property
    def server_address(self) -> tuple:
        return self._listen.getsockname()

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=self.POOL_SIZE, thread_name_prefix="rpc-handler")
        self._thread = threading.Thread(target=self._loop,
                                        name="rpc-reactor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopping.set()
        try:
            os.write(self._wpipe, b"x")
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            self._listen.close()
        except OSError:
            pass
        for fd in (self._rpipe, self._wpipe):
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            self._sel.close()   # the epoll fd leaks per stop otherwise
        except OSError:
            pass
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    # -------------------------------------------------------- the loop

    def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                events = self._sel.select(0.5)
            except OSError:
                return
            for key, _ in events:
                if key.data is None:
                    self._accept()
                elif key.data == "wake":
                    try:
                        os.read(self._rpipe, 4096)
                    except OSError:
                        pass
                else:
                    self._on_readable(key.data)

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listen.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # blocking sends with a bound: a response to a stuck peer
            # must drop the connection, never wedge the loop
            sock.settimeout(30.0)
            ctx = _ConnCtx(port=self._port, peer=addr[0])
            if self.rpc.secret is not None:
                import secrets as _secrets
                ctx.nonce = _secrets.token_hex(16)
                try:
                    _send_frame(sock, {"hello": 1, "nonce": ctx.nonce})
                except OSError:
                    sock.close()
                    continue
            conn = _RConn(sock, ctx)
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (ValueError, KeyError, OSError):
                sock.close()
                continue
            self.rpc._track_connection(sock)

    def _close(self, conn: "_RConn") -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        self.rpc._untrack_connection(conn.sock)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_readable(self, conn: "_RConn") -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError, socket.timeout):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        buf = conn.buf
        buf.extend(data)
        fast = self.rpc.fast_methods
        while True:
            if len(buf) < 4:
                return
            (length,) = _LEN.unpack_from(buf)
            if length > MAX_FRAME:
                self._close(conn)
                return
            end = 4 + length
            if len(buf) < end:
                return
            payload = bytes(buf[4:end])
            del buf[:end]
            try:
                req = deserialize(payload)
            except Exception:  # noqa: BLE001 — garbage frame
                self._close(conn)
                return
            # Pipelining clients (the shuffle fetchers' call_begin /
            # call_finish window) may have MANY frames of one
            # connection in flight at once, and they match responses to
            # requests purely by arrival order — so every frame that
            # arrives while a pooled response is still owed on this
            # connection queues IN ORDER behind it (fast methods
            # included: serving one inline would jump the queue). The
            # serving pool thread drains the queue itself, so one
            # connection occupies at most one pool slot however deep it
            # pipelines; parallelism comes from other connections.
            assert self._pool is not None
            mode = "inline"
            saturated = False
            with self._pool_lock:
                if conn.busy:
                    saturated = self._pool_inflight >= self.POOL_BACKLOG
                    if saturated:
                        conn.pending.append((None, self._busy_resp(req)))
                    else:
                        self._pool_inflight += 1
                        conn.pending.append(((req, length), None))
                    depth = 1 + len(conn.pending)
                    if depth > self.pipeline_depth_peak:
                        self.pipeline_depth_peak = depth
                    mode = "queued"
                elif isinstance(req, dict) and req.get("method") in fast:
                    mode = "inline"
                else:
                    saturated = self._pool_inflight >= self.POOL_BACKLOG
                    if saturated:
                        mode = "busy"
                    else:
                        self._pool_inflight += 1
                        conn.busy = True
                        mode = "submit"
            if mode == "inline":
                # the heartbeat fast path: parse → serve → respond on
                # the reactor thread, zero handoffs
                resp = self.rpc.serve_request(conn.ctx, req, length)
                try:
                    _send_frame(conn.sock, resp)
                except OSError:
                    self._close(conn)
                    return
            elif mode == "submit":
                self._pool.submit(self._serve_pooled, conn, req, length)
            elif mode == "busy":
                # bounded backpressure: answer busy NOW (an error
                # the caller sees and backs off on) instead of
                # queueing without bound. Deliberately NOT cached
                # in the replay cache — a retried id re-enters the
                # pipeline normally once the pool drains.
                try:
                    _send_frame(conn.sock, self._busy_resp(req))
                except OSError:
                    self._close(conn)
                    return
            if saturated:
                reg = self.rpc.metrics
                if reg is not None:
                    reg.incr("rpc_pool_saturated")

    @staticmethod
    def _busy_resp(req: Any) -> dict:
        return {"id": req.get("id") if isinstance(req, dict) else None,
                "error": "RpcError: handler pool saturated "
                         "(server busy, retry later)"}

    def _serve_pooled(self, conn: "_RConn", req: Any, length: int) -> None:
        while True:
            try:
                if not isinstance(req, dict):
                    raise RpcError(f"malformed request frame: {type(req)}")
                resp = self.rpc.serve_request(conn.ctx, req, length)
            except Exception as e:  # noqa: BLE001 — keep the pool alive
                resp = {"id": req.get("id") if isinstance(req, dict)
                        else None,
                        "error": f"{type(e).__name__}: {e}"}
            finally:
                with self._pool_lock:
                    self._pool_inflight -= 1
            if not self._send_or_abandon(conn, resp):
                return
            # in-order drain of frames the client pipelined behind the
            # one just answered; pre-built saturation responses send
            # without a dispatch
            while True:
                with self._pool_lock:
                    if not conn.pending:
                        conn.busy = False
                        return
                    work, canned = conn.pending.popleft()
                if work is not None:
                    req, length = work
                    break
                if not self._send_or_abandon(conn, canned):
                    return

    def _send_or_abandon(self, conn: "_RConn", resp: Any) -> bool:
        """Send one response; on a dead socket release the backlog slots
        of everything still queued behind it (the reactor reaps the
        socket itself on its next select) and report False."""
        try:
            _send_frame(conn.sock, resp)
            return True
        except OSError:
            with self._pool_lock:
                for work, _ in conn.pending:
                    if work is not None:
                        self._pool_inflight -= 1
                conn.pending.clear()
                conn.busy = False
            return False


class _RConn:
    """One reactor-served connection: socket + receive buffer + the
    transport-agnostic serving context, plus the per-connection request
    pipeline (``busy`` = a pooled response is owed; ``pending`` = frames
    queued in arrival order behind it, drained by the serving pool
    thread so responses keep request order)."""

    __slots__ = ("sock", "buf", "ctx", "pending", "busy")

    def __init__(self, sock: socket.socket, ctx: _ConnCtx) -> None:
        self.sock = sock
        self.buf = bytearray()
        self.ctx = ctx
        self.pending: "deque[tuple]" = deque()
        self.busy = False


class RpcServer:
    """Exposes public methods of a handler object (and optional extra named
    protocols) over TCP."""

    RESPONSE_CACHE_SIZE = 2048

    def __init__(self, handler: Any, host: str = "127.0.0.1",
                 port: int = 0, secret: "bytes | None" = None,
                 reactor: bool = False,
                 fast_methods: "set[str] | None" = None) -> None:
        self._handlers: dict[str, Any] = {"": handler}
        self.secret = secret
        #: methods the reactor transport may execute INLINE in its
        #: select loop (short, never block on another RPC to this
        #: server); ignored by the thread-per-connection transport
        self.fast_methods: "set[str]" = set(fast_methods or ())
        #: per-scope token lookup for scoped callers (job tokens):
        #: ``resolver(scope) -> bytes | None``. None = scoped frames are
        #: rejected (the default: only daemons hold the cluster secret).
        self.token_resolver: "Any | None" = None
        #: methods a token-scoped caller may invoke (umbilical + shuffle
        #: surface); everything else is denied before dispatch
        self.scoped_methods: "set[str]" = set()
        #: idempotent READ methods opted out of the (cid, id) replay
        #: machinery: their responses are never stored in the response
        #: cache (a shuffle chunk response is MiB-scale — caching 128
        #: per stripe would pin gigabytes of payload) and a replayed id
        #: re-executes instead of being rejected (re-reading a byte
        #: range is harmless). Everything else keeps exactly-once
        #: semantics.
        self.uncached_methods: "set[str]" = set()
        #: delegation-token liveness store (tpumr.security.tokens.
        #: TokenStore) for ISSUING daemons (jobtracker, namenode)
        self.token_store: "Any | None" = None
        #: stateless token acceptance (datanodes): verify signature +
        #: ident lifetime only, no liveness store — paired with a
        #: ``request_gate`` that demands NameNode-minted per-block
        #: access stamps, so a canceled token stops working once its
        #: stamps expire (the reference's BlockToken split). Default
        #: False: a daemon with neither store nor this flag rejects
        #: token scopes.
        self.token_stateless = False
        #: optional pre-dispatch hook ``gate(req, verified_user,
        #: job_scoped)`` raising RpcAuthError to deny (datanode block
        #: access enforcement)
        self.request_gate: "Any | None" = None
        #: service-level authorization (tpumr.security.authorize.
        #: ServiceAuthorizationManager) — the hadoop-policy.xml tier;
        #: None/disabled = every caller may reach every protocol
        self.authz: "Any | None" = None
        #: conf consulted for hadoop.proxyuser.* impersonation rules;
        #: None (default) rejects every doas frame — impersonation is
        #: strictly opt-in per daemon
        self.proxy_conf: "Any | None" = None
        #: optional MetricsRegistry: when set, every dispatched method
        #: records its server-side handler latency into a per-method
        #: ``rpc_<method>`` histogram (names are bounded by the
        #: handler's real method surface — lookup precedes timing), and
        #: the saturation gauges below register (rpc_inflight,
        #: rpc_inflight_peak, rpc_handler_threads)
        self._metrics: "Any | None" = None
        # in-flight dispatch accounting (control-plane saturation): how
        # many requests are past auth/replay and inside handler code
        # RIGHT NOW, plus the high-water mark since the last peak read
        self._inflight = 0
        self._inflight_peak = 0
        self._inflight_lock = threading.Lock()
        self._reactor: "_Reactor | None" = None
        if reactor:
            self._reactor = _Reactor(self, host, port)
            self._server: Any = self._reactor
        else:
            self._server = _ThreadingServer((host, port), _Handler)
            # expose hooks on the socketserver instance for _Handler
            self._server.rpc = self  # type: ignore[attr-defined]
            self._server.track_connection = self._track_connection  # type: ignore[attr-defined]
            self._server.untrack_connection = self._untrack_connection  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        # response/replay caches STRIPED by client id: every request of
        # every client passes through here, and one shared lock was a
        # measurable cross-tracker convoy on the master's heartbeat
        # path (a holder preempted mid-section stalls every handler)
        self._resp_stripes = [
            ({}, threading.Lock()) for _ in range(16)]
        #: method -> (latency_hist, bytes_hist), read LOCK-FREE on the
        #: dispatch path (GIL-atomic dict get; bounded because only
        #: successfully looked-up method names reach it)
        self._method_hists: "dict[str, tuple] | Any" = {}
        self._cid_hwm: dict[Any, int] = {}
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    @property
    def metrics(self) -> "Any | None":
        return self._metrics

    @metrics.setter
    def metrics(self, reg: "Any | None") -> None:
        self._metrics = reg
        self._method_hists.clear()   # hist cache binds to one registry
        if reg is not None:
            # the server's saturation gauges live in the same registry
            # as the per-method latency hists: one scrape answers both
            # "how slow" and "how deep is the queue"
            reg.set_gauge("rpc_inflight", lambda: self._inflight)
            reg.set_gauge("rpc_inflight_peak",
                          lambda: self.inflight_peak())
            reg.set_gauge("rpc_handler_threads",
                          lambda: len(self._conns))
            if self._reactor is not None:
                # deepest per-connection request pipeline observed:
                # >1 means clients are actually overlapping requests
                reg.set_gauge("rpc_pipeline_depth_peak",
                              lambda: self._reactor.pipeline_depth_peak)

    def note_dispatch_start(self) -> None:
        with self._inflight_lock:
            self._inflight += 1
            if self._inflight > self._inflight_peak:
                self._inflight_peak = self._inflight

    def note_dispatch_end(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    def inflight_peak(self, reset: bool = False) -> int:
        """High-water mark of concurrently dispatched requests since
        the last ``reset=True`` read (the bench_scale per-row peak)."""
        with self._inflight_lock:
            peak = self._inflight_peak
            if reset:
                self._inflight_peak = self._inflight
            return peak

    def _track_connection(self, sock: socket.socket) -> None:
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack_connection(self, sock: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(sock)

    def serve_request(self, ctx: _ConnCtx, req: dict,
                      req_len: int) -> "dict[str, Any]":
        """Serve ONE parsed request frame to a response dict — the whole
        auth → replay-dedupe → authorize → dispatch pipeline, transport
        agnostic (called from per-connection handler threads, from the
        reactor loop for fast-path methods, and from its handler pool
        for the rest)."""
        if "cid" in req:
            ctx.cid = req["cid"]
        else:
            req["cid"] = ctx.cid
        secret = self.secret
        scope = req.get("scope")
        # defined for every request path: an UNSECURED server never
        # enters the auth block below, yet the authz hook still reads
        # these (a scoped frame against a secret-less daemon must not
        # crash the handler)
        verified_user = None
        job_scoped = False
        if secret is not None:
            import time as _time
            sig = req.get("auth")
            ts = req.get("ts")
            if not sig or ts is None:
                return {"id": req.get("id"),
                        "error": "RpcAuthError: request not signed "
                                 "with the expected secret"}
            # freshness BEFORE any resolver lookup: needs no secret, so
            # replayed/garbage frames never trigger resolver work
            # (which may do real lookups)
            # the frame timestamp comes from ANOTHER HOST: freshness
            # is inherently a wall-clock comparison
            if abs(_time.time() - ts) > AUTH_WINDOW_S:  # tpulint: disable=clock-arith
                return {"id": req.get("id"),
                        "error": "RpcAuthError: stale or missing "
                                 "request timestamp (replay?)"}
            if scope is not None:
                # Scoped caller. Three scope families, all folded
                # into the signature canon (no re-labeling):
                #   user:<name>  — personal user key (derived
                #                  from the cluster secret)
                #   token:<hex>  — delegation token ident; the
                #                  signing secret is its password
                #   <job id>     — per-job token, restricted to
                #                  the scoped-method allowlist
                # Every failure mode yields the SAME error as a
                # bad signature — no oracle for which scopes
                # (job ids, users, tokens) exist.
                secret, verified_user, job_scoped = \
                    self.resolve_scope(scope, req)
            if secret is None or not hmac.compare_digest(
                    sig, _sign(secret, req, ctx.port, ctx.nonce)):
                return {"id": req.get("id"),
                        "error": "RpcAuthError: request not signed "
                                 "with the expected secret"}
        # client-side reconnect retries resend the same (cid, id):
        # replay the cached response instead of re-executing, so
        # non-idempotent methods (submit_job) never run twice
        dedupe_key = (req.get("cid"), req.get("id"))
        uncached = req.get("method") in self.uncached_methods
        if req.get("cid") is not None and not uncached:
            cached = self.response_cache_get(dedupe_key)
            if cached is not None:
                return cached
            if self.secret is not None and not self.advance_hwm(
                    req.get("cid"), req.get("id")):
                # id at/below this client's high-water mark and not in
                # the cache: a replayed old frame
                return {"id": req.get("id"),
                        "error": "RpcAuthError: replayed request id"}
        resp: dict[str, Any] = {"id": req.get("id")}
        # saturation accounting: requests currently past auth/replay
        # checks and occupying a handler (the master's rpc_inflight
        # gauge — climbing toward the connection count means handlers
        # can't drain the offered load)
        self.note_dispatch_start()
        try:
            if self.secret is not None and scope is not None \
                    and job_scoped and req.get("method") not in \
                    self.scoped_methods:
                raise RpcAuthError(
                    f"method {req.get('method')!r} is not "
                    "available to token-scoped callers")
            real_user = (verified_user if scope is not None
                         else None) or req.get("user")
            effective_user = real_user
            doas = req.get("doas")
            if doas is not None and (
                    not isinstance(doas, str) or not doas.strip()):
                # an empty/garbage effective identity resolves
                # downstream to the DAEMON's own process user — an
                # escalation, not an impersonation
                raise RpcAuthError("invalid doas identity")
            if doas is not None:
                # impersonation ≈ ProxyUsers.authorize: the REAL
                # caller's credential signed this frame (doas is in the
                # canon); the proxy rules decide whether it may act as
                # the effective user
                proxy_conf = self.proxy_conf
                if proxy_conf is None:
                    raise RpcAuthError(
                        "impersonation is not enabled on this daemon")
                from tpumr.security.authorize import authorize_proxy
                authorize_proxy(proxy_conf, str(real_user), str(doas),
                                ctx.peer)
                effective_user = doas
            authz = self.authz
            if authz is not None:
                # service-level authorization (hadoop-policy.xml tier):
                # who may reach this protocol at all — checked against
                # the EFFECTIVE identity (the reference authorizes the
                # proxy UGI)
                authz.check(req.get("method"), effective_user)
            gate = self.request_gate
            if gate is not None and self.secret is not None:
                gate(req, verified_user if scope is not None else None,
                     job_scoped if scope is not None else False)
            method = self.lookup(req["method"])
            # handlers see the EFFECTIVE identity; the real caller
            # stays available for audit
            # (current_rpc_real_user ≈ UGI.getRealUser)
            _current_user.user = effective_user
            _current_user.real = real_user if doas is not None else None
            _current_user.scope = scope if self.secret is not None \
                else None
            # a proxied identity is only as verified as the REAL
            # credential behind it
            _current_user.verified = (self.secret is not None
                                      and verified_user is not None)
            # per-method server-side latency + request-size
            # distributions (when the owning daemon wired a registry).
            # The size comes from the frame length the transport
            # ALREADY read — never re-serialized. Histogram pairs are
            # cached per method AFTER lookup succeeded (bogus names
            # mint no series), read lock-free: the registry's own lock
            # was a measurable per-request convoy at fleet heartbeat
            # rates.
            _hists = self.method_hists(req.get("method")) \
                if self._metrics is not None else None
            _t0 = time.monotonic() if _hists is not None else 0.0
            try:
                resp["result"] = method(*req.get("params", []))
            finally:
                if _hists is not None:
                    _hists[0].observe(time.monotonic() - _t0)
                    _hists[1].observe(req_len)
                _current_user.user = None
                _current_user.real = None
                _current_user.scope = None
                _current_user.verified = False
        except Exception as e:  # noqa: BLE001 — remote surface
            resp["error"] = f"{type(e).__name__}: {e}"
            resp["traceback"] = traceback.format_exc(limit=8)
        finally:
            self.note_dispatch_end()
        if req.get("cid") is not None and not uncached:
            self.response_cache_put(dedupe_key, resp)
        return resp

    def method_hists(self, method: Any) -> "tuple | None":
        """(latency, request_bytes) histogram pair for one REAL method
        (callers consult it only after lookup succeeded). The hit path
        is a lock-free dict read; the miss path builds through the
        registry once per method name."""
        pair = self._method_hists.get(method)
        if pair is None:
            reg = self._metrics
            if reg is None:
                return None
            from tpumr.metrics.histogram import BYTES
            name = "rpc_" + str(method).replace(".", "_")
            pair = (reg.histogram(name),
                    reg.histogram(name + "_request_bytes", BYTES))
            self._method_hists[method] = pair
        return pair

    def _resp_stripe(self, cid: Any) -> "tuple[dict, Any]":
        return self._resp_stripes[hash(cid) & 15]

    def response_cache_get(self, key: tuple) -> Any | None:
        cache, lock = self._resp_stripe(key[0])
        with lock:
            return cache.get(key)

    def advance_hwm(self, cid: Any, req_id: Any) -> bool:
        """Per-client monotonic id check (replay defense under auth):
        returns False for an id at/below the high-water mark."""
        if not isinstance(req_id, int):
            return False
        _, lock = self._resp_stripe(cid)
        with lock:
            hwm = self._cid_hwm.get(cid, 0)
            if req_id <= hwm:
                return False
            self._cid_hwm[cid] = req_id
            return True

    def response_cache_put(self, key: tuple, resp: Any) -> None:
        cache, lock = self._resp_stripe(key[0])
        cap = max(2, self.RESPONSE_CACHE_SIZE // 16)
        with lock:
            if len(cache) >= cap:
                # drop oldest half (insertion-ordered dict)
                for k in list(cache)[: cap // 2]:
                    del cache[k]
            cache[key] = resp

    def resolve_scope(self, scope: Any,
                      req: dict) -> "tuple[bytes | None, str | None, bool]":
        """(signing_secret, verified_user, job_scoped) for a scoped
        request. Any malformed/unknown/expired credential resolves to a
        None secret, which the handler reports with the same generic
        bad-signature error. The asserted ``user`` field must equal the
        credential's identity — a personal credential can only ever
        speak as its own user (the whole point)."""
        try:
            if isinstance(scope, str) and scope.startswith("user:"):
                name = scope[len("user:"):]
                if not name or req.get("user") != name:
                    return None, None, False
                from tpumr.security.tokens import derive_user_key
                return derive_user_key(self.secret, name), name, False
            if isinstance(scope, str) and scope.startswith("token:"):
                import time as _time
                from tpumr.security.tokens import (parse_ident,
                                                   token_password)
                ident = bytes.fromhex(scope[len("token:"):])
                tok = parse_ident(ident)
                store = self.token_store
                if store is not None:
                    ok = store.check(tok) is None
                elif self.token_stateless:
                    # token lifetimes are absolute wall instants
                    # minted by another daemon
                    now = _time.time()
                    ok = tok.issue_ts - AUTH_WINDOW_S <= now <= tok.max_ts  # tpulint: disable=clock-arith
                else:
                    ok = False
                if not ok or req.get("user") != tok.owner:
                    return None, None, False
                return token_password(self.secret, ident), tok.owner, \
                    False
        except Exception:  # noqa: BLE001 — malformed credential
            return None, None, False
        resolver = self.token_resolver
        return (resolver(scope) if resolver else None), None, True

    def add_protocol(self, name: str, handler: Any) -> None:
        self._handlers[name] = handler

    def lookup(self, method: str):
        ns, _, name = method.rpartition(".")
        handler = self._handlers.get(ns)
        if handler is None or name.startswith("_"):
            raise AttributeError(f"no such method {method!r}")
        fn = getattr(handler, name, None)
        if fn is None or not callable(fn):
            raise AttributeError(f"no such method {method!r}")
        return fn

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    @property
    def port(self) -> int:
        return self.address[1]

    def start(self) -> "RpcServer":
        if self._reactor is not None:
            self._reactor.start()
            return self
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rpc-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._reactor is not None:
            self._reactor.stop()
        else:
            # shutdown() blocks forever if serve_forever never ran — only
            # call it when start() actually happened
            if self._thread is not None:
                self._server.shutdown()
            self._server.server_close()
        # sever established connections too: a stopped server must not keep
        # answering RPCs through old handler threads (a restarted daemon on
        # the same port would otherwise never see its clients reconnect)
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class RpcClient:
    """Connection-cached, thread-safe client (one socket; calls serialized —
    fan-out callers hold one client per target like the reference's
    per-connection multiplexing without the async responder).

    Control-plane partition tolerance: transport failures (connect
    refused, reset mid-call, timeout) retry up to ``retries`` times with
    jittered exponential backoff (``tpumr.rpc.client.retries`` /
    ``tpumr.rpc.client.backoff.ms`` where daemons wire them through).
    The first retry is immediate — a dropped idle connection just needs
    a reconnect; sleeps start from the second. Retries are safe for
    non-idempotent methods because every resend carries the same
    ``(cid, id)`` and the server's response cache replays instead of
    re-executing. Application-level errors (``RpcError``) are never
    retried — the server answered."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 secret: "bytes | None" = None,
                 scope: "str | None" = None,
                 retries: int = 1, backoff_ms: float = 200.0,
                 backoff_max_ms: float = 10_000.0) -> None:
        self.host, self.port = host, port
        self.timeout = timeout
        self.secret = secret
        self.retries = max(0, int(retries))
        self.backoff_s = max(0.0, float(backoff_ms)) / 1000.0
        self.backoff_max_s = max(0.0, float(backoff_max_ms)) / 1000.0
        #: conf consulted by the rpc.drop / rpc.delay / rpc.reset chaos
        #: seams (tpumr/utils/fi.py); None (default) = zero-cost off
        self.fi_conf: "Any | None" = None
        #: token scope: set when ``secret`` is a per-job token rather
        #: than the cluster secret (task children) — the server resolves
        #: the verification key by scope and restricts callable methods
        self.scope = scope
        #: personal credentials BIND the asserted identity: a user:/
        #: token: scope always speaks as the credential's user, whatever
        #: the process UGI or OS login says — the server enforces the
        #: match, so deriving it anywhere else just manufactures
        #: unexplainable auth failures
        self._scope_user: "str | None" = None
        #: impersonation: when set, every request carries doas=<name>
        #: and the server enforces hadoop.proxyuser.<real>.* rules
        #: (≈ UserGroupInformation.createProxyUser + doAs)
        self.doas: "str | None" = None
        if isinstance(scope, str):
            if scope.startswith("user:"):
                self._scope_user = scope[len("user:"):]
            elif scope.startswith("token:"):
                try:
                    from tpumr.security.tokens import parse_ident
                    self._scope_user = parse_ident(
                        bytes.fromhex(scope[len("token:"):])).owner
                except Exception:  # noqa: BLE001 — server will reject
                    pass
        #: optional ``provider(method, params) -> dict | None`` merged
        #: into each request envelope (e.g. DFSClient attaching the
        #: NameNode-minted block-access stamp for DataNode calls). The
        #: stamp is a bearer credential signed by its minter, like the
        #: reference's block token accompanying data transfer — it does
        #: not need to ride the request signature canon.
        self.envelope_provider: "Any | None" = None
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._reader: "_FrameReader | None" = None
        self._nonce = ""
        self._id = 0
        import uuid
        self._cid = uuid.uuid4().hex  # pairs with server response cache
        #: has this connection already carried our cid? Unsecured
        #: clients send it once per connection (the server adopts it);
        #: secured clients resend it every frame (signature-bound)
        self._cid_sent = False
        #: requests sent via call_begin whose responses have not been
        #: collected yet — both transports serve one connection's
        #: frames in request order, so call_finish drains them FIFO
        self._outstanding = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._reader = _FrameReader(s)
            if self.secret is not None:
                # authenticated servers greet with a per-connection nonce;
                # an unsecured server sends nothing — fail fast with a
                # config-skew diagnosis instead of hanging for the full
                # socket timeout (both sides would otherwise wait forever)
                s.settimeout(min(5.0, self.timeout))
                try:
                    hello = self._reader.frame()
                except (TimeoutError, socket.timeout):
                    s.close()
                    raise RpcAuthError(
                        f"server {self.host}:{self.port} sent no auth "
                        "hello — this client has a cluster secret "
                        "configured but the server appears to run "
                        "unauthenticated (tpumr.rpc.secret mismatch?)")
                finally:
                    if s.fileno() >= 0:
                        s.settimeout(self.timeout)
                self._nonce = hello.get("nonce", "") \
                    if isinstance(hello, dict) else ""
            self._sock = s
        return self._sock

    def _stamp(self, req: dict) -> None:
        """Timestamp + sign a request for the CURRENT connection (must be
        re-done after any reconnect: the nonce changes)."""
        if self.secret is not None:
            import time as _time
            req["ts"] = _time.time()
            req["auth"] = _sign(self.secret, req, self.port, self._nonce)

    def _recv_resp(self) -> Any:
        # a client configured without a secret may still receive an
        # authenticated server's hello frame first — skip past it (the
        # real response, an auth error, follows)
        assert self._reader is not None
        resp = self._reader.frame()
        while isinstance(resp, dict) and "hello" in resp:
            resp = self._reader.frame()
        return resp

    def _build_req(self, method: str, params: tuple) -> dict:
        # caller identity rides every request (simple-auth assertion ≈ the
        # reference's UGI-in-ConnectionHeader); resolved per call so
        # UserGroupInformation.do_as scopes apply — unless a personal
        # credential fixes the identity
        if self._scope_user is not None:
            user = self._scope_user
        else:
            from tpumr.security import UserGroupInformation
            user = UserGroupInformation.get_current_user().user
        self._id += 1
        req = {"id": self._id, "method": method,
               "params": list(params), "user": user}
        if self.secret is not None or not self._cid_sent:
            req["cid"] = self._cid
        if self.scope is not None:
            req["scope"] = self.scope
        if self.doas is not None:
            req["doas"] = self.doas
        if self.envelope_provider is not None:
            extra = self.envelope_provider(method, params)
            if extra:
                req.update(extra)
        return req

    @staticmethod
    def _check_resp(resp: Any) -> Any:
        if "error" in resp:
            msg = resp["error"] + "\n[remote] " + resp.get("traceback", "")
            if resp["error"].startswith("RpcAuthError"):
                raise RpcAuthError(msg)
            raise RpcError(msg)
        return resp.get("result")

    def _fi_pre_send(self) -> None:
        """Chaos seams on the send side: ``rpc.delay`` sleeps the call
        (``tpumr.fi.rpc.delay.ms``, default 100), ``rpc.drop`` loses the
        request before it reaches the wire (the retry policy's quarry)."""
        from tpumr.utils import fi
        if fi.fires("rpc.delay", self.fi_conf):
            time.sleep(float(self.fi_conf.get(
                "tpumr.fi.rpc.delay.ms", 100) or 100) / 1000.0)
        if fi.fires("rpc.drop", self.fi_conf):
            raise ConnectionError("injected fault at rpc.drop")

    def _fi_post_send(self) -> None:
        """``rpc.reset``: the connection dies AFTER the request went out
        — delivery unknown, the hardest retry case (the server may have
        executed; the resent id must hit the replay cache)."""
        from tpumr.utils import fi
        if fi.fires("rpc.reset", self.fi_conf):
            self.close_locked()
            raise ConnectionError("injected fault at rpc.reset")

    def call(self, method: str, *params: Any) -> Any:
        import random as _random
        with self._lock:
            req = self._build_req(method, params)
            attempt = 0
            while True:
                try:
                    if self.fi_conf is not None:
                        self._fi_pre_send()
                    sock = self._connect()
                    # re-sign per attempt: a reconnect changed the nonce
                    self._stamp(req)
                    _send_frame(sock, req)
                    if self.fi_conf is not None:
                        self._fi_post_send()
                    resp = self._recv_resp()
                    break
                except (ConnectionError, OSError):
                    # server restart / idle drop / partition. The retry
                    # MUST carry the cid: the new connection has not
                    # adopted it yet, and the server-side (cid, id)
                    # dedupe is what keeps a resent submit_job from
                    # running twice.
                    self.close_locked()
                    req["cid"] = self._cid
                    attempt += 1
                    if attempt > self.retries:
                        raise
                    if attempt > 1:
                        # first retry immediate (a dropped idle
                        # connection just needs a reconnect); then
                        # jittered exponential backoff, capped — a
                        # restarting master must not be stampeded
                        time.sleep(min(self.backoff_max_s,
                                       self.backoff_s
                                       * (2 ** (attempt - 2)))
                                   * _random.uniform(0.5, 1.0))
            self._cid_sent = True
        return self._check_resp(resp)

    # ------------------------------------------------ pipelined calls
    #
    # Split call surface for fan-out callers (the scale fleet's load
    # generators, the shuffle copier's chunk streams): send many
    # requests back-to-back, then collect the responses — the server
    # overlaps its handling with the caller's next sends instead of
    # ping-ponging one context switch per call. NOT thread-safe by
    # design: a pipelining caller owns its client for the whole
    # begin/finish window (fleet worker sharding / a shuffle connection
    # -pool lease guarantees it). Any number of call_begins may be
    # outstanding at once; both server transports answer one
    # connection's frames in request order, so call_finish collects
    # responses strictly FIFO.

    @property
    def outstanding(self) -> int:
        """Responses still owed to this client's call_begin window —
        nonzero means the connection cannot be handed to another caller
        (the next response on the wire belongs to THIS window)."""
        return self._outstanding

    def call_begin(self, method: str, *params: Any) -> None:
        """Send one request WITHOUT waiting for the response; pair with
        :meth:`call_finish`. One reconnect retry, like :meth:`call`
        (the request has not been received when the send itself fails)
        — but only while NOTHING is outstanding: reconnecting under a
        live window would silently drop every in-flight response (the
        new connection never delivers them)."""
        req = self._build_req(method, params)
        try:
            sock = self._connect()
            self._stamp(req)
            _send_frame(sock, req)
        except (ConnectionError, OSError):
            had_outstanding = self._outstanding > 0
            self.close_locked()
            if had_outstanding:
                raise
            req["cid"] = self._cid
            sock = self._connect()
            self._stamp(req)
            _send_frame(sock, req)
        self._cid_sent = True
        self._outstanding += 1

    def call_finish(self) -> Any:
        """Receive the OLDEST outstanding :meth:`call_begin` response.
        No resend on failure: delivery is UNKNOWN once the request went
        out, and pipelined callers (heartbeats, shuffle fetch retries)
        have their own replay protocol for exactly this case."""
        try:
            resp = self._recv_resp()
        except (ConnectionError, OSError):
            # the stream may still deliver this response LATE; reusing
            # the connection would hand that stale frame to the next
            # call_finish (responses carry no request id) and desync
            # every call after it — drop the connection so the next
            # call starts clean, like call()'s error path
            self.close_locked()
            raise
        self._outstanding -= 1
        return self._check_resp(resp)

    def close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._reader = None
            self._cid_sent = False   # the next connection re-introduces it
            self._outstanding = 0    # in-flight responses died with it

    def close(self) -> None:
        with self._lock:
            self.close_locked()


class RpcClientPool:
    """Shared per-target connection pool for fan-out data-plane callers
    (the shuffle copier's fetchers, the streamed stage handoff): many
    worker threads multiplex over at most ``conns_per_target`` sockets
    per (host, port). A lease is EXCLUSIVE — the holder may pipeline
    call_begin/call_finish freely — and release() returns the
    connection warm for the next fetch (and the penalty-box recovery
    path), instead of the one-serialized-client-per-(addr, thread)
    caches that opened ``parallel.copies`` sockets per target and paid
    a fresh TCP (+auth hello) handshake after every eviction.

    ``factory(host, port) -> RpcClient`` builds new connections, so the
    owner attaches its own secret/scope/timeouts. Acquire blocks (with
    an optional timeout) when every connection to the target is leased
    — that bound is the point: a tracker being fetched from by hundreds
    of reducers sees ``conns_per_target`` sockets per reduce, not
    ``parallel.copies``."""

    def __init__(self, factory: Any, conns_per_target: int = 2,
                 idle_s: float = 0.0) -> None:
        self._factory = factory
        self._cap = max(1, int(conns_per_target))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # addr -> [(idle client, released_at)]; addr -> total live
        # (leased + idle)
        self._idle: "dict[str, list[tuple[RpcClient, float]]]" = {}
        self._count: "dict[str, int]" = {}
        self._closed = False
        #: close idle connections older than this on the next pool
        #: touch; 0 keeps them forever (the shuffle copier's choice —
        #: its targets stay hot for a whole copy phase). Long-lived
        #: clients with a drifting target set (a DFS client walking
        #: many datanodes) set it so the pool cannot accrete one socket
        #: per datanode ever contacted.
        self.idle_s = float(idle_s)
        #: connections ever built (pool efficiency: a healthy copy
        #: phase reuses — this stays near targets * conns_per_target)
        self.connects = 0

    def _prune_locked(self) -> "list[RpcClient]":
        """Collect expired idle connections (caller holds the lock and
        closes them OUTSIDE it)."""
        if not self.idle_s:
            return []
        cutoff = time.monotonic() - self.idle_s
        doomed: "list[RpcClient]" = []
        for addr in list(self._idle):
            fresh = []
            for client, ts in self._idle[addr]:
                if ts < cutoff:
                    doomed.append(client)
                    self._count[addr] = max(
                        0, self._count.get(addr, 1) - 1)
                else:
                    fresh.append((client, ts))
            if fresh:
                self._idle[addr] = fresh
            else:
                del self._idle[addr]
        if doomed:
            self._cond.notify_all()
        return doomed

    def acquire(self, addr: str, timeout_s: "float | None" = 30.0
                ) -> RpcClient:
        """Exclusive lease of one connection to ``addr`` ("host:port").
        Reuses an idle one, builds below the per-target cap, else waits
        for a release."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        with self._cond:
            doomed = self._prune_locked()
            while True:
                if self._closed:
                    raise RpcError("client pool is closed")
                idle = self._idle.get(addr)
                if idle:
                    client = idle.pop()[0]
                    break
                if self._count.get(addr, 0) < self._cap:
                    # reserve the slot, build OUTSIDE the lock (a slow
                    # connect must not block other targets' leases)
                    self._count[addr] = self._count.get(addr, 0) + 1
                    client = None
                    break
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"no shuffle connection to {addr} became free "
                        f"within {timeout_s:.0f}s")
                self._cond.wait(timeout=remaining)
        for c in doomed:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — already idle-expired
                pass
        if client is not None:
            return client
        try:
            host, _, port = addr.rpartition(":")
            client = self._factory(host, int(port))
            with self._cond:
                self.connects += 1
            return client
        except BaseException:
            with self._cond:
                self._count[addr] = self._count.get(addr, 1) - 1
                self._cond.notify()
            raise

    def release(self, addr: str, client: RpcClient,
                dead: bool = False) -> None:
        """Return a leased connection. ``dead=True`` (transport error,
        or responses abandoned mid-pipeline) closes it and frees the
        slot — the next acquire dials fresh."""
        if dead or getattr(client, "outstanding", 0):
            try:
                client.close()
            except Exception:  # noqa: BLE001 — already broken
                pass
            with self._cond:
                self._count[addr] = max(0, self._count.get(addr, 1) - 1)
                self._cond.notify()
            return
        with self._cond:
            doomed = self._prune_locked()
            if self._closed:
                self._count[addr] = max(0, self._count.get(addr, 1) - 1)
                doomed.append(client)
            else:
                self._idle.setdefault(addr, []).append(
                    (client, time.monotonic()))
                self._cond.notify()
        for c in doomed:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown/idle-expired
                pass

    def close(self) -> None:
        with self._cond:
            self._closed = True
            idle = [c for lst in self._idle.values() for c, _ in lst]
            self._idle.clear()
            self._cond.notify_all()
        for c in idle:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown
                pass


class _Proxy:
    def __init__(self, client: RpcClient, namespace: str = "") -> None:
        self._client = client
        self._ns = namespace

    def __getattr__(self, name: str):
        method = f"{self._ns}.{name}" if self._ns else name
        return lambda *params: self._client.call(method, *params)


def get_proxy(host: str, port: int, protocol_version: int | None = None,
              namespace: str = "", timeout: float = 30.0,
              secret: "bytes | None" = None,
              scope: "str | None" = None) -> Any:
    """Create a method proxy; verifies the protocol version handshake when
    ``protocol_version`` is given (≈ RPC.getProxy + VersionedProtocol)."""
    client = RpcClient(host, port, timeout=timeout, secret=secret,
                       scope=scope)
    proxy = _Proxy(client, namespace)
    if protocol_version is not None:
        remote = proxy.get_protocol_version()
        if remote != protocol_version:
            raise RpcError(f"protocol version mismatch: client "
                           f"{protocol_version}, server {remote}")
    return proxy
