from tpumr.ipc.rpc import RpcServer, RpcClient, RpcError, get_proxy

__all__ = ["RpcServer", "RpcClient", "RpcError", "get_proxy"]
