"""Subprocess line-protocol runners for streaming jobs.

≈ ``org.apache.hadoop.streaming.{PipeMapRed,PipeMapper,PipeReducer}``
(reference: src/contrib/streaming/src/java/org/apache/hadoop/streaming/
PipeMapRed.java:50). Contracts kept:

- records cross the pipe as ``key<TAB>value<NL>`` lines; output lines split
  at the first tab (``stream.map.output.field.separator`` honored);
- the REDUCER child receives the sorted stream and does its own grouping —
  streaming reducers see lines, not grouped keys (classic Hadoop streaming
  semantics);
- the stderr side-channel: ``reporter:counter:<group>,<name>,<amount>`` and
  ``reporter:status:<msg>`` update real counters/status
  (≈ PipeMapRed.MRErrorThread);
- job conf is exported to the child environment with dots → underscores
  (≈ PipeMapRed.addJobConfToEnvironment).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import threading
from typing import Any, BinaryIO

from tpumr.mapred.api import MapRunnable, OutputCollector, Reducer, Reporter
from tpumr.streaming.typedbytes import read_pairs, write_pair


def _child_env(conf: Any) -> dict:
    env = dict(os.environ)
    for k, v in conf:
        if isinstance(v, (str, int, float, bool)):
            env[str(k).replace(".", "_")] = str(v)
    return env


def _split_line(line: bytes, sep: bytes) -> tuple[str, str]:
    head, tab, tail = line.partition(sep)
    return head.decode("utf-8", "replace"), tail.decode("utf-8", "replace")


def _stderr_pump(stream: BinaryIO, reporter: Reporter) -> threading.Thread:
    """Parse the reporter: protocol off the child's stderr
    (≈ PipeMapRed.MRErrorThread); everything else is passed through."""

    def run() -> None:
        for raw in stream:
            line = raw.decode("utf-8", "replace").rstrip("\n")
            if line.startswith("reporter:counter:"):
                try:
                    group, name, amount = line[len("reporter:counter:"):] \
                        .split(",", 2)
                    reporter.incr_counter(group, name, int(amount))
                    continue
                except ValueError:
                    pass
            elif line.startswith("reporter:status:"):
                reporter.set_status(line[len("reporter:status:"):])
                continue
            import sys
            print(line, file=sys.stderr)

    t = threading.Thread(target=run, name="stream-stderr", daemon=True)
    t.start()
    return t


class _StreamProcess:
    """One child + stdin writer / stdout reader plumbing shared by the map
    and reduce sides. ``in_mode``/``out_mode`` select the wire format each
    direction: "text" (key<TAB>value lines) or "typedbytes" (binary-safe
    typed frames ≈ -io typedbytes, typedbytes/TypedBytesInput.java)."""

    def __init__(self, conf: Any, command: str, output: OutputCollector,
                 reporter: Reporter, in_mode: str = "text",
                 out_mode: str = "text") -> None:
        self.sep = conf.get("stream.map.output.field.separator", "\t") \
            .encode("utf-8")
        self.in_mode = in_mode
        self.out_mode = out_mode
        self.proc = subprocess.Popen(
            shlex.split(command), env=_child_env(conf),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE)
        self._err_thread = _stderr_pump(self.proc.stderr, reporter)
        self._out_error: BaseException | None = None
        self._out_thread = threading.Thread(
            target=self._drain_stdout, args=(output,),
            name="stream-stdout", daemon=True)
        self._out_thread.start()

    def _drain_stdout(self, output: OutputCollector) -> None:
        try:
            if self.out_mode == "typedbytes":
                for k, v in read_pairs(self.proc.stdout):
                    output.collect(k, v)
                return
            for raw in self.proc.stdout:
                line = raw.rstrip(b"\n")
                if not line:
                    continue
                k, v = _split_line(line, self.sep)
                output.collect(k, v)
        except BaseException as e:  # noqa: BLE001 — surfaced by finish()
            self._out_error = e
            # keep draining so a still-writing child never blocks on a
            # full pipe (which would hang finish()'s proc.wait forever)
            try:
                while self.proc.stdout.read(65536):
                    pass
            except OSError:
                pass

    def write_record(self, key: Any, value: Any) -> None:
        if self.in_mode == "typedbytes":
            write_pair(self.proc.stdin, key, value)
            return
        self.proc.stdin.write(f"{key}\t{value}\n".encode("utf-8"))

    def write_line(self, value: Any) -> None:
        self.proc.stdin.write(f"{value}\n".encode("utf-8"))

    def finish(self, what: str) -> None:
        self.proc.stdin.close()
        self._out_thread.join()
        self._err_thread.join()
        rc = self.proc.wait()
        if rc != 0:
            raise RuntimeError(
                f"streaming {what} exited rc={rc} "
                f"(≈ PipeMapRed 'subprocess failed with code')")
        if self._out_error is not None:
            raise RuntimeError(
                f"streaming {what} output protocol error: "
                f"{self._out_error}") from self._out_error


class StreamMapRunner(MapRunnable):
    """Map side ≈ PipeMapper: stream every input record to the child, collect
    its stdout lines."""

    def __init__(self) -> None:
        self.conf: Any = None

    def configure(self, conf: Any) -> None:
        self.conf = conf

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        command = self.conf.get("stream.map.command")
        if not command:
            raise ValueError("streaming job missing stream.map.command")
        in_mode = self.conf.get("stream.map.input", "text")
        out_mode = self.conf.get("stream.map.output", "text")
        # text input feeds the child only the line, not the byte offset
        # (≈ PipeMapper.ignoreKey for TextInputFormat); typed-bytes input
        # always frames full pairs (≈ PipeMapper with -io typedbytes)
        ignore_key = in_mode != "typedbytes" and self.conf.get_boolean(
            "stream.map.input.ignoreKey",
            self.conf.get_input_format().__name__ == "TextInputFormat")
        child = _StreamProcess(self.conf, command, output, reporter,
                               in_mode=in_mode, out_mode=out_mode)
        try:
            for key, value in reader:
                if ignore_key:
                    child.write_line(value)
                else:
                    child.write_record(key, value)
        finally:
            child.finish("mapper")


class StreamReducer(Reducer):
    """Reduce side ≈ PipeReducer: the child consumes the whole sorted
    partition as lines and groups keys itself."""

    def __init__(self) -> None:
        self.conf: Any = None
        self._child: _StreamProcess | None = None

    def configure(self, conf: Any) -> None:
        self.conf = conf

    def reduce(self, key, values, output, reporter) -> None:
        if self._child is None:
            command = self.conf.get("stream.reduce.command")
            if not command:
                raise ValueError("streaming job missing stream.reduce.command")
            self._child = _StreamProcess(
                self.conf, command, output, reporter,
                in_mode=self.conf.get("stream.reduce.input", "text"),
                out_mode=self.conf.get("stream.reduce.output", "text"))
        for v in values:
            self._child.write_record(key, v)

    def close(self) -> None:
        if self._child is not None:
            try:
                self._child.finish("reducer")
            finally:
                self._child = None


class StreamCombiner(StreamReducer):
    """Combiner through a child process (``stream.combine.command``) — one
    child per spill, since a combiner must see a complete sorted buffer."""

    def reduce(self, key, values, output, reporter) -> None:
        if self._child is None:
            command = self.conf.get("stream.combine.command")
            if not command:
                raise ValueError("streaming job missing stream.combine.command")
            self._child = _StreamProcess(
                self.conf, command, output, reporter,
                in_mode=self.conf.get("stream.reduce.input", "text"),
                out_mode=self.conf.get("stream.reduce.output", "text"))
        for v in values:
            self._child.write_record(key, v)
