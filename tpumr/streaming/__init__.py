"""Streaming tier: script mappers/reducers over stdin/stdout.

≈ the reference's contrib streaming (src/contrib/streaming/.../
PipeMapRed.java:50 and friends): any executable that reads
tab-separated key/value lines on stdin and writes them on stdout can be a
mapper or reducer. The stderr side-channel (``reporter:counter:...`` /
``reporter:status:...``) is carried over unchanged.
"""

from tpumr.streaming.pipe_runner import (StreamCombiner, StreamMapRunner,
                                         StreamReducer)
from tpumr.streaming.stream_job import StreamJob, setup_stream_job

__all__ = ["StreamMapRunner", "StreamReducer", "StreamCombiner",
           "StreamJob", "setup_stream_job"]
