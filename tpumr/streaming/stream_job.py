"""Streaming job builder + CLI.

≈ ``org.apache.hadoop.streaming.StreamJob`` (reference: src/contrib/
streaming/src/java/org/apache/hadoop/streaming/StreamJob.java): translate
``-mapper/-reducer/-combiner/-input/-output/-file`` options into a job conf
wired to the subprocess runners.
"""

from __future__ import annotations

from tpumr.mapred.jobconf import JobConf


def setup_stream_job(conf: JobConf, mapper: str | None = None,
                     reducer: str | None = None,
                     combiner: str | None = None,
                     io: str | None = None) -> None:
    from tpumr.streaming.pipe_runner import (StreamCombiner, StreamMapRunner,
                                             StreamReducer)
    if mapper:
        conf.set("stream.map.command", mapper)
        conf.set_map_runner_class(StreamMapRunner)
    if reducer == "aggregate":
        # ≈ StreamJob's `-reducer aggregate`: the script mapper emits
        # '<TYPE>:<id>\tvalue' lines; the framework-side aggregate
        # reducer/combiner fold them (lib/aggregate role)
        from tpumr.mapred.lib import (ValueAggregatorCombiner,
                                      ValueAggregatorReducer)
        conf.set_reducer_class(ValueAggregatorReducer)
        conf.set_combiner_class(ValueAggregatorCombiner)
    elif reducer:
        conf.set("stream.reduce.command", reducer)
        conf.set_reducer_class(StreamReducer)
    if combiner:
        conf.set("stream.combine.command", combiner)
        conf.set_combiner_class(StreamCombiner)
    if io:
        # ≈ StreamJob -io typedbytes: one flag sets all four directions
        if io not in ("text", "typedbytes"):
            raise ValueError(f"unknown -io format {io!r} "
                             "(expected text or typedbytes)")
        for key in ("stream.map.input", "stream.map.output",
                    "stream.reduce.input", "stream.reduce.output"):
            conf.set(key, io)


class StreamJob:
    """Programmatic builder ≈ StreamJob.createJob."""

    def __init__(self) -> None:
        self.conf = JobConf()

    def set_mapper(self, cmd: str) -> "StreamJob":
        setup_stream_job(self.conf, mapper=cmd)
        return self

    def set_reducer(self, cmd: str) -> "StreamJob":
        setup_stream_job(self.conf, reducer=cmd)
        return self

    def set_combiner(self, cmd: str) -> "StreamJob":
        setup_stream_job(self.conf, combiner=cmd)
        return self

    def run(self):
        from tpumr.mapred.job_client import JobClient
        return JobClient(self.conf).run_job(self.conf)


def main(argv: list[str]) -> int:
    """CLI ≈ bin/hadoop jar hadoop-streaming.jar …"""
    import argparse
    ap = argparse.ArgumentParser(prog="tpumr streaming")
    ap.add_argument("-input", dest="input", required=True, action="append")
    ap.add_argument("-output", dest="output", required=True)
    ap.add_argument("-mapper", dest="mapper", default=None)
    ap.add_argument("-reducer", dest="reducer", default=None)
    ap.add_argument("-combiner", dest="combiner", default=None)
    ap.add_argument("-numReduceTasks", dest="reduces", type=int, default=1)
    ap.add_argument("-io", dest="io", default=None,
                    choices=["text", "typedbytes"])
    ap.add_argument("-jobconf", "-D", dest="jobconf", action="append",
                    default=[])
    args = ap.parse_args(argv)

    conf = JobConf()
    conf.set_input_paths(*args.input)
    conf.set_output_path(args.output)
    conf.set_num_reduce_tasks(args.reduces)
    for kv in args.jobconf:
        k, _, v = kv.partition("=")
        conf.set(k.strip(), v.strip())
    setup_stream_job(conf, mapper=args.mapper, reducer=args.reducer,
                     combiner=args.combiner, io=args.io)
    from tpumr.mapred.job_client import JobClient
    result = JobClient(conf).run_job(conf)
    return 0 if result.successful else 1
