"""Typed-bytes: the binary-safe streaming wire format.

≈ ``org.apache.hadoop.typedbytes.{Type,TypedBytesInput,TypedBytesOutput}``
(reference: src/contrib/streaming/src/java/org/apache/hadoop/typedbytes/,
selected by StreamJob's ``-io typedbytes``): each value crosses the child
pipe as a 1-byte type code followed by a big-endian payload, so keys and
values may contain ANY bytes — newlines, tabs, NULs — that the default
line protocol cannot carry.

Wire format (Type.java codes, byte-for-byte compatible so existing
typed-bytes tools — dumbo-style scripts, the reference's own loadtb/
dumptb — interoperate):

====  =========  ==========================================
code  type       payload
====  =========  ==========================================
0     BYTES      int32 length + raw bytes
1     BYTE       1 signed byte
2     BOOL       1 byte (0/1)
3     INT        int32 big-endian
4     LONG       int64 big-endian
5     FLOAT      IEEE-754 float32 big-endian
6     DOUBLE     IEEE-754 float64 big-endian
7     STRING     int32 length + UTF-8 bytes
8     VECTOR     int32 count + that many typed values
9     LIST       typed values until a MARKER byte
10    MAP        int32 count + count × (typed key, typed value)
255   MARKER     (terminates LIST)
====  =========  ==========================================

Python mapping on write: bytes→BYTES, bool→BOOL, int→INT when it fits 32
bits else LONG, float→DOUBLE, str→STRING, tuple→VECTOR, list→LIST,
dict→MAP. On read, BYTE→int, FLOAT→float, VECTOR→tuple, LIST→list.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Iterator

BYTES, BYTE, BOOL, INT, LONG, FLOAT, DOUBLE, STRING = range(8)
VECTOR, LIST, MAP = 8, 9, 10
MARKER = 255

_INT32_MIN, _INT32_MAX = -(1 << 31), (1 << 31) - 1


class TypedBytesError(ValueError):
    pass


def write_typed(out: BinaryIO, obj: Any) -> None:
    """Write one typed value (≈ TypedBytesOutput.write)."""
    if isinstance(obj, bool):  # before int: bool is an int subclass
        out.write(bytes((BOOL, 1 if obj else 0)))
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.write(struct.pack(">Bi", BYTES, len(b)))
        out.write(b)
    elif isinstance(obj, int):
        if _INT32_MIN <= obj <= _INT32_MAX:
            out.write(struct.pack(">Bi", INT, obj))
        else:
            out.write(struct.pack(">Bq", LONG, obj))
    elif isinstance(obj, float):
        out.write(struct.pack(">Bd", DOUBLE, obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.write(struct.pack(">Bi", STRING, len(b)))
        out.write(b)
    elif isinstance(obj, tuple):
        out.write(struct.pack(">Bi", VECTOR, len(obj)))
        for el in obj:
            write_typed(out, el)
    elif isinstance(obj, list):
        out.write(bytes((LIST,)))
        for el in obj:
            write_typed(out, el)
        out.write(bytes((MARKER,)))
    elif isinstance(obj, dict):
        out.write(struct.pack(">Bi", MAP, len(obj)))
        for k, v in obj.items():
            write_typed(out, k)
            write_typed(out, v)
    else:
        raise TypedBytesError(
            f"no typed-bytes encoding for {type(obj).__name__}")


def write_pair(out: BinaryIO, key: Any, value: Any) -> None:
    write_typed(out, key)
    write_typed(out, value)


def _read_exact(inp: BinaryIO, n: int) -> bytes:
    data = inp.read(n)
    if data is None or len(data) != n:
        raise EOFError("typed-bytes stream truncated")
    return data


def read_typed(inp: BinaryIO) -> Any:
    """Read one typed value (≈ TypedBytesInput.read); raises EOFError at a
    clean end of stream, TypedBytesError on an unknown code."""
    head = inp.read(1)
    if not head:
        raise EOFError("end of typed-bytes stream")
    return _read_body(inp, head[0])


def _read_body(inp: BinaryIO, code: int) -> Any:
    """Payload for an already-consumed type code."""
    if code == BYTES:
        (n,) = struct.unpack(">i", _read_exact(inp, 4))
        return _read_exact(inp, n)
    if code == BYTE:
        return struct.unpack(">b", _read_exact(inp, 1))[0]
    if code == BOOL:
        return _read_exact(inp, 1)[0] != 0
    if code == INT:
        return struct.unpack(">i", _read_exact(inp, 4))[0]
    if code == LONG:
        return struct.unpack(">q", _read_exact(inp, 8))[0]
    if code == FLOAT:
        return struct.unpack(">f", _read_exact(inp, 4))[0]
    if code == DOUBLE:
        return struct.unpack(">d", _read_exact(inp, 8))[0]
    if code == STRING:
        (n,) = struct.unpack(">i", _read_exact(inp, 4))
        return _read_exact(inp, n).decode("utf-8")
    if code == VECTOR:
        (n,) = struct.unpack(">i", _read_exact(inp, 4))
        return tuple(read_typed(inp) for _ in range(n))
    if code == LIST:
        out = []
        while True:
            try:
                out.append(read_typed(inp))
            except _Marker:
                return out
    if code == MAP:
        (n,) = struct.unpack(">i", _read_exact(inp, 4))
        return {read_typed(inp): read_typed(inp) for _ in range(n)}
    if code == MARKER:
        raise _Marker()
    raise TypedBytesError(f"unknown typed-bytes code {code}")


class _Marker(TypedBytesError):
    """LIST terminator encountered (an error anywhere but inside a LIST)."""


def read_pairs(inp: BinaryIO) -> Iterator[tuple[Any, Any]]:
    """Iterate (key, value) pairs until end of stream (≈
    TypedBytesRecordReader pair framing). Only a stream ending exactly on
    a pair boundary is a clean end — a key truncated mid-frame, or a
    trailing lone key, raises so a child that died mid-record (or never
    flushed its last record) cannot silently pass for complete output."""
    while True:
        head = inp.read(1)
        if not head:
            return  # clean boundary: no next frame at all
        try:
            key = _read_body(inp, head[0])
        except EOFError:
            raise TypedBytesError(
                "typed-bytes key truncated mid-frame") from None
        try:
            value = read_typed(inp)
        except EOFError:
            raise TypedBytesError("odd number of typed-bytes values "
                                  "(dangling key at end of stream)") from None
        yield key, value
