"""Control-plane scale bench: where does the master saturate?

Ramps a simulated-tracker fleet (``tpumr/scale/``) against a real
``JobMaster`` — real RPC sockets, real heartbeat handling, real
scheduler passes, real completion-event polls; only task execution is
a timed no-op — and records, per fleet size, the master's saturation
series:

- ``heartbeat_p50_s`` / ``heartbeat_p99_s`` — master-side handling
  latency including deferred history I/O (``heartbeat_seconds``);
- ``heartbeat_lag_p99_s``   — scheduled-interval overrun per tracker
  (``heartbeat_lag_seconds``): the first externally visible symptom;
- ``lock_wait_p99_s``       — queueing on the GLOBAL master lock
  (``jt_lock_wait_seconds{lock=global}``), with hold time, the striped
  tracker-registry and scheduler locks, and the derived
  ``lock_wait_share`` (lock wait p99 / heartbeat p99 — ~1.0 means the
  lock IS the latency) alongside;
- ``assign_p99_s``          — scheduler pass cost (``assign_seconds``);
- ``rpc_inflight_peak``     — high-water concurrently dispatched RPCs;
- ``completion_event_lag_p99`` — events pending per reduce poll;
- ``cpu_share_{fold,assign,rpc,history,other}`` — where the master's
  CPU went, from the continuous sampler (``tpumr/metrics/sampler.py``)
  running at its default hz DURING the ramp — so the SLO gate also
  proves profiling overhead fits inside the SLO — plus
  ``gil_delay_p99``, the sampler's GIL-scheduling-delay proxy.

Each fleet size gets a FRESH master so rows are independent
distributions, not cumulative smears. The report names the max
sustainable fleet size at a p99 heartbeat-latency SLO
(``TPUMR_SCALE_SLO_MS``, default 250 ms) — the baseline number every
control-plane refactor (heartbeat batching, sharded master internals)
must move.

Output contract (same shape as ``bench.py``/``bench_shuffle.py``): ONE
JSON line on stdout {"metric", "value", "unit", "vs_baseline"}; every
per-size row goes to stderr and to ``bench_scale.json``. env
BENCH_SCALE=small (or --smoke) shrinks the ramp for CI smoke runs.
"""

from __future__ import annotations

import json
import os
import sys
import time

# measure the production configuration: the debug lock-order assertion
# (metrics/locks.py) is a development aid a deployed master would run
# without (python -O); honor an explicit override. Must be set before
# any tpumr import (the flag is read at module load).
os.environ.setdefault("TPUMR_LOCK_ORDER_CHECK", "0")


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small" or "--smoke" in sys.argv

#: fleet ramp (≥ 4 sizes in every mode — the per-size rows ARE the
#: trajectory) and the heartbeat interval the fleet schedules against
FLEETS = [4, 8, 12, 16] if SMALL else [25, 50, 100, 200, 400]
INTERVAL_S = 0.05 if SMALL else 0.1

#: fleet sizes for the master-restart recovery series (kill→first
#: post-restart assignment): smaller than the ramp — the series
#: measures the recovery protocol, not saturation
RECOVERY_FLEETS = [4, 8] if SMALL else [50, 200]

#: the SHARDED continuation of the ramp: (trackers, shards, batch).
#: One Python process tops out around the committed 400-tracker row;
#: these rows prove the partitioned master carries the fleet sizes the
#: single process cannot. Batch sizes are the heartbeat-coalescing
#: knob (tpumr.heartbeat.batch) the fleet mirrors client-side.
SHARD_FLEETS = [(16, 2, 8)] if SMALL else \
    [(600, 4, 16), (1200, 4, 32), (2000, 4, 32)]

#: shard-kill recovery series: (trackers, shards, batch) — the PR-9
#: master-restart bar (kill→first assignment well under a second),
#: now scoped to one shard while its siblings keep serving
SHARD_RECOVERY = [(8, 2, 4)] if SMALL else [(200, 4, 16)]

#: p99 heartbeat-latency SLO the "max sustainable fleet" is judged at
SLO_S = float(os.environ.get("TPUMR_SCALE_SLO_MS", "250")) / 1000.0

#: master-controlled adaptive heartbeat cadence
#: (tpumr.heartbeat.beats.per.second — the decomposed master's answer
#: to beat-rate saturation, ≈ mapreduce.jobtracker.heartbeats.in.
#: second): the master targets this AGGREGATE rate and instructs each
#: tracker's next interval in the heartbeat response; the configured
#: interval stays the FLOOR, so rows up to rate × floor trackers keep
#: the exact fixed-cadence baseline semantics. 800/s is sized to this
#: harness's measured single-core beat capacity (~1300 full client+
#: master beat round-trips/s when fleet and master share one core)
#: with ~40% queueing headroom — past ~80% utilization the 5 ms GIL
#: scheduling quanta push the lag p99 tail over the SLO even though
#: mean throughput keeps up. The instructable interval is CAPPED at
#: 2x the SLO (bounded staleness, recorded per row as
#: interval_instructed_ms), so adaptation degrades cadence smoothly
#: but can never trade unbounded staleness for a passing row.
BEATS_PER_SECOND = int(os.environ.get("TPUMR_SCALE_BEAT_RATE", "800"))


def _p(h: "dict | None", q: str) -> float:
    return float((h or {}).get(q, 0.0))


def _log_row(row: dict) -> None:
    tag = (f" ({row['shards']} shards, batch {row['batch']})"
           if row.get("shards") else "")
    log(f"[scale] {row['trackers']:4d} trackers{tag}: hb p50 "
        f"{row['heartbeat_p50_s'] * 1e3:.2f}ms p99 "
        f"{row['heartbeat_p99_s'] * 1e3:.2f}ms · lag p99 "
        f"{row['heartbeat_lag_p99_s'] * 1e3:.2f}ms · lock wait p99 "
        f"{row['lock_wait_p99_s'] * 1e3:.2f}ms (share "
        f"{row['lock_wait_share']:.2f}) · assign p99 "
        f"{row['assign_p99_s'] * 1e3:.2f}ms · inflight peak "
        f"{row['rpc_inflight_peak']} · interval "
        f"{row['interval_instructed_ms']}ms · "
        f"{row['heartbeats']} beats, {row['tasks_completed']} tasks "
        f"in {row['wall_s']:.1f}s · cpu "
        f"fold {row['cpu_share_fold']:.0%}/assign "
        f"{row['cpu_share_assign']:.0%}/rpc {row['cpu_share_rpc']:.0%}"
        f"/hist {row['cpu_share_history']:.0%}/other "
        f"{row['cpu_share_other']:.0%} · gil p99 "
        f"{row['gil_delay_p99'] * 1e3:.1f}ms"
        + ("" if row["completed"] else " · WORKLOAD INCOMPLETE"))


def run_step(n_trackers: int, interval_s: float,
             wait_timeout_s: float, shards: int = 0,
             batch: int = 0) -> dict:
    """One ramp step: fresh master, fleet of ``n_trackers``, a synthetic
    multi-job workload sized to keep every slot busy for a few seconds,
    then one snapshot of the master's saturation series. ``shards`` > 0
    measures the partitioned master (the fleet batches ``batch`` beats
    per RPC straight to each tracker's owning shard); the latency series
    then comes from the coordinator's MERGED registries and the
    ``cpu_share_*`` columns from each shard's own sampler."""
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.shardmaster import make_master
    from tpumr.scale import ScaleDriver, SimFleet

    conf = JobConf()
    conf.set("tpumr.heartbeat.interval.ms", int(interval_s * 1000))
    if shards:
        conf.set("tpumr.master.shards", shards)
    if batch:
        conf.set("tpumr.heartbeat.batch", batch)
    # the continuous profiler runs DURING the ramp at its default hz:
    # every row's latency series is measured with sampling on, so the
    # SLO gate also proves the profiler's overhead fits inside it —
    # and the row gains the cpu_share_* attribution columns (where the
    # master's CPU went at this fleet size)
    conf.set("tpumr.prof.enabled", True)
    # adaptive cadence: configured interval is the floor, 2x the SLO
    # is the ceiling — rows ≤ target_rate × floor trackers keep the
    # exact baseline cadence, larger fleets are instructed (and their
    # lag is measured) against a coarser but staleness-bounded schedule
    conf.set("tpumr.heartbeat.beats.per.second", BEATS_PER_SECOND)
    conf.set("tpumr.heartbeat.interval.max.ms", int(2 * SLO_S * 1000))
    # lagging trackers under saturation must stay registered — eviction
    # mid-row would re-queue work and double-count the chaos
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    master = make_master(conf).start()
    host, port = master.address

    if shards:
        # thousands of trackers on this harness: one slot each and
        # tasks of many beat intervals, so assignment + completion
        # traffic (piggybacked on beats) stays a fraction of the
        # 1/interval-cap beat rate the row is actually measuring —
        # at 2000 trackers that rate alone is near the harness's
        # whole-core folding capacity
        cpu_slots, reduce_slots = 1, 1
        task_mean_s = 8.0 * interval_s
        target_busy_s = 2.5 if SMALL else 4.0
    else:
        cpu_slots, reduce_slots = 2, 1
        task_mean_s = 3.0 * interval_s
        target_busy_s = 2.5 if SMALL else 6.0
    # size the workload to ~a few seconds of full-fleet occupancy:
    # total_maps ≈ slots × target_busy_s / task_mean — halved for
    # sharded rows (one map per TWO trackers): the workload there is
    # the end-to-end liveness proof riding a beat-rate measurement,
    # and a full-fleet assignment burst would measure the scheduler,
    # not the fold path
    total_maps = max(8, int(cpu_slots * n_trackers * target_busy_s
                            / task_mean_s))
    if shards:
        total_maps = max(8, total_maps // 2)
    n_jobs = max(2, min(4 * shards, n_trackers // 8)) if shards \
        else max(2, n_trackers // 8)
    maps_per_job = max(4, total_maps // n_jobs)
    reduces_per_job = 2

    fleet = SimFleet(host, port, n_trackers, interval_s=interval_s,
                     cpu_slots=cpu_slots, reduce_slots=reduce_slots,
                     task_time_mean_s=task_mean_s, batch=batch,
                     # few fat batches, not many thin workers: beats
                     # in flight ≈ workers × batch × shards, and every
                     # queued beat ages toward the lag SLO while it
                     # waits (Little's law does the rest)
                     workers=(2 * shards if shards else None),
                     shard_map=(master.shard_map() if shards
                                else None)).start()
    driver = ScaleDriver(host, port)
    t0 = time.monotonic()
    try:
        result = driver.run_workload(n_jobs, maps_per_job,
                                     reduces_per_job,
                                     timeout_s=wait_timeout_s,
                                     # completion detection, not a
                                     # measured series: don't let the
                                     # jobs' status polls (proxied
                                     # twice under a coordinator)
                                     # compete with 4000 beats/s for
                                     # the one core
                                     poll_s=(1.0 if shards else
                                             max(0.2, n_jobs / 100.0)))
        wall = time.monotonic() - t0
        if shards:
            # the merged registries trail the shards by one poll —
            # let the fold catch the tail before snapshotting
            time.sleep(2.5 * master.poll_s)
        snap = master.metrics.snapshot()
        jt = snap.get("jobtracker", {})
        fl = fleet.stats()
        row = {
            "trackers": n_trackers,
            "shards": shards,
            "batch": batch,
            "jobs": n_jobs,
            "maps_per_job": maps_per_job,
            "reduces_per_job": reduces_per_job,
            "completed": not result["unfinished"] and
                         not result["failed"],
            "wall_s": round(wall, 3),
            "heartbeats": int(_p(jt.get("heartbeat_seconds"), "count")),
            "heartbeat_p50_s": round(
                _p(jt.get("heartbeat_seconds"), "p50"), 6),
            "heartbeat_p99_s": round(
                _p(jt.get("heartbeat_seconds"), "p99"), 6),
            "heartbeat_lag_p99_s": round(
                _p(jt.get("heartbeat_lag_seconds"), "p99"), 6),
            # the GLOBAL lock (the decomposed master's widest-scope
            # lock — the one the pre-decomposition wall was made of)
            "lock_wait_p99_s": round(
                _p(jt.get("jt_lock_wait_seconds|lock=global"), "p99"), 6),
            "lock_hold_p99_s": round(
                _p(jt.get("jt_lock_hold_seconds|lock=global"), "p99"), 6),
            "lock_wait_trackers_p99_s": round(
                _p(jt.get("jt_lock_wait_seconds|lock=trackers"),
                   "p99"), 6),
            "lock_wait_scheduler_p99_s": round(
                _p(jt.get("jt_lock_wait_seconds|lock=scheduler"),
                   "p99"), 6),
            "assign_p99_s": round(
                _p(snap.get("scheduler", {}).get("assign_seconds"),
                   "p99"), 6),
            "completion_event_lag_p99": round(
                _p(jt.get("completion_event_lag"), "p99"), 2),
            "rpc_inflight_peak": master._server.inflight_peak(),
            # the cadence the master was instructing at full fleet —
            # == the configured floor until adaptation binds; the
            # lag series above is judged against THIS schedule
            "interval_instructed_ms": int(
                jt.get("heartbeat_interval_instructed_ms", 0) or 0),
            "client_rtt_p99_s": round(_p(fl["hb_rtt"], "p99"), 6),
            "client_lag_p99_s": round(_p(fl["hb_lag"], "p99"), 6),
            "hb_errors": int(fl["hb_errors"]),
            "tasks_completed": fl["tasks_completed"],
        }
        # lock wait p99 as a share of heartbeat p99: ~1.0 means the
        # lock IS the latency (the pre-decomposition saturation
        # signature); decoupled means the wall moved elsewhere
        hb = row["heartbeat_p99_s"]
        row["lock_wait_share"] = round(
            row["lock_wait_p99_s"] / hb, 3) if hb > 0 else 0.0
        # subsystem CPU attribution from the continuous sampler (whole-
        # row window): reactor rides with rpc and the shuffle/merger
        # categories (worker-side, ~0 on a master) ride with other, so
        # the five columns sum to ~1.0 whenever any sample landed
        if shards:
            # each shard runs its OWN sampler; the per-shard columns
            # are the proof the load actually spreads, the tracker-
            # weighted mean keeps the aggregate columns comparable
            # with the single-process rows
            stats = master.shard_stats()
            per = {}
            for k, s in sorted(stats.items()):
                sh = s["cpu_shares"] or {}
                per[k] = {
                    "trackers": s["trackers"],
                    "fold": round(sh.get("fold", 0.0), 4),
                    "assign": round(sh.get("assign", 0.0), 4),
                    "rpc": round(sh.get("rpc", 0.0)
                                 + sh.get("reactor", 0.0), 4),
                    "history": round(sh.get("history", 0.0), 4),
                    "other": round(sh.get("other", 0.0)
                                   + sh.get("shuffle", 0.0)
                                   + sh.get("merger", 0.0), 4),
                } if sh else {"trackers": s["trackers"]}
            row["shard_cpu_shares"] = per
            sampled = [(s["trackers"], per[k]) for k, s in stats.items()
                       if s["cpu_shares"]]
            total = sum(w for w, _ in sampled) or 1
            for col in ("fold", "assign", "rpc", "history", "other"):
                row[f"cpu_share_{col}"] = round(
                    sum(w * p[col] for w, p in sampled) / total, 4)
            row["rpc_inflight_peak"] = max(
                (s["rpc_inflight_peak"] for s in stats.values()),
                default=0)
            row["interval_instructed_ms"] = max(
                (s["interval_instructed_ms"] for s in stats.values()),
                default=0)
            row["shard_restarts"] = sum(
                s["restarts"] for s in stats.values())
            row["history_writes_dropped"] = sum(
                s["history_writes_dropped"] for s in stats.values())
        else:
            shares = master.sampler.subsystem_shares()
            row["cpu_share_fold"] = round(shares["fold"], 4)
            row["cpu_share_assign"] = round(shares["assign"], 4)
            row["cpu_share_rpc"] = round(
                shares["rpc"] + shares["reactor"], 4)
            row["cpu_share_history"] = round(shares["history"], 4)
            row["cpu_share_other"] = round(
                shares["other"] + shares["shuffle"] + shares["merger"], 4)
            row["history_writes_dropped"] = int(
                jt.get("history_writes_dropped", 0) or 0)
        row["gil_delay_p99"] = round(
            _p(snap.get("prof", {}).get("gil_delay_seconds"), "p99"), 6)
    finally:
        fleet.stop()
        driver.close()
        master.stop()
    return row


def _log_recovery_row(row: dict) -> None:
    log(f"[scale] recovery @ {row['trackers']:4d} trackers: master "
        f"kill→restart {row['restart_s'] * 1e3:.0f}ms · kill→first "
        f"assignment {row['recovery_first_assign_s'] * 1e3:.0f}ms · "
        f"{row['jobs_recovered']} jobs / {row['attempts_recovered']} "
        f"attempts recovered · {row['trackers_adopted']} trackers "
        f"adopted"
        + ("" if row["completed"] else " · WORKLOAD INCOMPLETE"))


def run_recovery_step(n_trackers: int, interval_s: float,
                      wait_timeout_s: float) -> dict:
    """Master-restart recovery time under a live fleet: run a workload
    to ~1/3 map completion, kill the master (stop with no goodbye),
    restart it on the same address with attempt-level recovery on, and
    measure kill→first post-restart task assignment — the window in
    which the cluster makes no scheduling progress. The fleet keeps its
    fake in-flight work running throughout (lost-master semantics), the
    driver keeps polling the OLD job ids (the job_recovered alias), and
    the workload must still complete. The recovery grace (sized to a
    few beats here, since the whole fleet re-joins within ~1 interval)
    is deliberately INSIDE the measured window: waiting for re-joins IS
    recovery time."""
    import shutil
    import tempfile

    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.jobtracker import JobMaster
    from tpumr.scale import ScaleDriver, SimFleet

    hist = tempfile.mkdtemp(prefix="tpumr-bench-recovery-")

    def _conf(recover: bool) -> "JobConf":
        conf = JobConf()
        conf.set("tpumr.history.dir", hist)
        conf.set("tpumr.heartbeat.interval.ms", int(interval_s * 1000))
        conf.set("tpumr.tracker.expiry.ms", 60_000)
        conf.set("mapred.jobtracker.restart.recover", recover)
        conf.set("mapred.jobtracker.restart.recovery.grace.ms",
                 int(4 * interval_s * 1000))
        return conf

    master = JobMaster(_conf(False)).start()
    host, port = master.address
    fleet = SimFleet(host, port, n_trackers, interval_s=interval_s,
                     cpu_slots=2, reduce_slots=1,
                     task_time_mean_s=6.0 * interval_s).start()
    driver = ScaleDriver(host, port)
    m2 = None
    try:
        n_jobs = max(2, n_trackers // 8)
        total_maps = 8 * 2 * n_trackers        # ~4 waves over the slots
        maps_per_job = max(8, total_maps // n_jobs)
        ids = driver.submit(n_jobs, maps_per_job, 2)
        deadline = time.monotonic() + wait_timeout_s

        def _finished_maps() -> int:
            done = 0
            for jid in ids:
                try:
                    done += driver.client.call("get_job_status",
                                               jid)["finished_maps"]
                except Exception:  # noqa: BLE001 — restart window
                    pass
            return done

        while _finished_maps() < (n_jobs * maps_per_job) // 3:
            if time.monotonic() > deadline:
                raise TimeoutError("workload never reached 1/3 maps")
            time.sleep(5 * interval_s)
        t_kill = time.monotonic()
        master.stop()
        for _ in range(250):
            try:
                m2 = JobMaster(_conf(True), host=host,
                               port=port).start()
                break
            except OSError:
                time.sleep(0.02)
        if m2 is None:
            raise RuntimeError("could not rebind the master port")
        t_up = time.monotonic()

        def _launched() -> int:
            jt = m2.metrics.snapshot().get("jobtracker", {})
            return int(jt.get("maps_launched_cpu", 0)
                       + jt.get("maps_launched_tpu", 0)
                       + jt.get("reduces_launched", 0))

        while _launched() == 0 and time.monotonic() < deadline:
            time.sleep(interval_s / 10)
        t_first = time.monotonic()
        result = driver.wait(ids, timeout_s=max(
            5.0, deadline - time.monotonic()), poll_s=0.5)
        jt = m2.metrics.snapshot().get("jobtracker", {})
        return {
            "trackers": n_trackers,
            "jobs": n_jobs,
            "maps_per_job": maps_per_job,
            "interval_s": interval_s,
            "grace_s": 4 * interval_s,
            "restart_s": round(t_up - t_kill, 3),
            "recovery_first_assign_s": round(t_first - t_kill, 3),
            "jobs_recovered": int(jt.get("jobs_recovered", 0)),
            "attempts_recovered": int(jt.get("attempts_recovered", 0)),
            "trackers_adopted": int(jt.get("trackers_adopted", 0)),
            "completed": not result["unfinished"]
                         and not result["failed"],
        }
    finally:
        fleet.stop()
        driver.close()
        (m2 if m2 is not None else master).stop()
        shutil.rmtree(hist, ignore_errors=True)


def run_recovery_bench(fleets: "list[int] | None" = None,
                       interval_s: "float | None" = None,
                       wait_timeout_s: "float | None" = None) -> list:
    """The recovery-time series (non-gating): one row per fleet size;
    a failed step becomes an error row rather than failing the bench."""
    rows = []
    for n in fleets or RECOVERY_FLEETS:
        try:
            row = run_recovery_step(n, interval_s or INTERVAL_S,
                                    wait_timeout_s
                                    or (60.0 if SMALL else 180.0))
        except Exception as e:  # noqa: BLE001 — non-gating series
            log(f"[scale] recovery @ {n} trackers FAILED: {e}")
            rows.append({"trackers": n, "error": str(e)})
            continue
        rows.append(row)
        _log_recovery_row(row)
    return rows


def _log_shard_recovery_row(row: dict) -> None:
    log(f"[scale] shard recovery @ {row['trackers']:4d} trackers "
        f"({row['shards']} shards): kill→respawn "
        f"{row['restart_s'] * 1e3:.0f}ms · kill→first assignment "
        f"{row['recovery_first_assign_s'] * 1e3:.0f}ms · "
        f"{row['jobs_recovered']} jobs / {row['attempts_recovered']} "
        f"attempts recovered · {row['trackers_adopted']} trackers "
        f"adopted · {row['map_reruns']} map re-runs"
        + ("" if row["completed"] else " · WORKLOAD INCOMPLETE"))


def run_shard_kill_step(n_trackers: int, shards: int, batch: int,
                        interval_s: float,
                        wait_timeout_s: float) -> dict:
    """SIGKILL one shard mid-workload and measure the scoped restart:
    kill→respawn (monitor reap + pinned-port rebind + recovery replay)
    and kill→first post-respawn assignment. The victim job's maps are
    ALL folded before the kill (reduces gated on slowstart 1.0), so the
    respawned shard's own launch counters prove zero map re-executions
    — the PR-9 adoption bar, scoped to one shard while its siblings
    keep serving untouched."""
    import shutil
    import tempfile

    from tpumr.ipc.rpc import RpcClient
    from tpumr.mapred.ids import JobID
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.shardmaster import make_master
    from tpumr.scale import ScaleDriver, SimFleet
    from tpumr.security import rpc_secret

    hist = tempfile.mkdtemp(prefix="tpumr-bench-shardkill-")
    conf = JobConf()
    conf.set("tpumr.history.dir", hist)
    conf.set("tpumr.heartbeat.interval.ms", int(interval_s * 1000))
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    conf.set("tpumr.master.shards", shards)
    if batch:
        conf.set("tpumr.heartbeat.batch", batch)
    # like the master-restart series: grace sized to a few beats (the
    # whole sub-fleet re-joins within ~1 interval) and deliberately
    # INSIDE the measured window — waiting for re-joins IS recovery
    conf.set("mapred.jobtracker.restart.recovery.grace.ms",
             int(4 * interval_s * 1000))

    master = make_master(conf).start()
    host, port = master.address
    shard_map = master.shard_map()
    fleet = SimFleet(host, port, n_trackers, interval_s=interval_s,
                     cpu_slots=2, reduce_slots=1,
                     task_time_mean_s=3.0 * interval_s,
                     secret=rpc_secret(conf), batch=batch,
                     shard_map=shard_map).start()
    driver = ScaleDriver(host, port, secret=rpc_secret(conf))
    victim_shard = 1 % shards
    try:
        # one job per shard (round-robin), all maps folded before the
        # kill (slowstart 1.0 holds reduces until then), and a reduce
        # phase of SEVERAL waves so the job is reliably still
        # incomplete when the kill lands — a finished job would
        # recover nothing and the row would measure an empty restart
        maps_per_job = 2 * max(1, n_trackers // shards)
        reduces_per_job = 4 * max(1, n_trackers // shards)
        ids = driver.submit(
            shards, maps_per_job, reduces_per_job,
            **{"mapred.reduce.slowstart.completed.maps": 1.0})
        victim = next(j for j in ids if JobID.parse(j).cluster
                      .endswith(f"s{victim_shard}"))
        deadline = time.monotonic() + wait_timeout_s
        while time.monotonic() < deadline:
            if driver.client.call("get_job_status",
                                  victim)["finished_maps"] \
                    >= maps_per_job:
                break
            time.sleep(interval_s)
        else:
            raise TimeoutError("victim job's maps never all finished")

        t_kill = time.monotonic()
        master.kill_shard(victim_shard)
        if not master.wait_shard_ready(
                victim_shard, max(5.0, deadline - time.monotonic())):
            raise TimeoutError("killed shard never re-registered")
        t_up = time.monotonic()

        probe = RpcClient(*master.shard_map()[victim_shard],
                          secret=rpc_secret(conf))

        def _snap() -> dict:
            return probe.call("shard_snapshot")["metrics"][
                "jobtracker"]["counters"]

        def _launched(c: dict) -> int:
            return int(c.get("maps_launched_cpu", 0)
                       + c.get("maps_launched_tpu", 0)
                       + c.get("reduces_launched", 0))

        while _launched(_snap()) == 0 \
                and time.monotonic() < deadline:
            time.sleep(interval_s / 10)
        t_first = time.monotonic()
        result = driver.wait(ids, timeout_s=max(
            5.0, deadline - time.monotonic()), poll_s=0.5)
        c = _snap()
        probe.close()
        return {
            "trackers": n_trackers,
            "shards": shards,
            "batch": batch,
            "jobs": shards,
            "maps_per_job": maps_per_job,
            "reduces_per_job": reduces_per_job,
            "interval_s": interval_s,
            "restart_s": round(t_up - t_kill, 3),
            "recovery_first_assign_s": round(t_first - t_kill, 3),
            "jobs_recovered": int(c.get("jobs_recovered", 0)),
            "attempts_recovered": int(c.get("attempts_recovered", 0)),
            "trackers_adopted": int(c.get("trackers_adopted", 0)),
            # the respawned process's OWN map-launch counters: any
            # nonzero value here is a re-executed (already-folded) map
            "map_reruns": int(c.get("maps_launched_cpu", 0)
                              + c.get("maps_launched_tpu", 0)),
            "completed": not result["unfinished"]
                         and not result["failed"],
        }
    finally:
        fleet.stop()
        driver.close()
        master.stop()
        shutil.rmtree(hist, ignore_errors=True)


def run_shard_recovery_bench(fleets: "list | None" = None,
                             interval_s: "float | None" = None,
                             wait_timeout_s: "float | None" = None
                             ) -> list:
    """The shard-kill recovery series (non-gating, like the restart
    series): one row per (trackers, shards, batch) triple."""
    rows = []
    for n, shards, batch in fleets or SHARD_RECOVERY:
        try:
            row = run_shard_kill_step(
                n, shards, batch, interval_s or INTERVAL_S,
                wait_timeout_s or (60.0 if SMALL else 180.0))
        except Exception as e:  # noqa: BLE001 — non-gating series
            log(f"[scale] shard recovery @ {n} trackers FAILED: {e}")
            rows.append({"trackers": n, "shards": shards,
                         "error": str(e)})
            continue
        rows.append(row)
        _log_shard_recovery_row(row)
    return rows


#: the scenario-lab mixes committed as bench rows: per-class latency
#: percentiles + chaos counters under a pinned seed (deterministic
#: traces), so a control-plane change shows its effect on interactive
#: vs batch SLOs — not just on raw heartbeat percentiles
SCENARIOS = ["steady_mix", "interactive_burst", "churn_storm",
             "overload_brownout", "master_failover", "shard_kill"]
SCENARIO_SEED = 1337


def _scenario_row(rep: dict) -> dict:
    """One committed row per mix: the report minus its bulky per-tick
    window history and replay plan (those live in -report output)."""
    row = {
        "scenario": rep["scenario"], "seed": rep["seed"],
        "wall_s": rep["wall_s"], "pass": rep["pass"],
        "jobs": rep["jobs"], "chaos": rep["chaos"],
        "brownout_max_level": rep["brownout_max_level"],
        "incidents": len(rep["incidents"]),
        "classes": {},
    }
    for cls_name, stats in rep["classes"].items():
        verdict = rep["verdicts"].get(cls_name, {})
        row["classes"][cls_name] = dict(stats,
                                        **{"pass": verdict.get("pass")})
    return row


def run_scenario_bench(names: "list[str] | None" = None,
                       seed: int = SCENARIO_SEED) -> list:
    """The scenario series (gated by --assert-scenarios): one row per
    named mix; a crashed run becomes an error row."""
    from tpumr.scale.scenario import run_named
    rows = []
    for name in names or SCENARIOS:
        try:
            rep = run_named(name, seed=seed)
        except Exception as e:  # noqa: BLE001 — keep the series going
            log(f"[scale] scenario {name} FAILED: {e}")
            rows.append({"scenario": name, "error": str(e)})
            continue
        row = _scenario_row(rep)
        rows.append(row)
        jobs = row["jobs"]
        log(f"[scale] scenario {name}: "
            f"{jobs['succeeded']}/{jobs['submitted']} jobs · "
            f"crashed {row['chaos']['trackers_crashed']} adopted "
            f"{row['chaos']['trackers_adopted']} restarts "
            f"{row['chaos']['master_restarts']} · brownout max "
            f"{row['brownout_max_level']} · "
            f"{'PASS' if row['pass'] else 'FAIL'} in {row['wall_s']}s")
    return rows


def run_bench(fleets: "list[int] | None" = None,
              interval_s: "float | None" = None,
              slo_s: "float | None" = None,
              wait_timeout_s: "float | None" = None) -> dict:
    fleets = fleets or FLEETS
    interval_s = interval_s or INTERVAL_S
    slo_s = slo_s or SLO_S
    wait_timeout_s = wait_timeout_s or (60.0 if SMALL else 180.0)
    # NOTE on the GIL switch interval: an earlier draft forced it to
    # 1 ms hoping for fairer tails; measured on the committed ramp it
    # LOWERED total beat throughput ~25% (hundreds of threads × 5x the
    # switch rate on one core) and pushed lag p99 UP. The default 5 ms
    # measures better on every row — leave it alone.
    rows = []
    for n in fleets:
        row = run_step(n, interval_s, wait_timeout_s)
        rows.append(row)
        _log_row(row)
    # the SLO gates BOTH latency series: handling p99 (the master is
    # slow) and lag p99 (trackers can't keep schedule — beats arriving
    # a second late mean stale statuses and expiring leases long before
    # raw handling time looks bad)
    sustainable = [r["trackers"] for r in rows
                   if r["completed"]
                   and r["heartbeat_p99_s"] <= slo_s
                   and r["heartbeat_lag_p99_s"] <= slo_s]
    return {
        "interval_s": interval_s,
        "beats_per_second": BEATS_PER_SECOND,
        "interval_max_s": 2 * slo_s,
        "slo_s": slo_s,
        "slo_series": ["heartbeat_p99_s", "heartbeat_lag_p99_s"],
        "max_sustainable_trackers": max(sustainable, default=0),
        "rows": rows,
    }


def run_shard_bench(shard_fleets: "list | None" = None,
                    interval_s: "float | None" = None,
                    slo_s: "float | None" = None,
                    wait_timeout_s: "float | None" = None) -> dict:
    """The sharded continuation of the ramp: same columns, same dual-
    p99 SLO judgment, but the master is ``shards`` worker processes and
    the fleet ships ``batch`` beats per RPC. Kept as a separate series
    so the single-process baseline rows stay directly comparable
    release over release."""
    slo_s = slo_s or SLO_S
    rows = []
    for n, shards, batch in shard_fleets or SHARD_FLEETS:
        # sharded rows run AT the staleness cap (2x SLO): that is the
        # cadence the master instructs any multi-thousand fleet to
        # anyway, and configuring it directly skips the adaptive ramp's
        # floor-cadence joining herd — at ~95% of one-core capacity a
        # transient backlog has no slack to drain inside the row
        row = run_step(n, interval_s or (2 * slo_s),
                       wait_timeout_s or (120.0 if SMALL else 300.0),
                       shards=shards, batch=batch)
        rows.append(row)
        _log_row(row)
    sustainable = [r["trackers"] for r in rows
                   if r["completed"]
                   and r["heartbeat_p99_s"] <= slo_s
                   and r["heartbeat_lag_p99_s"] <= slo_s]
    return {
        "max_sustainable_trackers_sharded": max(sustainable, default=0),
        "shard_rows": rows,
    }


def compare_with_prior(prior: "dict | None", report: dict) -> None:
    """One stderr line per common fleet size against a prior
    bench_scale.json — the before/after of a control-plane change in
    one glance (hb p99, lag p99, and whether lock wait still tracks
    heartbeat latency)."""
    if not prior or not prior.get("rows"):
        return
    old = {(r["trackers"], r.get("shards", 0)): r
           for r in (prior.get("rows", [])
                     + prior.get("shard_rows", []))}
    for row in report.get("rows", []) + report.get("shard_rows", []):
        o = old.get((row["trackers"], row.get("shards", 0)))
        if o is None:
            continue
        o_share = o.get("lock_wait_share")
        if o_share is None:   # pre-PR-8 rows lack the derived column
            o_hb = o.get("heartbeat_p99_s", 0.0)
            o_share = (o.get("lock_wait_p99_s", 0.0) / o_hb
                       if o_hb > 0 else 0.0)
        tag = (f" x{row['shards']}sh" if row.get("shards") else "")
        log(f"[scale] vs prior @ {row['trackers']:4d} trackers{tag}: "
            f"hb p99 {o.get('heartbeat_p99_s', 0) * 1e3:.2f}"
            f"->{row['heartbeat_p99_s'] * 1e3:.2f}ms · lag p99 "
            f"{o.get('heartbeat_lag_p99_s', 0) * 1e3:.2f}"
            f"->{row['heartbeat_lag_p99_s'] * 1e3:.2f}ms · "
            f"lock_wait_share {o_share:.2f}"
            f"->{row['lock_wait_share']:.2f}")
    log(f"[scale] vs prior: max sustainable "
        f"{prior.get('max_sustainable_trackers', 0)}"
        f"->{report['max_sustainable_trackers']} trackers")


def main() -> None:
    prior = None
    try:
        with open("bench_scale.json") as f:
            prior = json.load(f)
    except (OSError, ValueError):
        pass
    if "--scenarios-only" in sys.argv:
        # refresh ONLY the scenario-lab series, preserving the
        # committed ramp + recovery rows
        report = prior or {"rows": []}
        report["scenario_rows"] = run_scenario_bench()
        with open("bench_scale.json", "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
        passed = sum(1 for r in report["scenario_rows"]
                     if r.get("pass"))
        print(json.dumps({
            "metric": "scenario lab: mixes passing all per-class SLO "
                      "verdicts under chaos",
            "value": passed, "unit": "scenarios",
            "vs_baseline": 1.0}))
        if "--assert-scenarios" in sys.argv \
                and passed < len(report["scenario_rows"]):
            sys.exit(3)
        return
    if "--shards-only" in sys.argv:
        # refresh ONLY the sharded ramp + shard-kill recovery series,
        # preserving the committed single-process rows (those are the
        # baseline the sharded rows are judged against)
        report = prior or {"rows": []}
        report.update(run_shard_bench())
        report["shard_recovery_rows"] = run_shard_recovery_bench()
        with open("bench_scale.json", "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
        print(json.dumps({
            "metric": "sharded master: max simulated-tracker fleet at "
                      "the dual-p99 SLO",
            "value": report["max_sustainable_trackers_sharded"],
            "unit": "trackers", "vs_baseline": 1.0}))
        if "--assert-slo" in sys.argv and \
                report["max_sustainable_trackers_sharded"] < max(
                    n for n, _, _ in SHARD_FLEETS):
            sys.exit(3)
        return
    if "--recovery-only" in sys.argv:
        # refresh ONLY the master-restart recovery series, preserving
        # the committed ramp rows (the ramp is minutes of measurement;
        # the recovery series is seconds)
        report = prior or {"rows": []}
        report["recovery_rows"] = run_recovery_bench()
        with open("bench_scale.json", "w") as f:
            json.dump(report, f, sort_keys=True, indent=1)
        print(json.dumps({
            "metric": "master-restart recovery: kill→first assignment",
            "value": max((r.get("recovery_first_assign_s", 0.0)
                          for r in report["recovery_rows"]),
                         default=0.0),
            "unit": "s", "vs_baseline": 1.0}))
        return
    report = run_bench()
    # the sharded continuation + both recovery series + the scenario
    # series ride every run (the --assert-slo gate below judges the
    # ramp rows, sharded included; --assert-scenarios the scenarios)
    report.update(run_shard_bench())
    report["recovery_rows"] = run_recovery_bench()
    report["shard_recovery_rows"] = run_shard_recovery_bench()
    report["scenario_rows"] = run_scenario_bench()
    with open("bench_scale.json", "w") as f:
        json.dump(report, f, sort_keys=True, indent=1)
    log(f"detail rows -> bench_scale.json: "
        f"{json.dumps(report, sort_keys=True)}")
    compare_with_prior(prior, report)
    rows = report["rows"]
    shard_rows = report.get("shard_rows", [])
    best = max(report["max_sustainable_trackers"],
               report.get("max_sustainable_trackers_sharded", 0))
    print(json.dumps({
        "metric": f"control-plane scale: max simulated-tracker fleet "
                  f"(single-process ramp {[r['trackers'] for r in rows]}"
                  f" + sharded {[r['trackers'] for r in shard_rows]}, "
                  f"{report['interval_s'] * 1000:.0f}ms heartbeat floor, "
                  f"master-instructed adaptive cadence at "
                  f"{BEATS_PER_SECOND} beats/s capped at "
                  f"{report['slo_s'] * 2000:.0f}ms) the master sustains "
                  f"with workload completion and heartbeat handling AND "
                  f"lag p99 <= {report['slo_s'] * 1000:.0f}ms",
        "value": best,
        "unit": "trackers",
        # the committed single-process ramp is the baseline; the
        # sharded rows are the ceiling-break this bench exists to prove
        "vs_baseline": 1.0,
    }))
    if "--assert-slo" in sys.argv:
        if report["max_sustainable_trackers"] < max(FLEETS) or \
                report.get("max_sustainable_trackers_sharded", 0) < max(
                    n for n, _, _ in SHARD_FLEETS):
            # CI regression gate (smoke sizes only — the full ramp is a
            # measurement, not a gate): the whole smoke fleet, sharded
            # rows included, must hold the dual-p99 SLO, or the control
            # plane regressed
            log(f"[scale] SLO FAILED: sustained "
                f"{report['max_sustainable_trackers']} of {max(FLEETS)} "
                f"single-process and "
                f"{report.get('max_sustainable_trackers_sharded', 0)} "
                f"of {max(n for n, _, _ in SHARD_FLEETS)} sharded "
                f"trackers at the {report['slo_s'] * 1000:.0f}ms "
                f"dual-p99 SLO")
            sys.exit(3)
        # attribution sanity: every row's cpu_share_* columns must be
        # present and account for (essentially) all sampled CPU — a sum
        # outside [0.95, 1.05] means the classifier or the collapsing
        # above dropped a category
        for row in rows + shard_rows:
            s = sum(row.get(f"cpu_share_{k}", 0.0)
                    for k in ("fold", "assign", "rpc", "history",
                              "other"))
            if not 0.95 <= s <= 1.05:
                log(f"[scale] CPU ATTRIBUTION FAILED @ "
                    f"{row['trackers']} trackers: cpu_share_* sums to "
                    f"{s:.3f}, expected ~1.0")
                sys.exit(3)
    if "--assert-scenarios" in sys.argv:
        bad = [r.get("scenario", "?")
               for r in report.get("scenario_rows", [])
               if not r.get("pass")]
        if bad:
            log(f"[scale] SCENARIO VERDICTS FAILED: {bad}")
            sys.exit(3)


if __name__ == "__main__":
    main()
