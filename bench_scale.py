"""Control-plane scale bench: where does the master saturate?

Ramps a simulated-tracker fleet (``tpumr/scale/``) against a real
``JobMaster`` — real RPC sockets, real heartbeat handling, real
scheduler passes, real completion-event polls; only task execution is
a timed no-op — and records, per fleet size, the master's saturation
series:

- ``heartbeat_p50_s`` / ``heartbeat_p99_s`` — master-side handling
  latency including deferred history I/O (``heartbeat_seconds``);
- ``heartbeat_lag_p99_s``   — scheduled-interval overrun per tracker
  (``heartbeat_lag_seconds``): the first externally visible symptom;
- ``lock_wait_p99_s``       — queueing on THE master lock
  (``jt_lock_wait_seconds``), with hold time alongside;
- ``assign_p99_s``          — scheduler pass cost (``assign_seconds``);
- ``rpc_inflight_peak``     — high-water concurrently dispatched RPCs;
- ``completion_event_lag_p99`` — events pending per reduce poll.

Each fleet size gets a FRESH master so rows are independent
distributions, not cumulative smears. The report names the max
sustainable fleet size at a p99 heartbeat-latency SLO
(``TPUMR_SCALE_SLO_MS``, default 250 ms) — the baseline number every
control-plane refactor (heartbeat batching, sharded master internals)
must move.

Output contract (same shape as ``bench.py``/``bench_shuffle.py``): ONE
JSON line on stdout {"metric", "value", "unit", "vs_baseline"}; every
per-size row goes to stderr and to ``bench_scale.json``. env
BENCH_SCALE=small (or --smoke) shrinks the ramp for CI smoke runs.
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(*a: object) -> None:
    print(*a, file=sys.stderr, flush=True)


SMALL = os.environ.get("BENCH_SCALE") == "small" or "--smoke" in sys.argv

#: fleet ramp (≥ 4 sizes in every mode — the per-size rows ARE the
#: trajectory) and the heartbeat interval the fleet schedules against
FLEETS = [4, 8, 12, 16] if SMALL else [25, 50, 100, 200, 400]
INTERVAL_S = 0.05 if SMALL else 0.1

#: p99 heartbeat-latency SLO the "max sustainable fleet" is judged at
SLO_S = float(os.environ.get("TPUMR_SCALE_SLO_MS", "250")) / 1000.0


def _p(h: "dict | None", q: str) -> float:
    return float((h or {}).get(q, 0.0))


def run_step(n_trackers: int, interval_s: float,
             wait_timeout_s: float) -> dict:
    """One ramp step: fresh master, fleet of ``n_trackers``, a synthetic
    multi-job workload sized to keep every slot busy for a few seconds,
    then one snapshot of the master's saturation series."""
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.jobtracker import JobMaster
    from tpumr.scale import ScaleDriver, SimFleet

    conf = JobConf()
    conf.set("tpumr.heartbeat.interval.ms", int(interval_s * 1000))
    # lagging trackers under saturation must stay registered — eviction
    # mid-row would re-queue work and double-count the chaos
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    master = JobMaster(conf).start()
    host, port = master.address

    cpu_slots, reduce_slots = 2, 1
    task_mean_s = 3.0 * interval_s
    # size the workload to ~a few seconds of full-fleet occupancy:
    # total_maps ≈ slots × target_busy_s / task_mean
    target_busy_s = 2.5 if SMALL else 6.0
    total_maps = max(8, int(cpu_slots * n_trackers * target_busy_s
                            / task_mean_s))
    n_jobs = max(2, n_trackers // 8)
    maps_per_job = max(4, total_maps // n_jobs)
    reduces_per_job = 2

    fleet = SimFleet(host, port, n_trackers, interval_s=interval_s,
                     cpu_slots=cpu_slots, reduce_slots=reduce_slots,
                     task_time_mean_s=task_mean_s).start()
    driver = ScaleDriver(host, port)
    t0 = time.monotonic()
    try:
        result = driver.run_workload(n_jobs, maps_per_job,
                                     reduces_per_job,
                                     timeout_s=wait_timeout_s)
        wall = time.monotonic() - t0
        snap = master.metrics.snapshot()
        jt = snap.get("jobtracker", {})
        fl = fleet.stats()
        row = {
            "trackers": n_trackers,
            "jobs": n_jobs,
            "maps_per_job": maps_per_job,
            "reduces_per_job": reduces_per_job,
            "completed": not result["unfinished"] and
                         not result["failed"],
            "wall_s": round(wall, 3),
            "heartbeats": int(_p(jt.get("heartbeat_seconds"), "count")),
            "heartbeat_p50_s": round(
                _p(jt.get("heartbeat_seconds"), "p50"), 6),
            "heartbeat_p99_s": round(
                _p(jt.get("heartbeat_seconds"), "p99"), 6),
            "heartbeat_lag_p99_s": round(
                _p(jt.get("heartbeat_lag_seconds"), "p99"), 6),
            "lock_wait_p99_s": round(
                _p(jt.get("jt_lock_wait_seconds"), "p99"), 6),
            "lock_hold_p99_s": round(
                _p(jt.get("jt_lock_hold_seconds"), "p99"), 6),
            "assign_p99_s": round(
                _p(snap.get("scheduler", {}).get("assign_seconds"),
                   "p99"), 6),
            "completion_event_lag_p99": round(
                _p(jt.get("completion_event_lag"), "p99"), 2),
            "rpc_inflight_peak": master._server.inflight_peak(),
            "client_rtt_p99_s": round(_p(fl["hb_rtt"], "p99"), 6),
            "client_lag_p99_s": round(_p(fl["hb_lag"], "p99"), 6),
            "hb_errors": int(fl["hb_errors"]),
            "tasks_completed": fl["tasks_completed"],
        }
    finally:
        fleet.stop()
        driver.close()
        master.stop()
    return row


def run_bench(fleets: "list[int] | None" = None,
              interval_s: "float | None" = None,
              slo_s: "float | None" = None,
              wait_timeout_s: "float | None" = None) -> dict:
    fleets = fleets or FLEETS
    interval_s = interval_s or INTERVAL_S
    slo_s = slo_s or SLO_S
    wait_timeout_s = wait_timeout_s or (60.0 if SMALL else 180.0)
    rows = []
    for n in fleets:
        row = run_step(n, interval_s, wait_timeout_s)
        rows.append(row)
        log(f"[scale] {n:4d} trackers: hb p50 "
            f"{row['heartbeat_p50_s'] * 1e3:.2f}ms p99 "
            f"{row['heartbeat_p99_s'] * 1e3:.2f}ms · lag p99 "
            f"{row['heartbeat_lag_p99_s'] * 1e3:.2f}ms · lock wait p99 "
            f"{row['lock_wait_p99_s'] * 1e3:.2f}ms · assign p99 "
            f"{row['assign_p99_s'] * 1e3:.2f}ms · inflight peak "
            f"{row['rpc_inflight_peak']} · "
            f"{row['heartbeats']} beats, {row['tasks_completed']} tasks "
            f"in {row['wall_s']:.1f}s"
            + ("" if row["completed"] else " · WORKLOAD INCOMPLETE"))
    # the SLO gates BOTH latency series: handling p99 (the master is
    # slow) and lag p99 (trackers can't keep schedule — beats arriving
    # a second late mean stale statuses and expiring leases long before
    # raw handling time looks bad)
    sustainable = [r["trackers"] for r in rows
                   if r["completed"]
                   and r["heartbeat_p99_s"] <= slo_s
                   and r["heartbeat_lag_p99_s"] <= slo_s]
    return {
        "interval_s": interval_s,
        "slo_s": slo_s,
        "slo_series": ["heartbeat_p99_s", "heartbeat_lag_p99_s"],
        "max_sustainable_trackers": max(sustainable, default=0),
        "rows": rows,
    }


def main() -> None:
    report = run_bench()
    with open("bench_scale.json", "w") as f:
        json.dump(report, f, sort_keys=True, indent=1)
    log(f"detail rows -> bench_scale.json: "
        f"{json.dumps(report, sort_keys=True)}")
    rows = report["rows"]
    print(json.dumps({
        "metric": f"control-plane scale: max simulated-tracker fleet "
                  f"(of ramp {[r['trackers'] for r in rows]}, "
                  f"{report['interval_s'] * 1000:.0f}ms heartbeats) the "
                  f"master sustains with workload completion and "
                  f"heartbeat handling AND lag p99 <= "
                  f"{report['slo_s'] * 1000:.0f}ms",
        "value": report["max_sustainable_trackers"],
        "unit": "trackers",
        # this bench IS the baseline the control-plane refactor must
        # beat; nothing earlier exists to compare against
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
