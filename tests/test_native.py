"""Native tier ≈ SURVEY.md §2.6: libtdfs (C client over the tdfs
protocol, ≈ libhdfs) and the task-controller launcher. Builds with the
local toolchain; skipped when no C compiler is available."""

import getpass
import os
import shutil
import subprocess

import pytest

from tpumr.mapred.jobconf import JobConf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIBTDFS = os.path.join(REPO, "native", "libtdfs")
TASKCTL = os.path.join(REPO, "native", "task-controller")

pytestmark = pytest.mark.skipif(shutil.which("cc") is None,
                                reason="no C toolchain")


def build(path):
    r = subprocess.run(["make"], cwd=path, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(path, "build")


@pytest.fixture(scope="module")
def tdfs_cli():
    return os.path.join(build(LIBTDFS), "tdfs_cli")


@pytest.fixture(scope="module")
def task_controller():
    return os.path.join(build(TASKCTL), "task-controller")


class TestLibTdfs:
    @pytest.fixture()
    def cluster(self):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("dfs.block.size", 4096)  # force multi-block files
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            yield c

    def run(self, cli, cluster, *args, binary=False):
        host, port = cluster.namenode.address
        return subprocess.run([cli, host, str(port), *args],
                              capture_output=True, timeout=60,
                              text=not binary)

    def test_roundtrip_multi_block(self, tdfs_cli, cluster, tmp_path):
        payload = os.urandom(3 * 4096 + 123)  # 4 blocks
        local = tmp_path / "in.bin"
        local.write_bytes(payload)
        r = self.run(tdfs_cli, cluster, "put", str(local), "/n/file.bin")
        assert r.returncode == 0, r.stderr
        r = self.run(tdfs_cli, cluster, "size", "/n/file.bin")
        assert int(r.stdout) == len(payload)
        r = self.run(tdfs_cli, cluster, "cat", "/n/file.bin", binary=True)
        assert r.returncode == 0 and r.stdout == payload
        # Python client sees the C-written file bit-for-bit
        with cluster.client().open("/n/file.bin") as f:
            assert f.read() == payload

    def test_namespace_ops(self, tdfs_cli, cluster):
        assert self.run(tdfs_cli, cluster, "mkdirs", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster, "exists", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster,
                        "exists", "/n/nope").returncode == 1
        # C client reads a Python-written file
        with cluster.client().create("/n/py.txt") as f:
            f.write(b"from python")
        r = self.run(tdfs_cli, cluster, "cat", "/n/py.txt")
        assert r.stdout == "from python"
        assert self.run(tdfs_cli, cluster, "rename", "/n/py.txt",
                        "/n/d/moved.txt").returncode == 0
        assert self.run(tdfs_cli, cluster, "delete", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster,
                        "exists", "/n/d").returncode == 1

    def test_error_reporting(self, tdfs_cli, cluster):
        r = self.run(tdfs_cli, cluster, "cat", "/does/not/exist")
        assert r.returncode == 1
        assert "error" in r.stderr.lower()


class TestTaskController:
    def test_launches_sandboxed(self, task_controller, tmp_path):
        task_dir = tmp_path / "attempt_1"
        task_dir.mkdir()
        log = tmp_path / "task.log"
        env = dict(os.environ, TPUMR_MARKER="visible", SECRET_THING="hidden")
        r = subprocess.run(
            [task_controller, getpass.getuser(), str(task_dir), str(log),
             "/bin/sh", "-c", "pwd; echo M=$TPUMR_MARKER S=$SECRET_THING"],
            env=env, capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr
        out = log.read_text()
        assert str(task_dir) in out          # chdir'd into the sandbox
        assert "M=visible" in out            # TPUMR_* passes through
        assert "S=hidden" not in out         # everything else scrubbed

    def test_rejects_traversal_and_relative(self, task_controller, tmp_path):
        log = tmp_path / "l.log"
        for bad in ("relative/dir", "/tmp/../etc"):
            r = subprocess.run(
                [task_controller, getpass.getuser(), bad, str(log),
                 "/bin/true"], capture_output=True, text=True)
            assert r.returncode == 10
            assert "traversal" in r.stderr or "absolute" in r.stderr

    def test_rejects_other_user_when_not_root(self, task_controller,
                                              tmp_path):
        if os.getuid() == 0:
            pytest.skip("running as root")
        task_dir = tmp_path / "t"
        task_dir.mkdir()
        r = subprocess.run(
            [task_controller, "daemon", str(task_dir),
             str(tmp_path / "l.log"), "/bin/true"],
            capture_output=True, text=True)
        assert r.returncode == 10

    def test_missing_task_dir(self, task_controller, tmp_path):
        r = subprocess.run(
            [task_controller, getpass.getuser(), str(tmp_path / "nope"),
             str(tmp_path / "l.log"), "/bin/true"],
            capture_output=True, text=True)
        assert r.returncode == 10
