"""Native tier ≈ SURVEY.md §2.6: libtdfs (C client over the tdfs
protocol, ≈ libhdfs) and the task-controller launcher. Builds with the
local toolchain; skipped when no C compiler is available."""

import getpass
import os
import shutil
import subprocess

import pytest

from tpumr.mapred.jobconf import JobConf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIBTDFS = os.path.join(REPO, "native", "libtdfs")
TASKCTL = os.path.join(REPO, "native", "task-controller")

pytestmark = pytest.mark.skipif(shutil.which("cc") is None,
                                reason="no C toolchain")


def build(path):
    r = subprocess.run(["make"], cwd=path, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(path, "build")


@pytest.fixture(scope="module")
def tdfs_cli():
    return os.path.join(build(LIBTDFS), "tdfs_cli")


@pytest.fixture(scope="module")
def task_controller():
    return os.path.join(build(TASKCTL), "task-controller")


class TestLibTdfs:
    @pytest.fixture()
    def cluster(self):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("dfs.block.size", 4096)  # force multi-block files
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            yield c

    def run(self, cli, cluster, *args, binary=False):
        host, port = cluster.namenode.address
        return subprocess.run([cli, host, str(port), *args],
                              capture_output=True, timeout=60,
                              text=not binary)

    def test_roundtrip_multi_block(self, tdfs_cli, cluster, tmp_path):
        payload = os.urandom(3 * 4096 + 123)  # 4 blocks
        local = tmp_path / "in.bin"
        local.write_bytes(payload)
        r = self.run(tdfs_cli, cluster, "put", str(local), "/n/file.bin")
        assert r.returncode == 0, r.stderr
        r = self.run(tdfs_cli, cluster, "size", "/n/file.bin")
        assert int(r.stdout) == len(payload)
        r = self.run(tdfs_cli, cluster, "cat", "/n/file.bin", binary=True)
        assert r.returncode == 0 and r.stdout == payload
        # Python client sees the C-written file bit-for-bit
        with cluster.client().open("/n/file.bin") as f:
            assert f.read() == payload

    def test_namespace_ops(self, tdfs_cli, cluster):
        assert self.run(tdfs_cli, cluster, "mkdirs", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster, "exists", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster,
                        "exists", "/n/nope").returncode == 1
        # C client reads a Python-written file
        with cluster.client().create("/n/py.txt") as f:
            f.write(b"from python")
        r = self.run(tdfs_cli, cluster, "cat", "/n/py.txt")
        assert r.stdout == "from python"
        assert self.run(tdfs_cli, cluster, "rename", "/n/py.txt",
                        "/n/d/moved.txt").returncode == 0
        assert self.run(tdfs_cli, cluster, "delete", "/n/d").returncode == 0
        assert self.run(tdfs_cli, cluster,
                        "exists", "/n/d").returncode == 1

    def test_error_reporting(self, tdfs_cli, cluster):
        r = self.run(tdfs_cli, cluster, "cat", "/does/not/exist")
        assert r.returncode == 1
        assert "error" in r.stderr.lower()


IS_ROOT = os.getuid() == 0


@pytest.fixture(scope="module")
def tc_root(tmp_path_factory):
    """Root-mode test binary: TC_CONF_PATH relocated into scratch so the
    root-owned-config policy (≈ reference impl/task-controller.c:529-540)
    is testable without touching /etc."""
    scratch = tmp_path_factory.mktemp("tc")
    conf = scratch / "task-controller.cfg"
    sandbox = scratch / "local"
    sandbox.mkdir()
    # the dropped-privilege child must be able to traverse into its
    # sandbox: open up the (root-owned) pytest tmp dirs above it —
    # but never walk past the system tmp root (chmodding /root or /
    # as uid 0 would silently open the host)
    import tempfile
    stop = {tempfile.gettempdir(), "/"}
    p = sandbox
    while str(p) not in stop and str(p.parent) != str(p):
        try:
            os.chmod(p, 0o755)
        except OSError:
            break
        p = p.parent
    conf.write_text("min.user.id=100\nbanned.users=root,daemon\n"
                    f"allowed.local.dirs={sandbox}\n")
    os.chmod(conf, 0o600)
    r = subprocess.run(["make", "test-binary", f"TC_CONF={conf}"],
                       cwd=TASKCTL, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return os.path.join(TASKCTL, "build", "task-controller-test"), sandbox


@pytest.mark.skipif(not IS_ROOT, reason="root-mode policy needs uid 0")
class TestTaskControllerRootPolicy:
    def run_tc(self, binary, user, task_dir, log, *cmd):
        return subprocess.run([binary, user, str(task_dir), str(log), *cmd],
                              capture_output=True, text=True, timeout=30)

    def test_refuses_root_target(self, tc_root):
        binary, sandbox = tc_root
        d = sandbox / "r"
        d.mkdir(exist_ok=True)
        r = self.run_tc(binary, "root", d, sandbox / "r.log", "/bin/true")
        assert r.returncode == 10
        assert "root" in r.stderr or "banned" in r.stderr

    def test_refuses_dir_outside_allowed(self, tc_root, tmp_path):
        binary, _ = tc_root
        outside = tmp_path / "outside"
        outside.mkdir()
        r = self.run_tc(binary, "nobody", outside, tmp_path / "o.log",
                        "/bin/true")
        assert r.returncode == 10 and "allowed local dir" in r.stderr

    def test_refuses_when_no_config(self, task_controller, tmp_path):
        # stock binary points at /etc/tpumr/task-controller.cfg (absent here)
        d = tmp_path / "t"
        d.mkdir()
        r = self.run_tc(task_controller, "nobody", d, tmp_path / "t.log",
                        "/bin/true")
        assert r.returncode == 10 and "config" in r.stderr

    def test_symlink_cannot_escape_allowed_dir(self, tc_root, tmp_path):
        """A symlink planted inside the allowed dir must not smuggle the
        sandbox outside it (realpath runs before the prefix check)."""
        import pwd as pwd_mod
        binary, sandbox = tc_root
        pw = pwd_mod.getpwnam("nobody")
        outside = tmp_path / "victim"
        outside.mkdir()
        os.chown(outside, pw.pw_uid, pw.pw_gid)  # even user-owned: refused
        link = sandbox / "sneaky"
        if link.exists() or link.is_symlink():
            link.unlink()
        link.symlink_to(outside)
        r = self.run_tc(binary, "nobody", link, sandbox / "s.log",
                        "/bin/true")
        assert r.returncode == 10
        assert "allowed local dir" in r.stderr

    def test_launches_as_unprivileged_user(self, tc_root):
        import pwd as pwd_mod
        binary, sandbox = tc_root
        pw = pwd_mod.getpwnam("nobody")
        task_dir = sandbox / "attempt_1"
        task_dir.mkdir(exist_ok=True)
        os.chown(task_dir, pw.pw_uid, pw.pw_gid)
        log = task_dir / "task.log"
        env = dict(os.environ, TPUMR_MARKER="visible", SECRET_THING="hidden")
        r = subprocess.run(
            [binary, "nobody", str(task_dir), str(log),
             "/bin/sh", "-c", "id -u; echo M=$TPUMR_MARKER S=$SECRET_THING"],
            env=env, capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr
        out = log.read_text()
        assert str(pw.pw_uid) in out          # really dropped to nobody
        assert "M=visible" in out             # TPUMR_* passes through
        assert "S=hidden" not in out          # everything else scrubbed


class TestTaskController:
    @pytest.mark.skipif(IS_ROOT, reason="non-root path; root mode above")
    def test_launches_sandboxed(self, task_controller, tmp_path):
        task_dir = tmp_path / "attempt_1"
        task_dir.mkdir()
        log = tmp_path / "task.log"
        env = dict(os.environ, TPUMR_MARKER="visible", SECRET_THING="hidden")
        r = subprocess.run(
            [task_controller, getpass.getuser(), str(task_dir), str(log),
             "/bin/sh", "-c", "pwd; echo M=$TPUMR_MARKER S=$SECRET_THING"],
            env=env, capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr
        out = log.read_text()
        assert str(task_dir) in out          # chdir'd into the sandbox
        assert "M=visible" in out            # TPUMR_* passes through
        assert "S=hidden" not in out         # everything else scrubbed

    def test_rejects_traversal_and_relative(self, task_controller, tmp_path):
        log = tmp_path / "l.log"
        for bad in ("relative/dir", "/tmp/../etc"):
            r = subprocess.run(
                [task_controller, getpass.getuser(), bad, str(log),
                 "/bin/true"], capture_output=True, text=True)
            assert r.returncode == 10
            assert "traversal" in r.stderr or "absolute" in r.stderr

    def test_rejects_other_user_when_not_root(self, task_controller,
                                              tmp_path):
        if os.getuid() == 0:
            pytest.skip("running as root")
        task_dir = tmp_path / "t"
        task_dir.mkdir()
        r = subprocess.run(
            [task_controller, "daemon", str(task_dir),
             str(tmp_path / "l.log"), "/bin/true"],
            capture_output=True, text=True)
        assert r.returncode == 10

    def test_missing_task_dir(self, task_controller, tmp_path):
        r = subprocess.run(
            [task_controller, getpass.getuser(), str(tmp_path / "nope"),
             str(tmp_path / "l.log"), "/bin/true"],
            capture_output=True, text=True)
        assert r.returncode == 10


class TestLibTdfsAuth:
    """The C client against a SECRET-PROTECTED cluster: HMAC-SHA256
    frame signing at full parity with Python clients (VERDICT missing
    #5 closed; ≈ libhdfs inheriting auth via JNI)."""

    @pytest.fixture()
    def secure_cluster(self, tmp_path_factory):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        secret_dir = tmp_path_factory.mktemp("secret")
        secret_file = secret_dir / "cluster.secret"
        secret_file.write_text("s3cret-cluster-key\n")
        conf = JobConf()
        conf.set("dfs.block.size", 4096)
        conf.set("tpumr.rpc.secret.file", str(secret_file))
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            yield c, str(secret_file)

    def run(self, cli, cluster, *args, secret_file=None, binary=False):
        host, port = cluster.namenode.address
        env = dict(os.environ)
        env.pop("TDFS_SECRET_FILE", None)
        if secret_file:
            env["TDFS_SECRET_FILE"] = secret_file
        return subprocess.run([cli, host, str(port), *args], env=env,
                              capture_output=True, timeout=60,
                              text=not binary)

    def test_signed_roundtrip(self, tdfs_cli, secure_cluster, tmp_path):
        cluster, secret = secure_cluster
        payload = os.urandom(2 * 4096 + 77)   # multi-block through auth
        local = tmp_path / "in.bin"
        local.write_bytes(payload)
        r = self.run(tdfs_cli, cluster, "put", str(local), "/s/auth.bin",
                     secret_file=secret)
        assert r.returncode == 0, r.stderr
        r = self.run(tdfs_cli, cluster, "cat", "/s/auth.bin",
                     secret_file=secret, binary=True)
        assert r.returncode == 0 and r.stdout == payload
        # the authenticated Python client sees the C-written file
        with cluster.client().open("/s/auth.bin") as f:
            assert f.read() == payload
        # namespace ops through the signed path too
        assert self.run(tdfs_cli, cluster, "mkdirs", "/s/d",
                        secret_file=secret).returncode == 0
        assert self.run(tdfs_cli, cluster, "exists", "/s/d",
                        secret_file=secret).returncode == 0

    def test_unsigned_client_rejected(self, tdfs_cli, secure_cluster):
        cluster, _ = secure_cluster
        r = self.run(tdfs_cli, cluster, "exists", "/")
        assert r.returncode != 0
        assert "not signed" in (r.stderr + r.stdout).lower()

    def test_wrong_secret_rejected(self, tdfs_cli, secure_cluster,
                                   tmp_path):
        cluster, _ = secure_cluster
        bad = tmp_path / "bad.secret"
        bad.write_text("wrong-secret")
        r = self.run(tdfs_cli, cluster, "exists", "/",
                     secret_file=str(bad))
        assert r.returncode != 0
        assert "not signed" in (r.stderr + r.stdout).lower()


class TestSanitizers:
    """SURVEY.md §5 sanitizer note: the four native tiers parse untrusted
    or cross-trust bytes (codec frames off the wire, split text, the
    pipes socket protocol, task-controller argv/config), so their
    parsers run under ASAN+UBSAN in CI via deterministic fuzz drivers
    with checked-in corpora (native/fuzz/corpus/). libFuzzer isn't in
    this toolchain; the drivers are self-contained (fixed-seed xorshift,
    mutation + roundtrip properties)."""

    CORPUS = os.path.join(REPO, "native", "fuzz", "corpus")

    @staticmethod
    def _skip_if_no_asan(result):
        # compile failures mention 'sanitize'; a missing runtime fails at
        # LINK time with messages like 'cannot find -lasan' or
        # 'libasan_preinit.o: No such file' — match both families
        import re
        if result.returncode != 0 and \
                re.search(r"saniti[zs]e|[alut]san", result.stderr or ""):
            pytest.skip("toolchain lacks ASAN/UBSAN")

    def build_fuzz(self, path):
        r = subprocess.run(["make", "fuzz"], cwd=path,
                           capture_output=True, text=True)
        self._skip_if_no_asan(r)
        assert r.returncode == 0, r.stderr
        return os.path.join(path, "build")

    def run_fuzz(self, binary, *args):
        r = subprocess.run([binary, *args], capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0, \
            f"sanitized fuzz failed:\n{r.stdout}\n{r.stderr[-2000:]}"
        assert "clean" in r.stdout

    def test_codec_fuzz_asan(self):
        b = self.build_fuzz(LIBTDFS)
        self.run_fuzz(os.path.join(b, "fuzz_codec"), "1500",
                      os.path.join(self.CORPUS, "codec"))

    def test_tokencount_fuzz_asan(self):
        b = self.build_fuzz(os.path.join(REPO, "native", "textkit"))
        self.run_fuzz(os.path.join(b, "fuzz_tokencount"), "800",
                      os.path.join(self.CORPUS, "text"))

    def test_tlz_fuzz_asan(self):
        b = self.build_fuzz(os.path.join(REPO, "native", "tlz"))
        self.run_fuzz(os.path.join(b, "fuzz_tlz"), "1200")

    def test_recio_fuzz_asan(self):
        b = self.build_fuzz(os.path.join(REPO, "native", "recordio"))
        self.run_fuzz(os.path.join(b, "fuzz_recio"), "2000")

    def test_pipes_stream_fuzz_asan(self):
        if shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        b = self.build_fuzz(os.path.join(REPO, "native", "pipes"))
        self.run_fuzz(os.path.join(b, "fuzz_stream"), "400")

    def test_task_controller_policy_under_asan(self, tmp_path):
        """The setuid launcher's argv/path/config parsing, instrumented:
        same refusal policy the un-instrumented tests assert."""
        sandbox = tmp_path / "sandbox"
        sandbox.mkdir()
        conf = tmp_path / "taskcontroller.cfg"
        conf.write_text("min.user.id=1000\nbanned.users=root\n"
                        f"allowed.local.dirs={sandbox}\n")
        r = subprocess.run(["make", "test-binary-asan",
                            f"TC_CONF={conf}"], cwd=TASKCTL,
                           capture_output=True, text=True)
        self._skip_if_no_asan(r)
        assert r.returncode == 0, r.stderr
        tc = os.path.join(TASKCTL, "build", "task-controller-asan")
        task_dir = sandbox / "t"
        task_dir.mkdir()
        log = tmp_path / "log"
        # banned user refused; traversal refused — and each refusal must
        # come from the POLICY (stderr names it), not a config-load
        # failure, or the sanitized run never reaches the parsing under
        # test
        r = subprocess.run([tc, "root", str(task_dir), str(log),
                            "/bin/true"], capture_output=True, text=True)
        assert r.returncode != 0 and "refusing" in (r.stderr + r.stdout)
        r = subprocess.run([tc, getpass.getuser(),
                            str(sandbox / ".." / "escape"), str(log),
                            "/bin/true"], capture_output=True, text=True)
        assert r.returncode != 0
        assert "allowed.local.dirs" not in r.stderr or \
            "not under" in (r.stderr + r.stdout)


class TestThreadSanitizer:
    """SURVEY.md §5 race detection: the framework's Python concurrency
    is tested deterministically (scheduler/launcher tests); the native
    tier's answer is TSAN. The libtdfs contract is "one tdfsFS per
    thread" (tdfs.h header) — this runs N concurrently-connected
    handles through the full namespace + block read/write surface under
    -fsanitize=thread, proving the shared code paths (codec framing,
    HMAC signer, the __thread error buffer) hide no racy global
    state."""

    def test_libtdfs_threaded_tsan(self, tmp_path):
        r = subprocess.run(["make", "tsan"], cwd=LIBTDFS,
                           capture_output=True, text=True)
        import re
        # match only toolchain-capability messages, never the target
        # name ('tsan_stress' appears in EVERY make error for this
        # target, which would silently skip real build regressions)
        if r.returncode != 0 and re.search(
                r"unrecognized.*fsanitize|cannot find -ltsan|"
                r"libtsan[^_]|fsanitize=thread.*not supported",
                r.stderr or ""):
            pytest.skip("toolchain lacks TSAN")
        assert r.returncode == 0, r.stderr
        binary = os.path.join(LIBTDFS, "build", "tsan_stress")

        from tpumr.dfs.mini_cluster import MiniDFSCluster
        secret_file = tmp_path / "cluster.secret"
        secret_file.write_text("tsan-secret\n")
        conf = JobConf()
        conf.set("dfs.block.size", 4096)
        conf.set("tpumr.rpc.secret.file", str(secret_file))
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            host, port = c.namenode.address
            env = dict(os.environ,
                       TSAN_OPTIONS="halt_on_error=1 exitcode=66")
            r = subprocess.run(
                [binary, host, str(port), str(secret_file), "6", "8"],
                capture_output=True, text=True, timeout=300, env=env)
        assert r.returncode != 66, f"TSAN race:\n{r.stderr[-3000:]}"
        assert r.returncode == 0, \
            f"threaded stress failed:\n{r.stdout}\n{r.stderr[-2000:]}"
        assert "clean" in r.stdout
