"""Verified identity: per-user signing keys + delegation tokens
(tpumr/security/tokens.py, rpc scope families in tpumr/ipc/rpc.py).

≈ the reference's security/token tier (SecretManager.createPassword,
AbstractDelegationTokenSecretManager, SaslRpcServer DIGEST auth) — the
round-3 verdict's Missing #1: identities that ACLs can trust because a
user's credential can only sign as that user."""

import json
import time

import pytest

from tpumr.ipc.rpc import RpcAuthError, RpcClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.security.tokens import (DelegationToken, TokenStore,
                                   derive_user_key, parse_ident,
                                   token_password)

SECRET = b"cluster-secret-for-tests"


class TestKeyDerivation:
    def test_per_user_keys_differ(self):
        ka = derive_user_key(SECRET, "alice")
        kb = derive_user_key(SECRET, "bob")
        assert ka != kb and len(ka) == 32
        assert ka == derive_user_key(SECRET, "alice")  # deterministic

    def test_token_ident_roundtrip(self):
        store = TokenStore()
        tok = store.issue(SECRET, "carol", "ops")
        back = parse_ident(tok.ident_bytes())
        assert (back.owner, back.renewer, back.seq) == ("carol", "ops",
                                                        tok.seq)
        assert tok.password == token_password(SECRET, tok.ident_bytes())
        wire = DelegationToken.from_wire(tok.to_wire())
        assert wire.password == tok.password
        assert wire.ident_bytes() == tok.ident_bytes()


class TestTokenStore:
    def test_lifecycle(self):
        store = TokenStore()
        tok = store.issue(SECRET, "carol", "ops")
        assert store.check(tok) is None
        # renewer and owner may renew; strangers may not
        store.renew(tok, "ops")
        store.renew(tok, "carol")
        with pytest.raises(PermissionError, match="may not renew"):
            store.renew(tok, "mallory")
        with pytest.raises(PermissionError, match="may not cancel"):
            store.cancel(tok, "mallory")
        store.cancel(tok, "carol")
        assert store.check(tok) is not None      # gone

    def test_expiry(self):
        conf = JobConf()
        conf.set("tpumr.token.renew.interval.s", 0.05)
        store = TokenStore(conf)
        tok = store.issue(SECRET, "carol")
        assert store.check(tok) is None
        time.sleep(0.1)
        assert "expired" in store.check(tok)
        # renewal brings it back (owner, within max lifetime)
        store.renew(tok, "carol")
        assert store.check(tok) is None

    def test_unknown_token_rejected(self):
        store = TokenStore()
        foreign = TokenStore().issue(SECRET, "carol")
        assert "not known" in store.check(foreign)


@pytest.fixture()
def master():
    conf = JobConf()
    conf.set("tpumr.rpc.secret", SECRET.decode())
    conf.set("mapred.acls.enabled", True)
    conf.set("mapred.queue.names", "prod")
    conf.set("mapred.queue.prod.acl-submit-job", "carol")
    conf.set("mapred.queue.prod.acl-administer-jobs", " ops")
    conf.set("tpumr.user.groups.opsana", "ops")
    m = JobMaster(conf).start()
    yield m
    m.stop()


def rpc(master, secret, scope=None):
    host, port = master.address
    return RpcClient(host, port, secret=secret, scope=scope)


def submit(client, user="carol", queue="prod"):
    return client.call(
        "submit_job",
        {"mapred.job.queue.name": queue, "user.name": user,
         "mapred.reduce.tasks": 0}, [{"locations": []}])


class TestUserKeyAuth:
    def test_verified_user_passes_acl(self, master):
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "carol")
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, key, scope="user:carol")
            jid = submit(c)
        assert jid in master.list_jobs()

    def test_user_key_cannot_sign_as_other_user(self, master):
        from tpumr.ipc.rpc import RpcError
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "mallory")
        # a) mallory's credential BINDS the rpc identity to mallory no
        # matter what the process UGI claims: the request authenticates
        # as mallory and dies on the owner/ACL tier, never as carol
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, key, scope="user:mallory")
            with pytest.raises(RpcError, match="cannot submit"):
                submit(c)               # conf claims owner carol
        # b) claiming carol's scope outright: wrong key for that scope
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, key, scope="user:carol")
            with pytest.raises(RpcAuthError):
                submit(c)

    def test_verified_owner_binds_job(self, master):
        """A verified carol cannot submit a job OWNED by alice."""
        from tpumr.ipc.rpc import RpcError
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "carol")
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, key, scope="user:carol")
            with pytest.raises(RpcError, match="cannot submit a job "
                                               "owned by"):
                submit(c, user="alice")

    def test_wrong_cluster_secret_still_rejected(self, master):
        from tpumr.security import UserGroupInformation
        key = derive_user_key(b"other-cluster", "carol")
        with UserGroupInformation("carol", []).do_as():
            with pytest.raises(RpcAuthError):
                submit(rpc(master, key, scope="user:carol"))


class TestDelegationTokens:
    def get_token(self, master, user="carol", renewer=""):
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, user)
        with UserGroupInformation(user, []).do_as():
            c = rpc(master, key, scope=f"user:{user}")
            return c.call("get_delegation_token", renewer)

    def test_token_authenticates_owner(self, master):
        from tpumr.security import UserGroupInformation
        wire = self.get_token(master)
        tok = DelegationToken.from_wire(wire)
        assert tok.owner == "carol"
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, tok.password, scope=tok.scope())
            jid = submit(c)
        assert jid in master.list_jobs()

    def test_token_cannot_speak_as_other_user(self, master):
        from tpumr.ipc.rpc import RpcError
        from tpumr.security import UserGroupInformation
        tok = DelegationToken.from_wire(self.get_token(master))
        # carol's token BINDS the rpc identity to carol even under
        # alice's process UGI; a conf claiming alice as owner then dies
        # on the owner check — there is no way to speak as alice
        with UserGroupInformation("alice", []).do_as():
            c = rpc(master, tok.password, scope=tok.scope())
            with pytest.raises(RpcError, match="cannot submit a job "
                                               "owned by"):
                submit(c, user="alice")

    def test_canceled_token_rejected(self, master):
        from tpumr.security import UserGroupInformation
        wire = self.get_token(master)
        tok = DelegationToken.from_wire(wire)
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, tok.password, scope=tok.scope())
            assert submit(c) in master.list_jobs()
            assert c.call("cancel_delegation_token", wire) is True
        with UserGroupInformation("carol", []).do_as():
            c2 = rpc(master, tok.password, scope=tok.scope())
            with pytest.raises(RpcAuthError):
                submit(c2)

    def test_renew_requires_password(self, master):
        """Knowing the (loggable) ident is NOT enough to renew/cancel —
        possession of the password is what authorizes."""
        from tpumr.ipc.rpc import RpcError
        from tpumr.security import UserGroupInformation
        wire = self.get_token(master, renewer="opsana")
        forged = dict(wire)
        forged["password"] = "00" * 32
        key = derive_user_key(SECRET, "opsana")
        with UserGroupInformation("opsana", []).do_as():
            c = rpc(master, key, scope="user:opsana")
            with pytest.raises(RpcError, match="password mismatch"):
                c.call("renew_delegation_token", forged)
            assert c.call("renew_delegation_token", wire) > time.time()


class TestRequireVerified:
    def test_unverified_assertion_becomes_anonymous(self):
        """tpumr.acls.require.verified: cluster-secret assertions stop
        counting for ACLs — the tested negative-claim half of the
        verdict's ask, now an enforceable mode rather than prose."""
        conf = JobConf()
        conf.set("tpumr.rpc.secret", SECRET.decode())
        conf.set("mapred.acls.enabled", True)
        conf.set("tpumr.acls.require.verified", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.queue.prod.acl-submit-job", "carol")
        m = JobMaster(conf).start()
        try:
            from tpumr.security import UserGroupInformation
            # cluster-secret holder asserting carol: anonymous under
            # require.verified -> denied
            with UserGroupInformation("carol", []).do_as():
                c = rpc(m, SECRET)
                from tpumr.ipc.rpc import RpcError
                with pytest.raises(RpcError, match="cannot submit"):
                    submit(c)
            # carol with her OWN key: verified -> allowed
            key = derive_user_key(SECRET, "carol")
            with UserGroupInformation("carol", []).do_as():
                c = rpc(m, key, scope="user:carol")
                assert submit(c) in m.list_jobs()
        finally:
            m.stop()


class TestTokenCannotMintTokens:
    def test_token_caller_refused_issuance(self, master):
        from tpumr.ipc.rpc import RpcError
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "carol")
        with UserGroupInformation("carol", []).do_as():
            c = rpc(master, key, scope="user:carol")
            wire = c.call("get_delegation_token", "")
        tok = DelegationToken.from_wire(wire)
        with UserGroupInformation("carol", []).do_as():
            c2 = rpc(master, tok.password, scope=tok.scope())
            with pytest.raises(RpcError, match="cannot be used to "
                                               "obtain further"):
                c2.call("get_delegation_token", "")


class TestDfsTokens:
    """Cross-daemon credential story: the NameNode issues ITS OWN
    tokens (≈ ClientProtocol.getDelegationToken); DataNodes accept them
    statelessly (the BlockToken stance); JT tokens do not verify on the
    NameNode."""

    @pytest.fixture()
    def dfs(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        conf = JobConf()
        conf.set("tpumr.rpc.secret", SECRET.decode())
        conf.set("dfs.block.size", 4096)
        with MiniDFSCluster(num_datanodes=2, conf=conf,
                            root=str(tmp_path / "dfs")) as c:
            # carol's workspace, created by the (superuser) daemon
            # identity: verified users hit REAL namespace permissions
            admin = c.client()
            admin.mkdirs("/tok")
            admin.set_owner("/tok", "carol", "carol")
            yield c

    def _client_conf(self, tmp_path, tok_wire) -> JobConf:
        tf = tmp_path / "cred.json"
        tf.write_text(json.dumps({"namenode": tok_wire}))
        conf = JobConf()
        conf.set("tpumr.rpc.token.file", str(tf))
        return conf

    def test_user_key_full_dfs_roundtrip(self, dfs):
        from tpumr.dfs.client import DFSClient
        conf = JobConf()
        conf.set("tpumr.rpc.user.key",
                 derive_user_key(SECRET, "carol").hex())
        conf.set("user.name", "carol")
        from tpumr.security import UserGroupInformation
        with UserGroupInformation("carol", []).do_as():
            client = DFSClient(dfs.nn_host, dfs.nn_port, conf)
            payload = b"K" * 9000              # multi-block -> DN RPCs
            with client.create("/tok/key.bin") as f:
                f.write(payload)
            with client.open("/tok/key.bin") as f:
                assert f.read() == payload
            assert client.get_status("/tok/key.bin")["owner"] == "carol"

    def test_nn_token_roundtrip_and_cancel(self, dfs, tmp_path):
        from tpumr.dfs.client import DFSClient
        from tpumr.ipc.rpc import RpcAuthError, RpcClient
        from tpumr.security import UserGroupInformation
        # obtain an NN token as a verified user
        key = derive_user_key(SECRET, "carol")
        with UserGroupInformation("carol", []).do_as():
            nn = RpcClient(dfs.nn_host, dfs.nn_port, secret=key,
                           scope="user:carol")
            wire = nn.call("get_delegation_token", "")
        # token-only client: full write+read through NN AND datanodes
        conf = self._client_conf(tmp_path, wire)
        client = DFSClient(dfs.nn_host, dfs.nn_port, conf)
        payload = b"T" * 9000
        with client.create("/tok/t.bin") as f:
            f.write(payload)
        with client.open("/tok/t.bin") as f:
            assert f.read() == payload
        assert client.get_status("/tok/t.bin")["owner"] == "carol"
        # cancel -> namespace ops die (block ids become unreachable,
        # which is what bounds DN access too)
        with UserGroupInformation("carol", []).do_as():
            nn2 = RpcClient(dfs.nn_host, dfs.nn_port, secret=key,
                            scope="user:carol")
            assert nn2.call("cancel_delegation_token", wire) is True
        client2 = DFSClient(dfs.nn_host, dfs.nn_port, conf)
        with pytest.raises(RpcAuthError):
            client2.get_status("/tok/t.bin")

    def test_dn_requires_block_access_stamp(self, dfs, tmp_path):
        """The BlockToken split: a personal-credential caller reaching a
        DataNode DIRECTLY (block ids are guessable ints) is refused
        without a NameNode-minted stamp bound to that exact block."""
        from tpumr.dfs.client import DFSClient
        from tpumr.ipc.rpc import RpcAuthError, RpcClient
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "carol")
        conf = JobConf()
        conf.set("tpumr.rpc.user.key", key.hex())
        conf.set("user.name", "carol")
        with UserGroupInformation("carol", []).do_as():
            client = DFSClient(dfs.nn_host, dfs.nn_port, conf)
            with client.create("/tok/gate.bin") as f:
                f.write(b"G" * 5000)
            blocks = client.nn.call("get_block_locations",
                                    "/tok/gate.bin")
        bid = blocks[0]["block_id"]
        addr = blocks[0]["locations"][0]
        host, port = addr.rsplit(":", 1)
        # frame-authenticated as carol but with NO stamp attached
        bare = RpcClient(host, int(port), secret=key, scope="user:carol")
        with pytest.raises(RpcAuthError, match="access denied"):
            bare.call("read_block", bid, 0, -1)
        # a stamp for a DIFFERENT block must not open this one
        other_stamp = blocks[-1]["access"] if len(blocks) > 1 else None
        if other_stamp is not None:
            bare2 = RpcClient(host, int(port), secret=key,
                              scope="user:carol")
            bare2.envelope_provider = \
                lambda m, p: {"access": other_stamp}
            with pytest.raises(RpcAuthError, match="access denied"):
                bare2.call("read_block", bid, 0, -1)
        # a read stamp must not authorize writes
        r_stamp = blocks[0]["access"]
        bare3 = RpcClient(host, int(port), secret=key,
                          scope="user:carol")
        bare3.envelope_provider = lambda m, p: {"access": r_stamp}
        with pytest.raises(RpcAuthError, match="access denied"):
            bare3.call("write_block", bid, b"evil", [])
        # ...while the same stamp DOES authorize the read it names
        assert bare3.call("read_block", bid, 0, -1) == b"G" * 4096
        # daemon surface stays off-limits to personal credentials
        with pytest.raises(RpcAuthError, match="not available"):
            bare3.call("dn_blocks")

    def test_foreign_service_token_rejected(self, dfs, master, tmp_path):
        """A JOBTRACKER token presented to the NameNode must fail: the
        NN's store never issued it."""
        from tpumr.dfs.client import DFSClient
        from tpumr.ipc.rpc import RpcAuthError
        from tpumr.security import UserGroupInformation
        key = derive_user_key(SECRET, "carol")
        with UserGroupInformation("carol", []).do_as():
            jt = rpc(master, key, scope="user:carol")
            jt_wire = jt.call("get_delegation_token", "")
        conf = self._client_conf(tmp_path, jt_wire)
        client = DFSClient(dfs.nn_host, dfs.nn_port, conf)
        with pytest.raises(RpcAuthError):
            client.get_status("/")


class TestClientCredentialPlumbing:
    def test_user_key_conf_roundtrip(self, master, tmp_path):
        """tpumr keys user-key -> tpumr.rpc.user.key.file -> JobClient
        signs as the verified user (the full provisioning loop)."""
        from tpumr.cli import main as cli_main
        import io
        from contextlib import redirect_stdout
        conf = JobConf()
        conf.set("tpumr.rpc.secret", SECRET.decode())
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert cli_main(["-D", f"tpumr.rpc.secret={SECRET.decode()}",
                             "keys", "user-key", "carol"]) == 0
        key_hex = buf.getvalue().strip()
        assert bytes.fromhex(key_hex) == derive_user_key(SECRET, "carol")

        keyfile = tmp_path / "carol.key"
        keyfile.write_text(key_hex + "\n")
        cconf = JobConf()
        cconf.set("tpumr.rpc.user.key.file", str(keyfile))
        cconf.set("user.name", "carol")
        from tpumr.security import client_credentials
        secret, scope = client_credentials(cconf)
        assert secret == derive_user_key(SECRET, "carol")
        assert scope == "user:carol"

    def test_personal_credentials_never_ride_the_job_conf(self):
        """The user key is a full-impersonation secret and job confs
        land in history files — _wire_conf must strip every client-local
        credential key."""
        from tpumr.mapred.job_client import _wire_conf
        conf = JobConf()
        conf.set("tpumr.rpc.user.key", "aa" * 32)
        conf.set("tpumr.rpc.user.key.file", "/home/carol/key")
        conf.set("tpumr.rpc.token.file", "/home/carol/creds.json")
        conf.set("mapred.job.name", "j")
        wire = _wire_conf(conf)
        assert "tpumr.rpc.user.key" not in wire
        assert "tpumr.rpc.user.key.file" not in wire
        assert "tpumr.rpc.token.file" not in wire
        assert wire["mapred.job.name"] == "j"

    def test_keys_cli_token_lifecycle(self, master, tmp_path):
        """tpumr keys token/renew/cancel against a live master, driving
        the whole provisioning loop through the CLI surface."""
        import io
        from contextlib import redirect_stdout
        from tpumr.cli import main as cli_main
        from tpumr.security import UserGroupInformation

        host, port = master.address
        keyfile = tmp_path / "carol.key"
        keyfile.write_text(derive_user_key(SECRET, "carol").hex())
        credfile = tmp_path / "creds.json"
        base = ["-D", f"mapred.job.tracker={host}:{port}",
                "-D", f"tpumr.rpc.user.key.file={keyfile}",
                "-D", "user.name=carol"]
        with UserGroupInformation("carol", []).do_as():
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert cli_main([*base, "keys", "token",
                                 "-renewer", "carol",
                                 "-out", str(credfile)]) == 0
            assert "jobtracker token written" in buf.getvalue()
            data = json.loads(credfile.read_text())
            assert "jobtracker" in data
            tok = DelegationToken.from_wire(data["jobtracker"])
            assert tok.owner == "carol"
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert cli_main([*base, "keys", "renew",
                                 str(credfile)]) == 0
            assert "renewed until" in buf.getvalue()
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert cli_main([*base, "keys", "cancel",
                                 str(credfile)]) == 0
            assert "canceled" in buf.getvalue()
            # the canceled token no longer authenticates
            c = rpc(master, tok.password, scope=tok.scope())
            with pytest.raises(RpcAuthError):
                submit(c)

    def test_token_file_credentials(self, tmp_path):
        store = TokenStore()
        tok = store.issue(SECRET, "carol")
        tf = tmp_path / "tok.json"
        tf.write_text(json.dumps(tok.to_wire()))
        conf = JobConf()
        conf.set("tpumr.rpc.token.file", str(tf))
        from tpumr.security import client_credentials
        secret, scope = client_credentials(conf)
        assert secret == tok.password
        assert scope == tok.scope()
