"""Distributed job tracing (core/tracing.py): span model, cross-daemon
propagation (master → tracker → task → shuffle), Chrome-trace export,
critical-path analysis, and the zero-overhead-off contract."""

import json
import os
import time
import urllib.request

import pytest

from tpumr.core import tracing
from tpumr.fs import FileSystem, get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster
from tpumr.mapred.task import TaskState
from tpumr.utils import fi


def fetch(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


class WcMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


# ------------------------------------------------------------ unit


class TestTracerUnit:
    def test_span_lifecycle_and_flush_roundtrip(self, tmp_path):
        tr = tracing.Tracer("jobtracker", trace_dir=str(tmp_path))
        root = tr.start_span("job", "job_x_1", job_id="job_x_1")
        child = tr.start_span("schedule", "job_x_1", parent=root,
                              backend="tpu", attempt_id="a0")
        tr.finish(child)
        tr.finish(root)
        assert tr.flush() == 2
        spans = tracing.read_trace_files(str(tmp_path), "job_x_1")
        assert [s["name"] for s in spans] == ["job", "schedule"]
        sched = spans[1]
        assert sched["parent_span_id"] == root.span_id
        assert sched["backend"] == "tpu"
        assert sched["attributes"]["attempt_id"] == "a0"
        assert sched["attributes"]["host"]          # stamped at finish
        assert sched["end"] >= sched["start"] > 0
        # idempotent: nothing left to flush
        assert tr.flush() == 0

    def test_from_conf_disabled_returns_none(self):
        conf = JobConf()
        assert tracing.Tracer.from_conf(conf, "x") is None
        conf.set("tpumr.trace.enabled", True)
        assert tracing.Tracer.from_conf(conf, "x") is not None

    def test_ambient_noop_when_inactive(self):
        # the off fast path: no tracer installed → span yields None and
        # records nothing, instant returns without touching anything
        with tracing.span("anything", foo=1) as s:
            assert s is None
        tracing.instant("marker", bar=2)

    def test_ambient_nesting_and_thread_capture(self, tmp_path):
        import threading
        tr = tracing.Tracer("tasktracker", trace_dir=str(tmp_path))
        run = tr.start_span("task:run", "job_x_2", role="task")
        with tracing.activate(tr, run):
            with tracing.span("map:spill", records=5) as s:
                assert s.parent_span_id == run.span_id
                assert s.role == "task"      # inherited from parent
            cap = tracing.capture()

            def worker():
                with tracing.activate_captured(cap):
                    tracing.instant("shuffle:penalty", delay_s=0.1)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        tr.finish(run)
        tr.flush()
        spans = tracing.read_trace_files(str(tmp_path), "job_x_2")
        names = {s["name"] for s in spans}
        assert names == {"task:run", "map:spill", "shuffle:penalty"}
        pen = next(s for s in spans if s["name"] == "shuffle:penalty")
        assert pen["parent_span_id"] == run.span_id

    def test_chrome_trace_schema_and_validation(self):
        tr = tracing.Tracer("jobtracker")
        a = tr.start_span("job", "t1")
        tr.finish(a)
        doc = tracing.to_chrome_trace([s.to_dict() for s in tr.pending()])
        assert tracing.validate_chrome_trace(doc) == []
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert len(xs) == 1 and xs[0]["name"] == "job"
        assert any(m["name"] == "process_name" for m in metas)
        assert tracing.validate_chrome_trace({"nope": 1})
        assert tracing.validate_chrome_trace(
            {"traceEvents": [{"ph": "??", "pid": 1, "name": "x"}]})

    def test_critical_path_follows_dependency_chain(self):
        # job(0..10) with a zero-width schedule marker whose task
        # subtree (2..9) dominates, plus a short finalize (9.5..10):
        # the path must pass THROUGH the marker into the task, and the
        # summed durations must cover the makespan
        def span(name, sid, parent, start, end, role="jobtracker"):
            return {"trace_id": "t", "span_id": sid,
                    "parent_span_id": parent, "name": name, "role": role,
                    "backend": "", "start": start, "end": end,
                    "attributes": {}}

        spans = [
            span("job", "r", "", 0.0, 10.0),
            span("schedule", "s", "r", 2.0, 2.0),
            span("task:run", "t", "s", 2.0, 9.0, role="task"),
            span("job:finalize", "f", "r", 9.5, 10.0),
        ]
        cp = tracing.critical_path(spans)
        names = [p["name"] for p in cp["path"]]
        assert names == ["job", "schedule", "task:run", "job:finalize"]
        assert cp["makespan_s"] == pytest.approx(10.0)
        assert cp["total_s"] >= cp["makespan_s"]
        # contributions: the task dominates, and they sum to ~100%
        by = {p["name"]: p for p in cp["path"]}
        assert by["task:run"]["contribution_pct"] > 50
        assert sum(p["contribution_pct"] for p in cp["path"]) == \
            pytest.approx(100.0, abs=0.5)

    def test_swimlane_svg_escapes_and_renders(self):
        spans = [{"trace_id": "t", "span_id": "a", "parent_span_id": "",
                  "name": "<script>x</script>", "role": "task",
                  "backend": "tpu", "start": 0.0, "end": 1.0,
                  "attributes": {"attempt_id": "a1"}}]
        svg = tracing.swimlane_svg(spans)
        assert "<svg" in svg and "<script>x" not in svg
        assert tracing.swimlane_svg([]).startswith("<p")


# ------------------------------------------------------------ cluster


@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    hist = str(tmp_path_factory.mktemp("trace-hist"))
    conf = JobConf()
    conf.set("tpumr.history.dir", hist)
    conf.set("tpumr.trace.enabled", True)
    conf.set("mapred.job.tracker.http.port", 0)
    with MiniMRCluster(num_trackers=2, cpu_slots=2, tpu_slots=0,
                       conf=conf) as c:
        c.history_dir = hist
        yield c


def run_wc(cluster, name, n_maps=2, n_reduces=1):
    fs = get_filesystem("mem:///")
    fs.write_bytes(f"/tr/{name}.txt", b"alpha beta\nbeta gamma\n" * 100)
    conf = cluster.create_job_conf()
    conf.set_input_paths(f"mem:///tr/{name}.txt")
    conf.set_output_path(f"mem:///tr/{name}-out")
    conf.set_class("mapred.mapper.class", WcMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    conf.set("mapred.map.tasks", n_maps)
    conf.set("mapred.min.split.size", 1)
    conf.set_num_reduce_tasks(n_reduces)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    return result


def wait_for_spans(cluster, jid, pred, timeout=5.0):
    """Tracker task-thread flushes can land a beat after the client sees
    SUCCEEDED — poll the merged trace briefly."""
    deadline = time.monotonic() + timeout
    while True:
        t = cluster.master.get_job_trace(jid)
        if pred(t["spans"]) or time.monotonic() > deadline:
            return t
        time.sleep(0.05)


class TestMasterOnlyTracing:
    def test_master_flag_propagates_into_job_conf(self, tmp_path):
        """tpumr.trace.enabled on the MASTER conf alone must still
        produce a complete trace: trackers and children build their
        tracers from the job conf, so the master stamps both the trace
        id AND the enabled flag into it at submit."""
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        conf.set("tpumr.trace.enabled", True)
        master = JobMaster(conf)
        try:
            jid = master.submit_job({"mapred.reduce.tasks": 0},
                                    [{"locations": []}])
            jip = master.jobs[jid]
            assert jip.trace_id == jid
            # what get_job_conf ships to every tracker/child
            assert jip.conf["tpumr.trace.enabled"] is True
            assert jip.conf["tpumr.trace.id"] == jid
        finally:
            master.stop()

    def test_sink_converges_and_stale_trace_id_rejected(self, tmp_path):
        """One authoritative trace dir for writers AND readers (the
        master's, stamped into the job conf), and a clone-and-rerun of
        an old job's conf must get a FRESH trace id — never append to
        the previous job's files."""
        from tpumr.mapred.jobtracker import JobMaster
        master_dir = str(tmp_path / "master")
        conf = JobConf()
        conf.set("tpumr.history.dir", master_dir)
        master = JobMaster(conf)
        try:
            jid = master.submit_job(
                {"mapred.reduce.tasks": 0,
                 "tpumr.trace.enabled": True,
                 # a cloned conf carrying another job's id + own dir
                 "tpumr.trace.id": "job_stale_0001",
                 "tpumr.trace.dir": str(tmp_path / "client")},
                [{"locations": []}])
            jip = master.jobs[jid]
            assert jip.trace_id == jid            # fresh, not the clone's
            # master's dir wins and is what trackers/children will use
            assert jip.conf["tpumr.trace.dir"] == master_dir
            t = master.get_job_trace(jid)
            assert {s["trace_id"] for s in t["spans"]} == {jid}
        finally:
            master.stop()


class TestMiniClusterTracing:
    def test_wordcount_e2e_trace(self, traced_cluster):
        """Acceptance: one merged Chrome trace with spans from ≥3 roles,
        consistent trace_id/parent links, schema-validated, and a
        critical path whose durations sum past the measured makespan
        lower bound (the longest single task span)."""
        result = run_wc(traced_cluster, "e2e")
        jid = str(result.job_id)
        t = wait_for_spans(
            traced_cluster, jid,
            lambda spans: {"jobtracker", "tasktracker", "task"} <=
            {s["role"] for s in spans})
        spans = t["spans"]
        roles = {s["role"] for s in spans}
        assert {"jobtracker", "tasktracker", "task"} <= roles
        # one trace id, every parent link resolvable in-trace
        assert {s["trace_id"] for s in spans} == {jid}
        ids = {s["span_id"] for s in spans}
        orphans = [s for s in spans
                   if s["parent_span_id"] and s["parent_span_id"] not in ids]
        assert not orphans, orphans
        names = {s["name"] for s in spans}
        assert {"job", "job:submit", "schedule", "task:launch",
                "task:run", "reduce:shuffle", "shuffle:fetch",
                "job:finalize"} <= names
        # trace-event export is loadable by the schema
        chrome = tracing.to_chrome_trace(spans)
        assert tracing.validate_chrome_trace(chrome) == []
        # the critical path covers at least the longest task span (a
        # hard lower bound on the job makespan)
        cp = tracing.critical_path(spans)
        task_max = max(s["end"] - s["start"] for s in spans
                       if s["role"] == "task")
        assert cp["total_s"] >= task_max
        assert cp["makespan_s"] >= task_max
        assert [p["name"] for p in cp["path"]][0] == "job"
        assert any(p["role"] == "task" for p in cp["path"])
        # CI artifact: the merged trace of this e2e run (uploaded by
        # .github/workflows/tier1.yml)
        out = os.environ.get("TPUMR_E2E_TRACE_OUT",
                             "/tmp/tpumr-e2e-trace.json")
        try:
            with open(out, "w") as f:
                json.dump(chrome, f, indent=1)
        except OSError:
            pass

    def test_http_endpoints_and_cli_export(self, traced_cluster,
                                           tmp_path):
        result = run_wc(traced_cluster, "http")
        jid = str(result.job_id)
        wait_for_spans(traced_cluster, jid,
                       lambda spans: any(s["role"] == "task"
                                         for s in spans))
        base = traced_cluster.master.http_url
        code, body = fetch(base + f"/tracejson?job={jid}")
        assert code == 200
        doc = json.loads(body)
        assert tracing.validate_chrome_trace(doc) == []
        assert any(e.get("ph") == "X" for e in doc["traceEvents"])
        code, body = fetch(base + f"/trace?job={jid}")
        assert code == 200
        assert "<svg" in body and "Critical path" in body
        code, body = fetch(base + f"/json/trace?job={jid}")
        assert code == 200 and json.loads(body)["trace_id"] == jid
        # the job page links the timeline
        code, body = fetch(base + f"/job?id={jid}")
        assert f"/trace?job={jid}" in body

        # CLI offline export: merges the flushed span files directly
        from tpumr.cli import main as cli_main
        out = str(tmp_path / "t.json")
        cwd = os.getcwd()
        os.chdir(str(tmp_path))
        try:
            rc = cli_main(["job", "trace", jid, "-dir",
                           traced_cluster.history_dir, "-out", out])
        finally:
            os.chdir(cwd)
        assert rc == 0
        exported = json.load(open(out))
        assert tracing.validate_chrome_trace(exported) == []
        # unknown job: error, not a traceback
        rc = cli_main(["job", "trace", "job_nope_1", "-dir",
                       traced_cluster.history_dir])
        assert rc == 1

    def test_off_by_default_and_output_bytes_unchanged(
            self, tmp_path_factory):
        """Tracing is opt-in: an untraced cluster writes no span files
        and stamps no trace context; enabling it changes observability
        only — job output bytes are identical."""
        hist = str(tmp_path_factory.mktemp("untraced-hist"))
        conf = JobConf()
        conf.set("tpumr.history.dir", hist)
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/ob/in.txt", b"x y x\ny z x\n" * 50)

            def run(name, traced):
                jc = c.create_job_conf()
                jc.set_input_paths("mem:///ob/in.txt")
                jc.set_output_path(f"mem:///ob/{name}")
                jc.set_class("mapred.mapper.class", WcMapper)
                jc.set_class("mapred.reducer.class", SumReducer)
                jc.set_num_reduce_tasks(1)
                if traced:
                    jc.set("tpumr.trace.enabled", True)
                result = JobClient(jc).run_job(jc)
                assert result.successful
                return b"".join(
                    fs.read_bytes(st.path)
                    for st in sorted(fs.list_files(f"mem:///ob/{name}"),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path)), str(result.job_id)

        # plain job: off by default — no trace id, no span files
            plain_bytes, plain_jid = run("plain", traced=False)
            assert c.master.jobs[plain_jid].trace_id == ""
            t = c.master.get_job_trace(plain_jid)
            assert t["spans"] == [] and "not traced" in t["error"]
            assert not [f for f in os.listdir(hist)
                        if f.startswith("trace-")]
            # per-JOB opt-in on an untraced cluster still traces
            traced_bytes, traced_jid = run("traced", traced=True)
            assert c.master.jobs[traced_jid].trace_id == traced_jid
            time.sleep(0.3)
            spans = c.master.get_job_trace(traced_jid)["spans"]
            assert {s["role"] for s in spans} >= {"jobtracker", "task"}
            # observability must not perturb the data plane
            assert plain_bytes == traced_bytes and plain_bytes


class TestTracePropagationThroughReexecution:
    def test_trace_survives_fetch_failure_withdrawal(self):
        """PR 1's recovery path, traced: a persistent serve fault burns
        the map's first attempt; the re-executed attempt's spans join
        the SAME trace with consistent parent links, and the master's
        withdrawal decision is on the timeline."""
        fi.reset()
        import tempfile
        hist = tempfile.mkdtemp(prefix="trace-ff-")
        base = JobConf()
        base.set("tpumr.history.dir", hist)
        base.set("tpumr.trace.enabled", True)
        base.set("tpumr.fi.shuffle.serve.a0.probability", 1.0)
        base.set("tpumr.shuffle.fetch.retries.per.source", 1)
        base.set("tpumr.shuffle.copy.backoff.ms", 10)
        base.set("tpumr.shuffle.copy.backoff.max.ms", 100)
        base.set("mapred.max.fetch.failures.per.map", 2)
        try:
            with MiniMRCluster(num_trackers=2, conf=base) as c:
                fs = get_filesystem("mem:///")
                fs.write_bytes("/tff/in.txt", b"w x\n" * 500)
                conf = c.create_job_conf()
                conf.set_input_paths("mem:///tff/in.txt")
                conf.set_output_path("mem:///tff/out")
                conf.set("mapred.mapper.class",
                         "tpumr.mapred.lib.TokenCountMapper")
                conf.set("mapred.reducer.class",
                         "tpumr.examples.basic.LongSumReducer")
                conf.set("mapred.map.tasks", 1)
                conf.set_num_reduce_tasks(2)
                result = JobClient(conf).run_job(conf)
                assert result.successful
                jid = str(result.job_id)
                t = wait_for_spans(
                    c, jid,
                    lambda spans: any(
                        s["name"] == "fetch_failure:withdraw"
                        for s in spans))
                spans = t["spans"]
                # the withdrawal decision is a traced event
                withdraw = [s for s in spans
                            if s["name"] == "fetch_failure:withdraw"]
                assert withdraw
                assert withdraw[0]["attributes"]["reexecuted"] is True
                # BOTH map attempt generations ran under this trace
                map_runs = sorted(
                    (s["attributes"].get("attempt_id", "")
                     for s in spans
                     if s["name"] == "task:run"
                     and "_m_" in s["attributes"].get("attempt_id", "")))
                assert len(map_runs) == 2, map_runs
                assert map_runs[0].endswith("_0")
                assert map_runs[1].endswith("_1")
                # single trace, no dangling parents — the re-run's spans
                # hang off their own schedule span under the same root
                assert {s["trace_id"] for s in spans} == {jid}
                ids = {s["span_id"] for s in spans}
                assert not [s for s in spans if s["parent_span_id"]
                            and s["parent_span_id"] not in ids]
                # shuffle penalty/report spans from the stalled reduces
                assert any(s["name"] == "shuffle:penalty"
                           for s in spans)
                # no reduce attempt was failed by the fault (PR 1's
                # contract, restated under tracing)
                jip = c.master.jobs[jid]
                for tip in jip.reduces:
                    assert not [s for s in tip.attempts.values()
                                if s.state == TaskState.FAILED]
        finally:
            fi.reset()
            FileSystem.clear_cache()
