"""Tools tier ≈ src/tools (DistCp, archives, rumen) + io.MapFile
(SURVEY.md §2.2, §2.4)."""

import json

import pytest

from tpumr.cli import main as cli_main
from tpumr.fs import get_filesystem
from tpumr.io import mapfile


class TestMapFile:
    def test_write_get_iterate(self):
        fs = get_filesystem("mem:///")
        with mapfile.Writer(fs, "/mf/table", index_interval=8) as w:
            for i in range(0, 1000, 2):   # even keys only
                w.append(f"k{i:06d}", i * 10)
        with mapfile.Reader(fs, "/mf/table") as r:
            assert r.get("k000000") == 0
            assert r.get("k000498") == 4980
            assert r.get("k000998") == 9980
            assert r.get("k000499") is None          # odd: absent
            assert r.get("a") is None                # before first
            assert r.get("z") is None                # after last
            k, v = r.get_closest("k000499")
            assert k == "k000500" and v == 5000
            assert len(list(r)) == 500

    def test_duplicate_keys_across_index_boundary(self):
        # 200 records with the same key and index_interval=128: get() must
        # return the FIRST record's value, not the one at the 2nd index entry
        fs = get_filesystem("mem:///")
        with mapfile.Writer(fs, "/mf/dups", index_interval=128) as w:
            for i in range(200):
                w.append("same", i)
            w.append("tail", 999)
        with mapfile.Reader(fs, "/mf/dups") as r:
            assert r.get("same") == 0
            assert r.get("tail") == 999
            k, v = r.get_closest("s")
            assert k == "same" and v == 0

    def test_rejects_out_of_order_keys(self):
        fs = get_filesystem("mem:///")
        with pytest.raises(ValueError, match="out of order"):
            with mapfile.Writer(fs, "/mf/bad") as w:
                w.append("b", 1)
                w.append("a", 2)


class TestDistCp:
    def test_tree_copy_across_schemes(self, tmp_path):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dc/src/a.txt", b"alpha")
        fs.write_bytes("/dc/src/sub/b.txt", b"beta" * 1000)
        dst = tmp_path / "out"
        assert cli_main(["distcp", "mem:///dc/src", f"file://{dst}",
                         "-m", "2"]) == 0
        assert (dst / "a.txt").read_bytes() == b"alpha"
        assert (dst / "sub/b.txt").read_bytes() == b"beta" * 1000

    def test_update_skips_same_size(self, tmp_path):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dc2/src/x.txt", b"12345")
        dst = tmp_path / "out2"
        assert cli_main(["distcp", "mem:///dc2/src", f"file://{dst}"]) == 0
        # second run with -update: nothing breaks, file intact
        assert cli_main(["distcp", "mem:///dc2/src", f"file://{dst}",
                         "-update"]) == 0
        assert (dst / "x.txt").read_bytes() == b"12345"

    def test_single_file(self, tmp_path):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dc3/one.bin", b"\x00\x01\x02")
        assert cli_main(["distcp", "mem:///dc3/one.bin",
                         f"file://{tmp_path}/one.bin"]) == 0
        assert (tmp_path / "one.bin").read_bytes() == b"\x00\x01\x02"


class TestArchive:
    def test_create_list_read(self, capsys):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/ar/src/x.txt", b"XX")
        fs.write_bytes("/ar/src/d/y.txt", b"YYYY")
        fs.write_bytes("/ar/src/d/z.txt", b"Z" * 100)
        assert cli_main(["archive", "mem:///ar/src",
                         "mem:///ar/packed.tharch"]) == 0
        assert "Archived 3 files" in capsys.readouterr().out

        assert cli_main(["archive", "-ls", "mem:///ar/packed.tharch"]) == 0
        listing = capsys.readouterr().out
        assert "d/y.txt" in listing and "x.txt" in listing

        # transparent reads through the tharch:// FileSystem
        afs = get_filesystem("tharch://mem/ar/packed.tharch")
        assert afs.read_bytes(
            "tharch://mem/ar/packed.tharch/x.txt") == b"XX"
        assert afs.read_bytes(
            "tharch://mem/ar/packed.tharch/d/y.txt") == b"YYYY"
        st = afs.get_status("tharch://mem/ar/packed.tharch/d")
        assert st.is_dir
        names = {str(s.path.name)
                 for s in afs.list_status("tharch://mem/ar/packed.tharch/d")}
        assert names == {"y.txt", "z.txt"}
        with pytest.raises(FileNotFoundError):
            afs.read_bytes("tharch://mem/ar/packed.tharch/nope")
        with pytest.raises(PermissionError):
            afs.delete("tharch://mem/ar/packed.tharch/x.txt")

    def test_archive_as_job_input(self):
        """MR over archived inputs — the many-small-files use case."""
        fs = get_filesystem("mem:///")
        fs.write_bytes("/aj/src/f1.txt", b"one two\n")
        fs.write_bytes("/aj/src/f2.txt", b"two three\n")
        assert cli_main(["archive", "mem:///aj/src",
                         "mem:///aj/a.tharch"]) == 0
        assert cli_main(["examples", "wordcount",
                         "tharch://mem/aj/a.tharch/f1.txt,"
                         "tharch://mem/aj/a.tharch/f2.txt",
                         "mem:///aj/out", "--cpu-only"]) == 0
        text = fs.read_bytes("/aj/out/part-00000").decode()
        counts = dict(l.split("\t") for l in text.splitlines())
        assert counts == {"one": "1", "two": "2", "three": "1"}


class TestRumen:
    def test_traces_from_history(self, tmp_path, capsys):
        hist = tmp_path / "hist"
        hist.mkdir()
        events = [
            {"event": "JOB_SUBMITTED", "job_id": "job_x_1",
             "job_name": "demo", "num_maps": 2, "num_reduces": 1,
             "kernel": "kmeans-assign", "ts": 1.0},
            {"event": "TASK_FINISHED", "attempt_id": "attempt_m1_0",
             "is_map": True, "run_on_tpu": True, "tpu_device_id": 0,
             "runtime": 0.5, "tracker": "t0", "ts": 2.0},
            {"event": "TASK_FINISHED", "attempt_id": "attempt_m2_0",
             "is_map": True, "run_on_tpu": False, "tpu_device_id": -1,
             "runtime": 2.0, "tracker": "t0", "ts": 3.0},
            {"event": "JOB_FINISHED", "state": "SUCCEEDED",
             "wall_time": 3.0, "acceleration_factor": 4.0, "ts": 4.0},
        ]
        with open(hist / "job_x_1.jsonl", "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        assert cli_main(["rumen", str(hist)]) == 0
        traces = json.loads(capsys.readouterr().out)
        assert len(traces) == 1
        t = traces[0]
        assert t["job_id"] == "job_x_1" and t["outcome"] == "SUCCEEDED"
        assert t["cpu_task_mean"] == 2.0 and t["tpu_task_mean"] == 0.5
        backends = {x["backend"] for x in t["tasks"]}
        assert backends == {"cpu", "tpu"}

class TestFailmon:
    def test_collect_upload_merge_roundtrip(self, tmp_path, capsys):
        from tpumr.tools import failmon
        log = tmp_path / "daemon.log"
        log.write_text("INFO fine\nERROR disk on fire\nINFO ok\n")
        store = failmon.LocalStore(str(tmp_path / "store"))
        mons = [failmon.CpuMonitor(), failmon.MemoryMonitor(),
                failmon.DiskMonitor([str(tmp_path)]),
                failmon.LogMonitor(str(log))]
        n = failmon.run_once(store, mons)
        assert n >= 3
        # persistent offset: second pass reports no OLD error lines
        n2_events = []
        state = store.load_state()
        for ev in failmon.LogMonitor(str(log)).poll(state):
            n2_events.append(ev)
        assert n2_events == []
        # new error appended -> exactly one new event
        with open(log, "a") as f:
            f.write("FATAL cascading failure\n")
        new = list(failmon.LogMonitor(str(log)).poll(state))
        assert len(new) == 1 and "cascading" in new[0]["line"]

        # upload + merge through the FS abstraction
        dest = store.upload("mem:///fm/uploads")
        assert dest and dest.endswith(".jsonl")
        total = failmon.merge("mem:///fm/uploads", "mem:///fm/all.jsonl")
        assert total == n
        from tpumr.fs import get_filesystem
        lines = get_filesystem("mem:///").read_bytes(
            "mem:///fm/all.jsonl").decode().splitlines()
        assert len(lines) == n
        import json as _json
        kinds = {(_json.loads(l)["source"]) for l in lines}
        assert {"cpu", "memory", "disk", "log"} <= kinds
        # events are time-ordered after merge
        ts = [_json.loads(l)["ts"] for l in lines]
        assert ts == sorted(ts)

    def test_cli_and_anonymize(self, tmp_path, capsys):
        rc = cli_main(["failmon", "-collect", "-store",
                       str(tmp_path / "s"), "-anonymize"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "collected" in out
        import json as _json
        events = [_json.loads(l) for l in
                  (tmp_path / "s" / "failmon.events.jsonl")
                  .read_text().splitlines()]
        assert events and all(e["host"].startswith("host-")
                              for e in events)

    def test_log_monitor_truncated_pass_still_advances_offset(self, tmp_path):
        """A log with more matches than max_events must not re-emit old
        lines on the next pass — the offset advances past scanned bytes."""
        from tpumr.tools import failmon
        log = tmp_path / "busy.log"
        log.write_text("".join(f"ERROR e{i}\n" for i in range(150)))
        mon = failmon.LogMonitor(str(log), max_events=100)
        state: dict = {}
        first = list(mon.poll(state))
        assert len(first) == 100
        second = list(mon.poll(state))
        assert len(second) == 50
        assert second[0]["line"] == "ERROR e100"
        assert list(mon.poll(state)) == []

    def test_log_monitor_waits_for_complete_lines(self, tmp_path):
        """A partial trailing line (writer mid-append) is neither emitted
        nor skipped — the next poll sees it whole."""
        from tpumr.tools import failmon
        log = tmp_path / "p.log"
        log.write_bytes(b"ERROR one\nERR")  # append in progress
        mon = failmon.LogMonitor(str(log))
        state: dict = {}
        first = list(mon.poll(state))
        assert [e["line"] for e in first] == ["ERROR one"]
        with open(log, "ab") as f:
            f.write(b"OR two\n")
        second = list(mon.poll(state))
        assert [e["line"] for e in second] == ["ERROR two"]

    def test_log_monitor_emits_dead_writers_last_gasp(self, tmp_path):
        """An unterminated final line whose file stops growing (writer
        died mid-write) is emitted after one grace poll — exactly once."""
        from tpumr.tools import failmon
        log = tmp_path / "gasp.log"
        log.write_bytes(b"INFO ok\nERROR fatal oom")  # no trailing \n
        mon = failmon.LogMonitor(str(log))
        state: dict = {}
        assert list(mon.poll(state)) == []      # grace poll: wait
        second = list(mon.poll(state))          # size unchanged: emit
        assert [e["line"] for e in second] == ["ERROR fatal oom"]
        assert list(mon.poll(state)) == []      # once only

    def test_merge_never_remerges_its_own_output(self, tmp_path):
        from tpumr.tools import failmon
        store = failmon.LocalStore(str(tmp_path / "s4"))
        store.append([failmon.event("t", "x"), failmon.event("t", "y")])
        assert store.upload("mem:///fm3") is not None
        dest = "mem:///fm3/all.jsonl"
        assert failmon.merge("mem:///fm3", dest) == 2
        assert failmon.merge("mem:///fm3", dest) == 2  # idempotent rerun

    def test_upload_failure_keeps_events(self, tmp_path):
        from tpumr.tools import failmon
        store = failmon.LocalStore(str(tmp_path / "s3"))
        store.append([failmon.event("t", "x")])
        import pytest
        with pytest.raises(Exception):
            store.upload("nosuchscheme://nope")
        # events folded back — a later good upload ships them
        dest = store.upload("mem:///fm2/up")
        assert dest is not None

    def test_cli_rejects_bad_flags(self, capsys):
        assert cli_main(["failmon", "-collect", "-anonymise"]) == 255
        assert "bad or valueless" in capsys.readouterr().err
        assert cli_main(["failmon", "-collect", "-store"]) == 255

    def test_monitor_failure_does_not_kill_the_pass(self, tmp_path):
        from tpumr.tools import failmon

        class Bad(failmon.Monitor):
            name = "bad"

            def poll(self, state):
                raise RuntimeError("sensor exploded")

        store = failmon.LocalStore(str(tmp_path / "s2"))
        n = failmon.run_once(store, [Bad(), failmon.CpuMonitor()])
        assert n == 2  # the failure event + the cpu event
        text = (tmp_path / "s2" / "failmon.events.jsonl").read_text()
        assert "monitor-failed" in text and "sensor exploded" in text


class TestVaidya:
    def test_vaidya_rules_on_synthetic_history(self):
        from tpumr.core.counters import TaskCounter
        from tpumr.tools.vaidya import diagnose
        fw = TaskCounter.FRAMEWORK_GROUP

        def task(i, is_map, event="TASK_FINISHED", runtime=5.0, tpu=False,
                 counters=None):
            return {"event": event, "attempt_id": f"a{i}", "is_map": is_map,
                    "run_on_tpu": tpu, "runtime": runtime,
                    "counters": counters or {}}

        # skewed reduces: one reducer carries ~all records; maps spill 3x
        events = [
            {"event": "JOB_SUBMITTED", "job_id": "job_v_1",
             "job_name": "skewed", "num_maps": 2, "num_reduces": 4},
            task(0, True, counters={fw: {
                TaskCounter.MAP_OUTPUT_RECORDS: 100,
                TaskCounter.SPILLED_RECORDS: 300}}),
            task(1, True, event="TASK_FAILED"),
            *[task(10 + r, False, counters={fw: {
                TaskCounter.REDUCE_INPUT_RECORDS:
                    1000 if r == 0 else 1}}) for r in range(4)],
            {"event": "JOB_FINISHED", "state": "SUCCEEDED",
             "wall_time": 10.0, "acceleration_factor": 0.0},
        ]
        report = diagnose(events)
        hit = {f["test"] for f in report["findings"]}
        assert "balanced-reduce-partitioning" in hit
        assert "map-side-disk-spill" in hit
        assert "maps-reexecution-impact" in hit
        top = report["findings"][0]
        assert top["importance"] == "High" and top["prescription"]

    def test_vaidya_backend_placement_rule(self):
        from tpumr.tools.vaidya import diagnose
        # TPU 8x faster but nearly all map runtime spent on CPU slots
        events = [
            {"event": "JOB_SUBMITTED", "job_id": "job_v_2",
             "job_name": "misplaced", "num_maps": 10, "num_reduces": 1},
            *[{"event": "TASK_FINISHED", "attempt_id": f"m{i}",
               "is_map": True, "run_on_tpu": False, "runtime": 8.0,
               "counters": {}} for i in range(9)],
            {"event": "TASK_FINISHED", "attempt_id": "m9", "is_map": True,
             "run_on_tpu": True, "runtime": 1.0, "counters": {}},
            {"event": "JOB_FINISHED", "state": "SUCCEEDED",
             "wall_time": 20.0, "acceleration_factor": 8.0},
        ]
        report = diagnose(events)
        hit = {f["test"]: f for f in report["findings"]}
        assert "backend-placement" in hit
        assert "tpu" in hit["backend-placement"]["prescription"].lower()
        # balanced case: no finding
        events[-1]["acceleration_factor"] = 1.0
        assert "backend-placement" not in {
            f["test"] for f in diagnose(events)["findings"]}

    def test_vaidya_cli_on_live_cluster_history(self, tmp_path, capsys):
        from tpumr.mapred.jobconf import JobConf
        from tpumr.mapred.mini_cluster import MiniMRCluster
        from tpumr.mapred.job_client import JobClient
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/vd/in.txt", b"p q\n" * 20)
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///vd/in.txt")
            jc.set_output_path("mem:///vd/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            from tpumr.examples.basic import LongSumReducer
            jc.set_class("mapred.mapper.class", WordCountCpuMapper)
            jc.set_class("mapred.reducer.class", LongSumReducer)
            result = JobClient(jc).run_job(jc)
            assert result.successful
            job_id = str(result.job_id)
        rc = cli_main(["job", "-diagnose", job_id, str(tmp_path), "-json"])
        report = json.loads(capsys.readouterr().out)
        assert rc in (0, 2)
        assert report["job_id"] == job_id
        assert report["state"] == "SUCCEEDED"
        assert {r["test"] for r in
                report["findings"] + report["passed"]} >= {
            "balanced-reduce-partitioning", "map-side-disk-spill",
            "backend-placement", "map-granularity"}

    def test_live_cluster_history_has_task_events(self, tmp_path):
        from tpumr.mapred.jobconf import JobConf
        from tpumr.mapred.mini_cluster import MiniMRCluster
        from tpumr.mapred.job_client import JobClient
        from tpumr.tools.rumen import build_traces
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/ru/in.txt", b"p q\n" * 20)
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///ru/in.txt")
            jc.set_output_path("mem:///ru/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            from tpumr.examples.basic import LongSumReducer
            jc.set_class("mapred.mapper.class", WordCountCpuMapper)
            jc.set_class("mapred.reducer.class", LongSumReducer)
            assert JobClient(jc).run_job(jc).successful
        traces = build_traces(str(tmp_path))
        assert traces and traces[0]["outcome"] == "SUCCEEDED"
        assert traces[0]["tasks"], "task events must be in history"
        assert traces[0]["cpu_task_mean"] is not None


class TestDistCpDeletePreserve:
    def test_delete_removes_extraneous(self, tmp_path):
        import os

        from tpumr.tools.distcp import distcp
        src = tmp_path / "src"; dst = tmp_path / "dst"
        os.makedirs(src / "sub"); os.makedirs(dst)
        (src / "a.txt").write_text("aaa")
        (src / "sub" / "b.txt").write_text("bbb")
        (dst / "stale.txt").write_text("old")
        assert distcp(f"file://{src}", f"file://{dst}", update=True,
                      delete=True)
        assert (dst / "a.txt").read_text() == "aaa"
        assert (dst / "sub" / "b.txt").read_text() == "bbb"
        assert not (dst / "stale.txt").exists()

    def test_delete_requires_update(self, tmp_path):
        import pytest as _pytest

        from tpumr.tools.distcp import distcp
        with _pytest.raises(ValueError, match="requires -update"):
            distcp(f"file://{tmp_path}", f"file://{tmp_path}/o",
                   delete=True)

    def test_preserve_owner_and_mode_onto_tdfs(self, tmp_path):
        import os

        from tpumr.dfs.mini_cluster import MiniDFSCluster
        from tpumr.fs import get_filesystem
        from tpumr.mapred.jobconf import JobConf
        from tpumr.tools.distcp import distcp
        src = tmp_path / "src"; os.makedirs(src)
        (src / "f.txt").write_text("data")
        with MiniDFSCluster(num_datanodes=1,
                            root=str(tmp_path / "c")) as c:
            conf = JobConf()
            dst = c.uri + "/copied"
            assert distcp(f"file://{src}", dst, update=True,
                          preserve=True, conf=conf)
            fs = get_filesystem(dst + "/", conf)
            st = fs.get_status(dst + "/f.txt")
            assert st.length == 4
            # local source reports no owner/perm accessor -> best-effort
            # no-op is acceptable; round-trip the tdfs-native case too
            fs.set_permission(dst + "/f.txt", 0o640)
            dst2 = c.uri + "/copied2"
            assert distcp(dst + "/f.txt", dst2, update=True,
                          preserve=True, conf=conf)
            assert fs.get_permission(dst2) == 0o640

    def test_delete_sweeps_stale_dirs_and_empty_source(self, tmp_path):
        import os

        from tpumr.tools.distcp import distcp
        src = tmp_path / "src"; dst = tmp_path / "dst"
        os.makedirs(src); os.makedirs(dst / "old" / "deep")
        (dst / "old" / "deep" / "x.txt").write_text("stale")
        (dst / "keep.txt").write_text("stale-too")
        # EMPTY source + -delete: everything extraneous goes
        assert distcp(f"file://{src}", f"file://{dst}", update=True,
                      delete=True)
        assert not (dst / "old").exists()
        assert not (dst / "keep.txt").exists()
