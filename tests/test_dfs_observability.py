"""DFS observability: namenode op/lock attribution, audit log, the
SpaceSaving hot-block pipeline (DN sketch → heartbeat → NN fold →
/hotblocks), datanode read-path metrics, the uniform prom surfaces on
NN + DN, the NN flight-recorder incident e2e, and the bench_dfs row
contract."""

import json
import logging
import os
import shutil
import time
import urllib.request

import pytest

from tpumr.dfs.hotblocks import HotBlockTable, SpaceSaving
from tpumr.dfs.mini_cluster import MiniDFSCluster
from tpumr.mapred.jobconf import JobConf
from tpumr.metrics.flightrec import validate_incident
from tpumr.metrics.histogram import Histogram
from tpumr.metrics.locks import RANK_NAMESPACE, lock_table
from tpumr.metrics.prometheus import validate_exposition


def fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def small_conf(block_size=1024, replication=2):
    conf = JobConf()
    conf.set("dfs.block.size", block_size)
    conf.set("dfs.replication", replication)
    return conf


# ------------------------------------------------------------ SpaceSaving


class TestSpaceSaving:
    def test_accuracy_on_skewed_stream(self):
        sk = SpaceSaving(k=8)
        # 1 heavy hitter among uniform noise, N >> k
        for i in range(900):
            sk.offer(f"noise_{i % 40}")
            if i % 3 == 0:
                sk.offer("hot")
        assert sk.total == 1200
        rows = sk.topk(1)
        assert rows[0][0] == "hot"
        # the SpaceSaving bound: count - err <= true <= count
        _, count, err = rows[0]
        assert count - err <= 300 <= count

    def test_bounded_memory(self):
        sk = SpaceSaving(k=8)
        for i in range(1000):
            sk.offer(f"k{i}")
        assert len(sk) == 8
        assert sk.total == 1000

    def test_wire_round_trip_and_merge(self):
        a, b = SpaceSaving(k=4), SpaceSaving(k=4)
        for _ in range(10):
            a.offer("x")
        for _ in range(7):
            b.offer("x")
            b.offer("y")
        b2 = SpaceSaving.from_wire(
            json.loads(json.dumps(b.to_wire())))
        a.merge(b2)
        assert a.estimate("x") == 17
        assert a.estimate("y") == 7
        assert a.total == 24
        assert len(a) <= 4

    def test_merge_stays_bounded(self):
        a = SpaceSaving(k=4)
        for i in range(4):
            a.offer(f"a{i}", by=10)
        b = SpaceSaving(k=4)
        for i in range(4):
            b.offer(f"b{i}", by=20)
        a.merge(b)
        assert len(a) == 4
        # the larger stream's keys win the truncation
        assert all(key.startswith("b") for key, _c, _e in a.topk())


class TestHotBlockTable:
    def test_fold_is_idempotent(self):
        t = HotBlockTable(k=8)
        doc = {"total": 30, "top": [["5", 20, 0], ["9", 10, 0]]}
        t.fold("dn1:1", doc)
        t.fold("dn1:1", doc)   # re-delivered heartbeat
        assert t.total_reads() == 30
        top = t.top(2)
        assert top[0]["block"] == "5" and top[0]["reads"] == 20

    def test_merge_across_datanodes_and_drop(self):
        t = HotBlockTable(k=8)
        t.fold("dn1:1", {"total": 12, "top": [["5", 12, 0]]})
        t.fold("dn2:2", {"total": 9, "top": [["5", 6, 0], ["7", 3, 0]]})
        top = t.top(4)
        assert top[0]["block"] == "5" and top[0]["reads"] == 18
        assert sorted(top[0]["datanodes"]) == ["dn1:1", "dn2:2"]
        t.drop("dn1:1")   # dead datanode's reads stop counting
        assert t.total_reads() == 9
        assert t.top(1)[0]["reads"] == 6
        t.fold("dn2:2", None)   # empty piggyback is a no-op
        assert t.total_reads() == 9


# ------------------------------------------------------------ audit log


class TestAuditLog:
    def _ns(self, tmp_path, **conf_kv):
        from tpumr.dfs.namenode import FSNamesystem
        conf = small_conf()
        conf.set("tpumr.nn.audit.enabled", True)
        for k, v in conf_kv.items():
            conf.set(k, v)
        return FSNamesystem(str(tmp_path / "name"), conf)

    def test_create_delete_rename_lines(self, tmp_path, caplog):
        ns = self._ns(tmp_path)
        with caplog.at_level(logging.INFO, logger="tpumr.nn.audit"):
            ns.create("/a.txt", "cli_1", None, None, True)
            ns.rename("/a.txt", "/b.txt")
            ns.delete("/b.txt")
            ns.mkdirs("/d")
        lines = [r.getMessage() for r in caplog.records
                 if r.name == "tpumr.nn.audit"]
        assert any("cmd=create src=/a.txt" in ln for ln in lines)
        assert any("cmd=rename src=/a.txt dst=/b.txt" in ln
                   for ln in lines)
        assert any("cmd=delete src=/b.txt" in ln for ln in lines)
        assert any("cmd=mkdirs src=/d" in ln for ln in lines)
        # every line carries the caller identity field
        assert all("ugi=" in ln for ln in lines)
        assert ns.audit_emitted == 4 and ns.audit_suppressed == 0

    def test_rate_cap_counts_overflow(self, tmp_path, caplog):
        ns = self._ns(tmp_path, **{"tpumr.nn.audit.rate.limit": 5})
        with caplog.at_level(logging.INFO, logger="tpumr.nn.audit"):
            for i in range(40):
                ns.mkdirs(f"/r{i}")
        lines = [r for r in caplog.records if r.name == "tpumr.nn.audit"]
        # one wall-second window admits at most the cap (the loop can
        # straddle a window boundary, hence <= 2 windows' worth)
        assert len(lines) <= 10
        assert ns.audit_emitted + ns.audit_suppressed == 40
        assert ns.audit_suppressed >= 30

    def test_disabled_by_default(self, tmp_path, caplog):
        from tpumr.dfs.namenode import FSNamesystem
        ns = FSNamesystem(str(tmp_path / "name"), small_conf())
        with caplog.at_level(logging.INFO, logger="tpumr.nn.audit"):
            ns.mkdirs("/quiet")
        assert not [r for r in caplog.records
                    if r.name == "tpumr.nn.audit"]


# ------------------------------------------------------------ live cluster


@pytest.fixture(scope="module")
def obs_cluster():
    conf = small_conf()
    conf.set("tdfs.http.port", 0)
    conf.set("tpumr.dn.http.port", 0)
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        yield c


class TestNamespaceLock:
    def test_rank_and_lock_table(self, obs_cluster):
        rows = {r["name"]: r for r in lock_table()}
        assert "namespace" in rows
        assert rows["namespace"]["rank"] == RANK_NAMESPACE == 25

    def test_wait_hold_series_observe(self, obs_cluster):
        client = obs_cluster.client()
        client.mkdirs("/lockwork")
        reg = obs_cluster.namenode.metrics.snapshot()["namenode"]
        hold = reg["nn_lock_hold_seconds|lock=namespace"]
        assert hold["count"] > 0
        assert "nn_lock_wait_seconds|lock=namespace" in reg


class TestOpAndEditlogMetrics:
    def test_per_op_histograms(self, obs_cluster):
        client = obs_cluster.client()
        with client.create("/ops/f.bin") as f:
            f.write(b"z" * 2048)
        with client.open("/ops/f.bin") as f:
            assert len(f.read()) == 2048
        reg = obs_cluster.namenode.metrics.snapshot()["namenode"]
        for op in ("create", "add_block", "complete",
                   "get_block_locations"):
            assert reg[f"nn_op_seconds|op={op}"]["count"] > 0, op
        # heartbeats arrive on their own clock — poll for the first
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            reg = obs_cluster.namenode.metrics.snapshot()["namenode"]
            if reg.get("nn_op_seconds|op=dn_heartbeat", {}).get("count"):
                break
            time.sleep(0.05)
        assert reg["nn_op_seconds|op=dn_heartbeat"]["count"] > 0

    def test_editlog_hists_bound_to_nn(self, obs_cluster):
        client = obs_cluster.client()
        client.mkdirs("/editwork")
        reg = obs_cluster.namenode.metrics.snapshot()["namenode"]
        assert reg["nn_editlog_append_seconds"]["count"] > 0
        assert reg["nn_editlog_sync_seconds"]["count"] > 0
        assert reg["nn_editlog_batch_bytes"]["mean"] > 0

    def test_bare_namesystem_pays_nothing(self, tmp_path):
        # no NameNode, no registry: the editlog keeps its None hists
        from tpumr.dfs.namenode import FSNamesystem
        ns = FSNamesystem(str(tmp_path / "name"), small_conf())
        ns.mkdirs("/x")
        assert ns.edits._append_hist is None


class TestDatanodeReadPath:
    def test_read_metrics_and_sketch(self, obs_cluster):
        client = obs_cluster.client()
        with client.create("/dn/read.bin") as f:
            f.write(b"q" * 4096)
        for _ in range(3):
            with client.open("/dn/read.bin") as f:
                f.read()
        reads = bytes_ = 0
        for dn in obs_cluster.datanodes:
            reg = dn.metrics.snapshot()["datanode"]
            reads += reg.get("dn_read_seconds", {}).get("count", 0)
            bytes_ += reg.get("dn_read_bytes", {}).get("sum", 0)
            assert "dn_readers" in reg   # concurrent-reader gauge
        assert reads > 0 and bytes_ >= 4096
        assert sum(dn._hot.total for dn in obs_cluster.datanodes) > 0


class TestHotBlocksEndToEnd:
    def test_skewed_reads_rank_hot_block_first(self, obs_cluster):
        client = obs_cluster.client()
        with client.create("/hot/a.bin") as f:
            f.write(b"h" * 512)
        with client.create("/hot/b.bin") as f:
            f.write(b"c" * 512)
        for i in range(24):
            with client.open("/hot/a.bin") as f:
                f.read()
            if i % 8 == 0:
                with client.open("/hot/b.bin") as f:
                    f.read()
        # the sketch rides the NEXT heartbeat into the NN fold
        nn = obs_cluster.namenode
        deadline = time.monotonic() + 10.0
        top = []
        while time.monotonic() < deadline:
            top = nn.ns.get_hot_blocks(4)
            # reads land a bit under the raw 24: the locate response
            # shuffles replicas (the 24 reads split across both DNs'
            # sketches) and the per-heartbeat halflife decay ages them
            if top and top[0].get("path") == "/hot/a.bin" \
                    and top[0]["reads"] >= 16:
                break
            time.sleep(0.1)
        assert top and top[0]["path"] == "/hot/a.bin", top
        assert top[0]["reads"] >= 16
        assert top[0]["datanodes"], "no reporting datanode recorded"
        # the HTTP view serves the same ranking
        _, body = fetch(nn.http_url + "/hotblocks?n=4")
        doc = json.loads(body)
        assert doc["top"][0]["path"] == "/hot/a.bin"
        assert doc["total_reads"] >= doc["top"][0]["reads"]


class TestPromSurfaces:
    def test_namenode_exposition_validates(self, obs_cluster):
        client = obs_cluster.client()
        client.mkdirs("/prom")
        _, body = fetch(obs_cluster.namenode.http_url + "/metrics/prom")
        validate_exposition(body)   # raises on violation
        assert "nn_op_seconds" in body
        assert "nn_lock_wait_seconds" in body

    def test_datanode_exposition_and_status(self, obs_cluster):
        dn = obs_cluster.datanodes[0]
        assert dn.http_url, "datanode http did not start"
        _, body = fetch(dn.http_url + "/metrics/prom")
        validate_exposition(body)
        assert "dn_read" in body or "dn_readers" in body
        _, body = fetch(dn.http_url + "/metrics")
        assert "datanode" in json.loads(body)
        _, body = fetch(dn.http_url + "/hotblocks")
        doc = json.loads(body)
        assert set(doc) == {"total", "top"}


# ------------------------------------------------------------ incident e2e


@pytest.fixture(scope="module")
def incident_cluster(tmp_path_factory):
    """Mini-DFS with the NN flight recorder armed and the nn.op.slow
    seam stalling the first ops past the SLO."""
    inc_root = str(tmp_path_factory.mktemp("nn-incidents"))
    conf = small_conf()
    conf.set("tdfs.http.port", 0)
    conf.set("tpumr.prof.enabled", True)
    conf.set("tpumr.prof.incident.dir", inc_root)
    conf.set("tpumr.nn.incident.slo.ms", 250)
    conf.set("tpumr.prof.incident.cooldown.ms", 600_000)
    conf.set("tpumr.fi.nn.op.slow.probability", 1.0)
    conf.set("tpumr.fi.nn.op.slow.max.failures", 3)
    conf.set("tpumr.fi.nn.op.slow.ms", 400)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        c.incident_dir = os.path.join(inc_root, "incidents")
        yield c


class TestNNIncidentE2E:
    def test_breach_writes_valid_bundle(self, incident_cluster):
        nn = incident_cluster.namenode
        client = incident_cluster.client()
        client.mkdirs("/breach")   # op traffic through the stalled seam
        deadline = time.monotonic() + 15.0
        rows = []
        while time.monotonic() < deadline:
            _, body = fetch(nn.http_url + "/json/incidents")
            rows = json.loads(body)
            if rows:
                break
            time.sleep(0.25)
        assert rows, "no NN incident within deadline"
        assert rows[0]["reason"][0]["metric"].startswith("nn_op_seconds")
        _, body = fetch(nn.http_url + f"/incident?name={rows[0]['name']}")
        doc = json.loads(body)
        assert validate_incident(doc) == [], validate_incident(doc)
        assert doc["role"] == "namenode"
        assert doc["reason"][0]["p99_s"] > doc["slo_ms"] / 1000.0
        # the lock table rides along, namespace lock included
        assert any(r.get("name") == "namespace"
                   for r in doc["locks"]["live"])
        # the merged-op heartbeat section carries real counts
        assert doc["heartbeat"]["seconds"]["count"] > 0
        out = os.environ.get("TPUMR_INCIDENT_E2E_OUT")
        if out:
            os.makedirs(out, exist_ok=True)
            shutil.copy(os.path.join(incident_cluster.incident_dir,
                                     rows[0]["name"]),
                        os.path.join(out, "nn-" + rows[0]["name"]))

    def test_recorder_off_by_default(self, obs_cluster):
        assert obs_cluster.namenode.flightrec is None


# ------------------------------------------------------------ bench contract


REQUIRED_ROW_KEYS = {
    "clients", "wall_s", "ops", "errors", "completed",
    "nn_op_count", "nn_op_p50_s", "nn_op_p99_s", "nn_op_p99_by_op",
    "lock_wait_p99_s", "lock_hold_p99_s", "lock_wait_share",
    "lock_wait_p99_by_lock", "editlog_sync_p99_s",
    "editlog_group_ops_mean", "read_mb_s", "read_rtt_p50_s",
    "read_rtt_p99_s", "meta_rtt_p99_s", "lag_p99_s", "dn_read_p99_s",
    "hot_total_reads", "hot_top", "hot_top1_share",
    "hot_top1_replicas", "hot_top1_boost",
}


class TestBenchRowContract:
    def test_run_dfs_step_row(self, tmp_path):
        from tpumr.scale.simdfs import run_dfs_step
        prom = str(tmp_path / "nn.prom")
        row = run_dfs_step(2, interval_s=0.05, measure_s=1.5,
                           num_datanodes=2, n_files=2,
                           file_bytes=8192, prom_out=prom)
        assert REQUIRED_ROW_KEYS <= set(row)
        assert row["ops"] > 0
        assert row["nn_op_count"] > 0
        assert json.loads(json.dumps(row)) == row   # JSON-safe
        validate_exposition(open(prom).read())

    def test_merged_op_hist_matches_families(self, tmp_path):
        # the merge bench_dfs relies on: merging typed per-op hists
        # reproduces the union's count
        a = Histogram("nn_op_seconds")
        b = Histogram("nn_op_seconds")
        for _ in range(10):
            a.observe(0.001)
            b.observe(0.1)
        merged = Histogram("nn_op_seconds")
        merged.merge_typed(a.typed())
        merged.merge_typed(b.typed())
        snap = merged.snapshot()
        assert snap["count"] == 20
        assert snap["p99"] >= 0.05
