"""DFS chaos certification: the storage-layer kill/corrupt/partition
seams (fi.py "storage churn seams") against the PR-18 fast path — fd
cache invalidation races, editlog group-commit crash handling, striped
lock escalation, hot-boost state across an NN crash, and the
dn_crash / dn_partition / nn_restart / block_corrupt recovery loops on
a live MiniDFSCluster (docs/OPERATIONS.md "DFS failure runbook")."""

import copy
import json
import os
import threading
import time

import pytest

from tpumr.dfs.editlog import FSEditLog, list_segments
from tpumr.dfs.mini_cluster import MiniDFSCluster
from tpumr.dfs.namenode import FSNamesystem
from tpumr.dfs.nslock import NamespaceLocks
from tpumr.io.fdcache import FdCache
from tpumr.mapred.jobconf import JobConf
from tpumr.utils import fi


def small_conf(block_size=1024, replication=2):
    conf = JobConf()
    conf.set("dfs.block.size", block_size)
    conf.set("dfs.replication", replication)
    conf.set("tdfs.replication.interval.s", 0.2)
    conf.set("tdfs.datanode.expiry.s", 1.5)
    conf.set("tdfs.http.port", -1)
    return conf


@pytest.fixture(autouse=True)
def _fi_reset():
    fi.reset()
    yield
    fi.reset()


# ------------------------------------------------------------ fd cache


class TestFdCacheInvalidateRace:
    def test_invalidate_during_open_is_not_cached(self, tmp_path,
                                                  monkeypatch):
        """The staleness hole: _pin opens OUTSIDE the lock, so an
        invalidate() (delete + recreate of the same block id) landing
        between the open and the insert must NOT leave the old inode's
        fd cached — every later pread would serve the deleted bytes."""
        path = str(tmp_path / "blk_7")
        with open(path, "wb") as f:
            f.write(b"OLD" * 10)
        cache = FdCache(capacity=4)
        real_open = os.open
        raced = {"done": False}

        def racing_open(p, flags, *a):
            fd = real_open(p, flags, *a)
            if p == path and not raced["done"]:
                raced["done"] = True
                # the re-replication race: block deleted and recreated
                # with new contents while our open was in flight
                os.unlink(path)
                with open(path, "wb") as f:
                    f.write(b"NEW" * 10)
                cache.invalidate(path)
            return fd

        monkeypatch.setattr("tpumr.io.fdcache.os.open", racing_open)
        assert cache.pread(path, 30, 0) == b"NEW" * 10
        # and the cached entry serves the new inode from now on
        assert cache.pread(path, 30, 0) == b"NEW" * 10

    def test_storm_falls_back_to_locked_open(self, tmp_path, monkeypatch):
        """An invalidation storm (epoch bumps on every attempt) must
        still terminate: the fallback opens under the lock."""
        path = str(tmp_path / "blk_9")
        with open(path, "wb") as f:
            f.write(b"x" * 8)
        cache = FdCache(capacity=4)
        real_open = os.open

        calls = {"n": 0}

        def stormy_open(p, flags, *a):
            # every unlocked open attempt (the 8 retries) loses to a
            # concurrent invalidate; the 9th open is the under-lock
            # fallback, which an invalidate can no longer race
            calls["n"] += 1
            if calls["n"] <= 8:
                cache.invalidate("")
            return real_open(p, flags, *a)

        monkeypatch.setattr("tpumr.io.fdcache.os.open", stormy_open)
        assert cache.pread(path, 8, 0) == b"x" * 8


# ------------------------------------------------------------ editlog


class TestEditlogCrash:
    def test_follower_never_acks_failed_leader_sync(self, tmp_path,
                                                    monkeypatch):
        """fsyncgate: when a leader's fsync fails, a follower whose
        record that fsync would have covered must raise too — retrying
        fsync on the same fd could report success for pages the kernel
        already marked clean. Both callers error; later appends land on
        a FRESH segment and commit for real."""
        el = FSEditLog(str(tmp_path))
        real_fsync = os.fsync
        state = {"armed": True}

        def wedged_fsync(fd):
            if state["armed"]:
                state["armed"] = False
                # hold the leader's fsync open until the follower's
                # record has been appended behind it (so the failed
                # sync genuinely "covers" the follower)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and el._appended < 2:
                    time.sleep(0.005)
                assert el._appended >= 2
                raise OSError("injected fsync failure")
            real_fsync(fd)

        monkeypatch.setattr("tpumr.dfs.editlog.os.fsync", wedged_fsync)
        errors = {}

        def leader():
            try:
                el.log({"op": "t", "who": "leader"})
            except OSError as e:
                errors["leader"] = e

        def follower():
            # appended while the leader's doomed fsync is in flight
            try:
                el.log({"op": "t", "who": "follower"})
            except OSError as e:
                errors["follower"] = e

        t1 = threading.Thread(target=leader)
        t1.start()
        # wait for the leader to be mid-fsync (baton held)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not el._syncing:
            time.sleep(0.005)
        assert el._syncing
        t2 = threading.Thread(target=follower)
        t2.start()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert not t1.is_alive() and not t2.is_alive()
        assert "leader" in errors
        assert "follower" in errors          # never acked durability
        seg_after_fail = el._seg_no
        # the journal recovered onto a fresh segment: this append is
        # durable and replays
        el.log({"op": "t", "who": "after"})
        el.close()
        assert el._seg_no == seg_after_fail  # no further churn
        replayed = [op["who"] for op in FSEditLog.replay(str(tmp_path))]
        # the poisoned records may or may not have hit disk (durability
        # UNKNOWN is the point) — but the post-recovery record must
        assert replayed[-1] == "after"

    def test_roll_fsync_failure_poisons_waiters(self, tmp_path,
                                                monkeypatch):
        """A roll that fsyncs an unsynced tail and fails must poison the
        queued appenders (they raise, not hang) and re-raise to the
        roller."""
        el = FSEditLog(str(tmp_path))
        el.log({"op": "t", "i": 0})

        def bad_fsync(fd):
            raise OSError("injected roll-fsync failure")

        # append without syncing: grab the mutex ourselves so the
        # appender thread parks pre-leadership with an unsynced record
        with el._cond:
            el._f.write(b'{"op":"t","i":1}\n')
            el._f.flush()
            el._appended += 1
            el.records += 1
        monkeypatch.setattr("tpumr.dfs.editlog.os.fsync", bad_fsync)
        with pytest.raises(OSError):
            el.roll()
        assert el._failed >= el._appended
        monkeypatch.undo()
        el.close()

    def test_torn_tail_out_of_order_counters_replay(self, tmp_path):
        """Crash-replay of a group-committed segment: allocator counter
        records journaled out of allocation order (striped creates) plus
        a torn final line. Replay must stop at the tear AND apply
        counters as a monotonic max — never rewinding next_block onto
        already-issued ids."""
        name_dir = tmp_path / "name"
        name_dir.mkdir()
        seg = name_dir / "edits-0000000001.jsonl"
        recs = [
            {"op": "mkdir", "path": "/a", "t": 1.0},
            # out-of-order allocator bumps: 7 journaled before 5
            {"op": "counters", "values": {"next_block": 7, "gen": 3}},
            {"op": "counters", "values": {"next_block": 5, "gen": 1}},
            {"op": "mkdir", "path": "/b", "t": 2.0},
        ]
        body = b"".join(json.dumps(r).encode() + b"\n" for r in recs)
        # torn tail: a partial record with no newline (crash mid-write)
        seg.write_bytes(body + b'{"op": "mkdir", "pa')
        conf = small_conf()
        ns = FSNamesystem(str(name_dir), conf)
        try:
            assert ns.counters["next_block"] == 7      # max, not last
            assert ns.counters["gen"] == 3
            assert "/a" in ns.namespace and "/b" in ns.namespace
            # the torn record never applied
            assert len([p for p in ns.namespace
                        if p.startswith("/") and p != "/"]) == 2
            # the writer sealed the torn segment: appends go to a new one
            assert not ns.edits.path.endswith("edits-0000000001.jsonl")
        finally:
            ns.edits.close()


# ------------------------------------------------------------ nslock


class TestEscalationGuard:
    def test_structural_after_stripe_raises(self):
        """Escalating to the global lock while already holding stripes
        acquires rank 25 after rank 26 — a real deadlock against a
        concurrent structural() holder. The guard fails fast instead."""
        locks = NamespaceLocks(stripes=4, depth=2)
        with locks.for_paths("/user/alice/a"):
            assert not locks.structural_held()
            with pytest.raises(RuntimeError, match="escalation"):
                with locks.structural():
                    pass
        # and the stripe frame unwound cleanly: structural works now
        with locks.structural():
            assert locks.structural_held()

    def test_structural_reentry_still_allowed(self):
        locks = NamespaceLocks(stripes=4, depth=2)
        with locks.structural():
            with locks.structural():
                assert locks.structural_held()


# ------------------------------------------------------------ hot boost


class TestHotBoostAcrossRestart:
    def test_boosted_block_trims_after_crash_restart(self, tmp_path):
        """hot_boost is volatile (never journaled): after an NN crash
        the restarted namesystem sees 3 replicas of a 2-replica file
        with NO boost — the over-replication branch must trim back to
        base instead of stranding the extra copy forever."""
        conf = small_conf()
        conf.set("tdfs.hotblocks.replicate.share", 0.2)
        conf.set("tdfs.hotblocks.replicate.min.reads", 10)
        conf.set("tdfs.hotblocks.replicate.cap", 3)
        conf.set("tdfs.hotblocks.cool.s", 60)   # boost would NOT expire
        name_dir = str(tmp_path / "name")
        ns = FSNamesystem(name_dir, conf)
        dns = [f"127.0.0.1:{7001 + i}" for i in range(3)]
        for addr in dns:
            ns.register_datanode(addr, 1 << 30)
        ns.create("/hot.bin", "cli", 2, 1024, True)
        meta = ns.add_block("/hot.bin", "cli")
        bid = meta["block_id"]
        for addr in meta["targets"]:
            ns.block_received(addr, bid, 512)
        ns.complete("/hot.bin", "cli", 512)
        ns.hot_blocks.fold(dns[0], {"total": 50,
                                    "top": [[str(bid), 40, 0]]})
        assert ns.hotblock_check() == 1
        assert ns.replication_check() == 1
        third = {a for a in dns} - set(meta["targets"])
        ns.block_received(third.pop(), bid, 512)
        assert len(ns.block_locations[bid]) == 3
        # crash: the journal fd is abandoned, nothing shuts down cleanly
        ns2 = FSNamesystem(name_dir, conf)
        try:
            assert ns2.hot_boost == {}            # volatile, as designed
            for addr in dns:
                ns2.register_datanode(addr, 1 << 30)
            for addr in ns.block_locations[bid]:
                ns2.block_report(addr, [[bid, 512]])
            assert not ns2.safemode
            assert len(ns2.block_locations[bid]) == 3
            assert ns2.replication_check() >= 1   # the trim
            assert len(ns2.block_locations[bid]) == 2
        finally:
            ns2.edits.close()
            ns.edits.close()


# ------------------------------------------------------------ seams, live


class TestDataNodeCrashSeam:
    def test_dn_crash_failover_and_rereplication(self):
        """dn.crash.d<n>: the targeted node hard-kills mid-beat; the
        reader fails over to a surviving replica, the NN expires the
        node, and re-replication restores the target count."""
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
            client = c.client()
            payload = b"C" * 2500
            with client.create("/chaos/f", replication=2) as f:
                f.write(payload)
            blocks = client.nn.call("get_block_locations", "/chaos/f")
            dead_addr = blocks[0]["locations"][0]
            idx = next(i for i, dn in enumerate(c.datanodes)
                       if dn.addr == dead_addr)
            conf.set(f"tpumr.fi.dn.crash.d{idx}.probability", 1.0)
            conf.set(f"tpumr.fi.dn.crash.d{idx}.max.failures", 1)
            deadline = time.time() + 10
            while time.time() < deadline and not c.datanodes[idx].killed:
                time.sleep(0.05)
            assert c.datanodes[idx].killed
            assert fi.fired(f"dn.crash.d{idx}") == 1
            # reads keep working through surviving replicas the whole time
            with client.open("/chaos/f") as f:
                assert f.read() == payload
            deadline = time.time() + 15
            while time.time() < deadline:
                blocks = client.nn.call("get_block_locations", "/chaos/f")
                if all(dead_addr not in b["locations"]
                       and len(b["locations"]) >= 2 for b in blocks):
                    break
                time.sleep(0.2)
            else:
                pytest.fail(f"not re-replicated: {blocks}")
            with client.open("/chaos/f") as f:
                assert f.read() == payload


class TestDataNodePartitionSeam:
    def test_partition_expires_then_rejoins(self):
        """dn.partition: heartbeat silence without death — the NN
        expires the node; when the partition heals the node rides
        dn_heartbeat's "register" back in with a block report."""
        conf = small_conf()
        conf.set("tpumr.fi.dn.partition.ms", 2500)
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            with client.create("/part/f", replication=2) as f:
                f.write(b"P" * 900)
            conf.set("tpumr.fi.dn.partition.probability", 1.0)
            conf.set("tpumr.fi.dn.partition.max.failures", 1)
            ns = c.namenode.ns
            deadline = time.time() + 10
            while time.time() < deadline and len(ns.datanodes) == 2:
                time.sleep(0.05)
            assert len(ns.datanodes) == 1        # expired, not dead
            assert fi.fired("dn.partition") == 1
            assert not any(dn.killed for dn in c.datanodes)
            deadline = time.time() + 15
            while time.time() < deadline and len(ns.datanodes) < 2:
                time.sleep(0.1)
            assert len(ns.datanodes) == 2        # rejoined
            with client.open("/part/f") as f:
                assert f.read() == b"P" * 900


class TestBlockCorruptSeam:
    def test_reader_never_sees_rot_and_replica_heals(self):
        """block_corrupt end-to-end: a seeded dn.read.corrupt.b<id>
        flips a byte on disk just before a read serves it. The CRC path
        catches it, the reader fails over (bytes identical to the
        no-fault control), the bad replica is dropped, and
        re-replication restores the count."""
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
            client = c.client()
            payload = bytes(range(256)) * 3       # single 768 B block
            with client.create("/rot/f", replication=2) as f:
                f.write(payload)
            # no-fault control read
            with client.open("/rot/f") as f:
                control = f.read()
            assert control == payload
            blk = client.nn.call("get_block_locations", "/rot/f")[0]
            bid = blk["block_id"]
            assert len(blk["locations"]) == 2
            conf.set(f"tpumr.fi.dn.read.corrupt.b{bid}.probability", 1.0)
            conf.set(f"tpumr.fi.dn.read.corrupt.b{bid}.max.failures", 1)
            # the faulted read: bytes must equal the control exactly
            with client.open("/rot/f") as f:
                assert f.read() == control
            assert fi.fired(f"dn.read.corrupt.b{bid}") == 1
            ns = c.namenode.ns
            assert ns.corrupt_replicas.get(bid)   # reported, dropped
            bad_addr = next(iter(ns.corrupt_replicas[bid]))
            bad_dn = next(dn for dn in c.datanodes
                          if dn.addr == bad_addr)

            def bad_copy_resolved():
                # either the delete command landed, or re-replication
                # chose this node again and overwrote it with a CLEAN
                # copy — both end the incident
                if bid not in dict(bad_dn.store.blocks()):
                    return True
                try:
                    bad_dn.store.read(bid)
                    return True
                except Exception:  # noqa: BLE001 — still corrupt
                    return False

            deadline = time.time() + 15
            while time.time() < deadline:
                locs = client.nn.call("get_block_locations",
                                      "/rot/f")[0]["locations"]
                if len(locs) >= 2 and bad_copy_resolved():
                    break
                time.sleep(0.2)
            else:
                pytest.fail("corrupt replica not dropped+re-replicated")
            with client.open("/rot/f") as f:
                assert f.read() == payload


class TestNameNodeKillRecovery:
    def test_client_rides_retries_across_nn_kill(self):
        """nn_restart: SIGKILL the NN mid-fleet; a client configured
        with RPC retries blocks through the outage and succeeds once
        the restarted NN replays the journal and leaves safemode."""
        conf = small_conf()
        conf.set("tdfs.client.nn.retries", 60)
        conf.set("tdfs.client.nn.backoff.ms", 100)
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            payload = b"K" * 2500
            with client.create("/kill/f") as f:
                f.write(payload)
            c.kill_namenode()
            assert c.namenode.killed
            result = {}

            def read_through_outage():
                # transport errors ride the RPC retry policy; a
                # post-restart safemode refusal is an APPLICATION error
                # the caller retries (the HDFS client's SafeModeException
                # loop) — docs/OPERATIONS.md "safemode triage"
                cli = c.client()
                deadline = time.time() + 25
                try:
                    while time.time() < deadline:
                        try:
                            with cli.open("/kill/f") as f:
                                result["data"] = f.read()
                            return
                        except Exception as e:  # noqa: BLE001
                            if "safe mode" not in str(e):
                                raise
                            time.sleep(0.1)
                    result["error"] = "timed out in safemode"
                except Exception as e:  # noqa: BLE001
                    result["error"] = e
                finally:
                    cli.close()

            t = threading.Thread(target=read_through_outage)
            t.start()
            time.sleep(0.5)                       # a real outage window
            nn2 = c.restart_killed_namenode()
            t.join(timeout=30)
            assert not t.is_alive()
            assert result.get("data") == payload, result.get("error")
            # the replayed namespace has the file; safemode was earned
            # back out through the DNs' re-register + block reports
            assert not nn2.ns.safemode
            assert "/kill/f" in nn2.ns.namespace

    def test_phantom_uc_block_does_not_wedge_safemode(self):
        """A writer killed between add_block (journaled) and the first
        byte reaching a DataNode leaves a block NO replica can ever
        report. The restart denominator must exclude open files'
        blocks — matching live accounting, where complete/close adds
        them — or safemode never exits (the dfs_nn_failover wedge)."""
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            with client.create("/ph/closed") as f:
                f.write(b"P" * 2500)
            # journal an allocation the "writer" never ships: the
            # crash window between add_block and the DN write
            client.nn.call("create", "/ph/open", client.name,
                           None, None, True)
            client.nn.call("add_block", "/ph/open", client.name)
            c.kill_namenode()
            nn2 = c.restart_killed_namenode()
            assert nn2.ns.namespace["/ph/open"].get("uc")
            assert nn2.ns.namespace["/ph/open"]["blocks"]
            deadline = time.time() + 10
            while time.time() < deadline and nn2.ns.safemode:
                time.sleep(0.05)
            assert not nn2.ns.safemode, (
                f"safemode wedged at "
                f"{nn2.ns._reported_fraction():.3f} of "
                f"{nn2.ns.total_known_blocks} blocks")
            with client.open("/ph/closed") as f:
                assert f.read() == b"P" * 2500

    def test_reader_refetches_locations_when_replicas_vanish(self):
        """A reader holding stale block locations (every cached
        replica expired/dead, or the list empty — a restarted NN
        still re-learning its datanodes) refetches from the NameNode
        instead of failing the read (tdfs.client.read.acquire.*,
        ≈ dfs.client.max.block.acquire.failures)."""
        conf = small_conf()
        conf.set("tdfs.client.read.acquire.backoff.ms", 50.0)
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            payload = b"R" * 2500
            with client.create("/stale/f") as f:
                f.write(payload)
            reader = client.open("/stale/f")
            with reader:
                # stomp the cached map: one empty list, one dead addr
                reader.raw.blocks[0]["locations"] = []
                for blk in reader.raw.blocks[1:]:
                    blk["locations"] = ["127.0.0.1:1"]
                assert reader.read() == payload

    def test_nn_crash_seam_fires_from_monitor(self):
        """The nn.crash seam: the monitor sweep kills the NN in-process
        (the scenario engine's nn_restart trigger path)."""
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
            conf.set("tpumr.fi.nn.crash.probability", 1.0)
            conf.set("tpumr.fi.nn.crash.max.failures", 1)
            deadline = time.time() + 10
            while time.time() < deadline and not c.namenode.killed:
                time.sleep(0.05)
            assert c.namenode.killed
            assert fi.fired("nn.crash") == 1
            conf.set("tpumr.fi.nn.crash.probability", 0)
            nn2 = c.restart_killed_namenode()
            deadline = time.time() + 10
            while time.time() < deadline and \
                    (nn2.ns.safemode or not nn2.ns.datanodes):
                time.sleep(0.05)
            assert not nn2.ns.safemode


# ------------------------------------------------------------ checkpoint


class TestCheckpointUnderChaos:
    def test_kill_after_checkpoint_replays_image_plus_tail(self, tmp_path):
        """Secondary checkpoint mid-fleet, then an NN SIGKILL: the
        restart must come up from the merged image + ONLY the
        post-checkpoint edits, with a namespace byte-identical to the
        pre-kill truth (the uncheckpointed control)."""
        from tpumr.dfs.secondary import SecondaryNameNode
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            for i in range(4):
                with client.create(f"/pre/f{i}") as f:
                    f.write(b"a" * 600)
            name_dir = f"{c.root}/name"
            merged = set(list_segments(name_dir))
            sec = SecondaryNameNode(c.nn_host, c.nn_port,
                                    str(tmp_path / "ckpt"), conf)
            sec.do_checkpoint()
            assert os.path.exists(os.path.join(name_dir, "fsimage.json"))
            # every pre-checkpoint segment was merged into the image
            # and purged: the journal on disk is the tail only
            assert merged.isdisjoint(set(list_segments(name_dir)))
            # post-checkpoint mutations: only these live in the journal
            for i in range(3):
                with client.create(f"/post/f{i}") as f:
                    f.write(b"b" * 600)
            client.mkdirs("/post/dir")
            assert client.rename("/pre/f0", "/post/moved")
            control = copy.deepcopy(c.namenode.ns.namespace)
            c.kill_namenode()
            nn2 = c.restart_killed_namenode()
            assert json.dumps(nn2.ns.namespace, sort_keys=True) == \
                json.dumps(control, sort_keys=True)
            deadline = time.time() + 15
            while time.time() < deadline and nn2.ns.safemode:
                time.sleep(0.1)
            assert not nn2.ns.safemode
            with c.client().open("/post/moved") as f:
                assert f.read() == b"a" * 600
