"""Hybrid scheduler unit tests against fakes — the seam the reference tests
the same way (TestJobQueueTaskScheduler.java:33 drives the scheduler against
FakeTaskTrackerManager :114; SURVEY.md §4.1). Deterministic: no daemons, no
clocks — runtimes injected via TaskStatus timestamps."""

import time

from tpumr.mapred.ids import JobID
from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.scheduler import HybridQueueScheduler
from tpumr.mapred.task import TaskState, TaskStatus


class FakeManager:
    """≈ FakeTaskTrackerManager."""

    def __init__(self, jobs, n_trackers=1):
        self._jobs = jobs
        self._n = n_trackers

    def running_jobs(self):
        return self._jobs

    def num_trackers(self):
        return self._n

    def total_slots(self):
        return {"cpu": 3 * self._n, "tpu": 1 * self._n, "reduce": 2 * self._n}


def make_job(n_maps=8, n_reduces=1, kernel=True, optional=False, job_num=1,
             hosts=None):
    conf = {"mapred.reduce.tasks": n_reduces,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    if kernel:
        conf["tpumr.map.kernel"] = "kmeans-assign"
    if optional:
        conf["mapred.jobtracker.map.optionalscheduling"] = True
    splits = [{"locations": (hosts or [])} for _ in range(n_maps)]
    return JobInProgress(JobID("test", job_num), conf, splits)


def tracker_status(cpu=3, tpu=1, reduce=2, run_cpu=0, run_tpu=0, run_red=0,
                   devices=None, host="host0"):
    return {
        "tracker_name": "tracker_0", "host": host, "shuffle_port": 0,
        "max_cpu_map_slots": cpu, "max_tpu_map_slots": tpu,
        "max_reduce_slots": reduce,
        "count_cpu_map_tasks": run_cpu, "count_tpu_map_tasks": run_tpu,
        "count_reduce_tasks": run_red,
        "available_tpu_devices": devices if devices is not None
        else [True] * tpu,
    }


def make_scheduler(jobs, n_trackers=1, **conf_kv):
    sched = HybridQueueScheduler()
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched.configure(conf)
    sched.set_manager(FakeManager(jobs, n_trackers))
    return sched


def finish_map(job, task, runtime, on_tpu):
    now = time.time()
    st = TaskStatus(attempt_id=task.attempt_id, is_map=True,
                    state=TaskState.SUCCEEDED, start_time=now - runtime,
                    finish_time=now, run_on_tpu=on_tpu,
                    tpu_device_id=task.tpu_device_id)
    job.update_task_status(st, "h:0")


def test_fills_both_pools_with_device_ids():
    job = make_job(n_maps=8)
    sched = make_scheduler([job])
    tasks = sched.assign_tasks(tracker_status(cpu=3, tpu=2,
                                              devices=[True, True]))
    tpu_tasks = [t for t in tasks if t.run_on_tpu]
    cpu_tasks = [t for t in tasks if t.is_map and not t.run_on_tpu]
    reduce_tasks = [t for t in tasks if not t.is_map]
    assert len(tpu_tasks) == 2
    assert sorted(t.tpu_device_id for t in tpu_tasks) == [0, 1]
    assert len(cpu_tasks) == 3
    assert len(reduce_tasks) == 1  # at most one reduce per heartbeat


def test_kernel_gate_blocks_tpu_assignment():
    """Jobs without a device kernel never get TPU slots
    (≈ hadoop.pipes.gpu.executable gate, JobQueueTaskScheduler.java:342-347)."""
    job = make_job(kernel=False)
    sched = make_scheduler([job])
    tasks = sched.assign_tasks(tracker_status())
    assert all(not t.run_on_tpu for t in tasks)
    assert len([t for t in tasks if t.is_map]) == 3  # CPU pass still runs


def test_no_free_device_no_tpu_task():
    job = make_job()
    sched = make_scheduler([job])
    tasks = sched.assign_tasks(tracker_status(tpu=1, devices=[False]))
    assert all(not t.run_on_tpu for t in tasks)


def test_optional_scheduling_starves_cpu_when_load_fits_tpu():
    """The Shirahata rule (:290-291): with optionalscheduling and
    pending_load < accel × tpu_capacity × n_trackers, skip the CPU pass."""
    job = make_job(n_maps=20, optional=True)
    # profile: CPU maps take 10s, TPU maps 1s → accel = 10
    for on_tpu, runtime in [(False, 10.0), (True, 1.0)]:
        t = job.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                    tpu_device_id=0 if on_tpu else -1)
        finish_map(job, t, runtime, on_tpu)
    assert job.acceleration_factor() == 10.0

    sched = make_scheduler([job], n_trackers=2)
    # pending = 18 < 10 × 1 × 2 = 20 → CPU starved
    tasks = sched.assign_tasks(tracker_status())
    assert [t.run_on_tpu for t in tasks if t.is_map] == [True]

    # without profile data (fresh job) CPU is NOT starved
    fresh = make_job(n_maps=20, optional=True, job_num=2)
    sched2 = make_scheduler([fresh], n_trackers=2)
    tasks2 = sched2.assign_tasks(tracker_status())
    assert len([t for t in tasks2 if t.is_map and not t.run_on_tpu]) == 3


def test_optional_scheduling_keeps_cpu_under_heavy_load():
    job = make_job(n_maps=500, optional=True)
    for on_tpu, runtime in [(False, 10.0), (True, 1.0)]:
        t = job.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                    tpu_device_id=0 if on_tpu else -1)
        finish_map(job, t, runtime, on_tpu)
    sched = make_scheduler([job], n_trackers=2)
    # pending 498 >= 10 × 1 × 2 → CPU pass runs
    tasks = sched.assign_tasks(tracker_status())
    assert len([t for t in tasks if t.is_map and not t.run_on_tpu]) == 3


def test_minimize_mode_puts_everything_on_tpu_when_faster():
    """The implemented f(x,y) minimization (reference's commented-out
    :181-219): 8 pending maps, TPU 10× faster, 1 TPU slot → optimum is
    x=0 CPU tasks (8×1s on TPU beats any CPU share at 10s each)."""
    job = make_job(n_maps=10)
    for on_tpu, runtime in [(False, 10.0), (True, 1.0)]:
        t = job.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                    tpu_device_id=0 if on_tpu else -1)
        finish_map(job, t, runtime, on_tpu)
    sched = make_scheduler([job], **{"tpumr.scheduler.mode": "minimize"})
    tasks = sched.assign_tasks(tracker_status())
    assert [t.run_on_tpu for t in tasks if t.is_map] == [True]

    # inverse profile: CPU faster → CPU pass fills all slots
    job2 = make_job(n_maps=10, job_num=2)
    for on_tpu, runtime in [(False, 1.0), (True, 10.0)]:
        t = job2.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                     tpu_device_id=0 if on_tpu else -1)
        finish_map(job2, t, runtime, on_tpu)
    sched2 = make_scheduler([job2], **{"tpumr.scheduler.mode": "minimize"})
    tasks2 = sched2.assign_tasks(tracker_status())
    cpu_maps = [t for t in tasks2 if t.is_map and not t.run_on_tpu]
    assert len(cpu_maps) == 3


def test_locality_preference():
    job = make_job(n_maps=4, hosts=["far"])
    job.host_cache = {"host0": {2}, "far": {0, 1, 3}}
    sched = make_scheduler([job])
    tasks = sched.assign_tasks(tracker_status(cpu=1, tpu=0, host="host0"))
    assert tasks[0].partition == 2  # node-local split chosen first


def test_fifo_across_jobs():
    j1 = make_job(n_maps=2, job_num=1, kernel=False)
    j2 = make_job(n_maps=8, job_num=2, kernel=False)
    sched = make_scheduler([j1, j2])
    tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0))
    # j1 exhausted first, then j2
    jobs_in_order = [str(t.attempt_id.task.job) for t in tasks if t.is_map]
    assert jobs_in_order[:2] == ["job_test_0001"] * 2
    assert all(j == "job_test_0002" for j in jobs_in_order[2:])


def test_failure_requeues_and_eventually_fails_job():
    job = make_job(n_maps=1, kernel=False)
    for attempt in range(4):
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        assert t is not None and t.attempt_id.attempt == attempt
        st = TaskStatus(attempt_id=t.attempt_id, is_map=True,
                        state=TaskState.FAILED, diagnostics="boom")
        job.update_task_status(st, "h:0")
    assert job.state == "FAILED"
    assert "4 times" in job.error


def test_speculative_duplicate_success_ignored():
    job = make_job(n_maps=1, n_reduces=0, kernel=False)
    t0 = job.obtain_new_map_task("h", run_on_tpu=False)
    # second (speculative) attempt of same task
    tip = job.maps[0]
    a1 = tip.new_attempt()
    finish_map(job, t0, 1.0, False)
    assert job.finished_maps == 1
    st = TaskStatus(attempt_id=a1, is_map=True, state=TaskState.SUCCEEDED)
    job.update_task_status(st, "h:0")
    assert job.finished_maps == 1  # not double counted
    assert job.state == "SUCCEEDED"


def test_lost_tracker_requeues_completed_maps():
    job = make_job(n_maps=2, n_reduces=1, kernel=False)
    t0 = job.obtain_new_map_task("h", run_on_tpu=False)
    finish_map(job, t0, 1.0, False)
    assert job.finished_maps == 1
    aid = job.maps[0].successful_attempt
    job.requeue_lost_attempts([aid])
    assert job.finished_maps == 0
    assert job.pending_map_count() == 2
    # the event feed is append-only (cursor-based consumers): the lost
    # output's event is OBSOLETE-marked + tombstoned, never removed
    assert not [e for e in job.completion_events
                if e.get("status") != "OBSOLETE"]
    assert any(e["attempt_id"] == aid and e.get("status") == "OBSOLETE"
               for e in job.completion_events)


def test_per_job_minimize_mode_override():
    """A job may opt into the f(x,y) minimizer via its own conf while the
    cluster default stays shirahata (the bench's convergence round uses
    exactly this seam)."""
    job = make_job(n_maps=10)
    job.conf["tpumr.scheduler.mode"] = "minimize"
    for on_tpu, runtime in [(False, 10.0), (True, 1.0)]:
        t = job.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                    tpu_device_id=0 if on_tpu else -1)
        finish_map(job, t, runtime, on_tpu)
    sched = make_scheduler([job])          # cluster mode: shirahata
    tasks = sched.assign_tasks(tracker_status())
    # optimum at 10x accel, 1 TPU slot: zero CPU share — only TPU maps
    assert [t.run_on_tpu for t in tasks if t.is_map] == [True]

    # the same cluster WITHOUT the job override fills both pools
    plain = make_job(n_maps=10, job_num=2)
    for on_tpu, runtime in [(False, 10.0), (True, 1.0)]:
        t = plain.obtain_new_map_task("host0", run_on_tpu=on_tpu,
                                      tpu_device_id=0 if on_tpu else -1)
        finish_map(plain, t, runtime, on_tpu)
    sched2 = make_scheduler([plain])
    tasks2 = sched2.assign_tasks(tracker_status())
    assert len([t for t in tasks2 if t.is_map and not t.run_on_tpu]) == 3


def test_within_job_convergence_timeline():
    """The convergence clause end-to-end at the scheduler level: a many-
    map job starts with no profile (both pools fill); once per-backend
    means exist and pending drops below accel x tpuCapacity x trackers,
    the CPU pass stops and the TAIL of the job is all-TPU."""
    job = make_job(n_maps=24, optional=True)
    sched = make_scheduler([job], n_trackers=2)
    placements = []
    for _hb in range(100):
        if job.pending_map_count() == 0:
            break
        tasks = [t for t in sched.assign_tasks(tracker_status())
                 if t.is_map]
        for t in tasks:
            placements.append(t.run_on_tpu)
            # every map "runs" instantly: CPU maps 10s, TPU maps 1s
            finish_map(job, t, 10.0 if not t.run_on_tpu else 1.0,
                       t.run_on_tpu)
    assert job.pending_map_count() == 0
    # early waves used the CPU pool (TPU pass runs first, so the first
    # heartbeat is 1 TPU + 3 CPU maps), and the tail converged to all-TPU
    assert not all(placements[:4])
    assert placements[-1] and placements[-2]
    tail = 0
    for b in reversed(placements):
        if not b:
            break
        tail += 1
    # accel=10, capacity 1x2 -> starvation from pending<20: nearly the
    # whole job after the first profiled wave goes TPU
    assert tail >= 10, (placements, tail)


def test_priority_reorders_fifo_queue():
    """≈ JobQueueJobInProgressListener's FIFO comparator: priority
    outranks submit order, and set_job_priority reorders a live queue
    (hadoop job -set-priority)."""
    j1 = make_job(n_maps=2, job_num=1, kernel=False)
    j2 = make_job(n_maps=2, job_num=2, kernel=False)
    j2.priority = "HIGH"
    sched = make_scheduler([j1, j2])
    tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0))
    order = [str(t.attempt_id.task.job) for t in tasks if t.is_map]
    # HIGH j2 drains before NORMAL j1 despite submitting second
    assert order[:2] == ["job_test_0002"] * 2
    assert all(j == "job_test_0001" for j in order[2:])


def test_priority_from_conf_and_validation():
    import pytest

    from tpumr.mapred.job_in_progress import normalize_priority
    j = make_job(job_num=3)
    assert j.priority == "NORMAL"
    conf = {"mapred.reduce.tasks": 0, "mapred.job.priority": "very_low"}
    jlow = JobInProgress(JobID("test", 4), conf, [{"locations": []}])
    assert jlow.priority == "VERY_LOW"
    with pytest.raises(ValueError, match="unknown job priority"):
        normalize_priority("URGENT")
