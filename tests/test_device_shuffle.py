"""Device-shuffled reduce (tpumr.mapred.device_shuffle + parallel.device_sort):
the MapReduce shuffle+sort as an ICI all_to_all + per-device sort, wired
into the REAL job paths (LocalJobRunner and the mini-cluster through
JobClient) — ≈ the role of ReduceTask.java:659 ReduceCopier ↔
TaskTracker.java:4050 MapOutputServlet, re-planned as mesh collectives.
Runs on the conftest's virtual 8-device CPU mesh."""

import numpy as np
import pytest

from tpumr.core.counters import BackendCounter
from tpumr.fs import get_filesystem
from tpumr.io import sequencefile
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.local_runner import run_job
from tpumr.mapred.mini_cluster import MiniMRCluster


def _teragen(path: str, rows: int, maps: int = 3) -> None:
    from tpumr.cli import main as cli_main
    assert cli_main(["examples", "teragen", str(rows), path,
                     "-m", str(maps)]) == 0


def _read_parts(fs, d):
    recs = []
    parts = []
    for st in sorted(fs.list_status(d), key=lambda s: str(s.path)):
        if not st.path.name.startswith("part-"):
            continue
        parts.append(st.path.name)
        with fs.open(st.path) as f:
            recs.extend(sequencefile.Reader(f))
    return recs, parts


class TestDeviceSortPrimitives:
    def test_key_columns_order_preserving(self):
        from tpumr.parallel.device_sort import key_columns
        rng = np.random.default_rng(3)
        keys = rng.integers(0, 256, size=(500, 10), dtype=np.uint8)
        cols = key_columns(keys, 10)
        by_bytes = sorted(range(500), key=lambda i: bytes(keys[i]))
        by_cols = np.lexsort(tuple(cols[:, c] for c in range(2, -1, -1)))
        assert by_bytes == list(by_cols)

    def test_compute_dest_matches_host_partitioner(self):
        """Device dest must agree with TotalOrderPartitioner's bisect
        convention (equal key → lower range)."""
        import bisect
        from tpumr.parallel.device_sort import compute_dest, key_columns
        rng = np.random.default_rng(4)
        keys = rng.integers(32, 127, size=(300, 10), dtype=np.uint8)
        cuts = sorted(bytes(keys[i]) for i in [10, 50, 99])
        cuts_np = np.frombuffer(b"".join(cuts), np.uint8).reshape(-1, 10)
        dest = compute_dest(key_columns(keys, 10),
                            key_columns(cuts_np, 10))
        for i in range(300):
            expect = bisect.bisect_left(cuts, bytes(keys[i]))
            assert int(dest[i]) == expect, (i, bytes(keys[i]))

    def test_partition_sort_full_roundtrip(self):
        from tpumr.parallel.device_sort import device_partition_sort
        from tpumr.parallel.mesh import make_mesh
        rng = np.random.default_rng(7)
        n, klen = 1003, 10
        records = rng.integers(0, 256, size=(n, klen + 6), dtype=np.uint8)
        samp = np.sort(records[rng.choice(n, 50, replace=False), :klen]
                       .view("u1").reshape(-1, klen), axis=0)
        order = np.lexsort(tuple(samp[:, c] for c in range(klen - 1, -1, -1)))
        cuts = samp[order][[6, 12, 18, 24, 30, 36, 43]]
        mesh = make_mesh(8)
        shards, _ = device_partition_sort(mesh, records, klen, cuts, 8)
        assert shards is not None
        merged = np.concatenate(shards)
        assert merged.shape[0] == n
        kb = [bytes(r[:klen]) for r in merged]
        assert kb == sorted(kb)
        assert sorted(bytes(r) for r in merged) == \
            sorted(bytes(r) for r in records)

    def test_overflow_signals_fallback(self):
        from tpumr.parallel.device_sort import device_partition_sort
        from tpumr.parallel.mesh import make_mesh
        rng = np.random.default_rng(9)
        records = rng.integers(0, 256, size=(512, 12), dtype=np.uint8)
        # every record to range 0 (no splitters) with capacity 1: the
        # per-bucket load is 64 — retries 1→2→4 all overflow
        shards, overflow = device_partition_sort(
            make_mesh(8), records, 10, np.zeros((0, 10), np.uint8), 1,
            capacity=1)
        assert shards is None and overflow > 0


class TestDeviceShuffleLocalJob:
    def test_terasort_device_shuffle_local(self):
        """Terasort through LocalJobRunner with the device reduce: output
        part files globally sorted, same multiset, R parts kept."""
        from tpumr.examples.terasort import make_terasort_conf
        fs = get_filesystem("mem:///")
        _teragen("mem:///dsl/gen", 900, maps=3)
        conf = make_terasort_conf("mem:///dsl/gen", "mem:///dsl/out", 5,
                                  device_shuffle=True)
        result = run_job(conf)
        assert result.successful
        out, parts = _read_parts(fs, "/dsl/out")
        assert parts == [f"part-{r:05d}" for r in range(5)]
        assert len(out) == 900
        keys = [k for k, _ in out]
        assert keys == sorted(keys), "concatenated parts must be sorted"
        gen, _ = _read_parts(fs, "/dsl/gen")
        assert sorted(k + v for k, v in out) == sorted(k + v for k, v in gen)
        shuffled = result.counters.value(BackendCounter.GROUP,
                                       BackendCounter.TPU_SHUFFLE_RECORDS)
        assert shuffled == 900

    def test_device_shuffle_with_real_reducer(self):
        """A non-identity reducer still runs (grouped over the device-sorted
        stream): fixed-width count aggregation."""
        from tpumr.mapred.api import Mapper, Reducer
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dsr/in.txt",
                       b"\n".join(b"key%04d" % (i % 7) for i in range(210)))

        conf = JobConf()
        conf.set_job_name("dense-count")
        conf.set_input_paths("mem:///dsr/in.txt")
        conf.set_output_path("mem:///dsr/out")
        from tpumr.mapred.output_formats import SequenceFileOutputFormat
        conf.set_mapper_class(FixedKeyMapper)
        conf.set_reducer_class(FixedCountReducer)
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(3)
        conf.set_device_shuffle(7, 4)
        result = run_job(conf)
        assert result.successful
        out, parts = _read_parts(fs, "/dsr/out")
        assert len(parts) == 3
        counts = {bytes(k): int.from_bytes(v, "big") for k, v in out}
        assert counts == {b"key%04d" % i: 30 for i in range(7)}

    def test_identity_subclass_overriding_map_is_not_bypassed(self):
        """A subclass of an identity mapper that overrides map() (but
        inherits identity_map) must have its map() honored — the bulk
        fast path only applies to classes declaring the flag themselves."""
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dsi/in.txt",
                       b"\n".join(b"key%04d" % i for i in range(20)))
        conf = JobConf()
        conf.set_input_paths("mem:///dsi/in.txt")
        conf.set_output_path("mem:///dsi/out")
        from tpumr.mapred.output_formats import SequenceFileOutputFormat
        conf.set_mapper_class(DroppingIdentitySubclass)
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(1)
        conf.set_device_shuffle(7, 0)
        result = run_job(conf)
        assert result.successful
        out, _ = _read_parts(fs, "/dsi/out")
        assert len(out) == 10  # the override's filter ran

    def test_duplicate_heavy_input_short_cut_list(self):
        """write_partition_file dedups duplicate samples, so the cut list
        can be shorter than R-1 — top ranges must come back empty, not
        crash (host TotalOrderPartitioner tolerance preserved)."""
        from tpumr.mapred.output_formats import SequenceFileOutputFormat
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dsd/in.txt",
                       b"\n".join(b"dup%04d" % (i % 2) for i in range(100)))
        conf = JobConf()
        conf.set_input_paths("mem:///dsd/in.txt")
        conf.set_output_path("mem:///dsd/out")
        conf.set_mapper_class(FixedKeyMapper)
        conf.set_output_format(SequenceFileOutputFormat)
        conf.set_num_reduce_tasks(16)   # >> distinct keys: short cut list
        conf.set_device_shuffle(7, 4)
        assert run_job(conf).successful
        out, parts = _read_parts(fs, "/dsd/out")
        assert len(parts) == 16
        assert len(out) == 100
        keys = [k for k, _ in out]
        assert keys == sorted(keys)

    def test_custom_comparator_rejected(self):
        from tpumr.mapred.api import DeserializingComparator
        conf = JobConf()
        conf.set_input_paths("mem:///x/in.txt")
        conf.set_output_path("mem:///x/out")
        conf.set_num_reduce_tasks(2)
        conf.set_device_shuffle(10, 4)
        conf.set_output_key_comparator_class(DeserializingComparator)
        from tpumr.mapred.device_shuffle import prepare_device_shuffle_job
        with pytest.raises(ValueError, match="comparator"):
            prepare_device_shuffle_job(conf)

    def test_wrong_width_fails_with_clear_error(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/dsw/in.txt", b"hello world\n")
        conf = JobConf()
        conf.set_input_paths("mem:///dsw/in.txt")
        conf.set_output_path("mem:///dsw/out")
        conf.set_mapper_class(FixedKeyMapper)   # emits 7-byte keys
        conf.set_num_reduce_tasks(1)
        conf.set_device_shuffle(10, 4)          # conf says 10 — mismatch
        with pytest.raises(Exception, match="10-byte keys"):
            run_job(conf)


from tpumr.mapred.api import IdentityMapper


class DroppingIdentitySubclass(IdentityMapper):
    """Inherits identity_map=True but overrides map() to keep only even
    rows — the override must run (7-byte key, empty value)."""

    def map(self, key, value, output, reporter):
        line = value if isinstance(value, (bytes, bytearray)) else \
            str(value).encode()
        if int(line[-1:] or b"0", 10) % 2 == 0:
            output.collect(bytes(line.strip()[:7]), b"")


class FixedKeyMapper:
    """Emits (7-byte key, 4-byte big-endian 1) per input line."""

    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        line = value if isinstance(value, (bytes, bytearray)) else \
            str(value).encode()
        if line.strip():
            output.collect(bytes(line.strip()[:7]), (1).to_bytes(4, "big"))

    def close(self):
        pass


class FixedCountReducer:
    """Sums 4-byte big-endian counts into a 4-byte value."""

    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        total = sum(int.from_bytes(v, "big") for v in values)
        output.collect(key, total.to_bytes(4, "big"))

    def close(self):
        pass


class TestDeviceShuffleMiniCluster:
    def test_terasort_device_shuffle_through_jobclient(self):
        """The full distributed path: teragen + device-shuffled terasort
        submitted through JobClient to a mini-cluster (maps on trackers,
        dense outputs served over tracker RPC, ONE reduce gang task runs
        the mesh exchange), then validated globally sorted."""
        from tpumr.examples.terasort import make_terasort_conf
        fs = get_filesystem("mem:///")
        _teragen("mem:///dsc/gen", 600, maps=3)
        with MiniMRCluster(num_trackers=2, cpu_slots=2, tpu_slots=0) as c:
            conf = make_terasort_conf("mem:///dsc/gen", "mem:///dsc/out", 4,
                                      device_shuffle=True)
            for k, v in c.create_job_conf():
                conf.set_if_unset(k, v)
            result = JobClient(conf).run_job(conf)
            assert result.successful
            # collapsed to one gang reduce task
            assert result.num_reduces == 1
        out, parts = _read_parts(fs, "/dsc/out")
        assert parts == [f"part-{r:05d}" for r in range(4)]
        assert len(out) == 600
        keys = [k for k, _ in out]
        assert keys == sorted(keys)
        shuffled = result.counters.value(BackendCounter.GROUP,
                                       BackendCounter.TPU_SHUFFLE_RECORDS)
        assert shuffled == 600


def test_device_partition_sort_single_device_mesh():
    """The n_dev==1 short-circuit (the real single-chip bench path): no
    exchange, no padding — straight device sort, full row fidelity."""
    import numpy as np

    from tpumr.parallel.device_sort import device_partition_sort
    from tpumr.parallel.mesh import make_mesh

    rng = np.random.default_rng(7)
    n, klen, vlen = 5000, 10, 22
    records = rng.integers(0, 256, size=(n, klen + vlen), dtype=np.uint8)
    splitters = np.sort(
        rng.integers(0, 256, size=(3, klen), dtype=np.uint8), axis=0)
    mesh = make_mesh(1)
    shards, overflow = device_partition_sort(mesh, records, klen,
                                             splitters, 4)
    assert overflow == 0 and len(shards) == 1
    out = shards[0]
    assert out.shape == (n, klen + vlen)
    keys = [bytes(r) for r in out[:, :klen]]
    assert keys == sorted(keys)
    # permutation fidelity: exact multiset of rows survives
    assert sorted(map(bytes, out)) == sorted(map(bytes, records))
