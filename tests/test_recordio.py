"""Record I/O tier (tpumr/recordio ≈ org.apache.hadoop.record + rcc).

Wire-format fidelity is tested against HAND-DERIVED golden bytes from
the reference's documented encodings (Utils.java vlong contract,
BinaryRecordOutput field order, CsvRecordOutput escapes), then
roundtrips cover the compound grammar across all three formats.
"""

import io

import pytest

from tpumr.recordio import (BinaryRecordInput, BinaryRecordOutput,
                            CsvRecordInput, CsvRecordOutput, Record,
                            XmlRecordInput, XmlRecordOutput, read_vlong,
                            write_vlong)


def _vl(i):
    out = io.BytesIO()
    write_vlong(out, i)
    return out.getvalue()


class TestVlong:
    def test_golden_bytes(self):
        # Utils.java:455-489: one byte for -112..127; else length byte
        # then magnitude high-first (one's complement for negatives)
        assert _vl(0) == b"\x00"
        assert _vl(127) == b"\x7f"
        assert _vl(-112) == bytes([0x90])
        assert _vl(128) == bytes([0x8F, 0x80])
        assert _vl(-113) == bytes([0x87, 0x70])
        assert _vl(255) == bytes([0x8F, 0xFF])
        assert _vl(256) == bytes([0x8E, 0x01, 0x00])
        assert _vl(2 ** 31 - 1) == bytes([0x8C, 0x7F, 0xFF, 0xFF, 0xFF])

    def test_roundtrip_torture(self):
        vals = [0, 1, -1, 127, 128, -112, -113, 255, 256, 2 ** 16,
                -2 ** 16, 2 ** 31 - 1, -2 ** 31, 2 ** 63 - 1, -2 ** 63]
        for v in vals:
            assert read_vlong(io.BytesIO(_vl(v))) == v, v


class Inner(Record):
    FIELDS = [("s", "ustring")]


class Everything(Record):
    FIELDS = [
        ("byteVal", "byte"),
        ("boolVal", "boolean"),
        ("intVal", "int"),
        ("longVal", "long"),
        ("floatVal", "float"),
        ("doubleVal", "double"),
        ("stringVal", "ustring"),
        ("bufferVal", "buffer"),
        ("vectorVal", ("vector", "ustring")),
        ("mapVal", ("map", "ustring", "long")),
        ("recordVal", Inner),
        ("deepVal", ("vector", ("vector", Inner))),
        ("bmap", ("map", "byte", "ustring")),
    ]


def sample():
    return Everything(
        byteVal=-5, boolVal=True, intVal=-123456, longVal=2 ** 40,
        floatVal=1.5, doubleVal=-2.25,
        stringVal="héllo, wörld}\n100%",
        bufferVal=b"\x00\x01\xfe\xff",
        vectorVal=["a", "b,c", ""],
        mapVal={"k1": 1, "k2": -2},
        recordVal=Inner(s="in"),
        deepVal=[[Inner(s="x")], [], [Inner(s="y"), Inner(s="z")]],
        bmap={1: "one", -2: "minus"},
    )


@pytest.mark.parametrize("out_cls,in_cls", [
    (BinaryRecordOutput, BinaryRecordInput),
    (CsvRecordOutput, CsvRecordInput),
    (XmlRecordOutput, XmlRecordInput),
])
def test_roundtrip_all_formats(out_cls, in_cls):
    rec = sample()
    buf = io.BytesIO()
    rec.serialize(out_cls(buf))
    buf.seek(0)
    back = Everything()
    back.deserialize(in_cls(buf))
    assert back == rec
    # float fidelity across text formats
    assert abs(back.floatVal - 1.5) < 1e-6


def test_binary_golden_bytes():
    class Two(Record):
        FIELDS = [("i", "int"), ("s", "ustring")]
    buf = io.BytesIO()
    Two(i=300, s="ab").serialize(BinaryRecordOutput(buf))
    # vint(300)=8E 01 2C; string = vint(2) + 'ab'
    assert buf.getvalue() == bytes([0x8E, 0x01, 0x2C, 0x02]) + b"ab"


def test_csv_golden_text():
    class R(Record):
        FIELDS = [("b", "boolean"), ("s", "ustring"),
                  ("v", ("vector", "int")), ("buf", "buffer")]
    buf = io.BytesIO()
    R(b=True, s="a,b}c%", v=[1, 2], buf=b"\xca\xfe").serialize(
        CsvRecordOutput(buf))
    assert buf.getvalue() == b"T,'a%2Cb%7Dc%25,v{1,2},#cafe\n"


def test_multiple_records_per_stream():
    buf = io.BytesIO()
    out = CsvRecordOutput(buf)
    Inner(s="one").serialize(out)
    Inner(s="two").serialize(out)
    buf.seek(0)
    rin = CsvRecordInput(buf)
    a, b = Inner(), Inner()
    a.deserialize(rin)
    b.deserialize(rin)
    assert (a.s, b.s) == ("one", "two")
    # binary likewise (no framing between records)
    buf = io.BytesIO()
    bout = BinaryRecordOutput(buf)
    Inner(s="one").serialize(bout)
    Inner(s="two").serialize(bout)
    buf.seek(0)
    brin = BinaryRecordInput(buf)
    a, b = Inner(), Inner()
    a.deserialize(brin)
    b.deserialize(brin)
    assert (a.s, b.s) == ("one", "two")


def test_to_bytes_from_bytes():
    rec = sample()
    assert Everything.from_bytes(rec.to_bytes()) == rec


class TestRcc:
    DDL = """
    include "base.jr"
    module tpumr.test.rec {
        /* multi-line
           comment */
        class R0 {
            ustring stringVal; // trailing comment
        }
        class R1 {
            boolean boolVal;
            byte byteVal;
            int intVal;
            long longVal;
            float floatVal;
            double doubleVal;
            ustring stringVal;
            buffer bufferVal;
            vector<ustring> vectorVal;
            map<ustring, ustring> mapVal;
            R0 recordVal;
            vector<vector<R0>> deep;
            vector<map<int, long>> mvec;
        }
    }
    """

    def test_parse_and_generate(self, tmp_path):
        from tpumr.recordio.rcc import generate_python, parse_ddl
        mods = parse_ddl(self.DDL)
        assert [m["module"] for m in mods] == ["tpumr.test.rec"]
        assert mods[0]["includes"] == ["base.jr"]
        names = [c for c, _ in mods[0]["classes"]]
        assert names == ["R0", "R1"]
        src = generate_python(mods)["tpumr.test.rec"]
        ns: dict = {}
        exec(compile(src, "<gen>", "exec"), ns)
        R0, R1 = ns["R0"], ns["R1"]
        rec = R1(boolVal=True, intVal=7, recordVal=R0(stringVal="x"),
                 deep=[[R0(stringVal="d")]], mvec=[{1: 2}])
        assert R1.from_bytes(rec.to_bytes()) == rec

    def test_forward_reference(self):
        from tpumr.recordio.rcc import generate_python, parse_ddl
        ddl = """module m { class A { B b; } class B { int i; } }"""
        src = generate_python(parse_ddl(ddl))["m"]
        ns: dict = {}
        exec(compile(src, "<gen>", "exec"), ns)
        a = ns["A"]()
        assert isinstance(a.b, ns["B"])

    def test_unknown_type_is_loud(self):
        from tpumr.recordio.rcc import DdlError, generate_python, parse_ddl
        with pytest.raises(DdlError, match="unknown record type"):
            generate_python(parse_ddl("module m { class A { Nope n; } }"))

    def test_cli_writes_modules(self, tmp_path):
        (tmp_path / "t.jr").write_text(
            "module my.mod { class C { int i; } }")
        from tpumr.recordio.rcc import main
        assert main([str(tmp_path / "t.jr"),
                     "--dest", str(tmp_path)]) == 0
        gen = (tmp_path / "my_mod.py").read_text()
        assert "class C(Record):" in gen


class TestErrors:
    def test_csv_bad_string_prefix(self):
        rin = CsvRecordInput(io.BytesIO(b"nope\n"))
        with pytest.raises(ValueError, match="must start with"):
            rin.read_string("t")

    def test_truncated_binary(self):
        class Two(Record):
            FIELDS = [("s", "ustring")]
        data = Two(s="hello").to_bytes()[:-2]
        with pytest.raises(EOFError):
            Two.from_bytes(data)

    def test_xml_type_mismatch(self):
        buf = io.BytesIO()
        Inner(s="x").serialize(XmlRecordOutput(buf))
        buf.seek(0)
        rin = XmlRecordInput(buf)
        with pytest.raises(ValueError, match="expected"):
            rin.read_int("t")


class TestNativeCodec:
    """librecio (native/recordio ≈ src/c++/librecordio): the C validator
    agrees with the Python writer byte-for-byte."""

    def _lib_or_skip(self):
        try:
            from tpumr.utils.nativelib import load_native_lib
            lib = load_native_lib("recordio", "librecio.so")
        except Exception as e:  # noqa: BLE001 — no toolchain
            pytest.skip(f"native recio unavailable: {e}")
        if lib is None:        # loader reports failure as None, not raise
            pytest.skip("native recio unavailable (loader returned None)")
        return lib

    def test_descriptor_of(self):
        from tpumr.recordio.runtime import descriptor_of
        assert descriptor_of("int") == "i"
        assert descriptor_of(("vector", "ustring")) == "[s]"
        assert descriptor_of(("map", "byte", "long")) == "{bi}"
        assert descriptor_of(Inner) == "(s)"
        assert descriptor_of(("vector", ("vector", Inner))) == "[[(s)]]"

    def test_c_validates_python_stream(self):
        self._lib_or_skip()
        from tpumr.recordio.runtime import validate_binary
        data = sample().to_bytes() * 3
        assert validate_binary(data, Everything) == 3
        # truncation is malformed, not a crash
        assert validate_binary(data[:-3], Everything) == -1
        # trailing garbage likewise
        assert validate_binary(data + b"\xff\xff\xff\x01", Everything) == -1


class TestReviewRegressions:
    """Round-5 review findings, pinned."""

    def test_hash_consistent_with_eq(self):
        class R(Record):
            FIELDS = [("m", ("map", "ustring", "int"))]
        r1 = R(m={"a": 1, "b": 2})
        r2 = R(m={"b": 2, "a": 1})      # different insertion order
        assert r1 == r2 and hash(r1) == hash(r2)
        assert len({r1, r2}) == 1

    def test_vlong_range_checked_at_write(self):
        with pytest.raises(ValueError, match="int64 range"):
            _vl(2 ** 64)
        with pytest.raises(ValueError, match="int64 range"):
            _vl(-2 ** 63 - 1)

    def test_inf_nan_java_spelling(self):
        import math

        class F(Record):
            FIELDS = [("a", "float"), ("b", "double"), ("c", "double")]
        rec = F(a=float("inf"), b=float("-inf"), c=float("nan"))
        for O, I in ((CsvRecordOutput, CsvRecordInput),
                     (XmlRecordOutput, XmlRecordInput)):
            buf = io.BytesIO()
            rec.serialize(O(buf))
            text = buf.getvalue().decode()
            assert "Infinity" in text and "-Infinity" in text \
                and "NaN" in text, text
            assert "inf" not in text.replace("Infinity", ""), text
            buf.seek(0)
            back = F()
            back.deserialize(I(buf))
            assert math.isinf(back.a) and back.a > 0
            assert math.isinf(back.b) and back.b < 0
            assert math.isnan(back.c)

    def test_include_and_cross_module_refs(self, tmp_path):
        (tmp_path / "base.jr").write_text(
            "module base.types { class Point { int x; int y; } }")
        (tmp_path / "main.jr").write_text("""
            include "base.jr"
            module app.geo {
                class Path { vector<base.types.Point> pts; }
                class Box  { Point lo; Point hi; }   // bare cross-module
            }
        """)
        from tpumr.recordio.rcc import compile_files
        written = compile_files([str(tmp_path / "main.jr")],
                                dest=str(tmp_path))
        names = {p.rsplit("/", 1)[-1] for p in written}
        assert names == {"base_types.py", "app_geo.py"}
        import sys
        sys.path.insert(0, str(tmp_path))
        try:
            import app_geo
            import base_types
            p = app_geo.Path(pts=[base_types.Point(x=1, y=2)])
            assert app_geo.Path.from_bytes(p.to_bytes()) == p
            b = app_geo.Box(lo=base_types.Point(x=0, y=0),
                            hi=base_types.Point(x=3, y=4))
            assert app_geo.Box.from_bytes(b.to_bytes()) == b
        finally:
            sys.path.remove(str(tmp_path))
            sys.modules.pop("app_geo", None)
            sys.modules.pop("base_types", None)

    def test_missing_include_is_loud(self):
        from tpumr.recordio.rcc import DdlError, generate_python, parse_ddl
        with pytest.raises(DdlError, match="not in scope"):
            generate_python(parse_ddl(
                "module m { class A { other.mod.B b; } }"))

    def test_native_empty_struct_vector_no_hang(self):
        """A forged huge count over a zero-width element must fail or
        finish instantly, not spin 2^62 iterations."""
        pytest.importorskip("ctypes")
        lib = TestNativeCodec()._lib_or_skip()
        import ctypes
        import time
        lib.recio_validate.restype = ctypes.c_long
        lib.recio_validate.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                       ctypes.c_char_p]
        data = _vl(2 ** 62)            # count, then nothing
        t0 = time.time()
        lib.recio_validate(data, len(data), b"[()]")
        assert time.time() - t0 < 1.0


def test_xml_vector_of_empty_records_roundtrips():
    """Round-5 review: empty structs emit no leaf tokens, so the XML
    reader lost vector<EmptyRec> elements entirely; struct edges are
    events now and the count survives."""
    class E(Record):
        FIELDS = []

    class V(Record):
        FIELDS = [("v", ("vector", E)), ("tail", "int")]
    rec = V(v=[E(), E(), E()], tail=7)
    buf = io.BytesIO()
    rec.serialize(XmlRecordOutput(buf))
    buf.seek(0)
    back = V()
    back.deserialize(XmlRecordInput(buf))
    assert len(back.v) == 3 and back.tail == 7
    assert back == rec
