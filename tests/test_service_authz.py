"""Service-level authorization ≈ hadoop-policy.xml
(ServiceAuthorizationManager / PolicyProvider / refreshServiceAcl):
who may reach which protocol at all, enforced pre-dispatch in the RPC
layer, hot-reloadable via mradmin/dfsadmin -refreshServiceAcl."""

import json

import pytest

from tpumr.ipc.rpc import RpcClient, RpcError
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.security import UserGroupInformation
from tpumr.security.authorize import (AuthorizationError,
                                      ServiceAuthorizationManager)


def ugi(user, groups=()):
    return UserGroupInformation(user, list(groups))


class TestManager:
    def make(self, policy, default="security.client.protocol.acl", **kv):
        conf = JobConf()
        for k, v in kv.items():
            conf.set(k, v)
        return ServiceAuthorizationManager(conf, policy, default)

    def test_disabled_is_open(self):
        m = self.make({"op": ["security.x.acl"]},
                      **{"security.x.acl": ""})
        m.check("op", "anyone")          # off: no exception

    def test_unset_key_defaults_to_star(self):
        m = self.make({"op": ["security.x.acl"]},
                      **{"tpumr.security.authorization": True})
        m.check("op", "anyone")

    def test_deny_and_allow_by_key(self):
        m = self.make({"op": ["security.x.acl"]},
                      **{"tpumr.security.authorization": True,
                         "security.x.acl": "alice"})
        m.check("op", "alice")
        with pytest.raises(AuthorizationError, match="not authorized"):
            m.check("op", "bob")
        with pytest.raises(AuthorizationError):
            m.check("op", None)          # anonymous

    def test_any_of_multiple_services_admits(self):
        m = self.make({"op": ["security.a.acl", "security.b.acl"]},
                      **{"tpumr.security.authorization": True,
                         "security.a.acl": "svc",
                         "security.b.acl": "alice"})
        m.check("op", "svc")
        m.check("op", "alice")
        with pytest.raises(AuthorizationError):
            m.check("op", "eve")

    def test_unmapped_method_uses_default_key(self):
        m = self.make({}, **{"tpumr.security.authorization": True,
                             "security.client.protocol.acl": "alice"})
        m.check("new_client_rpc", "alice")
        with pytest.raises(AuthorizationError):
            m.check("new_client_rpc", "bob")

    def test_groups_resolve_server_side(self):
        m = self.make({"op": ["security.x.acl"]},
                      **{"tpumr.security.authorization": True,
                         "security.x.acl": " ops",
                         "tpumr.user.groups.carol": "ops"})
        m.check("op", "carol")
        with pytest.raises(AuthorizationError):
            m.check("op", "dave")


class TestJobMasterEnforcement:
    def master(self, **kv):
        conf = JobConf()
        conf.set("tpumr.security.authorization", True)
        for k, v in kv.items():
            conf.set(k, v)
        return JobMaster(conf).start()

    def client(self, m, user):
        host, port = m.address
        c = RpcClient(host, port)
        c._scope_user = user            # fix the asserted identity
        return c

    def test_submission_protocol_gated_over_rpc(self):
        m = self.master(**{
            "security.job.submission.protocol.acl": "alice"})
        try:
            assert self.client(m, "alice").call("list_jobs") == []
            with pytest.raises(RpcError, match="not authorized"):
                self.client(m, "eve").call("list_jobs")
        finally:
            m.stop()

    def test_intertracker_protocol_separate_from_client(self):
        m = self.master(**{
            "security.job.submission.protocol.acl": "alice",
            "security.inter.tracker.protocol.acl": "svc"})
        try:
            # the tracker identity may heartbeat but not submit
            hb = self.client(m, "svc").call(
                "heartbeat", {"tracker_name": "t", "host": "h",
                              "task_statuses": []}, True, False, 0)
            assert "actions" in hb
            with pytest.raises(RpcError, match="not authorized"):
                self.client(m, "svc").call("list_jobs")
            with pytest.raises(RpcError, match="not authorized"):
                self.client(m, "alice").call(
                    "heartbeat", {"tracker_name": "t2", "host": "h",
                                  "task_statuses": []}, True, False, 0)
        finally:
            m.stop()

    def test_refresh_service_acl_hot_reload(self, tmp_path):
        policy = tmp_path / "policy.json"
        policy.write_text(json.dumps(
            {"security.job.submission.protocol.acl": "alice"}))
        m = self.master(**{
            "tpumr.policy.file": str(policy),
            "security.refresh.policy.protocol.acl": "admin0"})
        try:
            with pytest.raises(RpcError, match="not authorized"):
                self.client(m, "bob").call("list_jobs")
            policy.write_text(json.dumps(
                {"security.job.submission.protocol.acl": "alice,bob"}))
            # refresh is itself gated by the refresh-policy ACL
            with pytest.raises(RpcError, match="not authorized"):
                self.client(m, "eve").call("refresh_service_acl")
            specs = self.client(m, "admin0").call("refresh_service_acl")
            assert specs[
                "security.job.submission.protocol.acl"] == "alice,bob"
            assert self.client(m, "bob").call("list_jobs") == []
        finally:
            m.stop()

    def test_refresh_refused_when_authorization_off(self):
        conf = JobConf()
        m = JobMaster(conf).start()
        try:
            with pytest.raises(PermissionError, match="disabled"):
                m.refresh_service_acl()
        finally:
            m.stop()


class TestNameNodeEnforcement:
    def test_client_protocol_gated(self, tmp_path):
        from tpumr.dfs.namenode import NameNode
        conf = JobConf()
        conf.set("tpumr.security.authorization", True)
        conf.set("security.client.protocol.acl", "alice")
        conf.set("tdfs.superuser", "alice")   # pass the FILE permission
        # tier; this test exercises the PROTOCOL tier in front of it
        nn = NameNode(str(tmp_path / "name"), conf).start()
        try:
            host, port = nn.address
            ca = RpcClient(host, port)
            ca._scope_user = "alice"
            assert ca.call("mkdirs", "/d") is True
            ce = RpcClient(host, port)
            ce._scope_user = "eve"
            with pytest.raises(RpcError, match="not authorized"):
                ce.call("exists", "/d")
        finally:
            nn.stop()


class TestClusterUnderRestrictedPolicy:
    def test_job_completes_with_split_acls(self, tmp_path):
        """End-to-end: submission ACL admits only the client user,
        umbilical ACL admits nobody directly — yet a real job with a
        reduce phase completes, because trackers relay the umbilical
        surface (commit grants, completion events) and the purge loop
        under the inter-tracker ACL."""
        import getpass
        import os

        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster
        me = getpass.getuser()
        conf = JobConf()
        conf.set("tpumr.security.authorization", True)
        conf.set("security.job.submission.protocol.acl", f"client9,{me}")
        conf.set("security.inter.tracker.protocol.acl", me)
        conf.set("security.task.umbilical.protocol.acl", "")
        cluster = MiniMRCluster(num_trackers=1, conf=conf,
                                cpu_slots=2, tpu_slots=0)
        try:
            os.makedirs(f"{tmp_path}/in", exist_ok=True)
            with open(f"{tmp_path}/in/f.txt", "w") as f:
                f.write("a b a\n")
            jc = JobConf()
            jc.set_job_name("authz-e2e")
            jc.set_input_paths(f"file://{tmp_path}/in")
            jc.set_output_path(f"file://{tmp_path}/out")
            jc.set("mapred.mapper.class",
                   "tpumr.ops.wordcount.WordCountCpuMapper")
            jc.set("mapred.reducer.class",
                   "tpumr.examples.basic.LongSumReducer")
            jc.set_num_reduce_tasks(1)
            jc.set("mapred.job.tracker", "%s:%d" % cluster.master.address)
            assert JobClient(jc).run_job(jc).successful
        finally:
            cluster.shutdown()


class TestProxyUsers:
    """≈ ProxyUsers.authorize: hadoop.proxyuser.<real>.groups/.hosts
    gate impersonation (doas); both rules required, default closed."""

    def _conf(self, **kv):
        conf = JobConf()
        for k, v in kv.items():
            conf.set(k, v)
        return conf

    def test_authorize_rules(self):
        from tpumr.security.authorize import authorize_proxy
        conf = self._conf(**{
            "hadoop.proxyuser.svc.groups": "webusers",
            "hadoop.proxyuser.svc.hosts": "127.0.0.1",
            "tpumr.user.groups.alice": "webusers",
            "tpumr.user.groups.carol": "admins"})
        authorize_proxy(conf, "svc", "alice", "127.0.0.1")
        with pytest.raises(AuthorizationError, match="not allowed to "
                           "impersonate"):
            authorize_proxy(conf, "svc", "carol", "127.0.0.1")  # group
        with pytest.raises(AuthorizationError, match="Unauthorized "
                           "connection"):
            authorize_proxy(conf, "svc", "alice", "10.0.0.9")   # host
        with pytest.raises(AuthorizationError):
            authorize_proxy(conf, "other", "alice", "127.0.0.1")  # no rules

    def test_star_wildcards(self):
        from tpumr.security.authorize import authorize_proxy
        conf = self._conf(**{"hadoop.proxyuser.svc.groups": "*",
                             "hadoop.proxyuser.svc.hosts": "*"})
        authorize_proxy(conf, "svc", "anyone", "10.9.9.9")

    def test_doas_over_rpc_lands_as_effective_user(self):
        """End-to-end: a doas submit is ACL-checked and owned as the
        effective user; the real caller is auditable."""
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "s3")
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.queue.prod.acl-submit-job", "alice")
        conf.set("hadoop.proxyuser.svc.groups", "webusers")
        conf.set("hadoop.proxyuser.svc.hosts", "127.0.0.1")
        conf.set("tpumr.user.groups.alice", "webusers")
        m = JobMaster(conf).start()
        try:
            host, port = m.address
            c = RpcClient(host, port, secret=b"s3")
            c._scope_user = "svc"
            c.doas = "alice"
            jid = c.call("submit_job",
                         {"mapred.job.queue.name": "prod",
                          "user.name": "alice",
                          "mapred.reduce.tasks": 0},
                         [{"locations": []}])
            assert jid in m.list_jobs()
            # svc directly (no doas) cannot pass alice's submit ACL
            c2 = RpcClient(host, port, secret=b"s3")
            c2._scope_user = "svc"
            with pytest.raises(RpcError, match="cannot submit"):
                c2.call("submit_job",
                        {"mapred.job.queue.name": "prod",
                         "user.name": "svc",
                         "mapred.reduce.tasks": 0},
                        [{"locations": []}])
            # an unauthorized impersonation target is refused
            c3 = RpcClient(host, port, secret=b"s3")
            c3._scope_user = "svc"
            c3.doas = "carol"
            with pytest.raises(RpcError, match="impersonate"):
                c3.call("list_jobs")
        finally:
            m.stop()

    def test_doas_rejected_without_proxy_conf(self):
        from tpumr.ipc.rpc import RpcServer

        class H:
            def ping(self):
                return "pong"

        srv = RpcServer(H(), secret=b"k")
        srv.proxy_conf = None
        srv.start()
        try:
            c = RpcClient(*srv.address, secret=b"k")
            c.doas = "anyone"
            with pytest.raises(RpcError, match="not enabled"):
                c.call("ping")
        finally:
            srv.stop()

    def test_doas_signature_binds(self):
        """Tampering the doas field after signing must fail auth."""
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "s4")
        conf.set("hadoop.proxyuser.svc.groups", "*")
        conf.set("hadoop.proxyuser.svc.hosts", "*")
        m = JobMaster(conf).start()
        try:
            host, port = m.address
            c = RpcClient(host, port, secret=b"s4")
            c._scope_user = "svc"
            c.doas = "alice"
            assert c.call("list_jobs") == []
            # flip doas post-signing via the envelope hook
            c2 = RpcClient(host, port, secret=b"s4")
            c2._scope_user = "svc"
            c2.doas = "alice"
            orig = c2._stamp

            def tamper(req):
                orig(req)
                req["doas"] = "root0"   # after the signature
            c2._stamp = tamper
            from tpumr.ipc.rpc import RpcAuthError
            with pytest.raises((RpcError, RpcAuthError)):
                c2.call("list_jobs")
        finally:
            m.stop()

    def test_empty_doas_rejected(self):
        """Empty doas must never resolve to the daemon's own identity."""
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "s5")
        conf.set("hadoop.proxyuser.svc.groups", "*")
        conf.set("hadoop.proxyuser.svc.hosts", "*")
        m = JobMaster(conf).start()
        try:
            from tpumr.ipc.rpc import RpcAuthError
            host, port = m.address
            c = RpcClient(host, port, secret=b"s5")
            c._scope_user = "svc"
            c.doas = ""
            with pytest.raises((RpcError, RpcAuthError),
                               match="invalid doas"):
                c.call("list_jobs")
        finally:
            m.stop()

    def test_doas_with_verified_real_caller(self):
        """The mode where doas is the ONLY route: a personal-key
        (verified) caller cannot assert another identity, but CAN act
        as one through authorized impersonation — and the job lands
        owned by the effective user even under require.verified."""
        from tpumr.security.tokens import derive_user_key
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "s6")
        conf.set("tpumr.acls.require.verified", True)
        conf.set("mapred.acls.enabled", True)
        conf.set("mapred.queue.names", "prod")
        conf.set("mapred.queue.prod.acl-submit-job", "alice")
        conf.set("hadoop.proxyuser.svc.groups", "webusers")
        conf.set("hadoop.proxyuser.svc.hosts", "127.0.0.1")
        conf.set("tpumr.user.groups.alice", "webusers")
        m = JobMaster(conf).start()
        try:
            host, port = m.address
            svc_key = derive_user_key(b"s6", "svc")
            # verified svc WITHOUT doas: its own identity fails the ACL
            c = RpcClient(host, port, secret=svc_key, scope="user:svc")
            with pytest.raises(RpcError, match="cannot submit"):
                c.call("submit_job",
                       {"mapred.job.queue.name": "prod",
                        "user.name": "svc", "mapred.reduce.tasks": 0},
                       [{"locations": []}])
            # verified svc WITH doas=alice: authorized impersonation
            c2 = RpcClient(host, port, secret=svc_key, scope="user:svc")
            c2.doas = "alice"
            jid = c2.call("submit_job",
                          {"mapred.job.queue.name": "prod",
                           "user.name": "alice",
                           "mapred.reduce.tasks": 0},
                          [{"locations": []}])
            assert jid in m.list_jobs()
            # ...and an unauthorized target stays refused
            c3 = RpcClient(host, port, secret=svc_key, scope="user:svc")
            c3.doas = "carol"
            with pytest.raises(RpcError, match="impersonate"):
                c3.call("list_jobs")
        finally:
            m.stop()
