"""Reliability tier ≈ SURVEY.md §5: restart recovery (RecoveryManager),
speculative execution, node health, task memory limits, fault injection."""

import os
import time

import pytest

from tpumr.fs import get_filesystem
from tpumr.mapred.ids import JobID
from tpumr.mapred.job_in_progress import JobInProgress, JobState
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.node_health import NodeHealthChecker, TaskMemoryManager
from tpumr.utils import fi


class TestFaultInjection:
    def setup_method(self):
        fi.reset()

    def test_disabled_by_default(self):
        conf = JobConf()
        fi.maybe_fail("map.task", conf)  # no raise
        fi.maybe_fail("map.task", None)

    def test_fires_and_respects_max_failures(self):
        conf = JobConf()
        conf.set("tpumr.fi.p1.probability", 1.0)
        conf.set("tpumr.fi.p1.max.failures", 2)
        for _ in range(2):
            with pytest.raises(fi.InjectedFault):
                fi.maybe_fail("p1", conf)
        fi.maybe_fail("p1", conf)  # third call: budget exhausted, no raise

    def test_retry_machinery_end_to_end(self):
        """First map attempt gets an injected fault; the retry succeeds —
        the deterministic replacement for the reference's fi weave tests."""
        fi.reset()
        from tpumr.mapred.mini_cluster import MiniMRCluster
        from tpumr.mapred.job_client import JobClient
        with MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=0) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/fi/in.txt", b"x y\n" * 10)
            conf = c.create_job_conf()
            conf.set_input_paths("mem:///fi/in.txt")
            conf.set_output_path("mem:///fi/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            from tpumr.examples.basic import LongSumReducer
            conf.set_class("mapred.mapper.class", WordCountCpuMapper)
            conf.set_class("mapred.reducer.class", LongSumReducer)
            conf.set("tpumr.fi.map.task.probability", 1.0)
            conf.set("tpumr.fi.map.task.max.failures", 1)
            result = JobClient(conf).run_job(conf)
            assert result.successful, "retry must absorb the injected fault"


class TestSpeculativeExecution:
    def _job(self, n_maps=4, **conf):
        base = {"mapred.reduce.tasks": 0,
                "mapred.speculative.execution": True,
                "mapred.reduce.slowstart.completed.maps": 0.0}
        base.update(conf)
        splits = [{"locations": []} for _ in range(n_maps)]
        return JobInProgress(JobID("spec", 1), splits=splits,
                             conf_dict=base)

    def _finish(self, job, task, runtime=1.0, is_map=True):
        from tpumr.mapred.task import TaskState, TaskStatus
        now = time.time()
        job.update_task_status(TaskStatus(
            attempt_id=task.attempt_id, is_map=is_map,
            state=TaskState.SUCCEEDED, start_time=now - runtime,
            finish_time=now), "t:0")

    def test_speculates_slow_straggler(self):
        job = self._job(n_maps=2)
        t0 = job.obtain_new_map_task("h", run_on_tpu=False)
        t1 = job.obtain_new_map_task("h", run_on_tpu=False)
        assert job.obtain_new_map_task("h", run_on_tpu=False) is None
        self._finish(job, t0, runtime=0.01)
        # t1 is now a straggler: backdate its start so elapsed >> mean
        job.maps[t1.partition].dispatch_mono = time.monotonic() - 100
        spec = job.obtain_new_map_task("h", run_on_tpu=False)
        assert spec is not None
        assert spec.partition == t1.partition
        assert spec.attempt_id != t1.attempt_id
        assert job.speculative_map_tasks == 1
        # only one speculative twin per task
        assert job.obtain_new_map_task("h", run_on_tpu=False) is None
        # first completion wins; the loser must be killed
        self._finish(job, spec, runtime=0.01)
        assert job.should_kill_attempt(str(t1.attempt_id))
        assert not job.should_kill_attempt(str(spec.attempt_id))

    def test_speculates_slow_reduce_straggler(self):
        """≈ JobInProgress.java:257,2320 hasSpeculativeReduces: a reduce
        running far beyond the completed-reduce mean gets a duplicate
        attempt; first completion wins and the loser is killed."""
        job = self._job(n_maps=0, **{"mapred.reduce.tasks": 2})
        r0 = job.obtain_new_reduce_task("h")
        r1 = job.obtain_new_reduce_task("h")
        assert r0 is not None and r1 is not None
        assert job.obtain_new_reduce_task("h") is None
        self._finish(job, r0, runtime=0.01, is_map=False)
        # r1 is now a straggler: backdate its start so elapsed >> mean
        job.reduces[r1.partition].dispatch_mono = time.monotonic() - 100
        spec = job.obtain_new_reduce_task("h")
        assert spec is not None
        assert spec.partition == r1.partition
        assert spec.attempt_id != r1.attempt_id
        assert job.speculative_reduce_tasks == 1
        # only one speculative twin per task
        assert job.obtain_new_reduce_task("h") is None
        # first completion wins; the loser must be killed
        self._finish(job, spec, runtime=0.01, is_map=False)
        assert job.should_kill_attempt(str(r1.attempt_id))
        assert not job.should_kill_attempt(str(spec.attempt_id))

    def test_reduce_speculation_needs_completion_and_flag(self):
        # no completed reduce yet -> no mean -> no speculation
        job = self._job(n_maps=0, **{"mapred.reduce.tasks": 1})
        r = job.obtain_new_reduce_task("h")
        job.reduces[r.partition].dispatch_mono = time.monotonic() - 100
        assert job.obtain_new_reduce_task("h") is None
        # mapred.reduce.speculative.execution=False turns ONLY reduces off
        off = self._job(n_maps=0, **{
            "mapred.reduce.tasks": 2,
            "mapred.reduce.speculative.execution": False})
        a = off.obtain_new_reduce_task("h")
        off.obtain_new_reduce_task("h")
        self._finish(off, a, runtime=0.01, is_map=False)
        off.reduces[1].dispatch_mono = time.monotonic() - 100
        assert off.obtain_new_reduce_task("h") is None

    def test_no_speculation_without_completions_or_flag(self):
        job = self._job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        job.maps[t.partition].dispatch_mono = time.monotonic() - 100
        assert job.obtain_new_map_task("h", run_on_tpu=False) is None
        off = self._job(n_maps=2,
                        **{"mapred.speculative.execution": False})
        a = off.obtain_new_map_task("h", run_on_tpu=False)
        off.obtain_new_map_task("h", run_on_tpu=False)
        self._finish(off, a, runtime=0.01)
        off.maps[1].dispatch_mono = time.monotonic() - 100
        assert off.obtain_new_map_task("h", run_on_tpu=False) is None


class TestRecovery:
    def test_jobmaster_restart_recovers_incomplete_jobs(self, tmp_path):
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        jm = JobMaster(conf).start()
        try:
            jid = jm.submit_job(
                {"mapred.job.name": "interrupted", "mapred.reduce.tasks": 0},
                [{"locations": []}, {"locations": []}])
            assert jm.jobs[jid].state == JobState.RUNNING
        finally:
            jm.stop()  # master dies with the job incomplete

        conf2 = JobConf()
        conf2.set("tpumr.history.dir", str(tmp_path))
        conf2.set("mapred.jobtracker.restart.recover", True)
        jm2 = JobMaster(conf2).start()
        try:
            recovered = [j for j in jm2.jobs.values()
                         if j.conf.get("mapred.job.name") == "interrupted"]
            assert len(recovered) == 1
            assert recovered[0].num_maps == 2
        finally:
            jm2.stop()

        # third start: the job was marked recovered — no duplicate replay
        jm3 = JobMaster(conf2).start()
        try:
            again = [j for j in jm3.jobs.values()
                     if j.conf.get("mapred.job.name") == "interrupted"]
            assert len(again) == 1  # only jm2's resubmission (recovered
            # again itself since it was never finished — but exactly once)
        finally:
            jm3.stop()


class TestFinalizeIdempotent:
    def test_double_finalize_emits_one_history_event(self, tmp_path):
        """kill_job racing a heartbeat-deferred finalization must not run
        commit/abort twice or duplicate JOB_FINISHED events."""
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        jm = JobMaster(conf).start()
        try:
            jid = jm.submit_job(
                {"mapred.job.name": "dupfin", "mapred.reduce.tasks": 0},
                [{"locations": []}])
            jip = jm.jobs[jid]
            jip.kill()
            jm._finalize_job(jip)
            jm._finalize_job(jip)          # second caller must no-op
            assert jm.kill_job(jid) is False  # already terminal
        finally:
            jm.stop()
        events = [e for f in os.listdir(tmp_path)
                  if f.endswith(".jsonl")
                  for e in open(os.path.join(tmp_path, f))
                  if '"JOB_FINISHED"' in e]
        assert len(events) == 1


class TestNodeHealth:
    def test_healthy_and_error_scripts(self):
        ok = NodeHealthChecker("echo all good")
        ok.check_once()
        assert ok.healthy and ok.report == ""
        bad = NodeHealthChecker("echo ERROR disk full")
        bad.check_once()
        assert not bad.healthy and "disk full" in bad.report
        crash = NodeHealthChecker("exit 3")  # nonzero exit alone: healthy
        crash.check_once()
        assert crash.healthy

    def test_unhealthy_tracker_gets_no_tasks(self):
        from tpumr.mapred.mini_cluster import MiniMRCluster
        from tpumr.mapred.job_client import JobClient
        import tempfile, os
        script = tempfile.mktemp(suffix=".sh")
        with open(script, "w") as f:
            f.write("echo ERROR synthetic\n")
        os.chmod(script, 0o755)
        conf = JobConf()
        conf.set("mapred.healthChecker.script.path", script)
        conf.set("mapred.healthChecker.interval.ms", 100)
        with MiniMRCluster(num_trackers=1, cpu_slots=1, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/nh/in.txt", b"a\n")
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///nh/in.txt")
            jc.set_output_path("mem:///nh/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            jc.set_class("mapred.mapper.class", WordCountCpuMapper)
            jc.set_num_reduce_tasks(0)
            client = JobClient(jc)
            running = client.submit_job(jc)
            time.sleep(1.0)
            st = running.status()
            assert st["map_progress"] == 0.0, \
                "unhealthy tracker must not receive tasks"
            running.kill()


class TestTaskMemoryManager:
    def test_kills_over_limit_process(self):
        import subprocess
        import sys
        # child that allocates ~80MB and sleeps
        code = ("import time\n"
                "x = bytearray(80 * 1024 * 1024)\n"
                "for i in range(0, len(x), 4096): x[i] = 1\n"
                "time.sleep(30)\n")
        proc = subprocess.Popen([sys.executable, "-c", code])
        try:
            mm = TaskMemoryManager(interval_s=0.1)
            killed = []
            mm.register("attempt_x", proc.pid, 16 << 20,
                        lambda aid: (killed.append(aid), proc.kill()))
            deadline = time.time() + 15
            while time.time() < deadline and not killed:
                time.sleep(0.2)
                mm.check_once()
            assert killed == ["attempt_x"]
            assert proc.wait(timeout=10) != 0
        finally:
            if proc.poll() is None:
                proc.kill()

    def test_under_limit_untouched(self):
        import subprocess
        import sys
        proc = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(5)"])
        try:
            mm = TaskMemoryManager()
            mm.register("a", proc.pid, 1 << 30, lambda aid: proc.kill())
            assert mm.check_once() == []
            assert proc.poll() is None
        finally:
            proc.kill()


class TestRecoveryPriority:
    def test_restart_preserves_runtime_priority_change(self, tmp_path):
        """A `job -set-priority` survives master restart: recovery
        replays the JOB_PRIORITY_CHANGED history event into the
        resubmitted conf (without it, the recovered job would silently
        revert to its submit-time priority)."""
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        jm = JobMaster(conf).start()
        try:
            jid = jm.submit_job(
                {"mapred.job.name": "bumped", "mapred.reduce.tasks": 0},
                [{"locations": []}])
            assert jm.jobs[jid].priority == "NORMAL"
            jm.set_job_priority(jid, "VERY_HIGH", "anyone")
        finally:
            jm.stop()

        conf2 = JobConf()
        conf2.set("tpumr.history.dir", str(tmp_path))
        conf2.set("mapred.jobtracker.restart.recover", True)
        jm2 = JobMaster(conf2).start()
        try:
            recovered = [j for j in jm2.jobs.values()
                         if j.conf.get("mapred.job.name") == "bumped"]
            assert len(recovered) == 1
            assert recovered[0].priority == "VERY_HIGH"
        finally:
            jm2.stop()
