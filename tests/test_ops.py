"""Kernel mapper tests: numeric parity vs numpy references, Pallas interpret
mode on CPU (real-TPU execution is exercised by bench.py on hardware)."""

import numpy as np
import pytest

from tpumr.io.recordbatch import DenseBatch, RecordBatch
from tpumr.mapred.jobconf import JobConf
from tpumr.ops import get_kernel, kernels
from tpumr.ops.kmeans import assign_and_partials, pallas_assign


def _np_assign(points, centroids):
    d2 = ((points[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
    return d2.argmin(1)


def test_registry_lists_builtins():
    names = kernels()
    for expected in ["kmeans-assign", "matmul-block", "pi-sampler",
                     "wordcount", "grep"]:
        assert expected in names
    with pytest.raises(KeyError):
        get_kernel("nope")


def test_kmeans_assign_jax_matches_numpy():
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(257, 5)).astype(np.float32)
    cents = rng.normal(size=(7, 5)).astype(np.float32)
    assign, sums, counts = assign_and_partials(pts, cents, use_pallas=False)
    expect = _np_assign(pts, cents)
    np.testing.assert_array_equal(np.asarray(assign), expect)
    assert int(np.asarray(counts).sum()) == 257
    for c in range(7):
        mask = expect == c
        if mask.any():
            np.testing.assert_allclose(np.asarray(sums)[c], pts[mask].sum(0),
                                       rtol=1e-4)


def test_kmeans_pallas_interpret_matches_numpy():
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(100, 3)).astype(np.float32)
    cents = rng.normal(size=(5, 3)).astype(np.float32)
    out = np.asarray(pallas_assign(pts, cents, block_n=32, interpret=True))
    np.testing.assert_array_equal(out, _np_assign(pts, cents))


def test_kmeans_kernel_mapper_partials(tmp_path):
    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(64, 4)).astype(np.float32)
    cents = rng.normal(size=(3, 4)).astype(np.float32)
    cpath = tmp_path / "c.npy"
    np.save(cpath, cents)
    conf = JobConf()
    conf.set("tpumr.kmeans.centroids", f"file://{cpath}")
    kernel = get_kernel("kmeans-assign")
    out = dict(kernel.map_batch(DenseBatch(pts, np.arange(64)), conf, None))
    expect = _np_assign(pts, cents)
    total = 0
    for cid, (s, n) in out.items():
        mask = expect == cid
        assert n == mask.sum()
        np.testing.assert_allclose(s, pts[mask].sum(0), rtol=1e-4)
        total += n
    assert total == 64


def test_matmul_kernel(tmp_path):
    from tpumr.ops.matmul import clear_b_cache
    clear_b_cache()
    rng = np.random.default_rng(3)
    a = rng.normal(size=(16, 8)).astype(np.float32)
    b = rng.normal(size=(8, 12)).astype(np.float32)
    np.save(tmp_path / "b.npy", b)
    conf = JobConf()
    conf.set("tpumr.matmul.b", f"file://{tmp_path}/b.npy")
    conf.set("tpumr.matmul.bf16", False)
    kernel = get_kernel("matmul-block")
    [(row0, c)] = list(kernel.map_batch(
        DenseBatch(a, np.arange(100, 116)), conf, None))
    assert row0 == 100
    np.testing.assert_allclose(c, a @ b, rtol=1e-4)


def test_pi_kernel_reasonable():
    conf = JobConf()
    kernel = get_kernel("pi-sampler")
    batch = RecordBatch.from_values([b"1 20000", b"2 20000"])
    out = dict(kernel.map_batch(batch, conf, None))
    assert out["total"] == 40000
    pi = 4.0 * out["inside"] / out["total"]
    assert abs(pi - np.pi) < 0.05


def test_wordcount_kernel_matches_split():
    text = ["the quick brown fox", "the lazy dog", "", "fox    fox"]
    batch = RecordBatch.from_values([t.encode() for t in text])
    out = dict(get_kernel("wordcount").map_batch(batch, JobConf(), None))
    assert out == {"the": 2, "quick": 1, "brown": 1, "fox": 3,
                   "lazy": 1, "dog": 1}


def test_grep_kernel():
    conf = JobConf()
    conf.set("tpumr.grep.pattern", r"err[a-z]+")
    batch = RecordBatch.from_values([b"error here", b"no match",
                                     b"errand and error"])
    out = dict(get_kernel("grep").map_batch(batch, conf, None))
    assert out == {"error": 2, "errand": 1}


class TestVectorizedTokenizer:
    """tokenize_count (numpy byte-matrix) and tokenize_count_native
    (native/textkit single-pass C) must both match bytes.split()/Counter
    exactly — including non-UTF8 bytes, NULs inside tokens, and every
    whitespace class."""

    CASES = [
        b"", b" \t\n\v\f\r ", b"a", b" a ", b"a b a\nc\t\tb",
        b"\x00weird\x00 to\x00kens \x00",
        b"x" * 300 + b" " + b"x" * 300,          # long tokens (>8 bytes)
        bytes(range(256)) * 20,                   # all byte values
    ]

    def test_numpy_path_matches_counter(self):
        from collections import Counter

        from tpumr.ops.wordcount import tokenize_count
        for d in self.CASES:
            assert dict(tokenize_count(d)) == dict(Counter(d.split())), d[:32]

    def test_native_path_matches_counter(self):
        import shutil

        import pytest as _pytest
        from collections import Counter

        from tpumr.ops.wordcount import tokenize_count_native
        if shutil.which("cc") is None:
            _pytest.skip("no C toolchain")
        for d in self.CASES:
            got = tokenize_count_native(d)
            if got is None:
                _pytest.skip("native tokenizer unavailable")
            assert dict(got) == dict(Counter(d.split())), d[:32]

    def test_kernel_job_output_unchanged(self):
        """The wordcount kernel end-to-end (large enough to take the
        vectorized path) produces the same counts as the naive mapper."""
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred import JobConf, run_job
        fs = get_filesystem("mem:///")
        text = b"".join(b"tok%03d fixed\n" % (i % 101)
                        for i in range(20000))   # > 64 KiB
        fs.write_bytes("/vt/in.txt", text)
        conf = JobConf()
        conf.set_input_paths("mem:///vt/in.txt")
        conf.set_output_path("mem:///vt/out")
        conf.set_map_kernel("wordcount")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        conf.set("tpumr.local.run.on.tpu", True)
        assert run_job(conf).successful
        out = b"".join(fs.read_bytes(st.path)
                       for st in fs.list_status("/vt/out")
                       if "part-" in str(st.path))
        counts = dict(l.split(b"\t") for l in out.splitlines())
        assert counts[b"fixed"] == b"20000"
        assert counts[b"tok000"] == b"199"   # ceil(20000/101)
        FileSystem.clear_cache()

    def test_raw_text_multi_split_boundary_ownership(self, tmp_path):
        """A wordcount job forced into MANY RawTextInputFormat splits
        must count every word exactly once — the split-boundary
        ownership rule (skip leading partial, finish trailing line) is
        exercised across dozens of boundaries, at varied line lengths
        so boundaries land mid-line, at line starts, and on newlines."""
        from collections import Counter

        from tpumr.fs import FileSystem
        from tpumr.mapred import JobConf, run_job
        import random
        random.seed(4)
        lines = []
        for i in range(4000):
            lines.append(" ".join(
                f"w{random.randrange(50):02d}"
                for _ in range(random.randrange(1, 9))))
        text = ("\n".join(lines) + "\n").encode()
        expected = Counter(text.split())
        p = tmp_path / "multi.txt"
        p.write_bytes(text)
        conf = JobConf()
        conf.set_input_paths(f"file://{p}")
        conf.set_output_path(f"file://{tmp_path}/out")
        from tpumr.mapred.input_formats import RawTextInputFormat
        conf.set_input_format(RawTextInputFormat)
        conf.set("mapred.max.split.size", 997)   # prime: odd boundaries
        conf.set("fs.local.block.size", 997)
        conf.set_map_kernel("wordcount")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.LongSumReducer")
        assert run_job(conf).successful
        got = {}
        import glob
        for part in glob.glob(f"{tmp_path}/out/part-*"):
            for line in open(part, "rb").read().splitlines():
                k, v = line.rsplit(b"\t", 1)
                got[k] = int(v)
        assert got == dict(expected)
        FileSystem.clear_cache()


class TestDeviceConstantCache:
    """ops/devcache.py: side-input uploads happen once per (tag, device),
    not once per map task — the tunneled-chip warm-job bottleneck."""

    def setup_method(self):
        from tpumr.ops import devcache
        devcache.clear_device_cache()
        # the byte budget is fixed at first construction; tests that
        # set their own budget need a fresh singleton
        devcache._cache = None

    def test_same_device_array_across_calls(self):
        import numpy as np
        from tpumr.ops.devcache import device_cached
        host = np.arange(12, dtype=np.float32).reshape(3, 4)
        a1 = device_cached("t:x", host)
        a2 = device_cached("t:x", host)
        assert a1 is a2          # no second upload
        np.testing.assert_array_equal(np.asarray(a1), host)

    def test_prefix_clear_and_budget_eviction(self):
        import numpy as np
        from tpumr.ops import devcache
        from tpumr.ops.devcache import clear_device_cache, device_cached

        class Conf:
            def get(self, k, d=None):
                return 1 if k == "tpumr.ops.device.cache.mb" else d

        half = np.zeros((150, 1024), np.float32)      # ~0.6 MB each
        a = device_cached("a:1", half, Conf())
        device_cached("b:1", half, Conf())            # evicts a:1 (LRU)
        assert device_cached("b:1", half, Conf()) is not None
        a2 = device_cached("a:1", half, Conf())       # re-upload: new obj
        assert a2 is not a
        clear_device_cache("a:")
        assert device_cached("a:1", half, Conf()) is not a2  # was dropped

    def test_kernels_reuse_device_side_inputs(self, tmp_path):
        """kmeans centroids and matmul B resolve to the SAME device
        array across tasks of a job (and re-upload after the iterative
        driver's clear)."""
        import numpy as np
        from tpumr.mapred.jobconf import JobConf
        from tpumr.ops.kmeans import _device_centroids, clear_centroid_cache
        from tpumr.ops.matmul import _device_b, clear_b_cache
        np.save(tmp_path / "c.npy", np.zeros((3, 4), np.float32))
        np.save(tmp_path / "b.npy", np.ones((4, 4), np.float32))
        conf = JobConf()
        conf.set("tpumr.kmeans.centroids", f"file://{tmp_path}/c.npy")
        conf.set("tpumr.matmul.b", f"file://{tmp_path}/b.npy")
        clear_centroid_cache(); clear_b_cache()
        c1, c2 = _device_centroids(conf), _device_centroids(conf)
        assert c1 is c2
        b1, b2 = _device_b(conf), _device_b(conf)
        assert b1 is b2
        clear_centroid_cache()
        assert _device_centroids(conf) is not c1   # rewritten rounds re-upload
        clear_b_cache()
        assert _device_b(conf) is not b1
