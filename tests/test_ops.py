"""Kernel mapper tests: numeric parity vs numpy references, Pallas interpret
mode on CPU (real-TPU execution is exercised by bench.py on hardware)."""

import numpy as np
import pytest

from tpumr.io.recordbatch import DenseBatch, RecordBatch
from tpumr.mapred.jobconf import JobConf
from tpumr.ops import get_kernel, kernels
from tpumr.ops.kmeans import assign_and_partials, pallas_assign


def _np_assign(points, centroids):
    d2 = ((points[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
    return d2.argmin(1)


def test_registry_lists_builtins():
    names = kernels()
    for expected in ["kmeans-assign", "matmul-block", "pi-sampler",
                     "wordcount", "grep"]:
        assert expected in names
    with pytest.raises(KeyError):
        get_kernel("nope")


def test_kmeans_assign_jax_matches_numpy():
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(257, 5)).astype(np.float32)
    cents = rng.normal(size=(7, 5)).astype(np.float32)
    assign, sums, counts = assign_and_partials(pts, cents, use_pallas=False)
    expect = _np_assign(pts, cents)
    np.testing.assert_array_equal(np.asarray(assign), expect)
    assert int(np.asarray(counts).sum()) == 257
    for c in range(7):
        mask = expect == c
        if mask.any():
            np.testing.assert_allclose(np.asarray(sums)[c], pts[mask].sum(0),
                                       rtol=1e-4)


def test_kmeans_pallas_interpret_matches_numpy():
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(100, 3)).astype(np.float32)
    cents = rng.normal(size=(5, 3)).astype(np.float32)
    out = np.asarray(pallas_assign(pts, cents, block_n=32, interpret=True))
    np.testing.assert_array_equal(out, _np_assign(pts, cents))


def test_kmeans_kernel_mapper_partials(tmp_path):
    from tpumr.ops.kmeans import clear_centroid_cache
    clear_centroid_cache()
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(64, 4)).astype(np.float32)
    cents = rng.normal(size=(3, 4)).astype(np.float32)
    cpath = tmp_path / "c.npy"
    np.save(cpath, cents)
    conf = JobConf()
    conf.set("tpumr.kmeans.centroids", f"file://{cpath}")
    kernel = get_kernel("kmeans-assign")
    out = dict(kernel.map_batch(DenseBatch(pts, np.arange(64)), conf, None))
    expect = _np_assign(pts, cents)
    total = 0
    for cid, (s, n) in out.items():
        mask = expect == cid
        assert n == mask.sum()
        np.testing.assert_allclose(s, pts[mask].sum(0), rtol=1e-4)
        total += n
    assert total == 64


def test_matmul_kernel(tmp_path):
    from tpumr.ops.matmul import clear_b_cache
    clear_b_cache()
    rng = np.random.default_rng(3)
    a = rng.normal(size=(16, 8)).astype(np.float32)
    b = rng.normal(size=(8, 12)).astype(np.float32)
    np.save(tmp_path / "b.npy", b)
    conf = JobConf()
    conf.set("tpumr.matmul.b", f"file://{tmp_path}/b.npy")
    conf.set("tpumr.matmul.bf16", False)
    kernel = get_kernel("matmul-block")
    [(row0, c)] = list(kernel.map_batch(
        DenseBatch(a, np.arange(100, 116)), conf, None))
    assert row0 == 100
    np.testing.assert_allclose(c, a @ b, rtol=1e-4)


def test_pi_kernel_reasonable():
    conf = JobConf()
    kernel = get_kernel("pi-sampler")
    batch = RecordBatch.from_values([b"1 20000", b"2 20000"])
    out = dict(kernel.map_batch(batch, conf, None))
    assert out["total"] == 40000
    pi = 4.0 * out["inside"] / out["total"]
    assert abs(pi - np.pi) < 0.05


def test_wordcount_kernel_matches_split():
    text = ["the quick brown fox", "the lazy dog", "", "fox    fox"]
    batch = RecordBatch.from_values([t.encode() for t in text])
    out = dict(get_kernel("wordcount").map_batch(batch, JobConf(), None))
    assert out == {"the": 2, "quick": 1, "brown": 1, "fox": 3,
                   "lazy": 1, "dog": 1}


def test_grep_kernel():
    conf = JobConf()
    conf.set("tpumr.grep.pattern", r"err[a-z]+")
    batch = RecordBatch.from_values([b"error here", b"no match",
                                     b"errand and error"])
    out = dict(get_kernel("grep").map_batch(batch, conf, None))
    assert out == {"error": 2, "errand": 1}
