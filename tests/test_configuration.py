"""Configuration semantics ≈ reference TestConfiguration
(src/test/org/apache/hadoop/conf/TestConfiguration.java): layering,
overrides, substitution, typed getters."""

import json

from tpumr.core.configuration import Configuration


def test_layering_and_override():
    conf = Configuration(load_defaults=False)
    conf.add_resource({"a": "1", "b": "base"})
    conf.add_resource({"b": "override"})
    assert conf.get("a") == "1"
    assert conf.get("b") == "override"
    conf.set("b", "explicit")
    assert conf.get("b") == "explicit"
    conf.unset("b")
    assert conf.get("b") is None


def test_variable_expansion(monkeypatch):
    conf = Configuration(load_defaults=False)
    conf.set("base.dir", "/data")
    conf.set("job.dir", "${base.dir}/jobs/${job.id}")
    conf.set("job.id", "job_001")
    assert conf.get("job.dir") == "/data/jobs/job_001"
    monkeypatch.setenv("TPUMR_TEST_HOME", "/home/x")
    conf.set("from.env", "${TPUMR_TEST_HOME}/y")
    assert conf.get("from.env") == "/home/x/y"


def test_typed_getters():
    conf = Configuration(load_defaults=False)
    conf.set("i", "42")
    conf.set("f", "2.5")
    conf.set("t", "true")
    conf.set("n", "no")
    conf.set("list", "a, b ,c")
    conf.set("size", "64m")
    assert conf.get_int("i") == 42
    assert conf.get_int("missing", 7) == 7
    assert conf.get_float("f") == 2.5
    assert conf.get_boolean("t") is True
    assert conf.get_boolean("n") is False
    assert conf.get_boolean("missing", True) is True
    assert conf.get_strings("list") == ["a", "b", "c"]
    assert conf.get_size("size") == 64 * 1024 * 1024


def test_file_resource(tmp_path):
    p = tmp_path / "site.json"
    p.write_text(json.dumps({"x.y": "zzz", "n": 3}))
    conf = Configuration(load_defaults=False)
    conf.add_resource(str(p))
    assert conf.get("x.y") == "zzz"
    assert conf.get_int("n") == 3


def test_deprecation_mapping():
    conf = Configuration(load_defaults=False)
    conf.add_deprecation("mapred.old.key", "tpumr.new.key")
    conf.set("mapred.old.key", "v")
    assert conf.get("tpumr.new.key") == "v"


def test_copy_isolation():
    a = Configuration(load_defaults=False)
    a.set("k", "1")
    b = a.copy()
    b.set("k", "2")
    assert a.get("k") == "1"
    assert b.get("k") == "2"


def test_get_class():
    conf = Configuration(load_defaults=False)
    conf.set("cls", "tpumr.core.configuration.Configuration")
    assert conf.get_class("cls") is Configuration
