"""Serialization / RecordBatch / SequenceFile tests ≈ reference io tests
(src/test/org/apache/hadoop/io/: TestWritable, TestSequenceFile,
TestText…)."""

from io import BytesIO

import numpy as np
import pytest

from tpumr.io import sequencefile
from tpumr.io.compress import get_codec, codec_for_path
from tpumr.io.recordbatch import DenseBatch, RecordBatch
from tpumr.io.writable import (
    deserialize, read_vint, serialize, write_vint, zigzag, unzigzag,
)


def test_vint_roundtrip():
    for v in [0, 1, 127, 128, 300, 2**31, 2**60]:
        buf = BytesIO()
        write_vint(buf, v)
        buf.seek(0)
        assert read_vint(buf) == v


def test_zigzag():
    for v in [0, -1, 1, -64, 63, -(2**40), 2**40]:
        assert unzigzag(zigzag(v)) == v


@pytest.mark.parametrize("obj", [
    None, True, False, b"raw\x00bytes", "unicode é中", 0, -17, 2**50,
    3.14159, [1, "two", b"three", [4.0]], {"k": 1, b"b": [None, True]},
])
def test_serialize_roundtrip(obj):
    assert deserialize(serialize(obj)) == obj


def test_serialize_ndarray():
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = deserialize(serialize(arr))
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == np.float32


def test_recordbatch_roundtrip():
    pairs = [(b"key1", b"val1"), (b"", b"v"), (b"longer-key", b"")]
    rb = RecordBatch.from_pairs(pairs)
    assert rb.num_records == 3
    assert rb.to_pairs() == pairs
    assert rb.key(2) == b"longer-key"


def test_recordbatch_padded():
    rb = RecordBatch.from_values([b"abc", b"defgh", b""])
    padded, lengths = rb.padded_values(4, fill=0)
    assert padded.shape == (3, 4)
    assert bytes(padded[0]) == b"abc\x00"
    assert bytes(padded[1]) == b"defg"  # truncated at width
    assert lengths.tolist() == [3, 5, 0]


def test_recordbatch_concat_slice():
    a = RecordBatch.from_pairs([(b"a", b"1")])
    b = RecordBatch.from_pairs([(b"b", b"2"), (b"c", b"3")])
    cat = RecordBatch.concat([a, b])
    assert cat.to_pairs() == [(b"a", b"1"), (b"b", b"2"), (b"c", b"3")]
    sl = cat.slice(1, 3)
    assert sl.to_pairs() == [(b"b", b"2"), (b"c", b"3")]


def test_densebatch():
    d1 = DenseBatch(np.ones((2, 3), np.float32), np.arange(2, dtype=np.int64))
    d2 = DenseBatch(np.zeros((1, 3), np.float32), np.array([5], np.int64))
    cat = DenseBatch.concat([d1, d2])
    assert cat.num_records == 3
    assert cat.ids.tolist() == [0, 1, 5]


@pytest.mark.parametrize("codec", ["none", "zlib", "gzip", "bzip2",
                                   "lzma", "tlz"])
def test_codec_roundtrip(codec):
    c = get_codec(codec)
    data = b"some repetitive data " * 100
    assert c.decompress(c.compress(data)) == data


class TestTlzCodec:
    """Native fast shuffle/spill codec (native/tlz ≈ the reference's
    JNI compression tier) — native and pure-Python ends must agree on
    the frame format in every combination."""

    PAYLOADS = [b"", b"x", b"abc" * 5000, bytes(range(256)) * 300,
                b"aaaaaaaaab" * 1 + b"Z" * 100 + b"aaaaaaaaab" * 40]

    def test_native_and_python_interop(self):
        import os
        from tpumr.io.compress import TlzCodec
        c = TlzCodec()
        rnd = os.urandom(50_000)              # stored-mode path
        for data in self.PAYLOADS + [rnd]:
            native = c.compress(data)
            if TlzCodec.available():
                # python reader decodes native frames
                assert TlzCodec._py_decompress(native) == data
            assert c.decompress(native) == data
            # python stored frames decode natively
            stored = TlzCodec._py_store(data)
            assert c.decompress(stored) == data

    def test_corrupt_frames_raise(self):
        import struct
        from tpumr.io.compress import TlzCodec
        c = TlzCodec()
        frame = bytearray(c.compress(b"abcabcabc" * 1000))
        with pytest.raises(ValueError):
            c.decompress(b"NOPE" + bytes(frame[4:]))
        with pytest.raises(ValueError):
            c.decompress(bytes(frame[: len(frame) // 2]))
        with pytest.raises(ValueError):
            TlzCodec._py_decompress(bytes(frame[: len(frame) // 2]))
        # a bit-flipped LENGTH header must raise, never size a huge
        # allocation off untrusted bytes
        bomb = bytes(frame[:4]) + struct.pack("<Q", 1 << 60) \
            + bytes(frame[12:])
        with pytest.raises(ValueError, match="implausible|corrupt"):
            c.decompress(bomb)

    def test_compresses_text_class_data(self):
        from tpumr.io.compress import TlzCodec
        if not TlzCodec.available():
            pytest.skip("no C toolchain")
        c = TlzCodec()
        data = b"word0001\t17\nword0002\t3\n" * 20000
        out = c.compress(data)
        assert len(out) < len(data) // 2      # real compression
        assert c.decompress(out) == data


def test_codec_for_path():
    assert codec_for_path("x.gz").name == "gzip"
    assert codec_for_path("x.txt") is None


@pytest.mark.parametrize("codec", ["none", "zlib"])
def test_sequencefile_roundtrip(codec):
    buf = BytesIO()
    with sequencefile.Writer(buf, codec=codec, block_records=3) as w:
        for i in range(10):
            w.append(f"key{i}", {"n": i, "payload": b"x" * i})
    # Writer closes buf; re-wrap its bytes
    data = buf.getvalue()
    r = sequencefile.Reader(BytesIO(data))
    items = list(r)
    assert len(items) == 10
    assert items[0] == ("key0", {"n": 0, "payload": b""})
    assert items[9][1]["n"] == 9


def test_sequencefile_sync_split():
    buf = BytesIO()
    w = sequencefile.Writer(buf, block_records=5)
    for i in range(100):
        w.append(i, b"v" * 50)
        if i % 20 == 19:
            w.sync_now()
    w._flush_block()
    data = buf.getvalue()
    # read from the middle: sync() must land on a block boundary
    r = sequencefile.Reader(BytesIO(data))
    assert r.sync(len(data) // 2)
    tail = list(r)
    assert 0 < len(tail) < 100
    keys = [k for k, _ in tail]
    assert keys == sorted(keys)
    assert keys[-1] == 99


class TestAppendFixedRows:
    def test_byte_identical_to_per_record_appends(self):
        """Bulk fixed-width append must produce exactly the framing of n
        scalar append() calls (same reader, same sync semantics)."""
        import io as _io
        import os as _os
        import numpy as np
        from tpumr.io import sequencefile as sf
        rng = np.random.default_rng(0)
        rows = rng.integers(0, 256, size=(2500, 14), dtype=np.uint8)
        orig = _os.urandom
        _os.urandom = lambda n: b"S" * n  # pin sync for comparability
        try:
            # DEFAULT block size: the contract must hold for production
            # writers (_SeqWriter passes no block_records)
            b1, b2 = _io.BytesIO(), _io.BytesIO()
            w1 = sf.Writer(b1)
            w1.append_fixed_rows(rows, 10)
            w1.close()
            w2 = sf.Writer(b2)
            for r in rows:
                w2.append(bytes(r[:10]), bytes(r[10:]))
            w2.close()
        finally:
            _os.urandom = orig
        assert b1.getvalue() == b2.getvalue()

    def test_roundtrip_and_mixed_appends(self):
        import io as _io
        import numpy as np
        from tpumr.io import sequencefile as sf
        rng = np.random.default_rng(1)
        rows = rng.integers(0, 256, size=(300, 12), dtype=np.uint8)
        b = _io.BytesIO()
        w = sf.Writer(b)
        w.append(b"first-0000", b"xx")       # scalar before bulk: ordered
        w.append_fixed_rows(rows, 10)
        w.append(b"last-00000", b"yy")
        w.close()
        b.seek(0)
        recs = list(sf.Reader(b))
        assert len(recs) == 302
        assert recs[0] == (b"first-0000", b"xx")
        assert recs[1] == (bytes(rows[0, :10]), bytes(rows[0, 10:]))
        assert recs[-1] == (b"last-00000", b"yy")

    def test_zero_width_values(self):
        import io as _io
        import numpy as np
        from tpumr.io import sequencefile as sf
        rows = np.arange(50, dtype=np.uint8).reshape(5, 10)
        b = _io.BytesIO()
        w = sf.Writer(b)
        w.append_fixed_rows(rows, 10)
        w.close()
        b.seek(0)
        assert list(sf.Reader(b)) == [(bytes(r), b"") for r in rows]


# ---------------------------------------------------------------- TFile


class TestTFile:
    """≈ io/file/tfile TestTFile*: sorted container, block index,
    range scanners, meta blocks."""

    def _build(self, f, n=500, codec="zlib", block_bytes=512):
        from tpumr.io import tfile
        with tfile.Writer(f, codec=codec, block_bytes=block_bytes) as w:
            for i in range(n):
                w.append(f"k{i:06d}".encode(), f"v{i}".encode() * 3)
            w.write_meta("stats", b'{"rows": 500}')
        return f

    def test_roundtrip_and_block_index(self):
        import io as _io

        from tpumr.io import tfile
        f = self._build(_io.BytesIO())
        r = tfile.Reader(f)
        assert r.num_records == 500
        assert len(r.block_keys) > 5, "never rolled a block"
        recs = list(r)
        assert len(recs) == 500
        assert recs[0][0] == b"k000000" and recs[-1][0] == b"k000499"
        assert recs == sorted(recs)

    def test_seek_and_range_scanner(self):
        import io as _io

        from tpumr.io import tfile
        r = tfile.Reader(self._build(_io.BytesIO()))
        # exact get
        assert r.get(b"k000123") == b"v123" * 3
        assert r.get(b"nope") is None
        # range [k000100, k000110)
        keys = [k for k, _ in r.scanner(b"k000100", b"k000110")]
        assert keys == [f"k{i:06d}".encode() for i in range(100, 110)]
        # seek positions at first key >= target
        it = r.seek_to(b"k000250")
        assert next(it)[0] == b"k000250"

    def test_meta_blocks(self):
        import io as _io

        from tpumr.io import tfile
        r = tfile.Reader(self._build(_io.BytesIO()))
        assert r.meta_names() == ["stats"]
        assert r.meta("stats") == b'{"rows": 500}'

    def test_out_of_order_append_rejected(self):
        import io as _io

        from tpumr.io import tfile
        w = tfile.Writer(_io.BytesIO())
        w.append(b"b", b"1")
        with pytest.raises(tfile.TFileError, match="out of order"):
            w.append(b"a", b"2")

    def test_uncompressed_and_corrupt_magic(self):
        import io as _io

        from tpumr.io import tfile
        f = self._build(_io.BytesIO(), codec="none")
        r = tfile.Reader(f)
        assert r.get(b"k000001") == b"v1v1v1"
        with pytest.raises(tfile.TFileError, match="magic"):
            tfile.Reader(_io.BytesIO(b"not a tfile at all"))

    def test_duplicate_keys_across_block_boundary(self):
        """Equal keys spanning a block boundary: scans starting at that
        key must include records from the EARLIER block too."""
        import io as _io

        from tpumr.io import tfile
        w = tfile.Writer(_io.BytesIO(), codec="none", block_bytes=16)
        for i in range(6):
            w.append(b"dup", b"v%d" % i)
        w.append(b"zz", b"tail")
        f = w._f
        w.close()
        r = tfile.Reader(f)
        assert len(r.block_keys) >= 2
        vals = [v for k, v in r.scanner(b"dup") if k == b"dup"]
        assert vals == [b"v%d" % i for i in range(6)]
        assert r.get(b"dup") == b"v0"
