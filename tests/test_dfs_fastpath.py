"""DFS fast-path tests: editlog group commit (ordering / durability /
batching), striped namespace locking under cross-stripe churn, and the
hot-block boost/cool-down state machine (docs/DFS_FASTPATH.md)."""

import os
import threading
import time

import pytest

from tpumr.dfs.editlog import FSEditLog
from tpumr.dfs.hotblocks import SpaceSaving
from tpumr.dfs.mini_cluster import MiniDFSCluster
from tpumr.dfs.namenode import FSNamesystem
from tpumr.dfs.nslock import NamespaceLocks
from tpumr.mapred.jobconf import JobConf


def small_conf(block_size=1024, replication=2):
    conf = JobConf()
    conf.set("dfs.block.size", block_size)
    conf.set("dfs.replication", replication)
    conf.set("tdfs.replication.interval.s", 0.2)
    conf.set("tdfs.datanode.expiry.s", 1.5)
    return conf


# ------------------------------------------------------------ editlog


class TestEditlogGroupCommit:
    def test_concurrent_appends_durable_and_ordered(self, tmp_path):
        """The WAL contract under concurrency: every log() that
        returned is on disk, journal order is append order (each
        writer's own records replay in its program order), and the
        group-commit counters stay coherent."""
        el = FSEditLog(str(tmp_path))
        writers, per = 8, 25

        def write(w):
            for i in range(per):
                el.log({"op": "t", "w": w, "i": i})

        threads = [threading.Thread(target=write, args=(w,))
                   for w in range(writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        el.close()
        seen = {w: [] for w in range(writers)}
        n = 0
        for op in FSEditLog.replay(str(tmp_path)):
            seen[op["w"]].append(op["i"])
            n += 1
        assert n == writers * per
        for w in range(writers):
            assert seen[w] == list(range(per))   # per-writer order kept
        assert el.records == writers * per
        assert 1 <= el.syncs <= el.records

    def test_slow_fsync_batches(self, tmp_path, monkeypatch):
        """With fsync made slow, concurrent appenders MUST coalesce:
        one leader's fsync covers the records appended while it was in
        flight, so syncs << records and the group histogram sees
        batches > 1."""
        from tpumr.metrics.histogram import Histogram
        real_fsync = os.fsync

        def slow_fsync(fd):
            time.sleep(0.01)
            real_fsync(fd)

        monkeypatch.setattr("tpumr.dfs.editlog.os.fsync", slow_fsync)
        el = FSEditLog(str(tmp_path))
        group = Histogram("nn_editlog_group_ops")
        el.bind_metrics(Histogram("a"), Histogram("s"), Histogram("b"),
                        group)
        writers, per = 6, 10

        def write(w):
            for i in range(per):
                el.log({"op": "t", "w": w, "i": i})

        threads = [threading.Thread(target=write, args=(w,))
                   for w in range(writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        el.close()
        assert el.records == writers * per
        assert el.syncs < el.records          # batching happened
        snap = group.snapshot()
        assert snap["count"] == el.syncs
        assert snap["max"] > 1                # some fsync covered many
        assert sum(1 for _ in FSEditLog.replay(str(tmp_path))) \
            == writers * per

    def test_failed_fsync_propagates_then_recovers(self, tmp_path,
                                                   monkeypatch):
        """A leader whose fsync fails must raise to ITS caller while
        followers retry as leaders — a failed sync never silently
        'covers' anyone."""
        real_fsync = os.fsync
        fail_once = {"armed": True}

        def flaky_fsync(fd):
            if fail_once["armed"]:
                fail_once["armed"] = False
                raise OSError("injected fsync failure")
            real_fsync(fd)

        el = FSEditLog(str(tmp_path))
        monkeypatch.setattr("tpumr.dfs.editlog.os.fsync", flaky_fsync)
        with pytest.raises(OSError):
            el.log({"op": "t", "i": 0})
        # the journal recovers: the next log() syncs for real and is
        # durable (the failed record was appended, so it is covered too)
        el.log({"op": "t", "i": 1})
        el.close()
        ops = list(FSEditLog.replay(str(tmp_path)))
        assert [op["i"] for op in ops] == [0, 1]


# ------------------------------------------------------------ stripe map


class TestNamespaceLocks:
    def test_stripe_map(self):
        locks = NamespaceLocks(stripes=8, depth=2)
        # shallower than the stripe depth: unstripable
        assert locks.stripe_index("/") is None
        assert locks.stripe_index("/user") is None
        # same depth-2 prefix -> same stripe; deterministic
        a = locks.stripe_index("/user/alice/out/part-0")
        assert a is not None
        assert locks.stripe_index("/user/alice/tmp") == a
        assert locks.stripe_index("/user/alice") == a
        # distinct prefixes spread over stripes (8 stripes, many users:
        # at least two distinct stripes must appear)
        idxs = {locks.stripe_index(f"/user/u{i}/f") for i in range(16)}
        assert len(idxs) > 1

    def test_striped_ctx_covers_and_structural_escalation(self):
        locks = NamespaceLocks(stripes=4, depth=2)
        with locks.for_paths("/user/alice/a", "/user/bob/b"):
            assert locks.covers("/user/alice/x")
            assert locks.covers("/user/bob/y")
            assert not locks.structural_held()
        # any shallow path escalates the whole op to structural
        with locks.for_paths("/user/alice/a", "/user"):
            assert locks.structural_held()
            assert locks.covers("/anything/at/all")


STRESS_WRITERS = 4
STRESS_ROUNDS = 12


class TestStripeStress:
    """Concurrent rename/delete churn racing reads across stripe
    boundaries on a live cluster: no lost updates, no deadlocks, and
    readers always see whole files. Run with TPUMR_LOCK_ORDER_CHECK=1
    to additionally assert the global->stripe->blocks acquisition
    order on every op."""

    def test_churn_across_stripes(self, tmp_path):
        conf = small_conf()
        payload = bytes(range(256)) * 8
        with MiniDFSCluster(num_datanodes=3, conf=conf) as cluster:
            seed = cluster.client()
            with seed.create("/bench/data/shared.bin") as f:
                f.write(payload)
            seed.mkdirs("/xdst")
            errors = []
            stop = threading.Event()

            def writer(w):
                cli = cluster.client()
                try:
                    home = f"/user/w{w}"
                    cli.mkdirs(home)
                    for i in range(STRESS_ROUNDS):
                        src = f"{home}/a_{i}"
                        with cli.create(src) as f:
                            f.write(b"x" * 512)
                        if i % 3 == 0:
                            # cross-stripe rename: /user/w* -> /xdst
                            assert cli.rename(src, f"/xdst/w{w}_{i}")
                        elif i % 3 == 1:
                            # same-stripe rename then delete
                            assert cli.rename(src, src + ".r")
                            assert cli.delete(src + ".r")
                        else:
                            assert cli.delete(src)
                except Exception as e:  # noqa: BLE001
                    errors.append(("writer", w, e))
                finally:
                    cli.close()

            def reader():
                cli = cluster.client()
                try:
                    while not stop.is_set():
                        with cli.open("/bench/data/shared.bin") as f:
                            assert f.read() == payload
                except Exception as e:  # noqa: BLE001
                    errors.append(("reader", 0, e))
                finally:
                    cli.close()

            def lister():
                cli = cluster.client()
                try:
                    while not stop.is_set():
                        # structural (shallow) ops racing striped ones
                        cli.list_status("/")
                        cli.list_status("/xdst")
                        cli.exists("/user")
                except Exception as e:  # noqa: BLE001
                    errors.append(("lister", 0, e))
                finally:
                    cli.close()

            threads = [threading.Thread(target=writer, args=(w,))
                       for w in range(STRESS_WRITERS)]
            aux = [threading.Thread(target=reader),
                   threading.Thread(target=lister)]
            for t in threads + aux:
                t.start()
            for t in threads:
                t.join(timeout=120)
            stop.set()
            for t in aux:
                t.join(timeout=30)
            # no deadlocks (every thread finished), no op failures
            assert not any(t.is_alive() for t in threads + aux)
            assert errors == []
            # no lost updates: exactly the cross-stripe renames
            # survive, everything else was deleted
            verify = cluster.client()
            try:
                names = {st["path"].rsplit("/", 1)[-1]
                         for st in verify.list_status("/xdst")}
                want = {f"w{w}_{i}" for w in range(STRESS_WRITERS)
                        for i in range(0, STRESS_ROUNDS, 3)}
                assert names == want
                for w in range(STRESS_WRITERS):
                    assert verify.list_status(f"/user/w{w}") == []
            finally:
                verify.close()


# ------------------------------------------------------------ hot blocks


class TestSpaceSavingDecay:
    def test_decay_halves_and_drops(self):
        sk = SpaceSaving(k=8)
        for _ in range(100):
            sk.offer("hot")
        sk.offer("cold")
        sk.decay(0.5)
        assert sk.estimate("hot") == 50
        assert sk.estimate("cold") == 0     # decayed below one count
        assert sk.total == pytest.approx(50.5)
        sk.decay(1.0)                       # no-op at factor >= 1
        assert sk.estimate("hot") == 50
        # fractional aging: repeated gentle decay must NOT collapse a
        # small count by a whole unit per round (the int-truncation
        # failure mode this sketch explicitly avoids)
        for _ in range(10):
            sk.decay(0.99)
        assert sk.estimate("hot") > 40

    def test_decay_to_empty(self):
        sk = SpaceSaving(k=4)
        sk.offer("a", by=3)
        sk.decay(0.0)
        assert len(sk) == 0 and sk.total == 0


def _hot_ns(tmp_path, **conf_kv):
    conf = small_conf()
    conf.set("tdfs.hotblocks.replicate.share", 0.2)
    conf.set("tdfs.hotblocks.replicate.min.reads", 10)
    conf.set("tdfs.hotblocks.replicate.cap", 3)
    conf.set("tdfs.hotblocks.cool.s", 0.2)
    for k, v in conf_kv.items():
        conf.set(k, v)
    ns = FSNamesystem(str(tmp_path / "name"), conf)
    dns = [f"127.0.0.1:{7001 + i}" for i in range(3)]
    for addr in dns:
        ns.register_datanode(addr, 1 << 30)
    return ns, dns


def _make_block(ns, path="/hot.bin", replication=2):
    ns.create(path, "cli", replication, 1024, True)
    meta = ns.add_block(path, "cli")
    bid = meta["block_id"]
    for addr in meta["targets"]:
        ns.block_received(addr, bid, 512)
    ns.complete(path, "cli", 512)
    return bid


def _fold_hot(ns, addr, bid, reads, total):
    ns.hot_blocks.fold(addr, {"total": total,
                              "top": [[str(bid), reads, 0]]})


class TestHotBlockPolicy:
    def test_boost_replicate_cooldown_cycle(self, tmp_path):
        """The full state machine: hot -> boosted -> extra replica
        scheduled -> cools -> boost expires -> extra replica trimmed."""
        ns, dns = _hot_ns(tmp_path)
        bid = _make_block(ns)
        assert len(ns.block_locations[bid]) == 2
        _fold_hot(ns, dns[0], bid, reads=40, total=50)   # share 0.8
        assert ns.hotblock_check() == 1
        assert ns.hot_boost[bid]["boost"] == 3
        # the ordinary replication sweep schedules the extra copy
        assert ns.replication_check() == 1
        cmds = [c for addr in dns for c in ns.commands.get(addr, [])
                if c.get("type") == "replicate"
                and c.get("block_id") == bid]
        assert len(cmds) == 1
        target = cmds[0]["targets"][0]
        ns.block_received(target, bid, 512)              # copy lands
        assert len(ns.block_locations[bid]) == 3
        # still hot: steady state, nothing more to schedule
        _fold_hot(ns, dns[0], bid, reads=40, total=50)
        ns.hotblock_check()
        assert ns.replication_check() == 0
        # cools: the sketch decays away, the boost expires after
        # cool.s, and the same sweep trims back to base replication
        _fold_hot(ns, dns[0], bid, reads=1, total=50)
        time.sleep(0.25)
        assert ns.hotblock_check() == 1                  # expiry
        assert bid not in ns.hot_boost
        assert ns.replication_check() >= 1               # the trim
        assert len(ns.block_locations[bid]) == 2

    def test_cap_respected_under_sustained_skew(self, tmp_path):
        """Sustained skew must not creep replicas past the cap: round
        after round of hot folds, the boost pins at the cap and the
        sweep schedules nothing once the cap-many replicas exist."""
        ns, dns = _hot_ns(tmp_path,
                          **{"tdfs.hotblocks.replicate.cap": 2})
        bid = _make_block(ns, replication=1)
        assert len(ns.block_locations[bid]) == 1
        for round_no in range(6):
            _fold_hot(ns, dns[0], bid, reads=90, total=100)
            ns.hotblock_check()
            assert ns.hot_boost[bid]["boost"] == 2       # never 3
            scheduled = ns.replication_check()
            for addr in dns:
                for c in ns.commands.get(addr, []):
                    if c.get("type") == "replicate" and \
                            c.get("block_id") == bid:
                        for t in c["targets"]:
                            ns.block_received(t, bid, 512)
                ns.commands[addr] = []
            if round_no == 0:
                assert scheduled == 1                    # 1 -> cap
            else:
                # cap-many replicas exist; sustained skew adds nothing
                assert scheduled == 0
                assert len(ns.block_locations[bid]) == 2
        assert len(ns.block_locations[bid]) == 2

    def test_min_reads_floor(self, tmp_path):
        """100%-share on a near-idle cluster is NOT hot: the absolute
        read floor keeps singleton blocks unboosted."""
        ns, dns = _hot_ns(tmp_path)
        bid = _make_block(ns)
        _fold_hot(ns, dns[0], bid, reads=5, total=5)     # share 1.0
        assert ns.hotblock_check() == 0
        assert bid not in ns.hot_boost

    def test_datanode_sketch_decays_per_heartbeat(self, tmp_path):
        """The DN applies the halflife decay before each heartbeat so
        the NN's view tracks the current mix (the cool-down driver)."""
        conf = small_conf()
        conf.set("tpumr.dn.hotblocks.halflife.s", 0.5)
        conf.set("tdfs.datanode.heartbeat.s", 0.1)
        conf.set("tdfs.http.port", -1)
        with MiniDFSCluster(num_datanodes=1, conf=conf) as cluster:
            cli = cluster.client()
            try:
                with cli.create("/d.bin") as f:
                    f.write(b"y" * 2048)
                for _ in range(20):
                    with cli.open("/d.bin") as f:
                        f.read()
            finally:
                cli.close()
            dn = cluster.datanodes[0]
            time.sleep(0.3)
            peak = sum(c[0] for c in dn._hot._counts.values())
            assert peak > 0
            # several half-lives with no reads: counts must fall
            time.sleep(1.5)
            later = sum(c[0] for c in dn._hot._counts.values())
            assert later < peak
