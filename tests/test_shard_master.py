"""Sharded master (perf tentpole PR: break the one-process ceiling).

Four legs:

- tracker→shard mapping is a pure, process-independent function (the
  fleet, the shards, and the coordinator must all agree without
  talking);
- heartbeat batching preserves the per-tracker replay cache inside a
  batch (a resent batch replays stored actions, never double-folds or
  double-assigns a member) and isolates member failures;
- the async history writer preserves ordering, read-your-writes (every
  reader flushes first), bounded-queue drop accounting, and
  synchronous fallback after stop();
- shard failover mirrors test_master_restart's acceptance e2e scoped
  to one shard: SIGKILL a shard mid-workload → the coordinator
  respawns it on its pinned port, its trackers are ADOPTED (not
  reinit), the job finishes with ZERO map re-executions — counters and
  history both asserted — while the sibling shard never notices.
"""

import os
import threading
import time

import pytest

from tpumr.ipc.rpc import RpcClient
from tpumr.mapred.history import JobHistory
from tpumr.mapred.ids import JobID
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.shardmaster import (ShardedMaster, make_master,
                                      tracker_shard)
from tpumr.scale.driver import ScaleDriver
from tpumr.scale.scenario import ScenarioError, plan, validate_spec
from tpumr.scale.simtracker import SimFleet, SimTracker
from tpumr.security import rpc_secret


# ------------------------------------------------------------ mapping


class TestTrackerShard:
    def test_deterministic_and_in_range(self):
        for n in (1, 2, 3, 7):
            for i in range(64):
                name = f"sim_{i:04d}"
                k = tracker_shard(name, n)
                assert 0 <= k < n
                assert k == tracker_shard(name, n), "must be stable"

    def test_spreads_the_fleet(self):
        counts = [0, 0]
        for i in range(64):
            counts[tracker_shard(f"sim_{i:04d}", 2)] += 1
        assert min(counts) >= 16, counts   # crc32, not hash(): balanced

    def test_fleet_endpoint_follows_the_map(self):
        fleet = SimFleet("127.0.0.1", 1, 8,
                         shard_map=[("127.0.0.1", 101),
                                    ("127.0.0.1", 102)])
        for i in range(8):
            name = f"sim_{i:04d}"
            host, port = fleet._endpoint(name)
            assert port == 101 + tracker_shard(name, 2)


# ------------------------------------------------------------ batching


def _master_conf(tmp_path, **over):
    conf = JobConf()
    conf.set("tpumr.history.dir", str(tmp_path / "history"))
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    for k, v in over.items():
        conf.set(k, v)
    return conf


class TestHeartbeatBatch:
    def test_resent_batch_replays_not_refolds(self, tmp_path):
        """The satellite's contract: a resent batch must not
        double-fold any member — each member rides the per-tracker
        replay cache exactly like a lone resent heartbeat."""
        master = JobMaster(_master_conf(tmp_path)).start()
        try:
            host, port = master.address
            tr = SimTracker("batcher_00", host, port)
            args = tr.heartbeat_build()
            assert args is not None
            tr.heartbeat_apply(master.heartbeat_batch([list(args)])[0])
            # second beat (initial contact is over — the replay cache
            # is armed now), delivered twice with the same response_id
            args = tr.heartbeat_build()
            first = master.heartbeat_batch([list(args)])
            again = master.heartbeat_batch([list(args)])
            assert first[0]["response_id"] == again[0]["response_id"]
            assert first[0]["actions"] == again[0]["actions"]
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap["heartbeat_batches"] == 3
            replay = snap.get(
                "heartbeat_phase_seconds|phase=replay", {})
            assert replay.get("count") == 1, \
                "second delivery must take the replay path"
            tr.heartbeat_abort()
            tr.close()
        finally:
            master.stop()

    def test_member_failures_are_isolated(self, tmp_path):
        master = JobMaster(_master_conf(tmp_path)).start()
        try:
            host, port = master.address
            tr = SimTracker("batcher_01", host, port)
            args = tr.heartbeat_build()
            out = master.heartbeat_batch(
                [["not-a-status", True, False, 0], list(args)])
            assert "error" in out[0]
            assert "response_id" in out[1], \
                "a bad member must not poison the rest of the batch"
            tr.heartbeat_abort()
            tr.close()
        finally:
            master.stop()

    def test_batched_fleet_drives_a_workload(self, tmp_path):
        conf = _master_conf(tmp_path)
        master = JobMaster(conf).start()
        fleet = None
        driver = None
        try:
            host, port = master.address
            fleet = SimFleet(host, port, 6, interval_s=0.05,
                             batch=4).start()
            driver = ScaleDriver(host, port)
            res = driver.run_workload(n_jobs=2, maps_per_job=4,
                                      reduces_per_job=1, timeout_s=30)
            assert len(res["succeeded"]) == 2, res
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap.get("heartbeat_batches", 0) > 0
            assert fleet.registry.snapshot().get("hb_errors", 0) == 0
        finally:
            if fleet is not None:
                fleet.stop()
            if driver is not None:
                driver.close()
            master.stop()


# ------------------------------------------------------------ history


class TestAsyncHistory:
    def _history(self, tmp_path, **over):
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        for k, v in over.items():
            conf.set(k, v)
        return JobHistory(conf)

    def test_readers_see_queued_writes(self, tmp_path):
        h = self._history(tmp_path)
        h.task_event("job_a_0001", "TASK_STARTED",
                     attempt_id="attempt_a_0001_m_000000_0")
        # read-your-writes: every reader flushes the queue first
        state = h.recovered_attempt_state("job_a_0001")
        assert state == {"maps": {}, "reduces": {}}
        assert h.queue_depth() == 0
        assert h.writes_dropped == 0
        h.stop()

    def test_per_file_order_is_enqueue_order(self, tmp_path):
        h = self._history(tmp_path)
        for i in range(50):
            h.task_event("job_b_0001", "E", seq=i)
        assert h.flush()
        events = h.read(os.path.join(str(tmp_path), "job_b_0001.jsonl"))
        assert [e["seq"] for e in events] == list(range(50))
        h.stop()

    def test_bounded_queue_drops_and_counts(self, tmp_path):
        h = self._history(tmp_path, **{"tpumr.history.queue.max": 8})
        gate = threading.Event()
        entered = threading.Event()
        real = h._write_now

        def slow(batch):
            entered.set()
            gate.wait(10.0)
            real(batch)

        h._write_now = slow
        h.task_event("job_c_0001", "E", seq=-1)   # writer picks this up
        assert entered.wait(5.0)
        for i in range(8 + 5):                   # fills queue, 5 dropped
            h.task_event("job_c_0001", "E", seq=i)
        assert h.writes_dropped == 5
        gate.set()
        assert h.flush()
        h.stop()
        events = h.read(os.path.join(str(tmp_path), "job_c_0001.jsonl"))
        assert len(events) == 1 + 8

    def test_post_stop_writes_fall_through_synchronously(self, tmp_path):
        h = self._history(tmp_path)
        h.stop()
        h.task_event("job_d_0001", "LATE")
        events = h.read(os.path.join(str(tmp_path), "job_d_0001.jsonl"))
        assert [e["event"] for e in events] == ["LATE"]

    def test_sync_mode_still_works(self, tmp_path):
        h = self._history(tmp_path, **{"tpumr.history.async": False})
        h.task_event("job_e_0001", "E")
        assert h.queue_depth() == 0
        events = h.read(os.path.join(str(tmp_path), "job_e_0001.jsonl"))
        assert len(events) == 1
        h.stop()


# ------------------------------------------------------------ spec/plan


class TestShardKillSpec:
    def _spec(self, **over):
        spec = {"name": "t", "seed": 7,
                "master": {"shards": 2},
                "classes": [{"name": "c", "jobs": 1, "maps": 1}],
                "chaos": [{"kind": "shard_kill", "at_ms": 100}]}
        spec.update(over)
        return spec

    def test_shard_kill_needs_shards(self):
        with pytest.raises(ScenarioError, match="master.shards"):
            validate_spec(self._spec(master={}))

    def test_shard_index_bounds(self):
        with pytest.raises(ScenarioError, match="shard index"):
            validate_spec(self._spec(
                chaos=[{"kind": "shard_kill", "at_ms": 1, "shard": 2}]))

    def test_master_restart_rejected_when_sharded(self):
        with pytest.raises(ScenarioError, match="shard_kill"):
            validate_spec(self._spec(
                chaos=[{"kind": "master_restart", "at_ms": 1}]))

    def test_plan_draws_victim_deterministically(self):
        a = [e for e in plan(self._spec()) if e["kind"] == "shard_kill"]
        b = [e for e in plan(self._spec()) if e["kind"] == "shard_kill"]
        assert a == b
        assert a[0]["shard"] in (0, 1)


# ------------------------------------------------------------ failover


def _sharded_conf(tmp_path, shards=2):
    conf = JobConf()
    conf.set("tpumr.history.dir", str(tmp_path / "history"))
    conf.set("tpumr.master.shards", shards)
    conf.set("tpumr.master.shards.poll.ms", 100)
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    return conf


class TestShardFailover:
    def test_kill_mid_workload_zero_map_reruns(self, tmp_path):
        """THE acceptance e2e, scoped to one shard: all of the victim
        job's maps folded, reduces gated behind slowstart=1.0, shard
        SIGKILLed → respawn on the pinned port, trackers adopted, job
        finishes under its recovered id with ZERO map re-executions on
        the respawned shard (counters + history both agree)."""
        conf = _sharded_conf(tmp_path)
        master = make_master(conf)
        assert isinstance(master, ShardedMaster)
        master.start()
        fleet = None
        driver = None
        try:
            host, port = master.address
            shard_map = master.shard_map()
            assert len(shard_map) == 2
            shard1_trackers = [i for i in range(8) if tracker_shard(
                f"sim_{i:04d}", 2) == 1]
            assert shard1_trackers, "fleet must put trackers on shard 1"
            fleet = SimFleet(host, port, 8, interval_s=0.05,
                             secret=rpc_secret(conf), batch=4,
                             shard_map=shard_map,
                             task_time_mean_s=0.05).start()
            driver = ScaleDriver(host, port, secret=rpc_secret(conf),
                                 timeout_s=10)
            # round-robin: job 0 → shard 0, job 1 → shard 1; the
            # cluster-id suffix in the job id proves the routing
            jids = driver.submit(
                2, 6, 1,
                **{"mapred.reduce.slowstart.completed.maps": 1.0})
            by_suffix = {JobID.parse(j).cluster[-2:]: j for j in jids}
            assert set(by_suffix) == {"s0", "s1"}
            victim = by_suffix["s1"]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st = driver.client.call("get_job_status", victim)
                if st["finished_maps"] >= 6:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("victim job's maps never finished")

            master.kill_shard(1)
            assert master.wait_shard_ready(1, 30.0)
            res = driver.wait(jids, timeout_s=60)
            # the driver polled the PRE-KILL id throughout; the
            # coordinator routes it via the merged alias table
            assert not res["failed"] and not res["unfinished"], res
            recovered = master.get_recovered_jobs()
            assert victim in recovered
            new_id = recovered[victim]

            # the respawned shard's OWN counters: adoption happened
            # there, and it launched zero maps
            snap = RpcClient(*shard_map[1],
                             secret=rpc_secret(conf)).call(
                "shard_snapshot")
            counters = snap["metrics"]["jobtracker"]["counters"]
            assert counters.get("jobs_recovered", 0) >= 1
            assert counters.get("trackers_adopted", 0) \
                >= len(shard1_trackers)
            assert counters.get("maps_launched_cpu", 0) == 0
            assert counters.get("maps_launched_tpu", 0) == 0
            # …and the shard's history agrees: no post-respawn map
            # TASK_STARTED under the recovered id
            hist = JobHistory(conf)
            events = hist.read(os.path.join(
                str(tmp_path / "history"), "shard-1",
                f"{new_id}.jsonl"))
            started_maps = [e for e in events
                            if e.get("event") == "TASK_STARTED"
                            and "_m_" in str(e.get("attempt_id", ""))]
            assert started_maps == []

            # the sibling shard never restarted
            stats = master.shard_stats()
            assert stats["0"]["restarts"] == 0
            assert stats["1"]["restarts"] == 1
            # the merged metrics carry the failover counters the
            # scenario report reads (wait out one coordinator poll)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                merged = master.metrics.snapshot()["jobtracker"]
                if merged.get("trackers_adopted", 0) \
                        >= len(shard1_trackers):
                    break
                time.sleep(0.05)
            assert merged.get("shard_restarts", 0) == 1
            assert merged.get("trackers_adopted", 0) \
                >= len(shard1_trackers)
        finally:
            if fleet is not None:
                fleet.stop()
            if driver is not None:
                driver.close()
            master.stop()

    def test_submissions_survive_a_dead_shard(self, tmp_path):
        """Round-robin submission fails over to a live shard while the
        victim is down — the client surface degrades, never breaks."""
        conf = _sharded_conf(tmp_path)
        master = ShardedMaster(conf).start()
        driver = None
        try:
            driver = ScaleDriver(*master.address,
                                 secret=rpc_secret(conf), timeout_s=10)
            master.kill_shard(0)
            jids = driver.submit(2, 1, 0)
            assert len(jids) == 2
            assert master.wait_shard_ready(0, 30.0)
        finally:
            if driver is not None:
                driver.close()
            master.stop()
