"""Security-lite (UGI + HMAC RPC auth ≈ security/, SaslRpcServer) and rack
topology (≈ net/NetworkTopology) — SURVEY.md §2.2."""

import pytest

from tpumr.ipc.rpc import RpcClient, RpcError, RpcServer
from tpumr.mapred.jobconf import JobConf
from tpumr.net.topology import (DEFAULT_RACK, NetworkTopology,
                                resolver_from_conf, static_resolver)
from tpumr.security import UserGroupInformation, rpc_secret


class Echo:
    def ping(self, x):
        return x

    def get_protocol_version(self):
        return 9


class TestRpcAuth:
    def test_signed_calls_work(self):
        srv = RpcServer(Echo(), secret=b"s3cret").start()
        try:
            cli = RpcClient(*srv.address, secret=b"s3cret")
            assert cli.call("ping", 42) == 42
        finally:
            srv.stop()

    def test_unsigned_and_wrong_secret_rejected(self):
        srv = RpcServer(Echo(), secret=b"s3cret").start()
        try:
            unsigned = RpcClient(*srv.address)
            with pytest.raises(RpcError, match="not signed"):
                unsigned.call("ping", 1)
            wrong = RpcClient(*srv.address, secret=b"nope")
            with pytest.raises(RpcError, match="not signed"):
                wrong.call("ping", 1)
        finally:
            srv.stop()

    def test_no_secret_means_open(self):
        srv = RpcServer(Echo()).start()
        try:
            assert RpcClient(*srv.address).call("ping", 7) == 7
        finally:
            srv.stop()

    def test_captured_frame_cannot_be_replayed_elsewhere(self):
        """A signed frame is bound to its connection by the server's hello
        nonce: replaying it to a sibling daemon, or to the same daemon over
        a new connection, must fail (≈ DIGEST SASL challenge semantics)."""
        import socket
        import time

        from tpumr.ipc import rpc as R

        a = RpcServer(Echo(), secret=b"s3cret").start()
        b = RpcServer(Echo(), secret=b"s3cret").start()
        socks = []
        try:
            sa = socket.create_connection(a.address)
            socks.append(sa)
            hello = R._recv_frame(sa)
            req = {"id": 1, "cid": "observed-cid", "method": "ping",
                   "params": [41], "ts": time.time()}
            req["auth"] = R._sign(b"s3cret", req, a.port, hello["nonce"])
            R._send_frame(sa, req)
            assert R._recv_frame(sa).get("result") == 41
            # replay verbatim to sibling daemon B
            sb = socket.create_connection(b.address)
            socks.append(sb)
            R._recv_frame(sb)  # B's hello — different nonce
            R._send_frame(sb, req)
            assert "RpcAuthError" in R._recv_frame(sb).get("error", "")
            # replay verbatim to A itself over a fresh connection
            sa2 = socket.create_connection(a.address)
            socks.append(sa2)
            R._recv_frame(sa2)
            R._send_frame(sa2, req)
            assert "RpcAuthError" in R._recv_frame(sa2).get("error", "")
        finally:
            for s in socks:
                s.close()
            a.stop()
            b.stop()

    def test_secured_mini_cluster_runs_job(self):
        from tpumr.fs import get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "cluster-shared-secret")
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/sec/in.txt", b"k l k\n" * 20)
            jc = c.create_job_conf()
            jc.set_input_paths("mem:///sec/in.txt")
            jc.set_output_path("mem:///sec/out")
            from tpumr.ops.wordcount import WordCountCpuMapper
            from tpumr.examples.basic import LongSumReducer
            jc.set_class("mapred.mapper.class", WordCountCpuMapper)
            jc.set_class("mapred.reducer.class", LongSumReducer)
            assert JobClient(jc).run_job(jc).successful
            # an unauthenticated client is refused
            host, port = c.master.address
            with pytest.raises(RpcError, match="not signed"):
                RpcClient(host, port).call("list_jobs")

    def test_token_scoped_callers(self):
        """Per-scope token auth (≈ JobTokenSecretManager): a scoped caller
        signs with its token, may only call allowlisted methods, and an
        unknown/wrong token is rejected."""
        srv = RpcServer(Echo(), secret=b"cluster").start()
        srv.token_resolver = {"job_1": b"tok-1"}.get
        srv.scoped_methods = {"ping"}
        try:
            ok = RpcClient(*srv.address, secret=b"tok-1", scope="job_1")
            assert ok.call("ping", 5) == 5
            wrong_key = RpcClient(*srv.address, secret=b"tok-2",
                                  scope="job_1")
            with pytest.raises(RpcError, match="not signed"):
                wrong_key.call("ping", 1)
            # unknown scope: SAME error as a bad signature (no oracle
            # for which job ids exist)
            unknown = RpcClient(*srv.address, secret=b"tok-9",
                                scope="job_9")
            with pytest.raises(RpcError, match="not signed"):
                unknown.call("ping", 1)
            # the cluster secret cannot be used AS a token scope signer
            cluster_as_scope = RpcClient(*srv.address, secret=b"cluster",
                                         scope="job_1")
            with pytest.raises(RpcError, match="not signed"):
                cluster_as_scope.call("ping", 1)
        finally:
            srv.stop()

    def test_token_scoped_method_allowlist(self):
        srv = RpcServer(Echo(), secret=b"cluster").start()
        srv.token_resolver = {"job_1": b"tok-1"}.get
        srv.scoped_methods = {"ping"}
        try:
            scoped = RpcClient(*srv.address, secret=b"tok-1", scope="job_1")
            with pytest.raises(RpcError,
                               match="not available to token-scoped"):
                scoped.call("get_protocol_version")
            # daemons (cluster secret, no scope) are unrestricted
            daemon = RpcClient(*srv.address, secret=b"cluster")
            assert daemon.call("get_protocol_version") == 9

        finally:
            srv.stop()

    def test_job_token_cannot_cross_jobs(self):
        """A tracker serving two jobs' outputs refuses a job-A-token
        fetch of job B's map output, and the master refuses token-scoped
        frames entirely."""
        from tpumr.fs import get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster
        conf = JobConf()
        conf.set("tpumr.rpc.secret", "cluster-shared-secret")
        with MiniMRCluster(num_trackers=1, cpu_slots=2, tpu_slots=0,
                           conf=conf) as c:
            fs = get_filesystem("mem:///")
            fs.write_bytes("/jt2/in.txt", b"a b\n" * 10)
            job_ids = []
            for i in range(2):
                jc = c.create_job_conf()
                jc.set_input_paths("mem:///jt2/in.txt")
                jc.set_output_path(f"mem:///jt2/out{i}")
                from tpumr.examples.basic import LongSumReducer
                from tpumr.ops.wordcount import WordCountCpuMapper
                jc.set_class("mapred.mapper.class", WordCountCpuMapper)
                jc.set_class("mapred.reducer.class", LongSumReducer)
                res = JobClient(jc).run_job(jc)
                assert res.successful
                job_ids.append(str(res.job_id))
            tracker = c.trackers[0]
            tok_a = tracker._job_token(job_ids[0])
            assert tok_a and tok_a != b"cluster-shared-secret"
            host, port = "127.0.0.1", tracker.shuffle_port
            scoped = RpcClient(host, port, secret=tok_a, scope=job_ids[0])
            # own job: served (or a clean KeyError if already purged)
            try:
                out = scoped.call("get_map_output", job_ids[0], 0, 0)
                assert "data" in out
            except RpcError as e:
                assert "KeyError" in str(e)
            # other job: denied by scope pinning, never a data response
            with pytest.raises(RpcError, match="cannot access job"):
                scoped.call("get_map_output", job_ids[1], 0, 0)
            # non-allowlisted tracker surface: denied
            with pytest.raises(RpcError, match="not available"):
                scoped.call("list_task_logs")
            # the master rejects token-scoped frames outright (no
            # resolver — indistinguishable from a bad signature)
            mh, mp = c.master.address
            with pytest.raises(RpcError, match="not signed"):
                RpcClient(mh, mp, secret=tok_a,
                          scope=job_ids[0]).call("list_jobs")
            # forged attempt/job binding: job A's token cannot settle an
            # attempt labeled with job A but belonging to job B
            scoped_a = RpcClient(host, port, secret=tok_a,
                                 scope=job_ids[0])
            bogus_attempt = job_ids[1].replace("job_", "attempt_") + \
                "_m_000000_0"
            with pytest.raises(RpcError, match="does not belong"):
                scoped_a.call("umbilical_done", bogus_attempt,
                              {"state": "SUCCEEDED"}, job_ids[0], 0, "", {})
            # same forged binding on the commit-grant proxy: task_id must
            # be the attempt's OWN task, or a caller could seed another
            # task's commit grant (master setdefaults to first claimant)
            # with an attempt that never fails — permanent commit DoS
            bogus_task = job_ids[1].replace("job_", "task_") + "_r_000000"
            own_attempt = (job_ids[0].replace("job_", "attempt_")
                           + "_r_000000_0")
            with pytest.raises(RpcError, match="does not belong"):
                scoped_a.call("umbilical_can_commit", bogus_task,
                              own_attempt)
            # sibling task of the SAME job: also rejected
            sibling_task = job_ids[0].replace("job_", "task_") + "_m_000007"
            with pytest.raises(RpcError, match="does not belong"):
                scoped_a.call("umbilical_can_commit", sibling_task,
                              own_attempt)

    def test_secret_file(self, tmp_path):
        p = tmp_path / "secret"
        p.write_text("filesecret\n")
        conf = JobConf()
        conf.set("tpumr.rpc.secret.file", str(p))
        assert rpc_secret(conf) == b"filesecret"
        assert rpc_secret(JobConf()) is None


class TestUgi:
    def test_current_user_and_do_as(self):
        me = UserGroupInformation.get_current_user()
        assert me.user
        with UserGroupInformation("erin").do_as():
            assert UserGroupInformation.get_current_user().user == "erin"
        assert UserGroupInformation.get_current_user().user == me.user

    def test_job_conf_stamps_user(self):
        from tpumr.mapred.job_client import _wire_conf
        conf = JobConf()
        wired = _wire_conf(conf)
        assert wired["user.name"]


class TestTopology:
    def test_static_resolver_and_ports(self):
        r = static_resolver({"h1": "/r1", "h2": "/r2"})
        assert r("h1") == "/r1"
        assert r("h1:8020") == "/r1"
        assert r("unknown") == DEFAULT_RACK

    def test_resolver_from_conf(self):
        conf = JobConf()
        conf.set("tpumr.topology.map", "a=/ra, b=/rb")
        r = resolver_from_conf(conf)
        assert r("a") == "/ra" and r("b") == "/rb"

    def test_script_resolver(self, tmp_path):
        script = tmp_path / "rack.sh"
        script.write_text("#!/bin/sh\necho /scripted-rack\n")
        script.chmod(0o755)
        conf = JobConf()
        conf.set("topology.script.file.name", str(script))
        r = resolver_from_conf(conf)
        assert r("anyhost") == "/scripted-rack"

    def test_network_topology(self):
        t = NetworkTopology(static_resolver({"a": "/r1", "b": "/r1",
                                             "c": "/r2"}))
        for h in "abc":
            t.add(h)
        assert t.on_same_rack("a", "b") and not t.on_same_rack("a", "c")
        assert t.racks() == {"/r1": ["a", "b"], "/r2": ["c"]}


class TestRackAwarePlacement:
    def test_second_replica_off_rack(self):
        from tpumr.dfs.namenode import FSNamesystem
        conf = JobConf()
        # distinct fake hosts exercise the rack split
        conf.set("tpumr.topology.map",
                 "dn1=/r1,dn2=/r1,dn3=/r2")
        import tempfile
        ns = FSNamesystem(tempfile.mkdtemp(), conf)
        for addr, used in (("dn1:1", 0), ("dn2:1", 10), ("dn3:1", 20)):
            ns.register_datanode(addr, 1 << 30)
            ns.datanodes[addr]["used"] = used
        targets = ns._choose_targets(2, set())
        assert targets[0] == "dn1:1"          # least used
        assert targets[1] == "dn3:1", \
            "second replica must land on a different rack"
        # with 3 replicas everyone gets one
        assert set(ns._choose_targets(3, set())) == \
            {"dn1:1", "dn2:1", "dn3:1"}

    def test_scheduler_prefers_rack_local(self):
        from tpumr.mapred.ids import JobID
        from tpumr.mapred.job_in_progress import JobInProgress
        conf = {"mapred.reduce.tasks": 0,
                "tpumr.topology.map": "h1=/r1,h2=/r1,h9=/r9",
                "mapred.reduce.slowstart.completed.maps": 0.0}
        splits = [{"locations": ["h9"]},   # off-rack split
                  {"locations": ["h1"]}]   # rack-local to h2
        job = JobInProgress(JobID("topo", 1), conf, splits)
        # h2 has no node-local split; rack tier must pick split 1 (h1,
        # same /r1 rack), not split 0
        t = job.obtain_new_map_task("h2", run_on_tpu=False)
        assert t.partition == 1
