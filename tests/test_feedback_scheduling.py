"""Feedback-driven scheduling: the per-TIP remaining-work model,
LATE-style targeted speculation (estimated-finish stragglers on the
critical path, capped), devcache-affinity placement, and size-aware
shuffle fetch ordering. The mini-cluster e2e at the bottom injects a
``task.slow`` straggler and proves the master twins EXACTLY it, with
byte-correct output."""

import io
import os
import threading
import time

import pytest

from tpumr.io import ifile
from tpumr.mapred.ids import JobID
from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.task import TaskState, TaskStatus
from tpumr.utils import fi

FI_SEED = os.environ.get("TPUMR_FI_SEED", "20260804")


def _job(n_maps=2, **conf):
    base = {"mapred.reduce.tasks": 0,
            "mapred.speculative.execution": True,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    base.update(conf)
    splits = [{"locations": []} for _ in range(n_maps)]
    return JobInProgress(JobID("fb", 1), splits=splits, conf_dict=base)


def _finish(job, task, runtime=1.0, is_map=True):
    now = time.time()
    job.update_task_status(TaskStatus(
        attempt_id=task.attempt_id, is_map=is_map,
        state=TaskState.SUCCEEDED, start_time=now - runtime,
        finish_time=now), "t:0")


def _running(job, task, progress):
    job.update_task_status(TaskStatus(
        attempt_id=task.attempt_id, is_map=True,
        state=TaskState.RUNNING, progress=progress), "t:0")


# ------------------------------------------------- remaining-work model


class TestRemainingWorkModel:
    def test_progress_folds_into_rate_ewma(self):
        job = _job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        tip = job.maps[t.partition]
        _running(job, t, 0.2)
        time.sleep(0.02)
        _running(job, t, 0.6)
        assert tip.rate_ewma > 0.0
        assert tip.last_progress == 0.6
        ewma = tip.rate_ewma
        # a beat with no advance must not move the anchor or the rate
        _running(job, t, 0.5)
        assert tip.last_progress == 0.6 and tip.rate_ewma == ewma

    def test_remaining_estimate_prefers_rate(self):
        job = _job(n_maps=1)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        tip = job.maps[t.partition]
        now = time.monotonic()
        tip.rate_ewma, tip.last_progress = 0.1, 0.5
        assert job._tip_remaining_s(tip, now, 99.0) == pytest.approx(5.0)
        # no EWMA yet: elapsed-proportional fallback
        tip.rate_ewma = 0.0
        tip.last_progress = 0.25
        tip.dispatch_mono = now - 30.0
        assert job._tip_remaining_s(tip, now, 99.0) == pytest.approx(
            90.0, rel=0.01)
        # silent tip: a full mean runtime — stalls must look LONG
        tip.last_progress = 0.0
        assert job._tip_remaining_s(tip, now, 7.0) == 7.0

    def test_critical_path_and_longest_path(self):
        job = _job(n_maps=3)
        t0 = job.obtain_new_map_task("h", run_on_tpu=False)
        t1 = job.obtain_new_map_task("h", run_on_tpu=False)
        t2 = job.obtain_new_map_task("h", run_on_tpu=False)
        fast, slow, mid = (job.maps[t.partition] for t in (t0, t1, t2))
        fast.rate_ewma, fast.last_progress = 1.0, 0.9    # ~0.1s left
        slow.rate_ewma, slow.last_progress = 0.01, 0.1   # ~90s left
        mid.rate_ewma, mid.last_progress = 0.01, 0.2     # ~80s left
        cp = job.critical_path_maps()
        assert slow.partition in cp and mid.partition in cp
        assert fast.partition not in cp
        est = job.map_remaining_estimates()
        assert len(est) == 3
        assert job.longest_remaining_path_s() == pytest.approx(
            est[slow.partition], rel=0.05)
        sd = job.status_dict()
        assert sd["longest_remaining_path_s"] > 0
        assert sd["speculative_in_flight"] == 0


# ------------------------------------------------- targeted speculation


class TestTargetedSpeculation:
    def test_targets_the_critical_straggler_not_the_nearly_done(self):
        """Two old running maps: one nearly done, one silent. Blanket
        would twin both; targeted twins ONLY the critical-path one."""
        job = _job(n_maps=3, **{"tpumr.speculative.cap": 1})
        t0 = job.obtain_new_map_task("h", run_on_tpu=False)
        near = job.obtain_new_map_task("h", run_on_tpu=False)
        stuck = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish(job, t0, runtime=1.0)
        for t in (near, stuck):
            job.maps[t.partition].dispatch_mono = time.monotonic() - 100
        # nearly done: high rate, high progress -> tiny remaining
        job.maps[near.partition].rate_ewma = 1.0
        job.maps[near.partition].last_progress = 0.99
        spec = job.obtain_new_map_task("h", run_on_tpu=False)
        assert spec is not None and spec.partition == stuck.partition
        assert job.speculative_launched == 1
        assert job.speculative_in_flight() == 1
        # cap=1: the nearly-done tip can't twin even if it qualified
        assert job.obtain_new_map_task("h", run_on_tpu=False) is None
        # the twin wins; the original's kill settles nothing extra
        _finish(job, spec, runtime=0.01)
        assert job.should_kill_attempt(str(stuck.attempt_id))
        assert job.speculative_won == 1 and job.speculative_wasted == 0
        assert job.speculative_in_flight() == 0

    def test_young_task_never_speculated(self):
        """Counter-case: all maps dispatched moments ago — under the
        min-runtime floor nothing twins, targeted or blanket."""
        for targeted in (True, False):
            job = _job(n_maps=2,
                       **{"tpumr.speculative.targeted": targeted})
            a = job.obtain_new_map_task("h", run_on_tpu=False)
            job.obtain_new_map_task("h", run_on_tpu=False)
            _finish(job, a, runtime=0.01)
            assert job.obtain_new_map_task("h", run_on_tpu=False) is None
            assert job.speculative_launched == 0

    def test_within_distribution_estimate_not_speculated(self):
        """A task whose ESTIMATED FINISH sits inside the completed-
        runtime distribution is left alone even past the age floor —
        the case blanket speculation gets wrong."""
        job = _job(n_maps=2,
                   **{"mapred.speculative.min.runtime.s": 0.0})
        a = job.obtain_new_map_task("h", run_on_tpu=False)
        b = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish(job, a, runtime=5.0)          # mean = 5s
        tip = job.maps[b.partition]
        tip.dispatch_mono = time.monotonic() - 1.0   # 1s old
        tip.rate_ewma, tip.last_progress = 1.0, 0.8  # ~0.2s remaining
        # est finish 1.2s << 1.5 * 5s: no twin
        assert job.obtain_new_map_task("h", run_on_tpu=False) is None
        assert job.speculative_launched == 0

    def test_cap_bounds_concurrent_twins_blanket_does_not(self):
        def straggling_job(**extra):
            job = _job(n_maps=3, **extra)
            t0 = job.obtain_new_map_task("h", run_on_tpu=False)
            s1 = job.obtain_new_map_task("h", run_on_tpu=False)
            s2 = job.obtain_new_map_task("h", run_on_tpu=False)
            _finish(job, t0, runtime=0.5)
            for t in (s1, s2):
                job.maps[t.partition].dispatch_mono = \
                    time.monotonic() - 100
            return job

        capped = straggling_job(**{"tpumr.speculative.cap": 1})
        assert capped.obtain_new_map_task("h", run_on_tpu=False) \
            is not None
        assert capped.obtain_new_map_task("h", run_on_tpu=False) is None
        assert capped.speculative_launched == 1

        blanket = straggling_job(**{"tpumr.speculative.targeted": False})
        assert blanket.obtain_new_map_task("h", run_on_tpu=False) \
            is not None
        assert blanket.obtain_new_map_task("h", run_on_tpu=False) \
            is not None
        assert blanket.speculative_launched == 2

    def test_wasted_twin_counted(self):
        job = _job(n_maps=2)
        t0 = job.obtain_new_map_task("h", run_on_tpu=False)
        slow = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish(job, t0, runtime=0.01)
        job.maps[slow.partition].dispatch_mono = time.monotonic() - 100
        spec = job.obtain_new_map_task("h", run_on_tpu=False)
        assert spec is not None
        # the ORIGINAL finishes first: the twin was wasted work
        _finish(job, slow, runtime=0.01)
        assert job.should_kill_attempt(str(spec.attempt_id))
        now = time.time()
        job.update_task_status(TaskStatus(
            attempt_id=spec.attempt_id, is_map=True,
            state=TaskState.KILLED, start_time=now, finish_time=now),
            "t:0")
        assert job.speculative_wasted == 1 and job.speculative_won == 0
        assert job.speculative_in_flight() == 0


# ---------------------------------------------- devcache-affinity placement


class _FakeManager:
    def __init__(self, index=None):
        self._index = index

    def devcache_tag_index(self):
        if self._index is None:
            raise AssertionError("index must not be consulted")
        return self._index


class _FakeJob:
    def __init__(self, jid, tags):
        self.job_id = jid
        self._tags = tuple(tags)

    def devcache_tags(self):
        return self._tags


def _affinity_sched(manager, **conf_kv):
    from tpumr.mapred.scheduler import HybridQueueScheduler
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched = HybridQueueScheduler()
    sched.conf = conf
    sched.manager = manager
    return sched


class TestDevcacheAffinity:
    TAG = "kmeans-centroids:mem:///c.npy"

    def test_warm_tracker_assigns_immediately(self):
        sched = _affinity_sched(_FakeManager({self.TAG: {"t1"}}))
        sched._begin_affinity({"devcache_tags": [self.TAG]})
        job = _FakeJob("job_a_1", [self.TAG])
        assert sched._affinity_defer(job) is False

    def test_cold_tracker_defers_until_budget_then_places(self):
        sched = _affinity_sched(
            _FakeManager({self.TAG: {"warm-tracker"}}),
            **{"tpumr.scheduler.affinity.defer.passes": 2})
        job = _FakeJob("job_a_1", [self.TAG])
        for _ in range(2):
            sched._begin_affinity({"devcache_tags": []})
            assert sched._affinity_defer(job) is True
        # budget spent: place cold rather than starve
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(job) is False
        # ...and the budget stays pinned on later beats
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(job) is False

    def test_budget_forgiven_on_warm_hit(self):
        sched = _affinity_sched(_FakeManager({self.TAG: {"w"}}))
        job = _FakeJob("job_a_1", [self.TAG])
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(job) is True
        sched._begin_affinity({"devcache_tags": [self.TAG]})
        assert sched._affinity_defer(job) is False
        assert job.job_id not in sched._affinity_defers

    def test_nobody_warm_anywhere_places_cold(self):
        sched = _affinity_sched(_FakeManager({}))
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(
            _FakeJob("job_a_1", [self.TAG])) is False

    def test_absent_index_and_absent_tags_are_inert(self):
        # manager without the devcache_tag_index seam: never deferred
        class Bare:
            pass
        sched = _affinity_sched(Bare())
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(
            _FakeJob("job_a_1", [self.TAG])) is False
        # a job with no side-input tags: never deferred (index unused)
        sched2 = _affinity_sched(_FakeManager({self.TAG: {"w"}}))
        sched2._begin_affinity({"devcache_tags": []})
        assert sched2._affinity_defer(_FakeJob("job_b_1", [])) is False

    def test_disabled_by_conf(self):
        sched = _affinity_sched(
            _FakeManager(None),  # raises if the index is consulted
            **{"tpumr.scheduler.affinity": False})
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(
            _FakeJob("job_a_1", [self.TAG])) is False

    def test_decision_memoized_per_beat(self):
        sched = _affinity_sched(_FakeManager({self.TAG: {"w"}}))
        job = _FakeJob("job_a_1", [self.TAG])
        sched._begin_affinity({"devcache_tags": []})
        assert sched._affinity_defer(job) is True
        # per-slot repeats in the same beat charge the budget ONCE
        assert sched._affinity_defer(job) is True
        assert sched._affinity_defers[job.job_id] == 1

    def test_job_devcache_tags_derived_and_explicit(self):
        derived = _job(n_maps=1, **{
            "tpumr.kmeans.centroids": "mem:///c.npy"})
        assert derived.devcache_tags() == (
            "kmeans-centroids:mem:///c.npy",)
        explicit = _job(n_maps=1, **{
            "tpumr.devcache.required.tags": "a:1, b:2",
            "tpumr.kmeans.centroids": "mem:///ignored.npy"})
        assert explicit.devcache_tags() == ("a:1", "b:2")
        assert _job(n_maps=1).devcache_tags() == ()


# --------------------------------------------------- size-aware fetching


def _make_spill(records, codec="zlib"):
    buf = io.BytesIO()
    w = ifile.Writer(buf, codec=codec)
    w.start_partition()
    for k, v in records:
        w.append_raw(k, v)
    w.end_partition()
    return buf.getvalue(), w.close()


class _SizedSource:
    """ChunkFetch fake advertising per-map output sizes, recording the
    order maps were first fetched in."""

    def __init__(self, spills, sizes):
        self.spills = spills
        self.sizes = sizes
        self.order = []
        self._lock = threading.Lock()

    def size_of(self, map_index):
        return self.sizes[map_index]

    def __call__(self, map_index, partition, offset):
        with self._lock:
            if map_index not in self.order:
                self.order.append(map_index)
        data, index = self.spills[map_index]
        off, raw_len, part_len = index["partitions"][partition]
        payload = data[off + 4: off + part_len]
        return {"data": payload[offset:], "total": len(payload),
                "raw": raw_len, "codec": index.get("codec", "none")}


class TestSizeAwareFetchOrder:
    def _run(self, conf, sizes):
        from tpumr.mapred.shuffle_copier import ShuffleCopier
        spills = [_make_spill([(b"k%d" % i, b"v")]) for i in range(4)]
        src = _SizedSource(spills, sizes)
        conf.set("tpumr.shuffle.parallel.copies", 1)
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            segs = ShuffleCopier(conf, src, 4, 0, d).copy_all()
        assert len(segs) == 4
        return src.order

    def test_largest_advertised_output_fetched_first(self):
        order = self._run(JobConf(), {0: 10, 1: 4000, 2: 50, 3: 900})
        assert order == [1, 3, 2, 0]

    def test_priority_disabled_keeps_seed_order(self):
        conf = JobConf()
        conf.set("tpumr.shuffle.size.priority", False)
        order = self._run(conf, {0: 10, 1: 4000, 2: 50, 3: 900})
        assert order == [0, 1, 2, 3]

    def test_unknown_sizes_sort_last_not_blocked(self):
        order = self._run(JobConf(), {0: 0, 1: 500, 2: 0, 3: 900})
        assert order[:2] == [3, 1]
        assert set(order[2:]) == {0, 2}

    def test_locator_size_of_from_completion_events(self):
        from tpumr.mapred.tasktracker import MapLocator
        events = [
            {"map_index": 0, "attempt_id": "a0", "status": "SUCCEEDED",
             "shuffle_addr": "h:1", "output_bytes": 1234},
            {"map_index": 1, "attempt_id": "a1", "status": "SUCCEEDED",
             "shuffle_addr": "h:1"},          # pre-size-field event
        ]
        loc = MapLocator(lambda cursor: events[cursor:], secret=None)
        loc.resolve(0)
        assert loc.size_of(0) == 1234
        assert loc.size_of(1) == 0            # unknown: advisory zero
        assert loc.size_of(7) == 0            # never completed
        loc.close()

    def test_status_output_bytes_rides_the_wire(self):
        from tpumr.mapred.ids import TaskAttemptID
        st = TaskStatus(
            attempt_id=TaskAttemptID.parse(
                "attempt_fb_0001_m_000000_1"),
            output_bytes=4096)
        assert TaskStatus.from_dict(st.to_dict()).output_bytes == 4096


# ------------------------------------------------- devcache observability


class TestDevcacheInventory:
    def test_inventory_and_occupancy_shapes(self):
        from tpumr.mapred.tpu_runner import HbmSplitCache
        from tpumr.ops import devcache
        cache = HbmSplitCache(1 << 20)
        cache.put(("kmeans-centroids:mem:///c", "dev0"), object(), 100)
        cache.put(("kmeans-centroids:mem:///c", "dev1"), object(), 100)
        cache.put(("matmul-b:mem:///b", "dev0"), object(), 5000)
        old = devcache._cache
        devcache._cache = cache
        try:
            inv = devcache.inventory()
            assert inv == {"kmeans-centroids:mem:///c": 200,
                           "matmul-b:mem:///b": 5000}
            # the bound keeps the MOST RECENTLY USED tags
            assert list(devcache.inventory(max_tags=1)) == \
                ["matmul-b:mem:///b"]
            occ = devcache.occupancy()
            assert occ["entries"] == 3 and occ["bytes"] == 5200
            assert occ["families"] == {"kmeans-centroids": 200,
                                       "matmul-b": 5000}
        finally:
            devcache._cache = old

    def test_empty_before_first_use(self):
        from tpumr.ops import devcache
        old = devcache._cache
        devcache._cache = None
        try:
            assert devcache.inventory() == {}
            assert devcache.occupancy() == {"entries": 0, "bytes": 0,
                                            "families": {}}
        finally:
            devcache._cache = old


# ------------------------------------------------------ straggler e2e


def _write_input(fs, path, n=2000):
    fs.write_bytes(path, b"".join(b"w%02d x\n" % (i % 23)
                                  for i in range(n)))


def _output_bytes(fs, out_dir):
    return b"".join(fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(out_dir),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))


class TestEndToEndTargetedSpeculation:
    def test_slow_map_gets_exactly_one_targeted_twin(self):
        """Acceptance: a ``task.slow``-injected straggler map is the
        ONLY tip twinned; the twin wins well before the straggler's
        crawl would end; output is byte-correct."""
        fi.reset()
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.mini_cluster import MiniMRCluster
        from tpumr.mapred.job_client import JobClient
        base = JobConf()
        base.set("tpumr.heartbeat.interval.ms", 100)
        base.set("tpumr.fi.seed", FI_SEED)
        try:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/straggle/in.txt")
            with MiniMRCluster(num_trackers=2, conf=base, cpu_slots=2,
                               tpu_slots=0) as c:
                conf = c.create_job_conf()
                conf.set_input_paths("mem:///straggle/in.txt")
                conf.set_output_path("mem:///straggle/out")
                conf.set("mapred.mapper.class",
                         "tpumr.mapred.lib.TokenCountMapper")
                conf.set("mapred.reducer.class",
                         "tpumr.examples.basic.LongSumReducer")
                conf.set("mapred.map.tasks", 4)
                conf.set_num_reduce_tasks(1)
                # map 0 crawls for 8s unless a twin rescues the job
                conf.set("tpumr.fi.task.slow.m0.probability", 1.0)
                conf.set("tpumr.fi.task.slow.m0.max.failures", 1)
                conf.set("tpumr.fi.task.slow.ms", 8000)
                conf.set("mapred.speculative.min.runtime.s", 0.3)
                t0 = time.monotonic()
                result = JobClient(conf).run_job(conf)
                wall = time.monotonic() - t0
                assert result.successful
                counts = dict(
                    line.split(b"\t") for line in
                    _output_bytes(fs, "/straggle/out").splitlines())
                assert counts[b"x"] == b"2000"
                assert fi.fired("task.slow.m0") == 1

                jip = c.master.jobs[str(result.job_id)]
                # EXACTLY the straggler was twinned, nothing else
                assert jip.maps[0].next_attempt == 2
                assert all(t.next_attempt == 1 for t in jip.maps[1:])
                assert jip.speculative_launched == 1
                assert jip.speculative_won == 1
                assert jip.speculative_wasted == 0
                assert jip.speculative_in_flight() == 0
                sd = jip.status_dict()
                assert sd["speculative_launched"] == 1
                # the twin beat the 8s crawl by a wide margin
                assert wall < 8.0, \
                    f"speculation should rescue the job, took {wall:.1f}s"
        finally:
            fi.reset()
            FileSystem.clear_cache()
