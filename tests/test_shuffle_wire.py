"""The shuffle wire path: reactor-served pipelined chunk streams, batched
multi-segment fetches, fd-cached serving, and wire compression (the copy
side of the data plane — ≈ MapOutputServlet + MapOutputCopier, rebuilt
around the selector-reactor RPC core)."""

import io
import os
import time

import pytest

from tpumr.io import ifile
from tpumr.io.compress import TlzCodec
from tpumr.ipc.rpc import RpcServer
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.shuffle_copier import RemoteChunkSource, ShuffleCopier
from tpumr.mapred.tasktracker import (SpillFdCache, make_map_locator,
                                      serve_batch, serve_chunk)
from tpumr.utils import fi

JOB = "job_wire_0001"


def write_spill(tmp_path, name, records, codec="none"):
    buf = io.BytesIO()
    w = ifile.Writer(buf, codec=codec)
    w.start_partition()
    for k, v in records:
        w.append_raw(k, v)
    w.end_partition()
    index = w.close()
    tmp_path.mkdir(parents=True, exist_ok=True)
    path = tmp_path / name
    path.write_bytes(buf.getvalue())
    return str(path), index


def records_for(n, tag=b"m"):
    # repetitive values: wire compression has something to win
    return [(b"%s-%06d" % (tag, i), b"value" * 8) for i in range(n)]


def payload_of(path, index, partition=0):
    off, _raw, part_len = index["partitions"][partition]
    with open(path, "rb") as f:
        f.seek(off + 4)
        return f.read(part_len - 4)


class ShuffleServeStub:
    """A tracker's shuffle-serving surface, minus the tracker: the same
    serve_chunk/serve_batch core over real spill files, with the fi
    ``shuffle.serve`` seams, behind a REAL RpcServer."""

    MAX_CHUNK = 4 << 20

    def __init__(self, outputs, conf=None, delay_s=0.0, fd_cap=64):
        self.outputs = outputs          # map_index -> (path, index)
        self.conf = conf if conf is not None else JobConf()
        self.delay_s = delay_s
        self.fds = SpillFdCache(fd_cap)

    def get_protocol_version(self):
        return 7

    def _lookup(self, map_index):
        from tpumr.utils.fi import maybe_fail
        maybe_fail(f"shuffle.serve.m{map_index}", self.conf)
        if map_index not in self.outputs:
            raise KeyError(f"no map output for map {map_index}")
        return self.outputs[map_index]

    def get_map_output_chunk(self, job_id, map_index, partition, offset,
                             max_bytes, wire="none"):
        if self.delay_s:
            time.sleep(self.delay_s)
        path, index = self._lookup(map_index)
        return serve_chunk(self.fds, path, index, partition, offset,
                           max_bytes, self.MAX_CHUNK, wire)

    def get_map_outputs_batch(self, job_id, partition, map_indexes,
                              max_bytes_each=1 << 20,
                              max_total_bytes=8 << 20, wire="none"):
        if self.delay_s:
            time.sleep(self.delay_s)
        return serve_batch(self.fds, self._lookup, partition,
                           list(map_indexes), max_bytes_each,
                           max_total_bytes, self.MAX_CHUNK, wire)


def start_server(stub, reactor=True):
    s = RpcServer(stub, reactor=reactor,
                  fast_methods={"get_protocol_version"} if reactor
                  else None)
    s.uncached_methods = {"get_map_output_chunk", "get_map_outputs_batch"}
    s.start()
    return s


def locator_for(port, maps, conns=2):
    events = [{"map_index": m, "attempt_id": "a%d" % m,
               "shuffle_addr": "127.0.0.1:%d" % port,
               "status": "SUCCEEDED"} for m in maps]
    return make_map_locator(lambda cursor: events[cursor:], None,
                            poll_s=0.01, timeout_s=10.0,
                            conns_per_target=conns)


def wire_conf(**kv):
    conf = JobConf()
    defaults = {"tpumr.shuffle.chunk.bytes": 65536}
    defaults.update(kv)
    for k, v in defaults.items():
        conf.set(k, v)
    return conf


def all_records(segs):
    out = []
    for s in segs:
        out.extend(s)
    return sorted(out)


# ------------------------------------------------------------ serve core


class TestSpillFdCache:
    def test_eviction_under_many_jobs(self, tmp_path):
        """10 spills through a 4-entry cache: bounded open fds, LRU
        evictions, and every byte still served correctly."""
        spills = [write_spill(tmp_path, "s%d" % i,
                              records_for(50, b"m%d" % i))
                  for i in range(10)]
        fds = SpillFdCache(4)
        for path, index in spills:
            got = serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20)
            assert got["data"] == payload_of(path, index)
        assert fds.opens == 10
        assert fds.evictions == 6
        assert len(fds) == 4
        # an evicted path re-opens (and re-serves) transparently
        path, index = spills[0]
        got = serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20)
        assert got["data"] == payload_of(path, index)
        assert fds.opens == 11

    def test_invalidate_prefix(self, tmp_path):
        spills = [write_spill(tmp_path, "j%d" % i, records_for(10))
                  for i in range(3)]
        fds = SpillFdCache(8)
        for path, index in spills:
            serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20)
        fds.invalidate(str(tmp_path / "j1"))
        assert len(fds) == 2

    def test_wire_compression_only_when_it_pays(self, tmp_path):
        if not TlzCodec.available():
            pytest.skip("native tlz unavailable")
        fds = SpillFdCache(4)
        # compressible, uncompressed spill: wire-compressed
        path, index = write_spill(tmp_path, "big", records_for(500))
        out = serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20,
                          wire="tlz")
        assert out["wire"] == "tlz"
        assert len(out["data"]) < out["n"]
        assert TlzCodec().decompress(out["data"]) == \
            payload_of(path, index)
        # tiny payload: below the wire floor, ships raw
        path, index = write_spill(tmp_path, "tiny", records_for(2))
        out = serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20,
                          wire="tlz")
        assert "wire" not in out
        # already-compressed spill: never re-compressed
        path, index = write_spill(tmp_path, "z", records_for(500),
                                  codec="zlib")
        out = serve_chunk(fds, path, index, 0, 0, 1 << 20, 4 << 20,
                          wire="tlz")
        assert "wire" not in out


class TestServeBatch:
    def _fixture(self, tmp_path, n=5):
        spills = {m: write_spill(tmp_path, "s%d" % m,
                                 records_for(30, b"m%d" % m))
                  for m in range(n)}
        return SpillFdCache(8), spills

    def test_per_entry_error_rides_back(self, tmp_path):
        fds, spills = self._fixture(tmp_path)

        def lookup(m):
            if m == 2:
                raise KeyError("no map output for map 2")
            return spills[m]

        out = serve_batch(fds, lookup, 0, [0, 1, 2, 3], 1 << 20, 8 << 20,
                          4 << 20)
        assert [e["map_index"] for e in out] == [0, 1, 2, 3]
        assert "error" in out[2] and "KeyError" in out[2]["error"]
        for e in (out[0], out[1], out[3]):
            path, index = spills[e["map_index"]]
            assert e["data"] == payload_of(path, index)

    def test_byte_budget_omits_tail(self, tmp_path):
        fds, spills = self._fixture(tmp_path)
        one = len(payload_of(*spills[0]))
        out = serve_batch(fds, lambda m: spills[m], 0, list(range(5)),
                          1 << 20, int(one * 2.5), 4 << 20)
        # ~2.5 payloads of budget: 3 entries (the overflowing one still
        # ships), the rest omitted for the copier to requeue
        assert len(out) == 3

    def test_oversized_entry_arrives_as_prefix(self, tmp_path):
        fds, spills = self._fixture(tmp_path)
        out = serve_batch(fds, lambda m: spills[m], 0, [0], 100, 8 << 20,
                          4 << 20)
        ent = out[0]
        assert len(ent["data"]) == 100 and ent["total"] > 100
        assert ent["data"] == payload_of(*spills[0])[:100]


# --------------------------------------------------------- the wire path


class TestWirePath:
    def _cluster(self, tmp_path, n_maps, recs_per_map=120, reactor=True,
                 delay_s=0.0, serve_conf=None):
        spills = {m: write_spill(tmp_path, "s%d" % m,
                                 records_for(recs_per_map, b"m%d" % m))
                  for m in range(n_maps)}
        stub = ShuffleServeStub(spills, conf=serve_conf, delay_s=delay_s)
        server = start_server(stub, reactor=reactor)
        return spills, stub, server

    def test_byte_identity_engine_on_vs_off(self, tmp_path):
        """Batching + pipelining + wire compression on the reactor
        transport must move byte-identical records vs the flat
        per-chunk path on the threaded transport."""
        spills, _, srv_on = self._cluster(tmp_path / "on", 6)
        _, _, srv_off = self._cluster(tmp_path / "off", 6, reactor=False)
        # re-point the off server at the SAME spills for identical input
        srv_off._handlers[""].outputs = spills
        try:
            conf_on = wire_conf(**{"tpumr.shuffle.batch.segments": 4,
                                   "tpumr.shuffle.wire.codec": "tlz"})
            src_on = RemoteChunkSource(
                conf_on, JOB, locator_for(srv_on.port, range(6)))
            segs_on = ShuffleCopier(
                conf_on, src_on, 6, 0, str(tmp_path / "sp_on"),
                on_fetch_failure=lambda m, a: None).copy_all()

            conf_off = wire_conf(**{"tpumr.shuffle.batch.segments": 1,
                                    "tpumr.shuffle.fetch.pipeline.depth": 1,
                                    "tpumr.shuffle.wire.codec": "none"})
            src_off = RemoteChunkSource(
                conf_off, JOB, locator_for(srv_off.port, range(6)))
            segs_off = ShuffleCopier(
                conf_off, src_off, 6, 0, str(tmp_path / "sp_off"),
                on_fetch_failure=None).copy_all()

            on, off = all_records(segs_on), all_records(segs_off)
            assert on == off
            assert len(on) == 6 * 120
        finally:
            srv_on.stop()
            srv_off.stop()

    def test_wire_bytes_shrink_and_are_accounted(self, tmp_path):
        if not TlzCodec.available():
            pytest.skip("native tlz unavailable")
        _, _, server = self._cluster(tmp_path, 4, recs_per_map=400)
        try:
            conf = wire_conf(**{"tpumr.shuffle.wire.codec": "tlz",
                                "tpumr.shuffle.batch.segments": 1})
            src = RemoteChunkSource(conf, JOB,
                                    locator_for(server.port, range(4)))
            segs = ShuffleCopier(conf, src, 4, 0, str(tmp_path / "sp"),
                                 ).copy_all()
            wire = sum(s.wire_length for s in segs if hasattr(s, "wire_length"))
            raw = sum(s.raw_length for s in segs)
            assert 0 < wire < raw   # compressed in flight, decompressed here
            assert len(all_records(segs)) == 4 * 400
        finally:
            server.stop()

    def test_pipelined_fetch_keeps_multiple_in_flight(self, tmp_path):
        """The reactor's per-connection pipeline depth proves requests
        genuinely overlap on one socket (in-flight > 1)."""
        recs = records_for(6000)   # payload ≫ several 64 KiB chunks
        path, index = write_spill(tmp_path, "big", recs)
        stub = ShuffleServeStub({0: (path, index)}, delay_s=0.002)
        server = start_server(stub, reactor=True)
        try:
            conf = wire_conf(**{"tpumr.shuffle.fetch.pipeline.depth": 4,
                                "tpumr.shuffle.wire.codec": "none"})
            src = RemoteChunkSource(conf, JOB,
                                    locator_for(server.port, [0]))
            chunks = list(src.fetch_chunks(0, 0))
            assert b"".join(c["data"] for c in chunks) == \
                payload_of(path, index)
            assert len(chunks) > 4
            assert server._reactor.pipeline_depth_peak > 1
        finally:
            server.stop()

    def test_batched_round_uses_one_rpc(self, tmp_path):
        _, _, server = self._cluster(tmp_path, 8, recs_per_map=20)
        try:
            conf = wire_conf(**{"tpumr.shuffle.batch.segments": 8})
            src = RemoteChunkSource(conf, JOB,
                                    locator_for(server.port, range(8)))
            entries = src.fetch_batch(list(range(8)), 0)
            assert sorted(e["map_index"] for e in entries) == list(range(8))
            assert all("error" not in e for e in entries)
        finally:
            server.stop()

    def test_chaos_mid_batch_reexecutes_exactly_the_lost_map(self,
                                                             tmp_path):
        """A batched fetch hitting the fi ``shuffle.serve`` seam for ONE
        map fails that member alone: its batch-mates land, the
        fetch-failure protocol reports exactly the lost map, and the
        retry (seam exhausted ≈ the re-run map) completes the copy."""
        fi.reset()
        serve_conf = JobConf()
        serve_conf.set("tpumr.fi.shuffle.serve.m2.probability", 1.0)
        serve_conf.set("tpumr.fi.shuffle.serve.m2.max.failures", 1)
        spills, _, server = self._cluster(tmp_path, 6, recs_per_map=40,
                                          serve_conf=serve_conf)
        reported = []
        try:
            conf = wire_conf(**{
                "tpumr.shuffle.batch.segments": 8,
                "tpumr.shuffle.parallel.copies": 1,
                "tpumr.shuffle.fetch.retries.per.source": 1,
                "tpumr.shuffle.copy.backoff.ms": 1})
            src = RemoteChunkSource(conf, JOB,
                                    locator_for(server.port, range(6)))
            copier = ShuffleCopier(
                conf, src, 6, 0, str(tmp_path / "sp"),
                on_fetch_failure=lambda m, a: reported.append((m, a)))
            segs = copier.copy_all()
            assert len(all_records(segs)) == 6 * 40
            assert reported == [(2, "a2")]
            assert copier.fetch_failures == 1
        finally:
            server.stop()
            fi.reset()

    def test_connection_pool_multiplexes_few_sockets(self, tmp_path):
        """parallel.copies fetchers over conns_per_target=2 sockets:
        the locator's shared pool, not one client per fetcher."""
        _, _, server = self._cluster(tmp_path, 10, recs_per_map=60)
        try:
            conf = wire_conf(**{"tpumr.shuffle.parallel.copies": 6,
                                "tpumr.shuffle.batch.segments": 1})
            loc = locator_for(server.port, range(10), conns=2)
            src = RemoteChunkSource(conf, JOB, loc)
            segs = ShuffleCopier(conf, src, 10, 0, str(tmp_path / "sp"),
                                 on_fetch_failure=lambda m, a: None
                                 ).copy_all()
            assert len(all_records(segs)) == 10 * 60
            assert loc.pool.connects <= 2
        finally:
            server.stop()
