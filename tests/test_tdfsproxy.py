"""tdfsproxy (≈ contrib/hdfsproxy): fail-closed path permissions, the
three servlet routes, IP pinning, TLS, and tdfs backing."""

import json
import urllib.error
import urllib.request

import pytest

from tpumr.mapred.jobconf import JobConf
from tpumr.tools.tdfsproxy import (TdfsProxy, load_permissions,
                                   path_permitted)


@pytest.fixture()
def tree(tmp_path):
    (tmp_path / "data" / "public").mkdir(parents=True)
    (tmp_path / "data" / "public" / "a.txt").write_bytes(b"alpha")
    (tmp_path / "data" / "public" / "sub").mkdir()
    (tmp_path / "data" / "public" / "sub" / "b.bin").write_bytes(
        b"\x00\x01beta")
    (tmp_path / "secret").mkdir()
    (tmp_path / "secret" / "s.txt").write_bytes(b"classified")
    return tmp_path


@pytest.fixture()
def proxy(tree, tmp_path):
    perms = tmp_path / "perms.toml"
    perms.write_text(
        '[alice]\npaths = ["/data/public", "/secret"]\n'
        '[bob]\npaths = ["/data/public"]\n'
        '[eve]\npaths = ["/data/public"]\nips = ["203.0.113.9"]\n')
    conf = JobConf()
    conf.set("tdfsproxy.permissions.file", str(perms))
    conf.set("fs.default.name", f"file://{tree}")
    p = TdfsProxy(conf, port=0, host="127.0.0.1").start()
    yield p
    p.stop()


def fetch(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestPermissions:
    def test_load_and_prefix_rules(self, tmp_path):
        f = tmp_path / "p.toml"
        f.write_text('[u]\npaths = ["/a/b"]\n')
        perms = load_permissions(str(f))
        assert path_permitted(perms, "u", "/a/b/c.txt", "1.2.3.4")
        assert path_permitted(perms, "u", "/a/b", "1.2.3.4")
        # /a/bc must NOT match the /a/b prefix; nor traversal escapes
        assert not path_permitted(perms, "u", "/a/bc", "1.2.3.4")
        assert not path_permitted(perms, "u", "/a/b/../../etc", "1.2.3.4")
        assert not path_permitted(perms, "nobody", "/a/b", "1.2.3.4")

    def test_requires_permissions_file(self):
        with pytest.raises(ValueError, match="permissions.file"):
            TdfsProxy(JobConf(), port=0)


class TestRoutes:
    def test_list_data_checksum(self, proxy):
        code, body = fetch(
            f"{proxy.url}/listPaths/data/public?user.name=alice")
        assert code == 200
        paths = json.loads(body)["paths"]
        names = {p["path"].rsplit("/", 1)[-1] for p in paths
                 if not p["is_dir"]}
        assert names == {"a.txt", "b.bin"}
        # namespace-relative, never the backing-store URI (trust
        # boundary: no file:///... leak) — round-trip into /data is
        # asserted in TestReviewRegressions.test_listing_roundtrips
        assert all(p["path"].startswith("/data/public") for p in paths), paths

        code, body = fetch(
            f"{proxy.url}/data/data/public/a.txt?user.name=bob")
        assert (code, body) == (200, b"alpha")

        code, body = fetch(
            f"{proxy.url}/fileChecksum/data/public/a.txt?user.name=bob")
        assert code == 200
        import hashlib
        assert json.loads(body)["checksum"] == \
            hashlib.md5(b"alpha").hexdigest()

    def test_denials(self, proxy):
        # no identity
        code, _ = fetch(f"{proxy.url}/data/data/public/a.txt")
        assert code == 401
        # outside the user's prefixes (fail closed)
        code, _ = fetch(f"{proxy.url}/data/secret/s.txt?user.name=bob")
        assert code == 403
        # unknown user
        code, _ = fetch(f"{proxy.url}/data/data/public/a.txt?user.name=x")
        assert code == 403
        # IP-pinned user from the wrong address
        code, _ = fetch(f"{proxy.url}/data/data/public/a.txt?user.name=eve")
        assert code == 403
        # traversal out of the prefix
        code, _ = fetch(
            f"{proxy.url}/data/data/public/../../secret/s.txt"
            f"?user.name=bob")
        assert code == 403
        # alice IS allowed into /secret
        code, body = fetch(f"{proxy.url}/data/secret/s.txt?user.name=alice")
        assert (code, body) == (200, b"classified")

    def test_missing_and_bad_paths(self, proxy):
        code, _ = fetch(f"{proxy.url}/data/data/public/nope?user.name=bob")
        assert code == 404
        code, _ = fetch(f"{proxy.url}/data/data/public?user.name=bob")
        assert code == 400          # directory, not a file
        code, _ = fetch(f"{proxy.url}/bogusroute/x?user.name=bob")
        assert code == 404


class TestTls:
    def test_https_serving(self, tree, tmp_path):
        try:
            import subprocess
            r = subprocess.run(
                ["openssl", "req", "-x509", "-newkey", "rsa:2048",
                 "-keyout", str(tmp_path / "key.pem"),
                 "-out", str(tmp_path / "cert.pem"),
                 "-days", "1", "-nodes", "-subj", "/CN=localhost"],
                capture_output=True, timeout=60)
            if r.returncode != 0:
                pytest.skip("openssl unavailable")
        except FileNotFoundError:
            pytest.skip("openssl unavailable")
        perms = tmp_path / "perms.toml"
        perms.write_text('[alice]\npaths = ["/data/public"]\n')
        conf = JobConf()
        conf.set("tdfsproxy.permissions.file", str(perms))
        conf.set("fs.default.name", f"file://{tree}")
        conf.set("tdfsproxy.ssl.cert", str(tmp_path / "cert.pem"))
        conf.set("tdfsproxy.ssl.key", str(tmp_path / "key.pem"))
        p = TdfsProxy(conf, port=0, host="127.0.0.1").start()
        try:
            import ssl
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                    f"{p.url}/data/data/public/a.txt?user.name=alice",
                    context=ctx) as r:
                assert r.read() == b"alpha"
            assert p.url.startswith("https://")
        finally:
            p.stop()


class TestTdfsBacked:
    def test_proxies_a_real_tdfs_namespace(self, tmp_path):
        from tpumr.dfs.mini_cluster import MiniDFSCluster
        from tpumr.fs import get_filesystem
        with MiniDFSCluster(num_datanodes=1) as c:
            fs = get_filesystem(c.uri + "/")
            fs.write_bytes(f"{c.uri}/exports/report.txt", b"quarterly")
            perms = tmp_path / "perms.toml"
            perms.write_text('[auditor]\npaths = ["/exports"]\n')
            conf = JobConf()
            conf.set("tdfsproxy.permissions.file", str(perms))
            conf.set("fs.default.name", c.uri)
            p = TdfsProxy(conf, port=0, host="127.0.0.1").start()
            try:
                code, body = fetch(
                    f"{p.url}/data/exports/report.txt?user.name=auditor")
                assert (code, body) == (200, b"quarterly")
                code, _ = fetch(
                    f"{p.url}/data/exports/report.txt?user.name=stranger")
                assert code == 403
            finally:
                p.stop()


class TestReviewRegressions:
    def test_empty_ip_pin_denies_all(self, tmp_path):
        f = tmp_path / "p.toml"
        f.write_text('[u]\npaths = ["/a"]\nips = []\n')
        perms = load_permissions(str(f))
        assert not path_permitted(perms, "u", "/a/x", "1.2.3.4")

    def test_root_namespace_default(self, tree, tmp_path):
        """fs.default.name='file:///': naive string joins mangle the
        root URI into 'file:' — requests must still resolve."""
        perms = tmp_path / "perms.toml"
        perms.write_text(f'[u]\npaths = ["{tree}/data"]\n')
        conf = JobConf()
        conf.set("tdfsproxy.permissions.file", str(perms))
        conf.set("fs.default.name", "file:///")
        p = TdfsProxy(conf, port=0, host="127.0.0.1").start()
        try:
            code, body = fetch(
                f"{p.url}/data{tree}/data/public/a.txt?user.name=u")
            assert (code, body) == (200, b"alpha")
        finally:
            p.stop()

    def test_listing_roundtrips_into_data(self, proxy):
        code, body = fetch(
            f"{proxy.url}/listPaths/data/public?user.name=alice")
        files = [p for p in json.loads(body)["paths"] if not p["is_dir"]]
        for ent in files:
            code, data = fetch(
                f"{proxy.url}/data{ent['path']}?user.name=alice")
            assert code == 200 and len(data) == ent["length"]

    def test_deleted_between_list_and_read_is_404(self, proxy, tree):
        (tree / "data" / "public" / "gone.txt").write_bytes(b"x")
        (tree / "data" / "public" / "gone.txt").unlink()
        code, _ = fetch(
            f"{proxy.url}/data/data/public/gone.txt?user.name=alice")
        assert code == 404
