"""Device-fetch coalescing: concurrent fetches share roundtrips, lone
fetches are never delayed, and a failing entry doesn't poison its
batch-mates."""

import threading
import time

import numpy as np
import pytest

from tpumr.mapred.fetch_batcher import DeviceFetchBatcher


def _device_arrays(n):
    import jax.numpy as jnp
    return [jnp.asarray(np.full((4,), i, np.float32)) for i in range(n)]


def test_single_fetch_roundtrip_and_result():
    b = DeviceFetchBatcher()
    (arr,) = _device_arrays(1)
    out = b.fetch({"x": arr, "aux": 7})
    assert out["aux"] == 7
    np.testing.assert_array_equal(np.asarray(out["x"]), np.zeros(4))
    assert b.roundtrips == 1 and b.fetches == 1 and b.batched == 0


def test_concurrent_fetches_coalesce():
    """N threads fetching at once must use far fewer than N roundtrips
    (arrivals during an in-flight fetch ride the next batch)."""
    import jax

    b = DeviceFetchBatcher()
    arrs = _device_arrays(8)
    results = [None] * 8
    errors = []

    real = jax.device_get
    slow_calls = []

    def slow_get(tree):
        slow_calls.append(1)
        time.sleep(0.05)  # make the roundtrip window wide
        return real(tree)

    gate = threading.Barrier(8)

    def run(i):
        try:
            gate.wait()
            results[i] = b.fetch((arrs[i],))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    import unittest.mock
    with unittest.mock.patch.object(jax, "device_get", slow_get):
        threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    for i in range(8):
        np.testing.assert_array_equal(np.asarray(results[i][0]),
                                      np.full(4, i))
    assert b.fetches == 8
    assert b.roundtrips <= 3, (b.roundtrips, b.batched)  # 1 leader + batch
    assert b.batched >= 5


def test_failing_entry_does_not_poison_batchmates():
    import jax

    b = DeviceFetchBatcher()
    good = _device_arrays(2)

    class Boom:
        pass  # device_get chokes on this leaf inside a batch

    real = jax.device_get

    def get(tree):
        # simulate: batched call fails, per-slot retry fails only for Boom
        def has_boom(t):
            if isinstance(t, Boom):
                return True
            if isinstance(t, (list, tuple)):
                return any(has_boom(x) for x in t)
            return False

        if has_boom(tree):
            raise RuntimeError("bad computation")
        return real(tree)

    import unittest.mock
    results = {}
    errors = {}
    gate = threading.Barrier(3)

    def run(name, tree):
        try:
            gate.wait()
            results[name] = b.fetch(tree)
        except Exception as e:  # noqa: BLE001
            errors[name] = e

    with unittest.mock.patch.object(jax, "device_get", get):
        threads = [threading.Thread(target=run, args=(f"g{i}", (good[i],)))
                   for i in range(2)]
        threads.append(threading.Thread(target=run, args=("bad", (Boom(),))))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert set(errors) == {"bad"}
    assert "bad computation" in str(errors["bad"])
    assert set(results) == {"g0", "g1"}


def test_tracker_tpu_tasks_share_roundtrips():
    """End-to-end: a mini-cluster job with several concurrent TPU slots
    funnels its kernel fetches through the shared batcher."""
    from tpumr.fs import get_filesystem
    from tpumr.mapred.fetch_batcher import shared_batcher
    from tpumr.mapred.job_client import JobClient
    from tpumr.mapred.mini_cluster import MiniMRCluster
    from tpumr.ops.kmeans import clear_centroid_cache

    clear_centroid_cache()
    fs = get_filesystem("mem:///")
    rng = np.random.default_rng(5)
    import io as _io
    buf = _io.BytesIO()
    np.save(buf, rng.normal(size=(400, 4)).astype(np.float32))
    fs.write_bytes("/fb/points.npy", buf.getvalue())
    buf = _io.BytesIO()
    np.save(buf, rng.normal(size=(3, 4)).astype(np.float32))
    fs.write_bytes("/fb/cents.npy", buf.getvalue())

    before = shared_batcher().fetches
    with MiniMRCluster(num_trackers=1, cpu_slots=0, tpu_slots=4) as c:
        conf = c.create_job_conf()
        from tpumr.mapred.input_formats import DenseInputFormat
        conf.set_input_paths("mem:///fb/points.npy")
        conf.set_output_path("mem:///fb/out")
        conf.set_input_format(DenseInputFormat)
        conf.set("tpumr.dense.split.rows", 50)  # 8 map tasks
        conf.set("tpumr.kmeans.centroids", "mem:///fb/cents.npy")
        conf.set_map_kernel("kmeans-assign")
        conf.set("mapred.reducer.class",
                 "tpumr.examples.basic.CentroidReducer")
        conf.set_num_reduce_tasks(1)
        assert JobClient(conf).run_job(conf).successful
    assert shared_batcher().fetches - before == 8
