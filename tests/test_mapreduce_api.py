"""New-style context-object API ≈ org.apache.hadoop.mapreduce (Job/Mapper/
Reducer with setup/cleanup lifecycles) — and, unlike the reference, the
new API is TPU-wired (SURVEY.md §2.4: reference GPU was old-API only)."""

import numpy as np

from tpumr.fs import get_filesystem
from tpumr.mapred.input_formats import DenseInputFormat, TextInputFormat
from tpumr.mapreduce import Context, Job, Mapper, Partitioner, Reducer


class TokenMapper(Mapper):
    def setup(self, context):
        self.setup_ran = True
        context.get_counter("app", "mapper_setups").increment()

    def map(self, key, value, context):
        assert self.setup_ran
        for w in value.split():
            context.write(w, 1)

    def cleanup(self, context):
        context.get_counter("app", "mapper_cleanups").increment()


class SumReducer(Reducer):
    def setup(self, context):
        self.seen = 0

    def reduce(self, key, values, context):
        total = sum(values)
        self.seen += 1
        context.write(key, total)

    def cleanup(self, context):
        context.get_counter("app", "reducer_groups").increment(self.seen)


class TestNewApiWordCount:
    def test_wordcount_with_lifecycle(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na/in.txt", b"ab cd ab\ncd ab\n")
        job = Job(name="new-api-wc")
        job.add_input_path("mem:///na/in.txt")
        job.set_output_path("mem:///na/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(TokenMapper)
        job.set_reducer_class(SumReducer)
        job.set_num_reduce_tasks(1)
        assert job.wait_for_completion()
        text = fs.read_bytes("/na/out/part-00000").decode()
        assert dict(l.split("\t") for l in text.splitlines()) == \
            {"ab": "3", "cd": "2"}
        counters = job.counters.to_dict()["app"]
        assert counters["mapper_setups"] >= 1
        assert counters["mapper_cleanups"] >= 1
        assert counters["reducer_groups"] == 2

    def test_identity_defaults(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na2/in.txt", b"x\ny\n")
        job = Job(name="identity")
        job.add_input_path("mem:///na2/in.txt")
        job.set_output_path("mem:///na2/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(Mapper)     # identity
        job.set_reducer_class(Reducer)   # identity
        assert job.wait_for_completion()
        text = fs.read_bytes("/na2/out/part-00000").decode()
        assert len(text.splitlines()) == 2


class EvenOddPartitioner(Partitioner):
    def get_partition(self, key, value, num_partitions):
        return int(key) % num_partitions


class TestNewApiPartitioner:
    def test_custom_partitioner(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na3/in.txt",
                       b"".join(b"%d\n" % i for i in range(10)))

        class NumMapper(Mapper):
            def map(self, key, value, context):
                context.write(int(value), 1)

        job = Job(name="parts")
        job.add_input_path("mem:///na3/in.txt")
        job.set_output_path("mem:///na3/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(NumMapper)
        job.set_reducer_class(Reducer)
        job.set_partitioner_class(EvenOddPartitioner)
        job.set_num_reduce_tasks(2)
        assert job.wait_for_completion()
        part0 = fs.read_bytes("/na3/out/part-00000").decode()
        keys0 = [int(l.split("\t")[0]) for l in part0.splitlines()]
        assert keys0 and all(k % 2 == 0 for k in keys0)


class TestNewApiTpuKernel:
    def test_kernel_job_through_new_api(self):
        """The device-kernel path composes with the new-API Job facade."""
        from tpumr.ops.kmeans import clear_centroid_cache
        clear_centroid_cache()
        import io as _io
        fs = get_filesystem("mem:///")
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(100, 4)).astype(np.float32)

        def save(path, arr):
            b = _io.BytesIO()
            np.save(b, arr)
            fs.write_bytes(path, b.getvalue())

        save("/na4/points.npy", pts)
        save("/na4/cents.npy", pts[:3])

        class CentReducer(Reducer):
            def reduce(self, key, values, context):
                total, n = None, 0
                for s, c in values:
                    s = np.asarray(s)
                    total = s if total is None else total + s
                    n += c
                context.write(key, (total / max(1, n)).tolist())

        job = Job(name="kmeans-new-api")
        job.add_input_path("mem:///na4/points.npy")
        job.set_output_path("mem:///na4/out")
        job.set_input_format(DenseInputFormat)
        job.conf.set("tpumr.dense.split.rows", 50)
        job.conf.set("tpumr.kmeans.centroids", "mem:///na4/cents.npy")
        job.set_map_kernel("kmeans-assign")
        job.set_reducer_class(CentReducer)
        job.conf.set("tpumr.local.run.on.tpu", True)
        assert job.wait_for_completion()
        text = fs.read_bytes("/na4/out/part-00000").decode()
        assert len(text.splitlines()) >= 1


class TestNewApiCombiner:
    def test_combiner_applied(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na5/in.txt", b"q q q q\n" * 50)

        class CountCombiner(Reducer):
            def reduce(self, key, values, context):
                context.write(key, sum(values))

        job = Job(name="combine")
        job.add_input_path("mem:///na5/in.txt")
        job.set_output_path("mem:///na5/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(TokenMapper)
        job.set_combiner_class(CountCombiner)
        job.set_reducer_class(SumReducer)
        job.conf.set("io.sort.mb", 1)
        assert job.wait_for_completion()
        text = fs.read_bytes("/na5/out/part-00000").decode()
        assert text.strip() == "q\t200"
        from tpumr.core.counters import TaskCounter
        fw = job.counters.to_dict()[TaskCounter.FRAMEWORK_GROUP]
        assert fw.get(TaskCounter.COMBINE_INPUT_RECORDS, 0) > 0

    def test_empty_partition_still_runs_lifecycle(self):
        # all keys partition to 0; partition 1's reducer sees zero groups
        # but must still run setup/cleanup (reference Reducer.run contract)
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na6/in.txt", b"same same\n")

        class LifecycleReducer(Reducer):
            def setup(self, context):
                context.get_counter("app", "reduce_setups").increment()

            def cleanup(self, context):
                context.get_counter("app", "reduce_cleanups").increment()

        job = Job(name="empty-part")
        job.add_input_path("mem:///na6/in.txt")
        job.set_output_path("mem:///na6/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(TokenMapper)
        job.set_reducer_class(LifecycleReducer)
        job.set_num_reduce_tasks(2)
        assert job.wait_for_completion()
        app = job.counters.to_dict()["app"]
        assert app["reduce_setups"] == 2
        assert app["reduce_cleanups"] == 2

    def test_wait_for_completion_returns_false_on_failure(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/na7/in.txt", b"boom\n")

        class FailingMapper(Mapper):
            def map(self, key, value, context):
                raise ValueError("intentional")

        job = Job(name="fails")
        job.add_input_path("mem:///na7/in.txt")
        job.set_output_path("mem:///na7/out")
        job.set_input_format(TextInputFormat)
        job.set_mapper_class(FailingMapper)
        assert job.wait_for_completion() is False
        assert "intentional" in job.error or job.error
