"""Master restart survival (robustness tentpole PR 9).

Three legs, mirroring the reference JobTracker's RecoveryManager
contract (JobTracker.java:1203) extended down to the ATTEMPT level:

- attempt-level recovery: a restarted master replays each interrupted
  job's history events into the resubmitted JobInProgress — completed
  maps are adopted with their original attempt ids and surviving
  shuffle outputs (zero re-runs), withdrawn outputs stay withdrawn;
- live tracker re-join: trackers that lose the master keep their
  in-flight tasks running, back off, and on re-contact send a full
  status the master ADOPTS (matching attempts bound to recovered TIPs,
  unknown attempts killed individually) — never a blanket reinit;
- control-plane partition tolerance: the RpcClient retry policy plus
  the rpc.drop/rpc.delay/rpc.reset chaos seams, with server-side
  (cid, id) replay dedupe keeping resends exactly-once.

The chaos e2es kill the master mid-job and assert byte-identical
output with zero map re-executions; a second e2e loses one recovered
output and watches the fetch-failure protocol re-run exactly that map.
"""

import json
import os
import threading
import time

import pytest

from tpumr.fs import FileSystem, get_filesystem
from tpumr.mapred.history import JobHistory
from tpumr.mapred.ids import JobID, TaskAttemptID
from tpumr.mapred.job_in_progress import JobInProgress, JobState
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.task import TaskPhase, TaskState, TaskStatus
from tpumr.utils import fi

RESTART_TRACE_OUT = "/tmp/tpumr-restart-trace.json"


# ------------------------------------------------------ history replay


class TestHistoryAttemptReplay:
    def _history(self, tmp_path):
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        return JobHistory(conf)

    def test_last_success_wins_and_withdrawals_erase(self, tmp_path):
        h = self._history(tmp_path)
        job = "job_old_0001"
        a0 = "attempt_old_0001_m_000000_0"
        a1 = "attempt_old_0001_m_000000_1"
        b0 = "attempt_old_0001_m_000001_0"
        r0 = "attempt_old_0001_r_000000_0"
        h.task_event(job, "TASK_STARTED", attempt_id=a0, tracker="t1")
        h.task_event(job, "TASK_FINISHED", attempt_id=a0, is_map=True,
                     runtime=1.5, tracker="t1", shuffle_addr="h1:70",
                     run_on_tpu=False, counters={"G": {"C": 3}})
        # the old master withdrew a0's output (fetch failures) and a
        # re-run succeeded elsewhere
        h.task_event(job, "MAP_OUTPUT_LOST", attempt_id=a0,
                     shuffle_addr="h1:70")
        h.task_event(job, "TASK_FINISHED", attempt_id=a1, is_map=True,
                     runtime=2.0, tracker="t2", shuffle_addr="h2:70",
                     run_on_tpu=True, tpu_device_id=3)
        h.task_event(job, "TASK_FINISHED", attempt_id=b0, is_map=True,
                     runtime=0.5, tracker="t1", shuffle_addr="h1:70")
        h.task_event(job, "TASK_FINISHED", attempt_id=r0, is_map=False,
                     runtime=4.0, tracker="t2")
        state = h.recovered_attempt_state(job)
        assert set(state["maps"]) == {0, 1}
        m0 = state["maps"][0]
        assert m0["attempt_id"] == a1
        assert m0["shuffle_addr"] == "h2:70"
        assert m0["run_on_tpu"] is True and m0["tpu_device_id"] == 3
        assert state["maps"][1]["counters"] == {}
        assert state["reduces"][0]["attempt_id"] == r0
        assert state["reduces"][0]["runtime"] == 4.0

    def test_withdrawn_without_rerun_is_not_recovered(self, tmp_path):
        h = self._history(tmp_path)
        job = "job_old_0002"
        a0 = "attempt_old_0002_m_000000_0"
        h.task_event(job, "TASK_FINISHED", attempt_id=a0, is_map=True,
                     runtime=1.0, tracker="t1", shuffle_addr="h1:70")
        h.task_event(job, "MAP_OUTPUT_LOST", attempt_id=a0,
                     shuffle_addr="h1:70", reason="tracker_lost")
        state = h.recovered_attempt_state(job)
        assert state["maps"] == {}

    def test_missing_history_is_empty(self, tmp_path):
        h = self._history(tmp_path)
        state = h.recovered_attempt_state("job_never_0001")
        assert state == {"maps": {}, "reduces": {}}


# -------------------------------------------------- JIP attempt replay


def _jip(n_maps=3, n_reduces=1, **conf):
    base = {"mapred.reduce.tasks": n_reduces,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    base.update(conf)
    return JobInProgress(JobID("new", 1), base,
                        [{"locations": []} for _ in range(n_maps)])


def _map_rec(old_job="old", task=0, attempt=0, addr="h1:70",
             runtime=1.0, on_tpu=False, **extra):
    rec = {"attempt_id": f"attempt_{old_job}_0001_m_{task:06d}_{attempt}",
           "attempt": attempt, "is_map": True, "runtime": runtime,
           "tracker": "t1", "shuffle_addr": addr, "run_on_tpu": on_tpu,
           "tpu_device_id": -1, "counters": {}, "ts": time.time()}
    rec.update(extra)
    return rec


class TestRecoverAttempts:
    def test_completed_maps_adopted_with_events(self):
        jip = _jip(n_maps=3, n_reduces=2)
        n = jip.recover_attempts(
            {"maps": {0: _map_rec(task=0), 1: _map_rec(task=1, addr="")},
             "reduces": {}}, "job_old_0001")
        # map 1 had no recorded shuffle address: not recoverable for a
        # job with reduces — it re-runs
        assert n == 1
        assert jip.recovered_from == "job_old_0001"
        assert jip.finished_maps == 1 and jip.finished_cpu_maps == 1
        assert jip.pending_map_count() == 2
        assert jip.maps[0].state == "succeeded"
        assert jip.maps[0].successful_attempt == \
            "attempt_old_0001_m_000000_0"
        assert jip.maps[0].next_attempt == 1   # old gen 0 consumed
        events, _ = jip.completion_events.read(0, 100)
        assert len(events) == 1
        assert events[0]["map_index"] == 0
        assert events[0]["attempt_id"] == "attempt_old_0001_m_000000_0"
        assert events[0]["shuffle_addr"] == "h1:70"
        # the terminal outcome is already history-logged: a tracker
        # replaying the old SUCCEEDED status must not double-log
        assert "attempt_old_0001_m_000000_0" in jip.history_logged

    def test_no_reduce_job_recovers_without_address(self):
        jip = _jip(n_maps=1, n_reduces=0)
        n = jip.recover_attempts({"maps": {0: _map_rec(addr="")},
                                  "reduces": {}}, "job_old_0001")
        assert n == 1 and jip.finished_maps == 1
        # map-only jobs publish no completion events
        assert len(jip.completion_events) == 0

    def test_fully_complete_job_recovers_terminal(self):
        jip = _jip(n_maps=1, n_reduces=1)
        rrec = dict(_map_rec(task=0), is_map=False,
                    attempt_id="attempt_old_0001_r_000000_0")
        n = jip.recover_attempts({"maps": {0: _map_rec(task=0)},
                                  "reduces": {0: rrec}}, "job_old_0001")
        assert n == 2
        assert jip.state == JobState.SUCCEEDED

    def test_profile_sums_recovered_per_backend(self):
        jip = _jip(n_maps=2, n_reduces=1)
        jip.recover_attempts(
            {"maps": {0: _map_rec(task=0, runtime=2.0),
                      1: _map_rec(task=1, runtime=1.0, on_tpu=True)},
             "reduces": {}}, "job_old_0001")
        assert jip.cpu_map_mean_time() == 2.0
        assert jip.tpu_map_mean_time() == 1.0
        assert jip.acceleration_factor() == 2.0


class TestAdoptRunningAttempt:
    def _running_status(self, jip, task=0, attempt=0, old_job="old"):
        aid = f"attempt_{old_job}_0001_m_{task:06d}_{attempt}"
        return TaskStatus(attempt_id=TaskAttemptID.parse(aid),
                          is_map=True, state=TaskState.RUNNING,
                          progress=0.4, phase=TaskPhase.MAP)

    def test_pending_tip_adopts_and_leaves_pending_set(self):
        jip = _jip(n_maps=2)
        st = self._running_status(jip, task=0)
        assert jip.adopt_running_attempt(st) is True
        assert jip.pending_map_count() == 1
        assert jip.maps[0].state == "running"
        # completion folds normally afterwards
        done = TaskStatus(attempt_id=st.attempt_id, is_map=True,
                          state=TaskState.SUCCEEDED, progress=1.0,
                          finish_time=time.time())
        jip.update_task_status(done, "h1:70")
        assert jip.finished_maps == 1
        assert jip.maps[0].successful_attempt == str(st.attempt_id)

    def test_succeeded_tip_rejects_unknown_twin(self):
        jip = _jip(n_maps=1)
        jip.recover_attempts({"maps": {0: _map_rec(task=0)},
                              "reduces": {}}, "job_old_0001")
        # a zombie twin (different generation) of the recovered winner
        assert jip.adopt_running_attempt(
            self._running_status(jip, task=0, attempt=3)) is False
        # the recorded winner itself is always welcome
        assert jip.adopt_running_attempt(TaskStatus(
            attempt_id=TaskAttemptID.parse(
                "attempt_old_0001_m_000000_0"),
            is_map=True, state=TaskState.RUNNING)) is True

    def test_terminal_job_rejects(self):
        jip = _jip(n_maps=1)
        jip.kill()
        assert jip.adopt_running_attempt(
            self._running_status(jip)) is False

    def test_unknown_task_index_rejects(self):
        jip = _jip(n_maps=1)
        assert jip.adopt_running_attempt(
            self._running_status(jip, task=7)) is False


# ---------------------------------------------- master-level recovery


def _tracker_status(name="t1", host="h1", port=70, cpu=2, reduce=2,
                    statuses=()):
    return {"tracker_name": name, "host": host,
            "shuffle_addr": f"{host}:{port}", "shuffle_port": port,
            "max_cpu_map_slots": cpu, "max_tpu_map_slots": 0,
            "max_reduce_slots": reduce, "count_cpu_map_tasks": 0,
            "count_tpu_map_tasks": 0, "count_reduce_tasks": 0,
            "available_tpu_devices": [], "available_memory_mb": -1,
            "task_statuses": [dict(s) for s in statuses],
            "fetch_failures": [], "healthy": True, "health_report": ""}


def _succeeded(aid, runtime=0.2):
    now = time.time()
    return {"attempt_id": aid, "is_map": "_m_" in aid,
            "state": TaskState.SUCCEEDED, "progress": 1.0,
            "phase": TaskPhase.MAP if "_m_" in aid else TaskPhase.REDUCE,
            "start_time": now - runtime, "finish_time": now,
            "diagnostics": "", "counters": {}, "run_on_tpu": False,
            "tpu_device_id": -1, "failure_class": ""}


def _running(aid, progress=0.5):
    return {"attempt_id": aid, "is_map": "_m_" in aid,
            "state": TaskState.RUNNING, "progress": progress,
            "phase": TaskPhase.MAP if "_m_" in aid else TaskPhase.SHUFFLE,
            "start_time": time.time(), "finish_time": 0.0,
            "diagnostics": "", "counters": {}, "run_on_tpu": False,
            "tpu_device_id": -1, "failure_class": ""}


class TestMasterRestartRecovery:
    def _conf(self, tmp_path, **extra):
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        conf.set("mapred.jobtracker.restart.recover", True)
        for k, v in extra.items():
            conf.set(k, v)
        return conf

    def _interrupt_job(self, tmp_path):
        """Master 1: submit a 3-map/1-reduce job, run 2 maps to
        completion over the real heartbeat path, leave map 2 RUNNING,
        then crash (stop without finalization). Returns (old job id,
        the RUNNING attempt id)."""
        m1 = JobMaster(self._conf(tmp_path)).start()
        try:
            old_id = m1.submit_job(
                {"mapred.job.name": "interrupted",
                 "mapred.reduce.tasks": 1,
                 "mapred.reduce.slowstart.completed.maps": 1.0},
                [{"locations": []} for _ in range(3)])
            r = m1.heartbeat(_tracker_status(cpu=3), True, True, 0)
            launches = [a for a in r["actions"] if a["type"] == "launch"]
            assert len(launches) == 3
            aids = [a["task"]["attempt_id"] for a in launches]
            done = [_succeeded(a) for a in aids[:2]]
            running = [_running(aids[2])]
            m1.heartbeat(_tracker_status(statuses=done + running),
                         False, False, r["response_id"])
        finally:
            m1.stop()   # crash: no JOB_FINISHED, no finalization
        return old_id, aids[2]

    def test_attempt_level_recovery_and_alias(self, tmp_path):
        old_id, running_aid = self._interrupt_job(tmp_path)
        m2 = JobMaster(self._conf(tmp_path)).start()
        try:
            snap = m2.metrics.snapshot()["jobtracker"]
            assert snap["jobs_recovered"] == 1
            assert snap["attempts_recovered"] == 2
            mapping = m2.get_recovered_jobs()
            assert list(mapping) == [old_id]
            new_id = mapping[old_id]
            # the old id serves the resubmitted job, announcing its id
            st = m2.get_job_status(old_id)
            assert st["job_id"] == new_id
            assert st["finished_maps"] == 2
            # recovered completion events carry the ORIGINAL attempt
            # ids and addresses — reducers fetch surviving outputs
            events = m2.get_map_completion_events(new_id, 0)
            assert {e["map_index"] for e in events} == {0, 1}
            assert all(e["shuffle_addr"] == "h1:70" for e in events)
            assert all("_old_" not in e["attempt_id"]
                       or True for e in events)
            jip = m2.jobs[new_id]
            assert jip.recovered_from == old_id
            assert jip.pending_map_count() == 1   # map 2 was in flight
            # recovery grace: the scheduler must NOT hand map 2 out
            # before its tracker had a chance to re-join
            assert jip.obtain_new_map_task("h1", False) is None
        finally:
            m2.stop()

    def test_rejoining_tracker_adopted_not_reinit(self, tmp_path):
        old_id, running_aid = self._interrupt_job(tmp_path)
        m2 = JobMaster(self._conf(tmp_path)).start()
        try:
            new_id = m2.get_recovered_jobs()[old_id]
            dead_aid = "attempt_dead_0009_m_000000_0"
            r = m2.heartbeat(
                _tracker_status(statuses=[_running(running_aid),
                                          _running(dead_aid)]),
                False, True, 7)
            kinds = [a["type"] for a in r["actions"]]
            assert "reinit" not in kinds and "resend_full" not in kinds
            # the in-flight attempt of the recovered job was adopted...
            jip = m2.jobs[new_id]
            assert jip.pending_map_count() == 0
            assert jip.maps[2].state == "running"
            # ...the dead job's orphan was killed INDIVIDUALLY...
            kills = [a["attempt_id"] for a in r["actions"]
                     if a["type"] == "kill_task"]
            assert kills == [dead_aid]
            # ...and the tracker learned the job id rebinding
            rebinds = [a for a in r["actions"]
                       if a["type"] == "recover_job"]
            assert rebinds == [{"type": "recover_job", "old": old_id,
                                "new": new_id}]
            snap = m2.metrics.snapshot()["jobtracker"]
            assert snap["trackers_adopted"] == 1
            assert snap["attempts_adopted"] == 1
            # the adopted attempt completes through the normal fold
            m2.heartbeat(
                _tracker_status(statuses=[_succeeded(running_aid)]),
                False, False, r["response_id"])
            assert m2.get_job_status(old_id)["finished_maps"] == 3
            # zero map re-executions: the restarted master launched none
            snap = m2.metrics.snapshot()["jobtracker"]
            assert snap.get("maps_launched_cpu", 0) == 0
            assert snap.get("maps_launched_tpu", 0) == 0
        finally:
            m2.stop()

    def test_commit_gate_follows_alias(self, tmp_path):
        old_id, running_aid = self._interrupt_job(tmp_path)
        m2 = JobMaster(self._conf(tmp_path)).start()
        try:
            task_id = str(TaskAttemptID.parse(running_aid).task)
            # adopt it first (the normal order: heartbeat, then commit)
            m2.heartbeat(
                _tracker_status(statuses=[_running(running_aid)]),
                False, False, 3)
            assert m2.can_commit(task_id, running_aid) is True
        finally:
            m2.stop()

    def test_finished_job_served_retired_from_history(self, tmp_path):
        """A job that COMPLETED before the crash must keep answering
        status polls after the restart (served from history, ≈ the
        reference's retired-jobs cache) — a client watching it must
        not suddenly see 'unknown job'."""
        m1 = JobMaster(self._conf(tmp_path)).start()
        try:
            jid = m1.submit_job(
                {"mapred.job.name": "done", "mapred.reduce.tasks": 0},
                [{"locations": []}])
            r = m1.heartbeat(_tracker_status(), True, True, 0)
            aid = [a for a in r["actions"]
                   if a["type"] == "launch"][0]["task"]["attempt_id"]
            m1.heartbeat(_tracker_status(statuses=[_succeeded(aid)]),
                         False, False, r["response_id"])
            assert m1.get_job_status(jid)["state"] == "SUCCEEDED"
        finally:
            m1.stop()
        m2 = JobMaster(self._conf(tmp_path)).start()
        try:
            assert m2.get_recovered_jobs() == {}   # nothing to recover
            st = m2.get_job_status(jid)
            assert st["state"] == "SUCCEEDED"
            assert st["retired"] is True
            assert st["num_maps"] == 1 and st["finished_maps"] == 1
            with pytest.raises(KeyError):
                m2.get_job_status("job_never_0001")
        finally:
            m2.stop()

    def test_withdrawn_output_not_recovered_after_eviction(
            self, tmp_path):
        """A completed map whose tracker the OLD master evicted (its
        output re-queued, MAP_OUTPUT_LOST journaled) must NOT come back
        from the dead on restart."""
        conf = self._conf(tmp_path, **{"tpumr.tracker.expiry.ms": 60_000})
        m1 = JobMaster(conf).start()
        try:
            old_id = m1.submit_job(
                {"mapred.job.name": "evicted",
                 "mapred.reduce.tasks": 1},
                [{"locations": []}])
            r = m1.heartbeat(_tracker_status(), True, True, 0)
            aid = [a for a in r["actions"]
                   if a["type"] == "launch"][0]["task"]["attempt_id"]
            m1.heartbeat(_tracker_status(statuses=[_succeeded(aid)]),
                         False, False, r["response_id"])
            assert m1.jobs[old_id].finished_maps == 1
            m1._evict_tracker("t1")   # output died with the tracker
            assert m1.jobs[old_id].finished_maps == 0
        finally:
            m1.stop()
        m2 = JobMaster(self._conf(tmp_path)).start()
        try:
            new_id = m2.get_recovered_jobs()[old_id]
            assert m2.jobs[new_id].finished_maps == 0
            assert m2.jobs[new_id].pending_map_count() == 1
        finally:
            m2.stop()


# ---------------------------------------- rpc retry + partition seams


class _CountingService:
    def __init__(self):
        self.calls = 0
        self.lock = threading.Lock()

    def get_protocol_version(self):
        return 1

    def bump(self):
        with self.lock:
            self.calls += 1
            return self.calls


class TestRpcPartitionTolerance:
    def setup_method(self):
        fi.reset()

    def teardown_method(self):
        fi.reset()

    def test_retry_absorbs_injected_drops(self):
        from tpumr.ipc.rpc import RpcClient, RpcServer
        conf = JobConf()
        conf.set("tpumr.fi.rpc.drop.probability", 1.0)
        conf.set("tpumr.fi.rpc.drop.max.failures", 2)
        srv = RpcServer(_CountingService()).start()
        try:
            cli = RpcClient(*srv.address, retries=3, backoff_ms=5)
            cli.fi_conf = conf
            assert cli.call("bump") == 1
            assert fi.fired("rpc.drop") == 2
            cli.close()
        finally:
            srv.stop()

    def test_reset_after_send_replays_not_reexecutes(self):
        """rpc.reset loses the connection AFTER the request went out —
        the hardest case: the server already executed. The resent
        (cid, id) must hit the replay cache, keeping a non-idempotent
        method exactly-once."""
        from tpumr.ipc.rpc import RpcClient, RpcServer
        conf = JobConf()
        conf.set("tpumr.fi.rpc.reset.probability", 1.0)
        conf.set("tpumr.fi.rpc.reset.max.failures", 1)
        svc = _CountingService()
        srv = RpcServer(svc).start()
        try:
            cli = RpcClient(*srv.address, retries=2, backoff_ms=5)
            cli.fi_conf = conf
            assert cli.call("bump") == 1
            assert svc.calls == 1, "resend must replay, never re-execute"
            assert fi.fired("rpc.reset") == 1
            # the channel is healthy again afterwards
            assert cli.call("bump") == 2
            cli.close()
        finally:
            srv.stop()

    def test_retries_exhausted_raises_transport_error(self):
        from tpumr.ipc.rpc import RpcClient
        cli = RpcClient("127.0.0.1", 1, retries=2, backoff_ms=1)
        with pytest.raises(OSError):
            cli.call("anything")

    def test_injected_delay_slows_but_succeeds(self):
        from tpumr.ipc.rpc import RpcClient, RpcServer
        conf = JobConf()
        conf.set("tpumr.fi.rpc.delay.probability", 1.0)
        conf.set("tpumr.fi.rpc.delay.max.failures", 1)
        conf.set("tpumr.fi.rpc.delay.ms", 150)
        srv = RpcServer(_CountingService()).start()
        try:
            cli = RpcClient(*srv.address)
            cli.fi_conf = conf
            t0 = time.monotonic()
            assert cli.call("bump") == 1
            assert time.monotonic() - t0 >= 0.14
            cli.close()
        finally:
            srv.stop()


# ------------------------------------------------ job id rebinding


class TestJobRebindServing:
    def test_rebound_outputs_serve_old_and_new_ids(self):
        """recover_job re-keys served outputs to the NEW job id, but a
        reducer ADOPTED across the restart keeps fetching with the OLD
        id — the serving lookup must follow the rebinding both ways or
        every adopted reducer's fetch misses and healthy maps get
        withdrawn."""
        from tpumr.mapred.tasktracker import NodeRunner
        nr = object.__new__(NodeRunner)
        nr.lock = threading.RLock()
        nr.map_outputs = {("job_old_0001", 0): ("/p", {"attempt": "a"})}
        nr._job_rebinds = {}
        nr._apply_action({"type": "recover_job", "old": "job_old_0001",
                          "new": "job_new_0001"})
        assert ("job_new_0001", 0) in nr.map_outputs
        assert ("job_old_0001", 0) not in nr.map_outputs
        # new-id reducers hit directly; adopted old-id reducers hit
        # through the rebinding; strangers still miss
        assert nr._map_output_entry("job_new_0001", 0) is not None
        assert nr._map_output_entry("job_old_0001", 0) is not None
        assert nr._map_output_entry("job_other_0001", 0) is None
        assert nr._map_output_entry("job_old_0001", 9) is None


# ------------------------------------------------ tracker lost-master


class TestTrackerLostMaster:
    def test_tracker_survives_restart_and_is_adopted(self, tmp_path):
        """A real NodeRunner rides out a master stop/start on the same
        port: lost-master state while down (no reinit, no task kill),
        adopted on re-contact, flag cleared."""
        from tpumr.mapred.tasktracker import NodeRunner
        conf = JobConf()
        conf.set("tpumr.history.dir", str(tmp_path))
        conf.set("tpumr.heartbeat.interval.ms", 50)
        conf.set("tpumr.tracker.expiry.ms", 60_000)
        m1 = JobMaster(conf).start()
        host, port = m1.address
        tconf = JobConf(conf)
        nr = NodeRunner(host, port, tconf, name="tt0").start()
        try:
            deadline = time.monotonic() + 5
            while "tt0" not in m1.trackers \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert "tt0" in m1.trackers
            m1.stop()
            deadline = time.monotonic() + 10
            while not nr.master_unreachable \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert nr.master_unreachable, \
                "tracker must enter the lost-master state"
            # restart on the SAME address; the tracker re-joins alone
            m2 = None
            for _ in range(100):
                try:
                    m2 = JobMaster(conf, host=host, port=port).start()
                    break
                except OSError:
                    time.sleep(0.05)
            assert m2 is not None
            try:
                deadline = time.monotonic() + 15
                while "tt0" not in m2.trackers \
                        and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert "tt0" in m2.trackers
                deadline = time.monotonic() + 5
                while nr.master_unreachable \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert not nr.master_unreachable
                assert m2.metrics.snapshot()["jobtracker"][
                    "trackers_adopted"] >= 1
            finally:
                m2.stop()
        finally:
            nr.stop()


# ------------------------------------------------------------ chaos e2e


def _write_input(fs, path, lines=3000):
    fs.write_bytes(path, b"".join(b"w%02d x\n" % (i % 31)
                                  for i in range(lines)))


def _read_output(fs, outdir):
    return b"".join(fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(outdir),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))


def _restart_cluster_conf(tmp_path):
    conf = JobConf()
    conf.set("tpumr.history.dir", str(tmp_path / "history"))
    conf.set("mapred.jobtracker.restart.recover", True)
    conf.set("mapred.jobtracker.restart.recovery.grace.ms", 800)
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    conf.set("tpumr.rpc.client.retries", 2)
    conf.set("tpumr.rpc.client.backoff.ms", 50)
    conf.set("tpumr.shuffle.fetch.retries.per.source", 1)
    conf.set("tpumr.shuffle.copy.backoff.ms", 10)
    conf.set("tpumr.shuffle.copy.backoff.max.ms", 100)
    conf.set("mapred.max.fetch.failures.per.map", 2)
    return conf


def _submit_wordcount(cluster, inpath, outdir, n_maps=6, trace=False):
    from tpumr.mapred.job_client import JobClient
    conf = cluster.create_job_conf()
    conf.set_input_paths(inpath)
    conf.set_output_path(outdir)
    conf.set("mapred.mapper.class", "tpumr.mapred.lib.TokenCountMapper")
    conf.set("mapred.reducer.class", "tpumr.examples.basic.LongSumReducer")
    conf.set("mapred.map.tasks", n_maps)
    conf.set_num_reduce_tasks(2)
    conf.set("mapred.reduce.slowstart.completed.maps", 1.0)
    conf.set("mapred.speculative.execution", False)
    if trace:
        conf.set("tpumr.trace.enabled", True)
    client = JobClient(conf)
    return client.submit_job(conf)


def _poll_status(running, deadline_s=60.0):
    """Status poll that rides out the restart window."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            return running.status()
        except Exception:  # noqa: BLE001 — master restarting
            time.sleep(0.05)
    raise TimeoutError("master never answered a status poll")


def _wait_maps(running, n, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        st = _poll_status(running)
        if st["finished_maps"] >= n:
            return st
        time.sleep(0.005)
    raise TimeoutError(f"never reached {n} finished maps")


def _wait_terminal(running, deadline_s=90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        st = _poll_status(running)
        if st["state"] in ("SUCCEEDED", "FAILED", "KILLED"):
            return st
        time.sleep(0.05)
    raise TimeoutError("job never finished")


def _kill_and_restart_master(cluster):
    """Abrupt master death (no finalization, no goodbye — the
    in-process stand-in for SIGKILL) + restart on the same address
    with recovery on."""
    host, port = cluster.master.address
    cluster.master.stop()
    m2 = None
    for _ in range(200):
        try:
            m2 = JobMaster(cluster.conf, host=host, port=port).start()
            break
        except OSError:
            time.sleep(0.05)
    assert m2 is not None, "could not rebind the master port"
    cluster.master = m2   # cluster shutdown now stops the new master
    return m2


class TestEndToEndRestartChaos:
    def setup_method(self):
        fi.reset()

    def teardown_method(self):
        fi.reset()
        FileSystem.clear_cache()

    def _control_output(self, cluster_conf_factory):
        """The same job on an undisturbed cluster — the byte-identity
        reference."""
        from tpumr.mapred.mini_cluster import MiniMRCluster
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=cluster_conf_factory()) as c:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/restart/in-control.txt")
            running = _submit_wordcount(c, "mem:///restart/in-control.txt",
                                        "mem:///restart/out-control")
            st = _wait_terminal(running)
            assert st["state"] == "SUCCEEDED"
            return _read_output(fs, "/restart/out-control")

    def test_master_killed_mid_job_finishes_with_zero_map_reruns(
            self, tmp_path):
        """THE acceptance e2e: all (or most) maps done, reduces not yet
        run, master SIGKILLed and restarted with recovery on → the job
        finishes with byte-identical output, attempts_recovered > 0,
        trackers adopted, and ZERO map re-executions."""
        from tpumr.mapred.mini_cluster import MiniMRCluster
        control = self._control_output(
            lambda: _restart_cluster_conf(tmp_path / "control"))
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_restart_cluster_conf(tmp_path)) as c:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/restart/in.txt")
            running = _submit_wordcount(c, "mem:///restart/in.txt",
                                        "mem:///restart/out",
                                        trace=True)
            old_id = running.job_id
            # 6 maps over 4 slots: kill the master once the first wave
            # folded (≥4 done) while the second wave is in flight and
            # the reduces (slowstart=1.0) have not been assigned
            _wait_maps(running, 4)
            m2 = _kill_and_restart_master(c)
            st = _wait_terminal(running)
            assert st["state"] == "SUCCEEDED", st
            new_id = running.job_id
            assert new_id != old_id, "polling client must follow the " \
                                     "recovered id"
            assert m2.get_recovered_jobs()[old_id] == new_id
            out = _read_output(fs, "/restart/out")
            assert out == control, "output must be byte-identical"
            snap = m2.metrics.snapshot()["jobtracker"]
            assert snap["jobs_recovered"] == 1
            assert snap["attempts_recovered"] >= 4
            assert snap["trackers_adopted"] >= 2
            # ZERO map re-executions by the restarted master: counters…
            assert snap.get("maps_launched_cpu", 0) == 0
            assert snap.get("maps_launched_tpu", 0) == 0
            assert snap.get("maps_reexecuted_fetch_failure", 0) == 0
            # …and the history agrees (no post-restart map TASK_STARTED)
            hist = JobHistory(c.conf)
            events = hist.read(os.path.join(
                str(tmp_path / "history"), f"{new_id}.jsonl"))
            started_maps = [e for e in events
                            if e.get("event") == "TASK_STARTED"
                            and "_m_" in str(e.get("attempt_id", ""))]
            assert started_maps == []
            # task-attempt continuity: the job completed on attempts
            # minted under the OLD id (recovered + adopted in flight)
            jip = m2.jobs[new_id]
            winners = {t.successful_attempt for t in jip.maps}
            assert all(f"_{JobID.parse(old_id).cluster}_" in w
                       for w in winners), winners
            # post-restart merged trace (CI artifact): spans exist for
            # the recovered job and the file is valid chrome-trace JSON
            from tpumr.core import tracing
            trace = m2.get_job_trace(new_id)
            assert trace["spans"], "recovered job must be traced"
            chrome = tracing.to_chrome_trace(trace["spans"])
            with open(RESTART_TRACE_OUT, "w") as f:
                json.dump(chrome, f)
            assert os.path.getsize(RESTART_TRACE_OUT) > 0

    def test_lost_recovered_output_reruns_exactly_that_map(
            self, tmp_path):
        """Second acceptance e2e: one recovered map output is gone
        after the restart (disk died with the crash). The PR-1
        fetch-failure protocol re-executes exactly that map; everything
        else stays recovered."""
        from tpumr.mapred.mini_cluster import MiniMRCluster
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_restart_cluster_conf(tmp_path)) as c:
            fs = get_filesystem("mem:///")
            _write_input(fs, "/restart2/in.txt")
            running = _submit_wordcount(c, "mem:///restart2/in.txt",
                                        "mem:///restart2/out")
            old_id = running.job_id
            _wait_maps(running, 4)
            m2 = _kill_and_restart_master(c)
            # vaporize ONE recovered output before any reduce fetches
            # it (reduces are held by slowstart + the recovery grace):
            # the entry may still be keyed by the old id (rebind not
            # yet delivered) — try both
            new_id = m2.get_recovered_jobs()[old_id]
            popped = None
            for tr in c.trackers:
                with tr.lock:
                    for key in ((old_id, 0), (new_id, 0)):
                        if key in tr.map_outputs:
                            popped = tr.map_outputs.pop(key)
                            break
                if popped:
                    break
            assert popped is not None, "map 0's recovered output " \
                                       "should exist on some tracker"
            st = _wait_terminal(running)
            assert st["state"] == "SUCCEEDED", st
            out = _read_output(fs, "/restart2/out")
            counts = dict(line.split(b"\t") for line in out.splitlines())
            assert counts[b"x"] == b"3000"
            assert counts[b"w00"] == b"97"
            snap = m2.metrics.snapshot()["jobtracker"]
            # exactly ONE map came back from the dead the hard way
            assert snap["maps_reexecuted_fetch_failure"] == 1
            assert snap.get("maps_launched_cpu", 0) == 1
            jip = m2.jobs[new_id]
            assert sum(t.failures for t in jip.maps) == 1
