"""Real object-store client (tpumr/fs/gcs.py ≈ S3FileSystem.java:50).

The loopback emulator below speaks just enough of the GCS JSON API
(storage/v1 objects: media upload/download, metadata GET, DELETE, list
with prefix + pagination) that the FULL stdlib HTTP client runs against
it — wire path, auth header, pagination and 404 mapping all exercised
with zero credentials and zero egress. A live-bucket integration test
runs only when TPUMR_GCS_TEST_BUCKET is set (and is skipped otherwise),
keeping emulation the default exactly like the in-tree backend."""

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpumr.fs import get_filesystem
from tpumr.fs.filesystem import FileSystem
from tpumr.mapred.jobconf import JobConf


class _FakeGcs(BaseHTTPRequestHandler):
    """One-bucket GCS JSON API emulator over an in-memory dict."""

    store: dict = {}          # key -> bytes
    auth_seen: list = []
    page_size = 2             # tiny, so pagination is actually exercised

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code, body=b"", ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _meta(self, key):
        return {"name": key, "size": str(len(self.store[key])),
                "updated": "2026-07-31T12:00:00Z"}

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        self.auth_seen.append(self.headers.get("Authorization"))
        if parsed.path.startswith("/upload/storage/v1/b/"):
            key = q["name"]
            length = int(self.headers.get("Content-Length", 0))
            self.store[key] = self.rfile.read(length)
            self._send(200, json.dumps(self._meta(key)).encode())
        else:
            self._send(404)

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        self.auth_seen.append(self.headers.get("Authorization"))
        parts = parsed.path.split("/")
        # /storage/v1/b/<bucket>/o            -> list
        # /storage/v1/b/<bucket>/o/<object>   -> media or metadata
        if len(parts) >= 6 and parts[5] == "o" and len(parts) == 6:
            keys = sorted(k for k in self.store
                          if k.startswith(q.get("prefix", "")))
            start = int(q.get("pageToken", 0))
            page = keys[start:start + self.page_size]
            body = {"items": [self._meta(k) for k in page]}
            if start + self.page_size < len(keys):
                body["nextPageToken"] = str(start + self.page_size)
            self._send(200, json.dumps(body).encode())
            return
        if len(parts) >= 7 and parts[5] == "o":
            key = urllib.parse.unquote(parts[6])
            if key not in self.store:
                self._send(404)
            elif q.get("alt") == "media":
                self._send(200, self.store[key],
                           ctype="application/octet-stream")
            else:
                self._send(200, json.dumps(self._meta(key)).encode())
            return
        self._send(404)

    def do_DELETE(self):
        parsed = urllib.parse.urlparse(self.path)
        key = urllib.parse.unquote(parsed.path.split("/")[6])
        if self.store.pop(key, None) is None:
            self._send(404)
        else:
            self._send(204)


@pytest.fixture()
def fake_gcs():
    _FakeGcs.store = {}
    _FakeGcs.auth_seen = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGcs)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()
    FileSystem.clear_cache()


def _conf(endpoint):
    conf = JobConf()
    conf.set("fs.gs.endpoint", endpoint)
    conf.set("fs.gs.auth.token", "test-token-123")
    return conf


class TestGcsJsonBackend:
    def test_blob_roundtrip_and_404_mapping(self, fake_gcs):
        from tpumr.fs.gcs import GcsJsonBackend
        b = GcsJsonBackend("bkt", _conf(fake_gcs))
        b.put("a/b.txt", b"hello")
        assert b.get("a/b.txt") == b"hello"
        assert b.exists("a/b.txt") and not b.exists("a/nope")
        size, mtime = b.head("a/b.txt")
        assert size == 5 and mtime > 0
        with pytest.raises(FileNotFoundError):
            b.get("missing")
        assert b.delete("a/b.txt") is True
        assert b.delete("a/b.txt") is False
        # every request carried the bearer token
        assert all(a == "Bearer test-token-123"
                   for a in _FakeGcs.auth_seen)

    def test_list_paginates(self, fake_gcs):
        from tpumr.fs.gcs import GcsJsonBackend
        b = GcsJsonBackend("bkt", _conf(fake_gcs))
        for i in range(5):
            b.put(f"p/{i}", bytes([i]))
        b.put("other/x", b"x")
        got = sorted(k for k, _, _ in b.list("p/"))
        assert got == [f"p/{i}" for i in range(5)]  # 3 pages of 2

    def test_full_fs_layer_over_real_client(self, fake_gcs):
        """The gs:// FileSystem (dir markers, rename, listing) over the
        HTTP client — the same SPI surface the emulation backend gets."""
        conf = _conf(fake_gcs)
        fs = get_filesystem("gs://bkt/", conf)
        fs.write_bytes("gs://bkt/d/one.txt", b"1")
        fs.write_bytes("gs://bkt/d/two.txt", b"22")
        names = sorted(s.path.name for s in fs.list_status("gs://bkt/d"))
        assert names == ["one.txt", "two.txt"]
        assert fs.read_bytes("gs://bkt/d/two.txt") == b"22"
        assert fs.rename("gs://bkt/d/one.txt", "gs://bkt/d/uno.txt")
        assert not fs.exists("gs://bkt/d/one.txt")
        assert fs.read_bytes("gs://bkt/d/uno.txt") == b"1"

    def test_distcp_local_to_gs(self, fake_gcs, tmp_path):
        """The VERDICT r4 #6 'done' bar: tpumr distcp local→gs://
        through the REAL client wire path."""
        from tpumr.tools.distcp import distcp
        (tmp_path / "src").mkdir()
        (tmp_path / "src" / "f1.txt").write_bytes(b"alpha")
        (tmp_path / "src" / "sub").mkdir()
        (tmp_path / "src" / "sub" / "f2.txt").write_bytes(b"beta")
        conf = _conf(fake_gcs)
        # distcp work dir must not land in the object store (gs:// temp
        # promote is copy-heavy); use local scratch like an operator would
        conf.set("tpumr.distcp.work", str(tmp_path / "work"))
        assert distcp(f"file://{tmp_path}/src", "gs://bkt/dest",
                      conf=conf)
        fs = get_filesystem("gs://bkt/", conf)
        assert fs.read_bytes("gs://bkt/dest/f1.txt") == b"alpha"
        assert fs.read_bytes("gs://bkt/dest/sub/f2.txt") == b"beta"

    def test_no_backend_error_is_actionable(self, monkeypatch):
        FileSystem.clear_cache()
        conf = JobConf()   # no emulation dir, no token, no endpoint
        monkeypatch.delenv("GCS_OAUTH_TOKEN", raising=False)
        # on an actual GCE/TPU VM the metadata server WOULD mint a token
        # and construction would rightly succeed — pin the no-credential
        # scenario instead of depending on where the suite runs
        from tpumr.fs import gcs
        monkeypatch.setattr(gcs.TokenProvider, "token", lambda self: None)
        with pytest.raises(ValueError, match="fs.gs.emulation.dir|token"):
            get_filesystem("gs://bkt/x", conf)
        FileSystem.clear_cache()


@pytest.mark.skipif(not os.environ.get("TPUMR_GCS_TEST_BUCKET"),
                    reason="live-GCS integration needs "
                           "TPUMR_GCS_TEST_BUCKET + credentials")
def test_live_bucket_roundtrip(tmp_path):
    """Against a real bucket (run manually where credentials exist)."""
    bucket = os.environ["TPUMR_GCS_TEST_BUCKET"]
    conf = JobConf()
    fs = get_filesystem(f"gs://{bucket}/", conf)
    key = f"gs://{bucket}/tpumr-it/probe.txt"
    fs.write_bytes(key, b"tpumr")
    try:
        assert fs.read_bytes(key) == b"tpumr"
    finally:
        fs.delete(key)
