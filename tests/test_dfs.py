"""tdfs tests ≈ the reference's MiniDFSCluster-based HDFS suite
(TestDFSShell/TestReplication/TestRestartDFS/TestCheckpoint/
TestBalancer, SURVEY.md §4.2): real NN+DN daemons over localhost RPC."""

import time

import pytest

from tpumr.dfs.mini_cluster import MiniDFSCluster
from tpumr.fs import get_filesystem
from tpumr.mapred.jobconf import JobConf


def small_conf(block_size=1024, replication=2):
    conf = JobConf()
    conf.set("dfs.block.size", block_size)
    conf.set("dfs.replication", replication)
    conf.set("tdfs.replication.interval.s", 0.2)
    conf.set("tdfs.datanode.expiry.s", 1.5)
    return conf


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3, conf=small_conf()) as c:
        yield c


def test_write_read_multiblock(cluster):
    client = cluster.client()
    data = bytes(range(256)) * 20  # 5120 B -> 5 blocks of 1 KiB
    with client.create("/a/b/data.bin") as f:
        f.write(data)
    st = client.get_status("/a/b/data.bin")
    assert st["length"] == len(data)
    with client.open("/a/b/data.bin") as f:
        assert f.read() == data
    # mid-file seek lands on the right block/offset
    with client.open("/a/b/data.bin") as f:
        f.seek(1500)
        assert f.read(600) == data[1500:2100]
    blocks = client.nn.call("get_block_locations", "/a/b/data.bin")
    assert len(blocks) == 5
    assert all(len(b["locations"]) >= 1 for b in blocks)


def test_filesystem_spi(cluster):
    fs = get_filesystem(cluster.uri + "/")
    fs.write_bytes(cluster.uri + "/spi/x.txt", b"hello tdfs")
    assert fs.read_bytes(cluster.uri + "/spi/x.txt") == b"hello tdfs"
    assert fs.exists(cluster.uri + "/spi/x.txt")
    fs.mkdirs(cluster.uri + "/spi/sub")
    names = {st.path.name for st in fs.list_status(cluster.uri + "/spi")}
    assert names == {"x.txt", "sub"}
    assert fs.rename(cluster.uri + "/spi/x.txt", cluster.uri + "/spi/y.txt")
    assert not fs.exists(cluster.uri + "/spi/x.txt")
    locs = fs.get_block_locations(cluster.uri + "/spi/y.txt", 0, 10)
    assert locs and locs[0].hosts
    assert fs.delete(cluster.uri + "/spi", recursive=True)
    assert not fs.exists(cluster.uri + "/spi/y.txt")


def test_lease_single_writer(cluster):
    client = cluster.client()
    f = client.create("/lease/file")
    f.write(b"x")
    other = cluster.client()
    from tpumr.ipc.rpc import RpcError
    with pytest.raises(RpcError, match="already being created"):
        other.create("/lease/file")
    f.close()
    # after close the lease is released; overwrite is allowed
    with other.create("/lease/file") as g:
        g.write(b"y")


def test_corrupt_replica_failover(cluster):
    client = cluster.client()
    with client.create("/corrupt/f", replication=2) as f:
        f.write(b"Z" * 900)
    blk = client.nn.call("get_block_locations", "/corrupt/f")[0]
    assert len(blk["locations"]) == 2
    # corrupt the copy on the first replica
    victim_addr = blk["locations"][0]
    victim = next(dn for dn in cluster.datanodes if dn.addr == victim_addr)
    path = victim.store._path(blk["block_id"])
    raw = bytearray(open(path, "rb").read())
    raw[10] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    # read still succeeds through the healthy replica
    with client.open("/corrupt/f") as f:
        assert f.read() == b"Z" * 900


def test_replication_on_datanode_death():
    with MiniDFSCluster(num_datanodes=3, conf=small_conf()) as c:
        client = c.client()
        with client.create("/repl/f", replication=2) as f:
            f.write(b"R" * 2500)
        blocks = client.nn.call("get_block_locations", "/repl/f")
        # kill a node holding the first block
        dead_addr = blocks[0]["locations"][0]
        dead = next(dn for dn in c.datanodes if dn.addr == dead_addr)
        dead.stop()
        deadline = time.time() + 15
        while time.time() < deadline:
            blocks = client.nn.call("get_block_locations", "/repl/f")
            live = [b for b in blocks
                    if dead_addr not in b["locations"]
                    and len(b["locations"]) >= 2]
            if len(live) == len(blocks):
                break
            time.sleep(0.2)
        else:
            pytest.fail(f"blocks not re-replicated: {blocks}")
        with client.open("/repl/f") as f:
            assert f.read() == b"R" * 2500


def test_namenode_restart_recovers_namespace():
    with MiniDFSCluster(num_datanodes=2, conf=small_conf()) as c:
        client = c.client()
        with client.create("/persist/f") as f:
            f.write(b"P" * 3000)
        client.mkdirs("/persist/dir")
        c.restart_namenode()
        client2 = c.client()
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                if not client2.nn.call("safemode", "get"):
                    break
            except Exception:
                pass
            time.sleep(0.2)
        else:
            pytest.fail("NameNode stuck in safemode after restart")
        st = client2.get_status("/persist/f")
        assert st["length"] == 3000
        assert client2.exists("/persist/dir")
        with client2.open("/persist/f") as f:
            assert f.read() == b"P" * 3000


def test_secondary_checkpoint():
    import os
    from tpumr.dfs.editlog import list_segments
    from tpumr.dfs.secondary import SecondaryNameNode
    with MiniDFSCluster(num_datanodes=1,
                        conf=small_conf(replication=1)) as c:
        client = c.client()
        for i in range(5):
            with client.create(f"/ckpt/f{i}") as f:
                f.write(b"data")
        name_dir = os.path.join(c.root, "name")

        def journal_bytes():
            return sum(os.path.getsize(p) for p in list_segments(name_dir))

        assert journal_bytes() > 0
        snn = SecondaryNameNode(c.nn_host, c.nn_port,
                                os.path.join(c.root, "secondary"))
        snn.do_checkpoint()
        # merged segments purged; namespace survives restart from image
        assert journal_bytes() == 0
        with client.create("/ckpt/after") as f:
            f.write(b"post-checkpoint")
        c.restart_namenode()
        client2 = c.client()
        time.sleep(0.8)
        assert client2.exists("/ckpt/f4")
        assert client2.exists("/ckpt/after")


def test_balancer_spreads_blocks():
    from tpumr.dfs.balancer import Balancer
    from tpumr.dfs.datanode import DataNode
    conf = small_conf(replication=1)
    conf.set("tdfs.datanode.capacity", 200_000)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        with client.create("/bal/big", replication=1) as f:
            f.write(b"B" * 20_000)  # 20 blocks, all on dn0
        dn1 = DataNode(c.nn_host, c.nn_port, f"{c.root}/data-extra",
                       conf).start()
        c.datanodes.append(dn1)
        time.sleep(0.5)
        moved = Balancer(c.nn_host, c.nn_port, threshold=0.02).balance()
        assert moved > 0
        time.sleep(1.0)  # let delete commands drain at the source
        assert dn1.store.used() > 0
        with client.open("/bal/big") as f:
            assert f.read() == b"B" * 20_000


def test_mapreduce_on_tdfs():
    """WordCount end-to-end with job input AND output on tdfs — the
    storage-slice/execution-runtime integration (≈ TestMiniMRWithDFS)."""
    from tpumr.mapred.job_client import JobClient

    conf = small_conf(block_size=512, replication=2)
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        fs = get_filesystem(c.uri + "/")
        fs.write_bytes(c.uri + "/wc/in.txt", b"dfs tpu dfs\ntpu dfs mr\n" * 40)
        jc = JobConf()
        jc.set_input_paths(c.uri + "/wc/in.txt")
        jc.set_output_path(c.uri + "/wc/out")
        jc.set("mapred.mapper.class", "tests.test_mini_cluster.WordCountMapper")
        jc.set("mapred.reducer.class", "tests.test_mini_cluster.SumReducer")
        jc.set_num_reduce_tasks(1)
        result = JobClient(jc).run_job(jc)
        assert result.successful
        out = {}
        for st in fs.list_files(c.uri + "/wc/out"):
            if st.path.name.startswith("part-"):
                for line in fs.read_bytes(st.path).decode().splitlines():
                    k, v = line.split("\t")
                    out[k] = int(v)
        assert out == {"dfs": 120, "tpu": 80, "mr": 40}


# ----------------------------------------------------- hardening (round 2)


def test_fsck_reports_under_replicated_and_missing(tmp_path):
    """≈ NamenodeFsck: healthy → under-replicated (DN death) → healthy
    again after re-replication... then missing when all replicas die."""
    conf = small_conf(replication=2)
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        client = c.client()
        with client.create("/fsck/f", replication=2) as f:
            f.write(b"j" * 3000)  # 3 blocks
        r = client.fsck("/")
        assert r["healthy"] and r["files"] == 1 and r["blocks"] == 3
        assert not r["under_replicated"] and not r["missing"]

        c.datanodes[0].stop()
        deadline = time.time() + 10
        while time.time() < deadline:
            r = client.fsck("/fsck")
            if r["under_replicated"] or r["missing"]:
                break
            time.sleep(0.2)
        assert r["under_replicated"], r
        assert r["healthy"]  # degraded but nothing lost

        c.datanodes[1].stop()
        deadline = time.time() + 10
        while time.time() < deadline:
            r = client.fsck("/")
            if len(r["missing"]) == 3:
                break
            time.sleep(0.2)
        assert len(r["missing"]) == 3
        assert not r["healthy"]


def test_permissions_owner_mode_enforced(tmp_path):
    """Owner/mode checks ≈ FSPermissionChecker: a non-owner cannot write
    into a 0755 dir, delete another user's file, or chmod it; the owner
    and the superuser can."""
    from tpumr.ipc.rpc import RpcError
    from tpumr.security import UserGroupInformation

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        alice = UserGroupInformation("alice")
        bob = UserGroupInformation("bob")

        client = c.client()
        # root is superuser-owned 0755 (like a formatted HDFS namespace):
        # the admin provisions the user's home, like `hadoop fs -mkdir
        # /home/alice && -chown alice` — alice alone could not
        with bob.do_as():
            with pytest.raises(RpcError, match="PermissionError"):
                client.mkdirs("/home/bob")
        client.mkdirs("/home/alice")
        client.set_owner("/home/alice", "alice")
        with alice.do_as():
            with client.create("/home/alice/secret") as f:
                f.write(b"mine")
            st = client.get_status("/home/alice")
            assert st["owner"] == "alice"

        with bob.do_as():
            with pytest.raises(RpcError, match="PermissionError"):
                client.create("/home/alice/intruder").close()
            with pytest.raises(RpcError, match="PermissionError"):
                client.delete("/home/alice/secret")
            with pytest.raises(RpcError, match="PermissionError"):
                client.nn.call("set_permission", "/home/alice/secret", 0o777)

        # owner chmods the dir open, bob can now create
        with alice.do_as():
            client.nn.call("set_permission", "/home/alice", 0o777)
        with bob.do_as():
            client.create("/home/alice/guestbook").close()
            st = client.get_status("/home/alice/guestbook")
            assert st["owner"] == "bob"

        # superuser (the test process user running the NN) bypasses all
        client.delete("/home/alice/secret")
        assert not client.exists("/home/alice/secret")


def test_permission_read_denied(tmp_path):
    from tpumr.ipc.rpc import RpcError
    from tpumr.security import UserGroupInformation

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        alice = UserGroupInformation("alice")
        bob = UserGroupInformation("bob")
        client.mkdirs("/p")
        client.set_permission("/p", 0o777)
        with alice.do_as():
            with client.create("/p/private") as f:
                f.write(b"top secret")
            client.nn.call("set_permission", "/p/private", 0o600)
        with bob.do_as():
            with pytest.raises(RpcError, match="PermissionError"):
                with client.open("/p/private") as f:
                    f.read()
        with alice.do_as():
            with client.open("/p/private") as f:
                assert f.read() == b"top secret"


def test_edit_log_segments_stay_bounded(tmp_path):
    """Size-bounded journal ≈ FSEditLog roll semantics: segments roll at
    the configured size; a checkpoint purges merged segments so the
    journal never grows without bound; state survives restart."""
    import os

    from tpumr.dfs.editlog import list_segments
    from tpumr.dfs.namenode import FSNamesystem

    conf = small_conf()
    conf.set("tdfs.edits.segment.mb", 2 / 1024)  # 2 KiB segments
    name_dir = str(tmp_path / "name")
    ns = FSNamesystem(name_dir, conf)
    for i in range(200):
        ns.mkdirs(f"/d{i:04d}")
    segs = list_segments(name_dir)
    assert len(segs) > 2, "journal never rolled"
    assert all(os.path.getsize(s) < 4096 for s in segs[:-1])

    before = ns.edits.total_bytes()
    ns.save_namespace()
    assert ns.edits.total_bytes() < before / 10, "checkpoint did not purge"

    # restart from image + remaining segments: nothing lost
    ns.edits.close()
    ns2 = FSNamesystem(name_dir, conf)
    assert sum(1 for p in ns2.namespace if p.startswith("/d")) == 200


def test_secondary_checkpoint_with_segments(tmp_path):
    """The 2NN cycle over the segmented journal: fetch seals segments,
    upload purges exactly those; edits during the cycle survive."""
    from tpumr.dfs.secondary import SecondaryNameNode

    conf = small_conf(replication=1)
    conf.set("tdfs.edits.segment.mb", 2 / 1024)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        for i in range(60):
            client.mkdirs(f"/pre{i}")
        host, port = c.namenode.address
        snn = SecondaryNameNode(host, port, str(tmp_path / "2nn"), conf=conf)
        snn.do_checkpoint()
        for i in range(5):
            client.mkdirs(f"/post{i}")
        # restart the namesystem from disk: both epochs present
        from tpumr.dfs.namenode import FSNamesystem
        c.namenode.ns.edits.close()
        ns2 = FSNamesystem(c.namenode.ns.name_dir, conf)
        assert "/pre59" in ns2.namespace
        assert "/post4" in ns2.namespace


def test_edit_log_torn_tail_recovery(tmp_path):
    """A crash mid-append leaves a torn last line; recovery must not
    append new ops AFTER the torn fragment (they would be skipped on the
    next replay while later segments still apply)."""
    from tpumr.dfs.namenode import FSNamesystem

    conf = small_conf()
    name_dir = str(tmp_path / "name")
    ns = FSNamesystem(name_dir, conf)
    ns.mkdirs("/before")
    seg = ns.edits.path
    ns.edits.close()
    with open(seg, "ab") as f:  # simulate the crash: torn tail
        f.write(b'{"op":"mkd')

    ns2 = FSNamesystem(name_dir, conf)
    assert "/before" in ns2.namespace
    assert ns2.edits.path != seg, "reopened the torn segment for append"
    ns2.mkdirs("/after")
    ns2.edits.close()

    ns3 = FSNamesystem(name_dir, conf)
    assert "/before" in ns3.namespace and "/after" in ns3.namespace


def test_stale_secondary_upload_refused(tmp_path):
    """Two overlapping checkpoint cycles: the superseded fetch's upload
    must be refused (its merged image does not cover the later sealed
    segments — accepting it would purge un-merged edits)."""
    from tpumr.ipc.rpc import RpcError

    import os

    from tpumr.dfs.secondary import SecondaryNameNode

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        client.mkdirs("/t1")
        stale = client.nn.call("get_name_state")  # secondary A's fetch
        client.mkdirs("/t2")
        # secondary B runs a full (correctly merged) cycle and wins
        snn = SecondaryNameNode(c.nn_host, c.nn_port,
                                os.path.join(c.root, "2nn-b"), conf=conf)
        snn.do_checkpoint()
        # A's upload is from a superseded fetch: must be refused — its
        # image covers neither /t2 nor even /t1's merge
        with pytest.raises(RpcError, match="superseded"):
            client.nn.call("put_image", stale["image"], stale["token"])
        # nothing lost: restart replays image + surviving segments
        from tpumr.dfs.namenode import FSNamesystem
        c.namenode.ns.edits.close()
        ns2 = FSNamesystem(c.namenode.ns.name_dir, conf)
        assert "/t1" in ns2.namespace and "/t2" in ns2.namespace


def test_owner_can_overwrite_in_readonly_dir(tmp_path):
    """create(overwrite) is a truncate, not an unlink: the file owner may
    overwrite their own writable file even when the parent dir denies
    them write (HDFS startFile semantics)."""
    from tpumr.security import UserGroupInformation

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        bob = UserGroupInformation("bob")
        client.mkdirs("/ro")
        client.set_permission("/ro", 0o777)
        with bob.do_as():
            with client.create("/ro/own") as f:
                f.write(b"v1")
        client.set_permission("/ro", 0o755)  # dir now read-only for bob
        with bob.do_as():
            with client.create("/ro/own", overwrite=True) as f:
                f.write(b"v2")
            with client.open("/ro/own") as f:
                assert f.read() == b"v2"


def test_namespace_and_space_quotas(tmp_path):
    """≈ TestQuota: dfsadmin-set quotas reject namespace/space overruns
    with actionable errors; clearing restores writes."""
    from tpumr.ipc.rpc import RpcError

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        client.mkdirs("/q")
        client.nn.call("set_quota", "/q", 3, None)   # max 3 inodes
        client.create("/q/a").close()
        client.create("/q/b").close()
        client.create("/q/c").close()
        with pytest.raises(RpcError, match="namespace quota"):
            client.create("/q/d").close()
        client.nn.call("set_quota", "/q", -1, None)  # clear
        client.create("/q/d").close()

        # space quota: 1 block of 1024 x rep 1 fits, the second doesn't
        client.mkdirs("/sq")
        client.nn.call("set_quota", "/sq", None, 1500)
        with pytest.raises(RpcError, match="space quota"):
            with client.create("/sq/big") as f:
                f.write(b"B" * 3000)  # needs 3 blocks


def test_decommission_drains_and_completes():
    """≈ TestDecommission: a draining node takes no new replicas, its
    blocks are copied off, and it reaches 'decommissioned'."""
    conf = small_conf(replication=2)
    with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
        client = c.client()
        with client.create("/dec/f", replication=2) as f:
            f.write(b"D" * 2500)
        blocks = client.nn.call("get_block_locations", "/dec/f")
        victim = blocks[0]["locations"][0]
        state = client.nn.call("set_decommission", victim, "start")
        assert state == "decommissioning"

        deadline = time.time() + 20
        while time.time() < deadline:
            report = {d["addr"]: d.get("state")
                      for d in client.datanode_report() if "addr" in d}
            if report.get(victim) == "decommissioned":
                break
            time.sleep(0.3)
        else:
            pytest.fail(f"never decommissioned: {report}")
        # every block now fully replicated on the OTHER nodes
        for blk in client.nn.call("get_block_locations", "/dec/f"):
            others = [a for a in blk["locations"] if a != victim]
            assert len(others) >= 2, blk
        with client.open("/dec/f") as f:
            assert f.read() == b"D" * 2500


def test_block_scanner_detects_and_heals_corruption():
    """≈ DataBlockScanner: background CRC sweep finds a silently corrupted
    replica, reports it, and the NameNode re-replicates from a good copy."""
    conf = small_conf(replication=2)
    conf.set("tdfs.datanode.scan.period.s", 0)  # drive scan_once manually
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        client = c.client()
        with client.create("/scan/f", replication=2) as f:
            f.write(b"S" * 900)
        blk = client.nn.call("get_block_locations", "/scan/f")[0]
        victim_addr = blk["locations"][0]
        victim = next(dn for dn in c.datanodes if dn.addr == victim_addr)
        path = victim.store._path(blk["block_id"])
        raw = bytearray(open(path, "rb").read())
        raw[5] ^= 0xFF
        open(path, "wb").write(bytes(raw))

        bad = victim.scan_once()
        assert bad == [blk["block_id"]]
        # NN dropped the corrupt replica and re-replicates to the victim
        # (the only other node) — eventually 2 healthy replicas again
        deadline = time.time() + 15
        while time.time() < deadline:
            locs = client.nn.call("get_block_locations",
                                  "/scan/f")[0]["locations"]
            if len(locs) == 2 and victim.scan_once() == []:
                break
            time.sleep(0.3)
        else:
            pytest.fail("corrupt replica never healed")
        with client.open("/scan/f") as f:
            assert f.read() == b"S" * 900


def test_quota_rename_and_setrep_and_intermediates(tmp_path):
    """Review regressions: renames charge the destination quota (exempting
    quota dirs that already contain the source), replication increases
    charge space quotas, and implicit intermediate dirs count."""
    from tpumr.ipc.rpc import RpcError

    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        # intermediates: quota 3, create /q/a/b/c/f needs 4 inodes
        client.mkdirs("/q")
        client.nn.call("set_quota", "/q", 3, None)
        with pytest.raises(RpcError, match="namespace quota"):
            client.create("/q/a/b/c/f").close()
        # rename INTO a full quota dir rejected
        client.create("/q/x").close()
        client.create("/q/y").close()
        client.mkdirs("/outside")
        client.create("/outside/z1").close()
        client.create("/outside/z2").close()
        with pytest.raises(RpcError, match="namespace quota"):
            client.rename("/outside", "/q/moved")
        # rename WITHIN the quota dir is net-zero and allowed
        assert client.rename("/q/x", "/q/x2")
        # space quota blocks raising replication
        client.mkdirs("/sp")
        client.nn.call("set_quota", "/sp", None, 2000)
        with client.create("/sp/f", replication=1) as f:
            f.write(b"Q" * 1024)
        time.sleep(0.3)  # block sizes reported
        with pytest.raises(RpcError, match="space quota"):
            client.set_replication("/sp/f", 3)


def test_decommission_survives_namenode_restart():
    conf = small_conf(replication=2)
    with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
        client = c.client()
        victim = c.datanodes[0].addr
        client.nn.call("set_decommission", victim, "start")
        c.restart_namenode()
        client2 = c.client()
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                report = {d["addr"]: d.get("state")
                          for d in client2.datanode_report()
                          if "addr" in d}
                if victim in report:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert report.get(victim, "in-service") != "in-service", report


def test_quota_usage_cache_stays_consistent(tmp_path):
    """The incremental quota counters (review: no full-namespace scan per
    write) must agree with a from-scratch recount after a workout of
    creates, writes, renames, deletes, and replication changes."""
    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        client = c.client()
        client.mkdirs("/w")
        client.nn.call("set_quota", "/w", 1000, 10_000_000)
        client.mkdirs("/w/sub")
        client.nn.call("set_quota", "/w/sub", 500, None)
        for i in range(4):
            with client.create(f"/w/sub/f{i}", replication=1) as f:
                f.write(b"x" * (700 + i * 400))  # multi-block sizes
        client.rename("/w/sub/f0", "/w/f0-moved")
        client.delete("/w/sub/f1")
        client.set_replication("/w/sub/f2", 2)
        client.mkdirs("/w/deep/a/b")
        client.rename("/w/deep", "/w/deeper")

        ns = c.namenode.ns
        with ns.lock:
            for qpath, cached in ns._quota_usage.items():
                actual = list(ns._subtree_usage(qpath))
                assert cached == actual, \
                    f"{qpath}: cached {cached} != recount {actual}"


def test_dead_draining_node_never_reports_decommissioned():
    """Review regression: a node that dies mid-drain must stay
    'decommissioning' — reporting it decommissioned invites wiping the
    only copy of its blocks."""
    conf = small_conf(replication=1)
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        client = c.client()
        with client.create("/dd/f", replication=1) as f:
            f.write(b"x" * 900)
        blk = client.nn.call("get_block_locations", "/dd/f")[0]
        victim = next(dn for dn in c.datanodes
                      if dn.addr == blk["locations"][0])
        victim.stop()  # the ONLY replica's host dies...
        client.nn.call("set_decommission", victim.addr, "start")
        time.sleep(2.5)  # expiry + several monitor sweeps
        state = c.namenode.ns.decommissioning.get(victim.addr)
        assert state == "decommissioning", state


def test_trash_emptier_runs_on_namenode(tmp_path):
    """≈ Trash.Emptier: the NN monitor checkpoints every user's
    /user/<u>/.Trash/Current and expunges aged checkpoints."""
    conf = small_conf(replication=1)
    conf.set("fs.trash.interval", 1 / 600)      # 0.1 s aging
    conf.set("fs.trash.checkpoint.interval.s", 0.3)
    conf.set("tdfs.replication.interval.s", 0.1)
    with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
        client = c.client()
        client.mkdirs("/user/alice/.Trash/Current/doomed")
        client.create("/user/alice/.Trash/Current/doomed/f").close()
        deadline = time.time() + 10
        while time.time() < deadline:
            ns = c.namenode.ns
            with ns.lock:
                paths = [p for p in ns.namespace
                         if p.startswith("/user/alice/.Trash")]
            # Current sealed into a checkpoint, checkpoint then expunged
            if paths == ["/user/alice/.Trash"]:
                break
            time.sleep(0.2)
        else:
            pytest.fail(f"emptier never cleaned: {paths}")


class TestAppend:
    """Block-granular append + hflush (≈ the dfs.support.append client
    path, hdfs/DFSClient.java; divergence documented in OPERATIONS.md)."""

    def test_append_extends_file(self, cluster):
        client = cluster.client()
        with client.create("/ap/log.txt") as f:
            f.write(b"first|")
        with client.append("/ap/log.txt") as f:
            f.write(b"second|")
        with client.append("/ap/log.txt") as f:
            f.write(b"third")
        with client.open("/ap/log.txt") as f:
            assert f.read() == b"first|second|third"
        assert client.get_status("/ap/log.txt")["length"] == \
            len(b"first|second|third")

    def test_append_multiblock_payload(self, cluster):
        client = cluster.client()
        base = bytes(range(256)) * 2            # 512 B
        more = b"Z" * 3000                      # > 2 blocks of 1 KiB
        with client.create("/ap/big.bin") as f:
            f.write(base)
        with client.append("/ap/big.bin") as f:
            f.write(more)
        with client.open("/ap/big.bin") as f:
            assert f.read() == base + more

    def test_hflush_publishes_to_concurrent_reader(self, cluster):
        client = cluster.client()
        writer = client.create("/ap/stream.log")
        writer.write(b"record-1\n")
        writer.hflush()
        # a second client reads everything up to the hflush while the
        # writer still holds the lease
        reader = cluster.client()
        with reader.open("/ap/stream.log") as f:
            assert f.read() == b"record-1\n"
        writer.write(b"record-2\n")             # buffered, NOT yet visible
        with reader.open("/ap/stream.log") as f:
            assert f.read() == b"record-1\n"
        writer.hflush()
        with reader.open("/ap/stream.log") as f:
            assert f.read() == b"record-1\nrecord-2\n"
        writer.close()
        with reader.open("/ap/stream.log") as f:
            assert f.read() == b"record-1\nrecord-2\n"

    def test_append_respects_single_writer_lease(self, cluster):
        client = cluster.client()
        with client.create("/ap/lease.txt") as f:
            f.write(b"x")
        w1 = client.append("/ap/lease.txt")
        other = cluster.client()
        from tpumr.ipc.rpc import RpcError
        with pytest.raises(RpcError, match="open for writing"):
            other.append("/ap/lease.txt")
        w1.close()
        # lease released on close: now the other client may append
        w2 = other.append("/ap/lease.txt")
        w2.write(b"y")
        w2.close()
        with client.open("/ap/lease.txt") as f:
            assert f.read() == b"xy"

    def test_append_close_does_not_inflate_block_count(self, cluster):
        # every append→close must add only the NEW blocks to the
        # safemode denominator; re-counting the whole list each close
        # inflates total_known_blocks and can wedge post-restart
        # safemode below threshold forever
        ns = cluster.namenode.ns
        client = cluster.client()
        with client.create("/ap/count.bin") as f:
            f.write(b"A" * 2500)                # 3 blocks of 1 KiB
        base = ns.total_known_blocks
        for i in range(3):                      # 3 cycles, 1 new block each
            with client.append("/ap/count.bin") as f:
                f.write(b"B" * 100)
        assert ns.total_known_blocks == base + 3
        actual = sum(len(i.get("blocks", []))
                     for i in ns.namespace.values()
                     if i.get("type") == "file")
        assert ns.total_known_blocks == actual

    def test_delete_and_rename_of_open_file_keep_count_exact(self, cluster):
        # deleting a file open for append must remove exactly its
        # COUNTED blocks from the denominator (post-open blocks were
        # never added); renaming one must move its counted-entry so the
        # eventual close settles under the new path
        ns = cluster.namenode.ns
        client = cluster.client()

        def actual():
            return sum(len(i.get("blocks", []))
                       for i in ns.namespace.values()
                       if i.get("type") == "file" and not i.get("uc")) \
                + sum(ns._uc_counted.get(p, 0) for p, i in
                      ns.namespace.items() if i.get("uc"))

        with client.create("/acc/del.bin") as f:
            f.write(b"D" * 2500)                 # 3 counted blocks
        w = client.append("/acc/del.bin")        # _uc_counted = 3
        w.write(b"E" * 1500)                     # ~2 new, uncounted
        w.hflush()
        base = ns.total_known_blocks
        ns._delete_impl("/acc/del.bin", recursive=False)
        assert ns.total_known_blocks == base - 3
        assert "/acc/del.bin" not in ns._uc_counted

        with client.create("/acc/mv.bin") as f:
            f.write(b"F" * 2500)
        w2 = client.append("/acc/mv.bin")
        w2.write(b"G" * 100)
        w2.hflush()
        ns.rename("/acc/mv.bin", "/acc/mv2.bin")
        assert "/acc/mv.bin" not in ns._uc_counted
        assert ns._uc_counted.get("/acc/mv2.bin") == 3
        assert ns.total_known_blocks == actual()

    def test_append_survives_namenode_restart(self):
        conf = small_conf()
        with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
            client = c.client()
            with client.create("/ap/r.txt") as f:
                f.write(b"aa")
            with client.append("/ap/r.txt") as f:
                f.write(b"bb")
            c.restart_namenode()
            client2 = c.client()
            deadline = time.time() + 15
            while time.time() < deadline:   # wait out safemode + reports
                try:
                    if not client2.nn.call("safemode", "get"):
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            with client2.open("/ap/r.txt") as f:
                assert f.read() == b"aabb"


class TestStreamedTransfer:
    """Chunked block transfer (≈ DataTransferProtocol streaming,
    BlockSender/BlockReceiver): payloads ride bounded chunks in both
    directions, never whole blocks per RPC response."""

    def _conf(self, chunk=512):
        conf = small_conf(block_size=8192)
        conf.set("tdfs.client.write.chunk.bytes", chunk)
        conf.set("tdfs.client.read.chunk.bytes", chunk)
        return conf

    def test_streamed_write_read_roundtrip(self):
        """Blocks far larger than the chunk size stream through the
        open/chunk/commit pipeline and back through chunked reads."""
        import os as _os
        with MiniDFSCluster(num_datanodes=2, conf=self._conf()) as c:
            client = c.client()
            payload = _os.urandom(3 * 8192 + 777)   # 4 blocks, 16 chunks each
            with client.create("/st/big.bin") as f:
                f.write(payload)
            with client.open("/st/big.bin") as f:
                assert f.read() == payload
            # replication happened through the streamed pipeline: every
            # datanode holds every block
            blocks = client.nn.call("get_block_locations", "/st/big.bin")
            for blk in blocks:
                assert len(blk["locations"]) == 2, blk

    def test_chunked_read_range_checksum(self):
        """Corrupting ONE CRC chunk fails only range reads covering it;
        the client fails over to the good replica and reports the bad
        one."""
        import os as _os
        with MiniDFSCluster(num_datanodes=2, conf=self._conf()) as c:
            client = c.client()
            payload = bytes(range(256)) * 1024      # 256 KiB, multi CRC-chunk
            with client.create("/st/c.bin", replication=2) as f:
                f.write(payload)
            blk = client.nn.call("get_block_locations", "/st/c.bin")[0]
            # flip a byte INSIDE the first replica's block file
            victim = sorted(blk["locations"])[0]
            dn = next(d for d in c.datanodes if d.addr == victim)
            path = dn.store._path(blk["block_id"])
            with open(path, "r+b") as f:
                f.seek(100)
                b = f.read(1)
                f.seek(100)
                f.write(bytes([b[0] ^ 0xFF]))
            with client.open("/st/c.bin") as f:
                assert f.read() == payload          # failover, not garbage

    def test_abandoned_stream_purged(self):
        """An upload whose client died is aborted by the stale sweep —
        temp files don't accumulate."""
        import os as _os
        conf = self._conf()
        conf.set("tdfs.upload.stale.s", 0.2)
        conf.set("tdfs.datanode.heartbeat.s", 0.1)
        with MiniDFSCluster(num_datanodes=1, conf=conf) as c:
            dn = c.datanodes[0]
            dn.open_block_stream(987654, [])
            dn.write_block_chunk(987654, b"half a block")
            tmp = dn.store._path(987654) + ".tmp"
            assert _os.path.exists(tmp)
            deadline = time.time() + 10
            while _os.path.exists(tmp) and time.time() < deadline:
                time.sleep(0.1)
            assert not _os.path.exists(tmp), "stale upload never purged"
            assert 987654 not in dn._uploads


class TestDfsRefreshNodes:
    """≈ FSNamesystem.refreshNodes: dfs.hosts / dfs.hosts.exclude drive
    datanode admission and decommissioning (dfsadmin -refreshNodes)."""

    def test_exclude_starts_drain_and_unexclude_stops(self, tmp_path):
        excl = tmp_path / "dfs-exclude.txt"
        excl.write_text("")
        conf = small_conf()
        conf.set("dfs.hosts.exclude", str(excl))
        with MiniDFSCluster(num_datanodes=2, conf=conf,
                            root=str(tmp_path / "c")) as c:
            addr = c.datanodes[0].addr
            excl.write_text(addr.split(":")[0] + "\n")
            r = c.namenode.ns.refresh_nodes()
            # both datanodes share 127.0.0.1, so both start draining —
            # host-granular lists, like the reference's
            assert all(v == "decommissioning"
                       for v in r["changed"].values())
            assert c.namenode.ns.decommissioning
            excl.write_text("")
            r = c.namenode.ns.refresh_nodes()
            assert all(v == "in-service" for v in r["changed"].values())
            assert not c.namenode.ns.decommissioning

    def test_refresh_without_lists_keeps_manual_drains(self, tmp_path):
        """Documented divergence: with NO hosts files configured, a
        refresh must not cancel addr-keyed manual drains."""
        with MiniDFSCluster(num_datanodes=2, conf=small_conf(),
                            root=str(tmp_path / "c")) as c:
            addr = c.datanodes[0].addr
            c.namenode.ns.set_decommission(addr, "start")
            r = c.namenode.ns.refresh_nodes()
            assert r["changed"] == {}
            assert c.namenode.ns.decommissioning.get(addr) \
                == "decommissioning"

    def test_not_in_include_refused_at_registration(self, tmp_path):
        inc = tmp_path / "dfs-include.txt"
        inc.write_text("allowedhost\n")
        conf = small_conf()
        conf.set("dfs.hosts", str(inc))
        from tpumr.dfs.namenode import NameNode
        nn = NameNode(str(tmp_path / "name"), conf).start()
        try:
            with pytest.raises(PermissionError, match="not in the "
                               "dfs.hosts include"):
                nn.ns.register_datanode("127.0.0.1:7777", 1 << 20)
            nn.ns.register_datanode("allowedhost:7777", 1 << 20)
            assert "allowedhost:7777" in nn.ns.datanodes
        finally:
            nn.stop()

    def test_excluded_host_registers_then_drains(self, tmp_path):
        excl = tmp_path / "dfs-exclude.txt"
        excl.write_text("drainhost\n")
        conf = small_conf()
        conf.set("dfs.hosts.exclude", str(excl))
        from tpumr.dfs.namenode import NameNode
        nn = NameNode(str(tmp_path / "name"), conf).start()
        try:
            nn.ns.register_datanode("drainhost:7777", 1 << 20)
            assert nn.ns.decommissioning.get("drainhost:7777") \
                == "decommissioning"
        finally:
            nn.stop()

    def test_hosts_file_reference_grammar(self, tmp_path):
        """HostsFileReader grammar: whitespace-separated tokens, a
        '#' token ends its line."""
        from tpumr.utils.hostsfile import read_hosts_file
        p = tmp_path / "hosts.txt"
        p.write_text("hostA hostB\nhostC  # drained 2026-07\n"
                     "# full comment line\n  hostD\n")
        assert read_hosts_file(p) == {"hostA", "hostB", "hostC", "hostD"}

    def test_dead_mid_drain_node_never_marked_decommissioned(self,
                                                             tmp_path):
        """A dead decommissioning node must not flip to 'decommissioned'
        on refresh — its blocks were never confirmed safe."""
        inc = tmp_path / "dfs-include.txt"
        inc.write_text("someotherhost\n")
        conf = small_conf()
        from tpumr.dfs.namenode import NameNode
        nn = NameNode(str(tmp_path / "name"), conf).start()
        try:
            # a drain recorded for a node that is NOT registered (died)
            nn.ns.set_decommission("deadhost:1234", "start")
            conf.set("dfs.hosts", str(inc))
            r = nn.ns.refresh_nodes()
            assert "deadhost:1234" not in r["changed"]
            assert nn.ns.decommissioning["deadhost:1234"] \
                == "decommissioning"
        finally:
            nn.stop()
