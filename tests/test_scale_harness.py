"""Control-plane scale harness (tpumr/scale/) + master saturation
observability: the instrumented master lock, RPC inflight accounting,
heartbeat lag/phase series, completion-event feed lag, trace-volume
controls, and the simulated-tracker fleet driving the REAL heartbeat
wire path end-to-end (acceptance: the saturation series render and
validate on a live JobTracker's /metrics/prom)."""

import json
import threading
import time
import urllib.request

import pytest

from tpumr.ipc.rpc import RpcClient, RpcServer
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.metrics.core import MetricsRegistry
from tpumr.metrics.locks import InstrumentedRLock
from tpumr.scale import ScaleDriver, SimFleet, SimTracker


def fetch(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.getcode(), r.read().decode("utf-8")


# ------------------------------------------------------------ lock


class TestInstrumentedRLock:
    def test_wait_and_hold_recorded(self):
        wait = MetricsRegistry("x").histogram("w")
        hold = MetricsRegistry("x").histogram("h")
        lock = InstrumentedRLock(wait, hold)
        with lock:
            time.sleep(0.02)
        assert wait.count == 1 and hold.count == 1
        assert hold.max >= 0.015
        assert wait.max < 0.015  # uncontended: no queueing

        # contention: a second thread must observe real wait time
        def contender():
            with lock:
                pass

        with lock:
            t = threading.Thread(target=contender)
            t.start()
            time.sleep(0.03)
        t.join()
        # main thread's second acquire + the contender's contended one
        assert wait.count == 3
        assert wait.max >= 0.02

    def test_reentrant_acquire_measures_outermost_hold_only(self):
        wait = MetricsRegistry("x").histogram("w")
        hold = MetricsRegistry("x").histogram("h")
        lock = InstrumentedRLock(wait, hold)
        with lock:
            with lock:          # re-entrant: no extra wait/hold sample
                time.sleep(0.01)
        assert wait.count == 1
        assert hold.count == 1
        assert hold.max >= 0.008

    def test_unbound_lock_works_and_binds_later(self):
        lock = InstrumentedRLock()
        with lock:
            pass
        h = MetricsRegistry("x").histogram("h")
        lock.bind(MetricsRegistry("x").histogram("w"), h)
        with lock:
            pass
        assert h.count == 1


# ------------------------------------------------------------ rpc server


class _MixedService:
    def get_protocol_version(self):
        return 1

    def echo(self, x):
        return x

    def slow(self, t):
        time.sleep(t)
        return "ok"


class TestRpcServerConcurrency:
    """Satellite: parallel in-flight requests observe correct
    rpc_inflight accounting, and the per-method latency histograms stay
    bounded to the handler's REAL method surface under concurrent
    mixed-method load (bogus method names must not mint series)."""

    def test_inflight_peak_and_return_to_zero(self):
        reg = MetricsRegistry("rpc")
        srv = RpcServer(_MixedService()).start()
        srv.metrics = reg
        try:
            n = 6
            barrier = threading.Barrier(n)
            errors = []

            def worker(i):
                cli = RpcClient(*srv.address)
                try:
                    barrier.wait(timeout=5)
                    if i % 3 == 0:
                        cli.call("echo", i)
                    cli.call("slow", 0.15)
                    # unknown + private methods error server-side but
                    # must not create latency series
                    with pytest.raises(Exception):
                        cli.call(f"no_such_method_{i}")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                finally:
                    cli.close()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert not errors
            # all n slow() calls overlapped on the barrier: the peak saw
            # the parallelism, and everything drained back to zero
            assert srv.inflight_peak() >= n - 1
            snap = reg.snapshot()
            assert snap["rpc_inflight"] == 0
            assert snap["rpc_inflight_peak"] >= n - 1
            # handler-thread gauge tracked the open connections
            assert snap["rpc_handler_threads"] >= 0
            # latency histograms exist ONLY for the real method surface
            hist_names = {name for name, v in snap.items()
                          if isinstance(v, dict) and "p99" in v}
            assert "rpc_slow" in hist_names
            assert "rpc_echo" in hist_names
            assert not [h for h in hist_names if "no_such_method" in h]
            # peak reads with reset=True re-arm the high-water mark
            assert srv.inflight_peak(reset=True) >= n - 1
            assert srv.inflight_peak() == 0
        finally:
            srv.stop()


# ------------------------------------------------------------ fleet e2e


def _master(extra=None):
    conf = JobConf()
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 30_000)
    for k, v in (extra or {}).items():
        conf.set(k, v)
    return JobMaster(conf).start()


class TestSimFleetEndToEnd:
    def test_fleet_drives_real_wire_heartbeats_and_jobs_complete(self):
        master = _master()
        host, port = master.address
        fleet = SimFleet(host, port, 4, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.05).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(2, 8, 2, timeout_s=30)
            assert not res["unfinished"] and not res["failed"], res
            snap = master.metrics.snapshot()
            jt = snap["jobtracker"]
            # master-side saturation series all populated
            assert jt["heartbeat_seconds"]["count"] > 0
            assert jt["heartbeat_lag_seconds"]["count"] > 0
            assert jt["jt_lock_wait_seconds"]["count"] > 0
            assert jt["jt_lock_hold_seconds"]["count"] > 0
            assert jt["completion_event_lag"]["count"] > 0
            for phase in ("fold", "assign"):
                assert jt[f"heartbeat_phase_seconds|phase={phase}"][
                    "count"] > 0, phase
            assert snap["scheduler"]["assign_seconds"]["count"] > 0
            # WIRE-LEVEL proof: the transport-side per-method histogram
            # only populates when heartbeats arrive as real RPC frames
            assert snap["rpc"]["rpc_heartbeat"]["count"] > 0
            assert snap["rpc"]["rpc_heartbeat_request_bytes"]["count"] > 0
            assert master._server.inflight_peak() >= 1
            # the sim trackers' metrics piggybacks merged cluster-side
            assert snap["cluster"]["sim_tasks_completed"] > 0
            fl = fleet.stats()
            assert fl["heartbeats"] > 0 and fl["hb_errors"] == 0
            assert fl["tasks_completed"] >= 2 * (8 + 2)
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_fetch_failure_injection_drives_master_protocol(self):
        master = _master()
        host, port = master.address
        fleet = SimFleet(host, port, 3, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.05,
                         fetch_failure_rate=1.0).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(1, 6, 3, timeout_s=45)
            assert not res["failed"], res
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap.get("fetch_failures_reported", 0) >= 1
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_prom_scrape_renders_and_validates_saturation_series(self):
        """Acceptance: jt_lock_wait_seconds, rpc_inflight,
        heartbeat_phase_seconds{phase=...}, heartbeat_lag_seconds render
        and validate on a live JobTracker's /metrics/prom."""
        from tpumr.metrics.prometheus import validate_exposition
        master = _master({"mapred.job.tracker.http.port": 0})
        host, port = master.address
        fleet = SimFleet(host, port, 3, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.04).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(1, 6, 1, timeout_s=30)
            assert not res["unfinished"] and not res["failed"], res
            code, body = fetch(master.http_url + "/metrics/prom")
            assert code == 200
            validate_exposition(body)
            for series in ("tpumr_jt_lock_wait_seconds_bucket",
                           "tpumr_jt_lock_hold_seconds_bucket",
                           "tpumr_heartbeat_lag_seconds_bucket",
                           "tpumr_completion_event_lag_bucket",
                           "tpumr_rpc_inflight{",
                           "tpumr_rpc_inflight_peak{",
                           "tpumr_rpc_handler_threads{"):
                assert series in body, series
            # the phase breakdown is ONE family with phase labels
            assert "# TYPE tpumr_heartbeat_phase_seconds histogram" \
                in body
            assert 'phase="fold"' in body and 'phase="assign"' in body
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_sim_tracker_honors_reinit_and_kill(self):
        master = _master()
        host, port = master.address
        t = SimTracker("solo", host, port, cpu_slots=1, reduce_slots=1)
        try:
            t.heartbeat_once()   # initial contact registers
            assert t.heartbeats == 1
            # master restart amnesia: evict it, next beat gets reinit
            with master.lock:
                master._evict_tracker_locked("solo")
            t.heartbeat_once()
            assert t._initial_contact is True and t._response_id == 0
            t.heartbeat_once()   # re-registers
            with master.lock:
                assert "solo" in master.trackers
        finally:
            t.close()
            master.stop()


# ------------------------------------------------------------ heartbeat spans


def _sim_status(name="t1"):
    return {"tracker_name": name, "host": "h1", "shuffle_addr": "h1:0",
            "shuffle_port": 0, "max_cpu_map_slots": 1,
            "max_tpu_map_slots": 0, "max_reduce_slots": 1,
            "count_cpu_map_tasks": 0, "count_tpu_map_tasks": 0,
            "count_reduce_tasks": 0, "available_tpu_devices": [],
            "task_statuses": [], "fetch_failures": [], "healthy": True}


class TestHeartbeatPhaseSpans:
    def test_master_records_phase_subspans_of_tracker_heartbeat(self):
        master = _master()
        try:
            status = _sim_status()
            status["trace"] = {"trace_id": "daemon-t1", "span_id": "ab12"}
            master.heartbeat(status, True, True, 0)
            spans = [s for s in master.tracer.pending()
                     if s.trace_id == "daemon-t1"]
            names = {s.name for s in spans}
            assert "heartbeat:fold" in names
            assert "heartbeat:assign" in names
            assert all(s.parent_span_id == "ab12" for s in spans)
            # and the context never leaks into the stored status
            with master.lock:
                assert "trace" not in master.trackers["t1"].status
        finally:
            master.stop()

    def test_untraced_heartbeat_records_no_spans(self):
        master = _master()
        try:
            master.heartbeat(_sim_status(), True, True, 0)
            assert master.tracer.pending() == []
        finally:
            master.stop()


# ------------------------------------------------------------ trace volume


class TestTraceVolumeControls:
    def test_sample_zero_mints_no_trace(self):
        master = _master({"tpumr.trace.enabled": True,
                          "tpumr.trace.sample": 0.0})
        try:
            jid = master.submit_job({"mapred.reduce.tasks": 1,
                                     "user.name": "u"}, [{}])
            jip = master.jobs[jid]
            assert jip.trace_id == "" and jip.trace_root is None
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap.get("traces_sampled_out", 0) == 1
        finally:
            master.stop()

    def test_sample_one_traces_and_job_conf_rate_wins(self):
        master = _master({"tpumr.trace.enabled": True,
                          "tpumr.trace.sample": 0.0})
        try:
            # the job conf's explicit rate overrides the master default
            jid = master.submit_job({"mapred.reduce.tasks": 1,
                                     "user.name": "u",
                                     "tpumr.trace.sample": 1.0}, [{}])
            assert master.jobs[jid].trace_id == jid
        finally:
            master.stop()

    def test_sample_rate_parsing(self):
        from tpumr.core.tracing import trace_sample_rate
        assert trace_sample_rate({"tpumr.trace.sample": "0.25"}) == 0.25
        assert trace_sample_rate({}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": "bogus"}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": 7}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": -3}) == 0.0

    def test_span_buffer_high_water_drops_oldest_bounded(self):
        from tpumr.core import tracing
        tracer = tracing.Tracer("t", trace_dir=None)
        tracer._flush_pending = True   # pin the flusher: pure cap test
        total = tracing.MAX_BUFFERED + 57
        for i in range(total):
            tracer.finish(tracer.start_span(f"s{i}", "tid"))
        assert len(tracer.pending()) == tracing.MAX_BUFFERED
        assert tracer.dropped == 57
        # oldest were shed, newest survived
        assert tracer.pending()[-1].name == f"s{total - 1}"


# ------------------------------------------------------------ prometheus


class TestLabeledFamilies:
    def test_extra_label_convention_renders_one_family(self):
        from tpumr.metrics.prometheus import (render_exposition,
                                              validate_exposition)
        reg = MetricsRegistry("jt")
        reg.histogram("hb_phase_seconds|phase=fold").observe(0.01)
        reg.histogram("hb_phase_seconds|phase=assign").observe(0.02)
        reg.incr("beats|kind=sim", 3)
        text = render_exposition({"jt": reg.typed_snapshot()})
        validate_exposition(text)
        assert text.count("# TYPE tpumr_hb_phase_seconds histogram") == 1
        assert 'phase="fold"' in text and 'phase="assign"' in text
        assert 'tpumr_beats{source="jt",kind="sim"} 3' in text


# ------------------------------------------------------------ bench


class TestBenchScale:
    def test_run_bench_rows_carry_required_series(self):
        import bench_scale
        # generous SLO: this test gates the ROW CONTRACT, not latency —
        # a loaded CI runner must not flake it on a wall-clock p99
        report = bench_scale.run_bench(fleets=[2, 3], interval_s=0.05,
                                       slo_s=30.0, wait_timeout_s=60)
        assert len(report["rows"]) == 2
        for row in report["rows"]:
            for key in ("heartbeat_p50_s", "heartbeat_p99_s",
                        "heartbeat_lag_p99_s", "lock_wait_p99_s",
                        "assign_p99_s", "rpc_inflight_peak",
                        "completed", "trackers"):
                assert key in row, key
            assert row["completed"], row
        assert report["max_sustainable_trackers"] == 3
        assert report["slo_series"] == ["heartbeat_p99_s",
                                        "heartbeat_lag_p99_s"]
