"""Control-plane scale harness (tpumr/scale/) + master saturation
observability: the instrumented master lock, RPC inflight accounting,
heartbeat lag/phase series, completion-event feed lag, trace-volume
controls, and the simulated-tracker fleet driving the REAL heartbeat
wire path end-to-end (acceptance: the saturation series render and
validate on a live JobTracker's /metrics/prom)."""

import json
import threading
import time
import urllib.request

import pytest

from tpumr.ipc.rpc import RpcClient, RpcServer
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.metrics.core import MetricsRegistry
from tpumr.metrics.locks import InstrumentedRLock
from tpumr.scale import ScaleDriver, SimFleet, SimTracker


def fetch(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.getcode(), r.read().decode("utf-8")


# ------------------------------------------------------------ lock


class TestInstrumentedRLock:
    def test_wait_and_hold_recorded(self):
        wait = MetricsRegistry("x").histogram("w")
        hold = MetricsRegistry("x").histogram("h")
        lock = InstrumentedRLock(wait, hold)
        with lock:
            time.sleep(0.02)
        assert wait.count == 1 and hold.count == 1
        assert hold.max >= 0.015
        assert wait.max < 0.015  # uncontended: no queueing

        # contention: a second thread must observe real wait time
        def contender():
            with lock:
                pass

        with lock:
            t = threading.Thread(target=contender)
            t.start()
            time.sleep(0.03)
        t.join()
        # main thread's second acquire + the contender's contended one
        assert wait.count == 3
        assert wait.max >= 0.02

    def test_reentrant_acquire_measures_outermost_hold_only(self):
        wait = MetricsRegistry("x").histogram("w")
        hold = MetricsRegistry("x").histogram("h")
        lock = InstrumentedRLock(wait, hold)
        with lock:
            with lock:          # re-entrant: no extra wait/hold sample
                time.sleep(0.01)
        assert wait.count == 1
        assert hold.count == 1
        assert hold.max >= 0.008

    def test_unbound_lock_works_and_binds_later(self):
        lock = InstrumentedRLock()
        with lock:
            pass
        h = MetricsRegistry("x").histogram("h")
        lock.bind(MetricsRegistry("x").histogram("w"), h)
        with lock:
            pass
        assert h.count == 1


# ------------------------------------------------------------ rpc server


class _MixedService:
    def get_protocol_version(self):
        return 1

    def echo(self, x):
        return x

    def slow(self, t):
        time.sleep(t)
        return "ok"


class TestRpcServerConcurrency:
    """Satellite: parallel in-flight requests observe correct
    rpc_inflight accounting, and the per-method latency histograms stay
    bounded to the handler's REAL method surface under concurrent
    mixed-method load (bogus method names must not mint series)."""

    def test_inflight_peak_and_return_to_zero(self):
        reg = MetricsRegistry("rpc")
        srv = RpcServer(_MixedService()).start()
        srv.metrics = reg
        try:
            n = 6
            barrier = threading.Barrier(n)
            errors = []

            def worker(i):
                cli = RpcClient(*srv.address)
                try:
                    barrier.wait(timeout=5)
                    if i % 3 == 0:
                        cli.call("echo", i)
                    cli.call("slow", 0.15)
                    # unknown + private methods error server-side but
                    # must not create latency series
                    with pytest.raises(Exception):
                        cli.call(f"no_such_method_{i}")
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                finally:
                    cli.close()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert not errors
            # all n slow() calls overlapped on the barrier: the peak saw
            # the parallelism, and everything drained back to zero
            assert srv.inflight_peak() >= n - 1
            snap = reg.snapshot()
            assert snap["rpc_inflight"] == 0
            assert snap["rpc_inflight_peak"] >= n - 1
            # handler-thread gauge tracked the open connections
            assert snap["rpc_handler_threads"] >= 0
            # latency histograms exist ONLY for the real method surface
            hist_names = {name for name, v in snap.items()
                          if isinstance(v, dict) and "p99" in v}
            assert "rpc_slow" in hist_names
            assert "rpc_echo" in hist_names
            assert not [h for h in hist_names if "no_such_method" in h]
            # peak reads with reset=True re-arm the high-water mark
            assert srv.inflight_peak(reset=True) >= n - 1
            assert srv.inflight_peak() == 0
        finally:
            srv.stop()


# ------------------------------------------------ reactor hardening


class TestReactorEdgeCases:
    """Satellite: the selector-reactor transport under hostile/unlucky
    connections — torn frames, resets between request and response,
    oversized frames, and handler-pool saturation. The loop must shrug
    each one off: later connections keep being served, and overload
    answers bounded backpressure instead of queueing without bound."""

    def _reactor_server(self, handler=None, fast=()):
        srv = RpcServer(handler or _MixedService(), reactor=True,
                        fast_methods=set(fast)).start()
        return srv

    def _alive(self, srv):
        cli = RpcClient(*srv.address)
        try:
            assert cli.call("echo", "ping") == "ping"
        finally:
            cli.close()

    def test_mid_frame_disconnect_leaves_server_serving(self):
        import socket
        import struct
        srv = self._reactor_server()
        try:
            host, port = srv.address
            # announce a 1000-byte frame, send 10 bytes, hang up
            s = socket.create_connection((host, port), timeout=5)
            s.sendall(struct.pack(">I", 1000) + b"x" * 10)
            s.close()
            time.sleep(0.1)
            self._alive(srv)
        finally:
            srv.stop()

    def test_reset_between_request_and_response(self):
        import socket
        from tpumr.io.writable import serialize
        import struct
        srv = self._reactor_server()
        try:
            host, port = srv.address
            # a well-formed slow request whose connection dies before
            # the response can be written back
            req = serialize({"id": 1, "method": "slow", "params": [0.2]})
            s = socket.create_connection((host, port), timeout=5)
            s.sendall(struct.pack(">I", len(req)) + req)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))   # RST on close
            s.close()
            time.sleep(0.4)   # the pooled handler writes into the void
            self._alive(srv)
        finally:
            srv.stop()

    def test_oversized_frame_rejected_without_allocation(self):
        import socket
        import struct
        srv = self._reactor_server()
        try:
            host, port = srv.address
            s = socket.create_connection((host, port), timeout=5)
            # length prefix far beyond MAX_FRAME: the reactor must drop
            # the connection on the prefix alone, never buffer toward it
            s.sendall(struct.pack(">I", 0xFFFFFFFE)[:4])
            s.sendall(b"y" * 64)
            time.sleep(0.1)
            # connection observably dead...
            s.settimeout(2)
            assert s.recv(1) == b""
            s.close()
            # ...server observably alive
            self._alive(srv)
        finally:
            srv.stop()

    def test_handler_pool_saturation_returns_backpressure(self):
        from tpumr.ipc.rpc import RpcError, _Reactor
        reg = MetricsRegistry("rpc")
        srv = self._reactor_server()
        srv.metrics = reg
        old_backlog = _Reactor.POOL_BACKLOG
        _Reactor.POOL_BACKLOG = 4
        srv._reactor.POOL_BACKLOG = 4
        try:
            n = 12
            barrier = threading.Barrier(n)
            results = {"ok": 0, "busy": 0, "other": []}
            rlock = threading.Lock()

            def worker():
                cli = RpcClient(*srv.address)
                try:
                    barrier.wait(timeout=5)
                    cli.call("slow", 0.3)
                    with rlock:
                        results["ok"] += 1
                except RpcError as e:
                    with rlock:
                        if "saturated" in str(e):
                            results["busy"] += 1
                        else:
                            results["other"].append(e)
                except Exception as e:  # noqa: BLE001
                    with rlock:
                        results["other"].append(e)
                finally:
                    cli.close()

            threads = [threading.Thread(target=worker) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
            assert not [t for t in threads if t.is_alive()], \
                "saturation must never deadlock callers"
            assert not results["other"], results["other"]
            # the pool (8 threads, backlog 4) absorbed some, pushed the
            # rest back IMMEDIATELY as busy errors — and nothing hung
            assert results["busy"] >= 1
            assert results["ok"] >= 4
            assert results["ok"] + results["busy"] == n
            assert reg.snapshot()["rpc_pool_saturated"] >= 1
            # after the storm the server serves normally again
            self._alive(srv)
        finally:
            _Reactor.POOL_BACKLOG = old_backlog
            srv.stop()


# ------------------------------------------------------------ fleet e2e


def _master(extra=None):
    conf = JobConf()
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 30_000)
    for k, v in (extra or {}).items():
        conf.set(k, v)
    return JobMaster(conf).start()


class TestSimFleetEndToEnd:
    def test_fleet_drives_real_wire_heartbeats_and_jobs_complete(self):
        master = _master()
        host, port = master.address
        fleet = SimFleet(host, port, 4, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.05,
                         piggyback_interval_s=0.05).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(2, 8, 2, timeout_s=30)
            assert not res["unfinished"] and not res["failed"], res
            snap = master.metrics.snapshot()
            jt = snap["jobtracker"]
            # master-side saturation series all populated — the lock
            # series are per decomposed lock class since PR 8
            assert jt["heartbeat_seconds"]["count"] > 0
            assert jt["heartbeat_lag_seconds"]["count"] > 0
            for lock in ("global", "trackers", "scheduler"):
                assert jt[f"jt_lock_wait_seconds|lock={lock}"][
                    "count"] > 0, lock
                assert jt[f"jt_lock_hold_seconds|lock={lock}"][
                    "count"] > 0, lock
            assert jt["completion_event_lag"]["count"] > 0
            for phase in ("fold", "assign"):
                assert jt[f"heartbeat_phase_seconds|phase={phase}"][
                    "count"] > 0, phase
            assert snap["scheduler"]["assign_seconds"]["count"] > 0
            # WIRE-LEVEL proof: the transport-side per-method histogram
            # only populates when heartbeats arrive as real RPC frames
            assert snap["rpc"]["rpc_heartbeat"]["count"] > 0
            assert snap["rpc"]["rpc_heartbeat_request_bytes"]["count"] > 0
            assert master._server.inflight_peak() >= 1
            # the sim trackers' metrics piggybacks merged cluster-side
            assert snap["cluster"]["sim_tasks_completed"] > 0
            fl = fleet.stats()
            assert fl["heartbeats"] > 0 and fl["hb_errors"] == 0
            assert fl["tasks_completed"] >= 2 * (8 + 2)
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_fetch_failure_injection_drives_master_protocol(self):
        master = _master()
        host, port = master.address
        fleet = SimFleet(host, port, 3, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.05,
                         fetch_failure_rate=1.0).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(1, 6, 3, timeout_s=45)
            assert not res["failed"], res
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap.get("fetch_failures_reported", 0) >= 1
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_prom_scrape_renders_and_validates_saturation_series(self):
        """Acceptance: jt_lock_wait_seconds, rpc_inflight,
        heartbeat_phase_seconds{phase=...}, heartbeat_lag_seconds render
        and validate on a live JobTracker's /metrics/prom."""
        from tpumr.metrics.prometheus import validate_exposition
        master = _master({"mapred.job.tracker.http.port": 0})
        host, port = master.address
        fleet = SimFleet(host, port, 3, interval_s=0.05, cpu_slots=2,
                         reduce_slots=1, task_time_mean_s=0.04).start()
        driver = ScaleDriver(host, port)
        try:
            res = driver.run_workload(1, 6, 1, timeout_s=30)
            assert not res["unfinished"] and not res["failed"], res
            code, body = fetch(master.http_url + "/metrics/prom")
            assert code == 200
            validate_exposition(body)
            for series in ("tpumr_jt_lock_wait_seconds_bucket",
                           "tpumr_jt_lock_hold_seconds_bucket",
                           "tpumr_heartbeat_lag_seconds_bucket",
                           "tpumr_completion_event_lag_bucket",
                           "tpumr_rpc_inflight{",
                           "tpumr_rpc_inflight_peak{",
                           "tpumr_rpc_handler_threads{"):
                assert series in body, series
            # the phase breakdown is ONE family with phase labels
            assert "# TYPE tpumr_heartbeat_phase_seconds histogram" \
                in body
            assert 'phase="fold"' in body and 'phase="assign"' in body
            # per-lock wait/hold of the decomposed master locks render
            # as ONE labeled family (satellite: the decomposition is
            # observable on /metrics/prom)
            assert "# TYPE tpumr_jt_lock_wait_seconds histogram" in body
            for lock in ("global", "trackers", "scheduler"):
                assert f'lock="{lock}"' in body, lock
        finally:
            fleet.stop()
            driver.close()
            master.stop()

    def test_sim_tracker_rejoins_after_eviction_without_reinit(self):
        master = _master()
        host, port = master.address
        t = SimTracker("solo", host, port, cpu_slots=1, reduce_slots=1)
        try:
            t.heartbeat_once()   # initial contact registers
            assert t.heartbeats == 1
            # master amnesia (eviction/restart): the next DELTA beat is
            # asked for a full re-send — no reinit, nothing dropped —
            # and the full beat after that is ADOPTED
            master._evict_tracker("solo")
            t.heartbeat_once()
            assert t._initial_contact is False, \
                "resend_full must not reset the tracker like reinit"
            assert "solo" not in master.trackers
            t.heartbeat_once()   # full status → adopted
            with master.lock:
                assert "solo" in master.trackers
            assert master.metrics.snapshot()["jobtracker"][
                "trackers_adopted"] == 1
        finally:
            t.close()
            master.stop()


# ------------------------------------------------------------ heartbeat spans


def _sim_status(name="t1"):
    return {"tracker_name": name, "host": "h1", "shuffle_addr": "h1:0",
            "shuffle_port": 0, "max_cpu_map_slots": 1,
            "max_tpu_map_slots": 0, "max_reduce_slots": 1,
            "count_cpu_map_tasks": 0, "count_tpu_map_tasks": 0,
            "count_reduce_tasks": 0, "available_tpu_devices": [],
            "task_statuses": [], "fetch_failures": [], "healthy": True}


class TestHeartbeatPhaseSpans:
    def test_master_records_phase_subspans_of_tracker_heartbeat(self):
        master = _master()
        try:
            status = _sim_status()
            status["trace"] = {"trace_id": "daemon-t1", "span_id": "ab12"}
            master.heartbeat(status, True, True, 0)
            spans = [s for s in master.tracer.pending()
                     if s.trace_id == "daemon-t1"]
            names = {s.name for s in spans}
            assert "heartbeat:fold" in names
            assert "heartbeat:assign" in names
            assert all(s.parent_span_id == "ab12" for s in spans)
            # and the context never leaks into the stored status
            with master.lock:
                assert "trace" not in master.trackers["t1"].status
        finally:
            master.stop()

    def test_untraced_heartbeat_records_no_spans(self):
        master = _master()
        try:
            master.heartbeat(_sim_status(), True, True, 0)
            assert master.tracer.pending() == []
        finally:
            master.stop()


# ------------------------------------------------------------ trace volume


class TestTraceVolumeControls:
    def test_sample_zero_mints_no_trace(self):
        master = _master({"tpumr.trace.enabled": True,
                          "tpumr.trace.sample": 0.0})
        try:
            jid = master.submit_job({"mapred.reduce.tasks": 1,
                                     "user.name": "u"}, [{}])
            jip = master.jobs[jid]
            assert jip.trace_id == "" and jip.trace_root is None
            snap = master.metrics.snapshot()["jobtracker"]
            assert snap.get("traces_sampled_out", 0) == 1
        finally:
            master.stop()

    def test_sample_one_traces_and_job_conf_rate_wins(self):
        master = _master({"tpumr.trace.enabled": True,
                          "tpumr.trace.sample": 0.0})
        try:
            # the job conf's explicit rate overrides the master default
            jid = master.submit_job({"mapred.reduce.tasks": 1,
                                     "user.name": "u",
                                     "tpumr.trace.sample": 1.0}, [{}])
            assert master.jobs[jid].trace_id == jid
        finally:
            master.stop()

    def test_sample_rate_parsing(self):
        from tpumr.core.tracing import trace_sample_rate
        assert trace_sample_rate({"tpumr.trace.sample": "0.25"}) == 0.25
        assert trace_sample_rate({}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": "bogus"}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": 7}) == 1.0
        assert trace_sample_rate({"tpumr.trace.sample": -3}) == 0.0

    def test_span_buffer_high_water_drops_oldest_bounded(self):
        from tpumr.core import tracing
        tracer = tracing.Tracer("t", trace_dir=None)
        tracer._flush_pending = True   # pin the flusher: pure cap test
        total = tracing.MAX_BUFFERED + 57
        for i in range(total):
            tracer.finish(tracer.start_span(f"s{i}", "tid"))
        assert len(tracer.pending()) == tracing.MAX_BUFFERED
        assert tracer.dropped == 57
        # oldest were shed, newest survived
        assert tracer.pending()[-1].name == f"s{total - 1}"


# ------------------------------------------------------------ delta protocol


class TestHeartbeatDelta:
    def test_delta_reconstruction_and_per_beat_keys(self):
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        master = _master()
        try:
            enc = HeartbeatEncoder(True)
            full = _sim_status("d1")
            r = master.heartbeat(enc.encode(dict(full)), True, False, 0)
            enc.delivered()
            assert master.trackers["d1"].status["host"] == "h1"
            # idle beat: near-empty wire dict
            wire = enc.encode(dict(full))
            assert wire.get("delta") is True
            assert set(wire) == {"tracker_name", "delta"}
            r = master.heartbeat(wire, False, False, r["response_id"])
            enc.delivered()
            stored = master.trackers["d1"].status
            # baseline keys inherited; per-beat keys are NOT
            assert stored["host"] == "h1"
            assert stored["max_cpu_map_slots"] == 1
            assert not stored.get("task_statuses")
            # a changed slot count rides the delta (and only it)
            full["max_cpu_map_slots"] = 5
            wire = enc.encode(dict(full))
            assert wire["max_cpu_map_slots"] == 5
            assert "host" not in wire
            master.heartbeat(wire, False, False, r["response_id"])
            enc.delivered()
            assert master.trackers["d1"].status[
                "max_cpu_map_slots"] == 5
        finally:
            master.stop()

    def test_unknown_delta_gets_resend_full(self):
        master = _master()
        try:
            resp = master.heartbeat(
                {"tracker_name": "ghost", "delta": True}, False, True, 7)
            # a baseline-less delta is asked for the full status — the
            # master can't use the delta, but unlike the old reinit
            # nothing on the tracker is killed
            assert resp["actions"] == [{"type": "resend_full"}]
            assert "ghost" not in master.trackers
        finally:
            master.stop()

    def test_failed_delivery_resets_to_full_status(self):
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        enc = HeartbeatEncoder(True)
        full = _sim_status("d2")
        enc.encode(dict(full))
        enc.delivered()
        assert enc.encode(dict(full)).get("delta") is True
        # an RPC failure leaves delivery unknown: next beat must be full
        enc.reset()
        wire = enc.encode(dict(full))
        assert "delta" not in wire and wire["host"] == "h1"

    def test_unchanged_metrics_piggyback_is_omitted(self):
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        enc = HeartbeatEncoder(True)
        full = _sim_status("d3")
        m = {"tasktracker": {"counters": {"x": 1}}}
        first = enc.encode(dict(full), m)
        assert first["metrics"] == m
        enc.delivered()
        assert "metrics" not in enc.encode(dict(full), m)
        # a delivered piggyback-less beat (the common case — piggyback
        # intervals are longer than heartbeat intervals) must not
        # clobber the baseline: the snapshot is STILL unchanged after
        enc.encode(dict(full), None)
        enc.delivered()
        assert "metrics" not in enc.encode(dict(full), m)
        changed = {"tasktracker": {"counters": {"x": 2}}}
        assert enc.encode(dict(full), changed)["metrics"] == changed

    def test_delta_disabled_sends_full_every_beat(self):
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        enc = HeartbeatEncoder(False)
        full = _sim_status("d4")
        for _ in range(2):
            wire = enc.encode(dict(full))
            enc.delivered()
            assert "delta" not in wire and wire["host"] == "h1"


# ------------------------------------------------------------ replay path


class TestReplayObservability:
    def test_replayed_beat_observes_phase_and_lag_series(self):
        """Satellite: a replayed heartbeat (stale response id) lands in
        heartbeat_lag_seconds AND heartbeat_phase_seconds{phase=replay},
        so replays are distinguishable from first deliveries."""
        master = _master()
        try:
            st = _sim_status("r1")
            r1 = master.heartbeat(dict(st), True, True, 0)
            r2 = master.heartbeat(dict(st), False, True,
                                  r1["response_id"])

            def jt():
                return master.metrics.snapshot()["jobtracker"]

            replays = jt().get("heartbeat_phase_seconds|phase=replay",
                               {}).get("count", 0)
            lags = jt()["heartbeat_lag_seconds"]["count"]
            # retry echoing the ALREADY-CONSUMED id: response was lost
            r3 = master.heartbeat(dict(st), False, True,
                                  r1["response_id"])
            assert r3 == r2            # stored actions replayed
            snap = jt()
            assert snap["heartbeat_phase_seconds|phase=replay"][
                "count"] == replays + 1
            assert snap["heartbeat_lag_seconds"]["count"] == lags + 1
        finally:
            master.stop()


# ------------------------------------------------------------ adaptive cadence


class TestAdaptiveCadence:
    def test_interval_scales_with_fleet_floor_and_cap(self):
        """max(floor, fleet/rate), capped: small fleets keep the
        configured floor; the instruction grows with registrations and
        never exceeds the cap."""
        master = _master({"tpumr.heartbeat.beats.per.second": 100,
                          "tpumr.heartbeat.interval.max.ms": 120})
        try:
            first = master.heartbeat(_sim_status("ac000"), True, False, 0)
            # one registered tracker: 1/100 s << the 50 ms floor
            assert first["next_interval_ms"] == 50
            for i in range(1, 20):
                master.heartbeat(_sim_status(f"ac{i:03d}"), True,
                                 False, 0)
            # 20 trackers at 100 beats/s wants 200 ms — the cap wins
            again = master.heartbeat(_sim_status("ac000"), False,
                                     False, first["response_id"])
            assert again["next_interval_ms"] == 120
            assert master._mreg.snapshot()[
                "heartbeat_interval_instructed_ms"] == 120
        finally:
            master.stop()

    def test_rate_zero_always_instructs_the_floor(self):
        master = _master()   # beats.per.second unset -> adaptation off
        try:
            for i in range(8):
                r = master.heartbeat(_sim_status(f"off{i}"), True,
                                     False, 0)
            assert r["next_interval_ms"] == 50
        finally:
            master.stop()

    def test_floor_above_cap_pins_the_cadence(self):
        master = _master({"tpumr.heartbeat.beats.per.second": 1,
                          "tpumr.heartbeat.interval.max.ms": 20})
        try:
            r = master.heartbeat(_sim_status("pin"), True, False, 0)
            # operator pinned a 50 ms floor above the 20 ms cap: the
            # floor wins (adaptation never speeds beats up)
            assert r["next_interval_ms"] == 50
        finally:
            master.stop()

    def test_replay_carries_current_interval(self):
        master = _master({"tpumr.heartbeat.beats.per.second": 2})
        try:
            r1 = master.heartbeat(_sim_status("rp"), True, True, 0)
            # mismatched response id -> the replay path must still
            # instruct the cadence (1 tracker / 2 per s = 500 ms)
            r2 = master.heartbeat(_sim_status("rp"), False, True, 999)
            assert r2["response_id"] == r1["response_id"]
            assert r2["next_interval_ms"] == 500
        finally:
            master.stop()

    def test_sim_tracker_honors_instructed_interval(self):
        master = _master({"tpumr.heartbeat.beats.per.second": 2})
        host, port = master.address
        tracker = SimTracker("ad0001", host, port)
        try:
            tracker.heartbeat_once()
            assert tracker.next_interval_s == 0.5
        finally:
            tracker.close()
            master.stop()

    def test_node_runner_honors_instructed_interval(self):
        """The REAL tracker reschedules its loop from the response —
        two runners at 4 beats/s aggregate settle on 500 ms beats."""
        from tpumr.mapred.mini_cluster import MiniMRCluster
        base = JobConf()
        base.set("tpumr.heartbeat.beats.per.second", 4)
        with MiniMRCluster(num_trackers=2, conf=base) as c:
            deadline = time.monotonic() + 15
            want = [0.5, 0.5]
            while time.monotonic() < deadline and \
                    [t.heartbeat_s for t in c.trackers] != want:
                time.sleep(0.05)
            assert [t.heartbeat_s for t in c.trackers] == want


# ------------------------------------------------------------ lock order


class TestLockOrdering:
    def test_descending_acquisition_raises_in_debug_mode(self):
        from tpumr.metrics import locks
        if not locks.ORDER_CHECK:
            pytest.skip("lock-order checking disabled")
        job = locks.InstrumentedRLock(name="job-x", rank=locks.RANK_JOB)
        sched = locks.InstrumentedRLock(name="scheduler",
                                        rank=locks.RANK_SCHEDULER)
        with sched:      # scheduler -> job: the documented legal order
            with job:
                pass
        with pytest.raises(AssertionError, match="lock-order violation"):
            with job:    # job -> scheduler: the deadlock direction
                with sched:
                    pass
        # the held stack unwound cleanly after the violation
        with sched:
            with job:
                pass

    def test_reentrancy_and_unranked_locks_exempt(self):
        from tpumr.metrics import locks
        job = locks.InstrumentedRLock(name="job-x", rank=locks.RANK_JOB)
        plain = locks.InstrumentedRLock()          # unranked: exempt
        with job:
            with job:      # same-lock re-entrancy always legal
                with plain:
                    pass


# ------------------------------------------------------------ event feed


class TestCompletionEventFeed:
    def test_cursor_reads_and_post_serve_backlog(self):
        from tpumr.mapred.job_in_progress import CompletionEventFeed
        feed = CompletionEventFeed()
        for i in range(10):
            feed.append({"map_index": i, "attempt_id": f"a{i}",
                         "shuffle_addr": "x", "status": "SUCCEEDED"})
        events, pending = feed.read(0, 4)
        assert [e["map_index"] for e in events] == [0, 1, 2, 3]
        assert pending == 6       # backlog AFTER the batch, not before
        events, pending = feed.read(4, 100)
        assert len(events) == 6 and pending == 0
        events, pending = feed.read(10, 5)
        assert events == [] and pending == 0
        events, _ = feed.read(-3, 2)     # clamped, not wrapped
        assert events[0]["map_index"] == 0
        # list-like surface the eviction/withdrawal paths rely on
        assert len(feed) == 10
        assert feed[3]["attempt_id"] == "a3"
        assert [e["map_index"] for e in feed][:3] == [0, 1, 2]


# ------------------------------------------------------------ stress


class TestLockDecompositionStress:
    def test_concurrent_folds_and_polls_no_deadlock_no_lost_status(self):
        """Satellite: N in-process trackers heartbeat concurrently into
        ONE job (half of them speaking delta) while pollers hammer
        get_map_completion_events — no deadlock, no lost terminal
        status, and every poller sees a monotone, self-consistent
        event feed."""
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        from tpumr.mapred.ids import TaskAttemptID
        from tpumr.mapred.task import TaskPhase, TaskState, TaskStatus

        n_maps, n_trackers, n_pollers = 48, 6, 3
        master = _master()
        jid = master.submit_job(
            {"user.name": "stress", "mapred.reduce.tasks": 0,
             "mapred.speculative.execution": False},
            [{} for _ in range(n_maps)])
        jip = master.jobs[jid]
        done = threading.Event()
        errors: list = []

        def tracker(i):
            enc = HeartbeatEncoder(enabled=(i % 2 == 0))
            name, rid, initial = f"st{i}", 0, True
            running: dict = {}
            try:
                deadline = time.monotonic() + 60
                while not done.is_set():
                    if time.monotonic() > deadline:
                        errors.append(f"{name}: never drained")
                        return
                    statuses = []
                    for aid in list(running):
                        a = TaskAttemptID.parse(aid)
                        statuses.append(TaskStatus(
                            attempt_id=a, is_map=True,
                            state=TaskState.SUCCEEDED, progress=1.0,
                            phase=TaskPhase.MAP,
                            finish_time=time.time()).to_dict())
                    full = dict(_sim_status(name), max_cpu_map_slots=2,
                                task_statuses=statuses)
                    resp = master.heartbeat(enc.encode(full), initial,
                                            True, rid)
                    enc.delivered()
                    initial = False
                    rid = resp["response_id"]
                    for sd in statuses:
                        running.pop(sd["attempt_id"], None)
                    for act in resp["actions"]:
                        if act["type"] == "launch":
                            running[act["task"]["attempt_id"]] = act
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        poller_seen = [0] * n_pollers

        def poller(pi):
            cursor, seen = 0, []
            try:
                while not done.is_set():
                    events = master.get_map_completion_events(
                        jid, cursor, 10)
                    # cursor-based serving: batches are contiguous and
                    # an index, once served, never changes identity
                    seen.extend(events)
                    cursor += len(events)
                    poller_seen[pi] = cursor
                    time.sleep(0.001)
                if len(seen) != n_maps:
                    errors.append(f"poller saw {len(seen)}/{n_maps}")
                if sorted(e["map_index"] for e in seen) != \
                        list(range(n_maps)):
                    errors.append("non-monotone/duplicated event feed")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=tracker, args=(i,))
                   for i in range(n_trackers)]
        threads += [threading.Thread(target=poller, args=(pi,))
                    for pi in range(n_pollers)]
        try:
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if jip.state != "RUNNING" and jip.finalized.is_set():
                    break
                time.sleep(0.01)
            # let every poller drain the tail (deterministically — a
            # fixed sleep flaked under ambient load)
            drain = time.monotonic() + 20
            while time.monotonic() < drain \
                    and min(poller_seen) < n_maps:
                time.sleep(0.01)
            done.set()
            for t in threads:
                t.join(timeout=30)
            assert not [t for t in threads if t.is_alive()], "deadlock"
            assert not errors, errors
            # no lost terminal status: every map completed exactly once
            assert jip.state == "SUCCEEDED"
            assert jip.finished_maps == n_maps
            assert all(t.state == "succeeded" for t in jip.maps)
            assert len(jip.completion_events) == n_maps
        finally:
            done.set()
            master.stop()


# ------------------------------------------------------------ delta e2e


class TestDeltaHeartbeatEndToEnd:
    def test_job_output_byte_identical_delta_on_vs_off(self):
        """Acceptance: wordcount over a real mini-cluster produces
        byte-identical output with delta heartbeats on vs off."""
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster

        def run(enabled):
            base = JobConf()
            base.set("tpumr.heartbeat.delta", enabled)
            with MiniMRCluster(num_trackers=2, conf=base) as c:
                fs = get_filesystem("mem:///")
                fs.write_bytes("/hd/in.txt",
                               b"".join(b"w%02d x\n" % (i % 23)
                                        for i in range(3000)))
                conf = c.create_job_conf()
                conf.set_input_paths("mem:///hd/in.txt")
                conf.set_output_path(f"mem:///hd/out-{enabled}")
                conf.set("mapred.mapper.class",
                         "tpumr.mapred.lib.TokenCountMapper")
                conf.set("mapred.reducer.class",
                         "tpumr.examples.basic.LongSumReducer")
                conf.set_num_reduce_tasks(2)
                conf.set("mapred.map.tasks", 4)
                conf.set("mapred.min.split.size", 1)
                result = JobClient(conf).run_job(conf)
                assert result.successful
                out = b"".join(
                    fs.read_bytes(st.path)
                    for st in sorted(
                        fs.list_status(f"/hd/out-{enabled}"),
                        key=lambda s: str(s.path))
                    if "part-" in str(st.path))
            FileSystem.clear_cache()
            return out

        assert run(True) == run(False)


# ------------------------------------------------------------ prometheus


class TestLabeledFamilies:
    def test_extra_label_convention_renders_one_family(self):
        from tpumr.metrics.prometheus import (render_exposition,
                                              validate_exposition)
        reg = MetricsRegistry("jt")
        reg.histogram("hb_phase_seconds|phase=fold").observe(0.01)
        reg.histogram("hb_phase_seconds|phase=assign").observe(0.02)
        reg.incr("beats|kind=sim", 3)
        text = render_exposition({"jt": reg.typed_snapshot()})
        validate_exposition(text)
        assert text.count("# TYPE tpumr_hb_phase_seconds histogram") == 1
        assert 'phase="fold"' in text and 'phase="assign"' in text
        assert 'tpumr_beats{source="jt",kind="sim"} 3' in text


# ------------------------------------------------------------ bench


class TestBenchScale:
    def test_run_bench_rows_carry_required_series(self):
        import bench_scale
        # generous SLO: this test gates the ROW CONTRACT, not latency —
        # a loaded CI runner must not flake it on a wall-clock p99
        report = bench_scale.run_bench(fleets=[2, 3], interval_s=0.05,
                                       slo_s=30.0, wait_timeout_s=60)
        assert len(report["rows"]) == 2
        for row in report["rows"]:
            for key in ("heartbeat_p50_s", "heartbeat_p99_s",
                        "heartbeat_lag_p99_s", "lock_wait_p99_s",
                        "lock_wait_share", "lock_wait_trackers_p99_s",
                        "lock_wait_scheduler_p99_s",
                        "assign_p99_s", "rpc_inflight_peak",
                        "interval_instructed_ms",
                        "completed", "trackers"):
                assert key in row, key
            assert row["completed"], row
        assert report["max_sustainable_trackers"] == 3
        assert report["slo_series"] == ["heartbeat_p99_s",
                                        "heartbeat_lag_p99_s"]
