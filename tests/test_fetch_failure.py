"""Lost-map-output recovery — the "too many fetch failures" protocol
(≈ ReduceTask fetch-failure notification → JobInProgress.
fetchFailureNotification → TaskCompletionEvent OBSOLETE): copier penalty
box + reporting, master-side distinct-reducer counting and map
re-execution, append-only OBSOLETE completion events, and the
end-to-end chaos run over a live mini-cluster."""

import threading
import time

import pytest

from tpumr.mapred.ids import JobID, TaskAttemptID
from tpumr.mapred.job_in_progress import JobInProgress, JobState
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.shuffle_copier import PenaltyBox, ShuffleCopier
from tpumr.mapred.task import TaskState, TaskStatus
from tpumr.utils import fi

from test_shuffle_copier import SpillChunkSource, make_spill, records_for


# --------------------------------------------------------------- copier


class FlakySource(SpillChunkSource):
    """A chunk source whose map 0 is unfetchable until a fetch-failure
    report arrives — then it 'relocates' (as if the map re-ran) and
    serves fine. Duck-types the locator hooks of RemoteChunkSource."""

    def __init__(self, spills):
        super().__init__(spills)
        self.addr = {m: f"t0:{m}" for m in range(len(spills))}
        self.attempts = {m: f"attempt_x_0001_m_{m:06d}_0"
                         for m in range(len(spills))}
        self.recovered = threading.Event()
        self.invalidated = []

    def addr_of(self, m):
        return self.addr.get(m, "")

    def attempt_of(self, m):
        return self.attempts.get(m, "")

    def invalidate(self, m):
        self.invalidated.append(m)
        # the "re-run" publishes a new location + attempt
        self.addr[m] = f"t1:{m}"
        self.attempts[m] = f"attempt_x_0001_m_{m:06d}_1"
        self.recovered.set()

    def __call__(self, map_index, partition, offset):
        if map_index == 0 and not self.recovered.is_set():
            raise ConnectionError("output gone (disk lost)")
        return super().__call__(map_index, partition, offset)


def _conf(**kv):
    conf = JobConf()
    for k, v in kv.items():
        conf.set(k, v)
    return conf


class TestCopierRecovery:
    def test_report_then_reresolve_instead_of_failing(self, tmp_path):
        """A persistently-failing source must NOT fail the reduce when a
        report callback is wired: the copier reports, invalidates, and
        picks up the new location mid-shuffle."""
        spills = [make_spill(records_for(100, b"m%d" % i))
                  for i in range(3)]
        src = FlakySource(spills)
        reports = []
        conf = _conf(**{"tpumr.shuffle.copy.backoff.ms": 1,
                        "tpumr.shuffle.copy.backoff.max.ms": 5,
                        "tpumr.shuffle.fetch.retries.per.source": 2})
        copier = ShuffleCopier(conf, src, 3, 0, str(tmp_path),
                               on_fetch_failure=lambda m, a:
                               reports.append((m, a)))
        segs = copier.copy_all()
        assert len(segs) == 3
        assert reports == [(0, "attempt_x_0001_m_000000_0")]
        assert src.invalidated == [0]
        assert copier.fetch_failures >= 2     # per-source threshold hit
        assert copier.fetch_failures_reported == 1
        for s in segs:
            s.close()

    def test_without_callback_failure_stays_terminal(self, tmp_path):
        """Legacy contract preserved: no callback → local retries then
        raise (a LocalJobRunner reduce has no master to report to)."""
        class DeadSource:
            chunk_bytes = 1 << 20

            def __call__(self, m, p, o):
                raise ConnectionError("gone")

        conf = _conf(**{"tpumr.shuffle.copy.retries": 1,
                        "tpumr.shuffle.copy.backoff.ms": 1})
        with pytest.raises(RuntimeError, match="failed after 2 attempts"):
            ShuffleCopier(conf, DeadSource(), 1, 0,
                          str(tmp_path)).copy_all()

    def test_max_failures_ceiling_is_terminal_even_with_callback(
            self, tmp_path):
        class DeadSource:
            chunk_bytes = 1 << 20

            def __call__(self, m, p, o):
                raise ConnectionError("gone")

        conf = _conf(**{"tpumr.shuffle.copy.backoff.ms": 1,
                        "tpumr.shuffle.copy.backoff.max.ms": 2,
                        "tpumr.shuffle.fetch.retries.per.source": 2,
                        "tpumr.shuffle.fetch.max.failures": 5})
        copier = ShuffleCopier(conf, DeadSource(), 1, 0, str(tmp_path),
                               on_fetch_failure=lambda m, a: None)
        with pytest.raises(ConnectionError):
            copier.copy_all()
        assert copier.fetch_failures == 5

    def test_penalty_box_backoff_capped_and_jittered(self):
        box = PenaltyBox(base_s=1.0, cap_s=4.0)
        delays = [box.punish("t0") for _ in range(6)]
        # nominal 1,2,4,4,4,4 jittered into [0.5, 1.0) of nominal
        for d, nominal in zip(delays, [1, 2, 4, 4, 4, 4]):
            assert 0.5 * nominal <= d <= nominal
        assert box.active() == 1
        # hold-offs are MONOTONIC stamps (clock-step immunity, this
        # PR's deadline sweep) — compare against the monotonic clock
        assert box.until("t0") > time.monotonic()
        box.clear("t0")
        assert box.active() == 0
        # strikes reset: next punishment starts from the base again
        assert box.punish("t0") <= 1.0

    def test_local_backoff_jitter_and_cap(self, tmp_path):
        conf = _conf(**{"tpumr.shuffle.copy.backoff.ms": 100,
                        "tpumr.shuffle.copy.backoff.max.ms": 400})
        copier = ShuffleCopier(conf, lambda m, p, o: {}, 1, 0,
                               str(tmp_path))
        for attempt, nominal in [(0, 0.1), (1, 0.2), (2, 0.4), (8, 0.4)]:
            for _ in range(8):
                d = copier._local_backoff_s(attempt)
                assert 0.5 * nominal <= d <= nominal


# --------------------------------------------------------- master state


def _job(n_maps=2, n_reduces=2, **conf):
    base = {"mapred.reduce.tasks": n_reduces,
            "mapred.speculative.execution": False,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    base.update(conf)
    return JobInProgress(JobID("ff", 1),
                         splits=[{"locations": []}
                                 for _ in range(n_maps)],
                         conf_dict=base)


def _finish_map(job, task, runtime=1.0, on_tpu=False, addr="t0:1"):
    now = time.time()
    job.update_task_status(TaskStatus(
        attempt_id=task.attempt_id, is_map=True, run_on_tpu=on_tpu,
        state=TaskState.SUCCEEDED, start_time=now - runtime,
        finish_time=now), addr)


def _running_reduce(job):
    """Obtain a reduce and fold its RUNNING heartbeat status — reports
    are only accepted from reducers the master knows are running."""
    t = job.obtain_new_reduce_task("h")
    job.update_task_status(TaskStatus(
        attempt_id=t.attempt_id, is_map=False,
        state=TaskState.RUNNING), "t:0")
    return str(t.attempt_id)


class TestFetchFailureNotification:
    def test_distinct_reducers_until_threshold(self):
        job = _job(n_maps=1, n_reduces=3,
                   **{"mapred.max.fetch.failures.per.map": 2})
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t, addr="t0:9")
        aid = job.maps[0].successful_attempt
        r0, r1, r2 = (_running_reduce(job) for _ in range(3))
        # same reducer reporting twice counts ONCE
        res = job.fetch_failure_notification(aid, r0)
        assert res == {"withdrawn": False, "reexecuted": False,
                       "shuffle_addr": "", "reports": 1}
        assert job.fetch_failure_notification(aid, r0)["reports"] == 1
        # a speculative TWIN of the same reduce corroborates nothing new
        twin = TaskAttemptID(TaskAttemptID.parse(r0).task, 99)
        job.update_task_status(TaskStatus(
            attempt_id=twin, is_map=False,
            state=TaskState.RUNNING), "t:0")
        assert job.fetch_failure_notification(aid,
                                              str(twin))["reports"] == 1
        assert job.fetch_failure_pending_count() == 1
        res = job.fetch_failure_notification(aid, r1)
        assert res["withdrawn"] and res["reexecuted"]
        assert res["shuffle_addr"] == "t0:9"
        assert res["reports"] == 2
        # the map is back in the pending pool, attempt burned
        assert job.pending_map_count() == 1
        assert job.finished_maps == 0
        assert job.maps[0].failures == 1
        assert job.maps[0].successful_attempt == ""
        assert job.fetch_failure_pending_count() == 0
        # events: original mutated OBSOLETE + tombstone appended
        obs = [e for e in job.completion_events
               if e.get("status") == "OBSOLETE"]
        assert len(obs) == 2 and all(e["attempt_id"] == aid for e in obs)
        # stale report after withdrawal is a no-op
        assert job.fetch_failure_notification(aid, r2) is None

    def test_single_reduce_job_triggers_below_default_threshold(self):
        """A 1-reduce job can never produce 3 distinct reporters — once
        EVERY live reduce is complaining, nothing can progress and the
        map must re-execute."""
        job = _job(n_maps=1, n_reduces=1)   # default threshold 3
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t)
        r0 = _running_reduce(job)
        aid = job.maps[0].successful_attempt
        res = job.fetch_failure_notification(aid, r0)
        assert res["withdrawn"] and res["reexecuted"]

    def test_profile_sums_unwound_exactly(self):
        job = _job(n_maps=2, n_reduces=1)
        t0 = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        t1 = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t0, runtime=4.0, on_tpu=True)
        _finish_map(job, t1, runtime=8.0, on_tpu=False)
        assert job.finished_tpu_maps == 1 and job.finished_cpu_maps == 1
        tpu_sum, cpu_sum = job._tpu_time_sum, job._cpu_time_sum
        r0 = _running_reduce(job)
        aid = job.maps[t0.partition].successful_attempt
        res = job.fetch_failure_notification(aid, r0)
        assert res["withdrawn"]
        # the TPU books are restored exactly; CPU books untouched
        assert job.finished_tpu_maps == 0
        assert job._tpu_time_sum == pytest.approx(tpu_sum - 4.0)
        assert job.finished_cpu_maps == 1
        assert job._cpu_time_sum == pytest.approx(cpu_sum)
        assert job.tpu_map_mean_time() == 0.0

    def test_repeated_output_loss_fails_the_job(self):
        job = _job(n_maps=1, n_reduces=1,
                   **{"mapred.map.max.attempts": 2})
        r0 = _running_reduce(job)
        for round_no in range(2):
            t = job.obtain_new_map_task("h", run_on_tpu=False)
            _finish_map(job, t)
            aid = job.maps[0].successful_attempt
            res = job.fetch_failure_notification(aid, r0)
            assert res["withdrawn"]
        assert res["reexecuted"] is False
        assert job.state == JobState.FAILED
        assert "fetch failures" in job.error

    def test_unknown_and_reduce_attempts_ignored(self):
        job = _job(n_maps=1, n_reduces=1)
        r0 = _running_reduce(job)
        assert job.fetch_failure_notification("garbage", r0) is None
        assert job.fetch_failure_notification(
            "attempt_ff_0001_r_000000_0", r0) is None
        # a map that is still running (not succeeded) can't be withdrawn
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        assert job.fetch_failure_notification(
            str(t.attempt_id), r0) is None

    def test_replayed_success_cannot_resurrect_withdrawn_attempt(self):
        """The wedged-but-heartbeating tracker this protocol targets can
        re-deliver the map's terminal SUCCEEDED on every beat (statuses
        fold before replay detection): it must not re-publish the
        withdrawn output or re-increment finished_maps."""
        job = _job(n_maps=1, n_reduces=1)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t, addr="t0:9")
        r0 = _running_reduce(job)
        aid = job.maps[0].successful_attempt
        assert job.fetch_failure_notification(aid, r0)["withdrawn"]
        now = time.time()
        job.update_task_status(TaskStatus(
            attempt_id=TaskAttemptID.parse(aid), is_map=True,
            state=TaskState.SUCCEEDED, start_time=now - 1,
            finish_time=now), "t0:9")
        assert job.finished_maps == 0             # not resurrected
        assert job.pending_map_count() == 1
        assert job.maps[0].successful_attempt == ""
        assert not [e for e in job.completion_events
                    if e.get("status") != "OBSOLETE"]

    def test_forged_or_finished_reporters_ignored(self):
        """Reports count only from reduce attempts the master knows are
        RUNNING in THIS job — a job-token child inventing reducer names
        (or a finished reduce) cannot manufacture corroboration."""
        job = _job(n_maps=1, n_reduces=2)
        t = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t)
        aid = job.maps[0].successful_attempt
        # never-launched (forged) reducer
        assert job.fetch_failure_notification(
            aid, "attempt_ff_0001_r_000001_7") is None
        # another job's reducer
        assert job.fetch_failure_notification(
            aid, "attempt_other_0002_r_000000_0") is None
        # a finished reduce no longer corroborates
        r0 = _running_reduce(job)
        now = time.time()
        job.update_task_status(TaskStatus(
            attempt_id=TaskAttemptID.parse(r0), is_map=False,
            state=TaskState.SUCCEEDED, start_time=now - 1,
            finish_time=now), "t:0")
        assert job.fetch_failure_notification(aid, r0) is None
        assert job.fetch_failure_pending_count() == 0


class TestRequeueLostAttemptsUnwind:
    def test_hybrid_profile_unwound_exactly_on_lost_tracker(self):
        """Satellite: a completed map on a lost tracker must restore
        finished_tpu_maps/_tpu_time_sum (and the CPU twins) EXACTLY, so
        the hybrid scheduler's means stay unpoisoned."""
        job = _job(n_maps=3, n_reduces=1)
        t0 = job.obtain_new_map_task("h", run_on_tpu=True, tpu_device_id=0)
        t1 = job.obtain_new_map_task("h", run_on_tpu=False)
        t2 = job.obtain_new_map_task("h", run_on_tpu=False)
        _finish_map(job, t0, runtime=2.0, on_tpu=True, addr="lost:1")
        _finish_map(job, t1, runtime=6.0, on_tpu=False, addr="lost:1")
        _finish_map(job, t2, runtime=10.0, on_tpu=False, addr="ok:2")
        assert (job.finished_tpu_maps, job.finished_cpu_maps) == (1, 2)
        lost = [job.maps[t0.partition].successful_attempt,
                job.maps[t1.partition].successful_attempt]
        job.requeue_lost_attempts(lost)
        assert job.finished_maps == 1
        assert job.finished_tpu_maps == 0
        assert job._tpu_time_sum == pytest.approx(0.0)
        assert job.finished_cpu_maps == 1
        assert job._cpu_time_sum == pytest.approx(10.0)
        assert job.cpu_map_mean_time() == pytest.approx(10.0)
        assert job.tpu_map_mean_time() == 0.0
        assert job.pending_map_count() == 2
        # the survivor's event is still live; the lost ones tombstoned
        live = [e for e in job.completion_events
                if e.get("status") != "OBSOLETE"]
        assert [e["map_index"] for e in live] == [t2.partition]


# --------------------------------------------------------------- locator


class TestMapLocatorReresolution:
    def _feed(self, events):
        return lambda cursor: events[cursor:]

    def test_obsolete_evicts_and_rerun_replaces(self):
        from tpumr.mapred.tasktracker import make_map_locator
        events = [{"map_index": 0, "attempt_id": "a0",
                   "shuffle_addr": "127.0.0.1:7001",
                   "status": "SUCCEEDED"}]
        loc = make_map_locator(self._feed(events), None, poll_s=0.01,
                               timeout_s=2.0)
        cli = loc(0)
        assert (cli.host, cli.port) == ("127.0.0.1", 7001)
        assert loc.attempt_of(0) == "a0"
        assert loc.addr_of(0) == "127.0.0.1:7001"
        # the master withdraws a0 and a re-run publishes a new address
        events.append({"map_index": 0, "attempt_id": "a0",
                       "shuffle_addr": "127.0.0.1:7001",
                       "status": "OBSOLETE"})
        events.append({"map_index": 0, "attempt_id": "a1",
                       "shuffle_addr": "127.0.0.1:7002",
                       "status": "SUCCEEDED"})
        loc.invalidate(0)
        cli = loc(0)
        assert (cli.host, cli.port) == ("127.0.0.1", 7002)
        assert loc.attempt_of(0) == "a1"

    def test_invalidate_falls_back_to_stale_until_replaced(self):
        """An invalidated location the master never withdraws (the fault
        may be OUR network path, not the output) must stay usable: the
        cursor-based feed never re-serves the original event, so without
        the stale fallback the reducer would block to the full shuffle
        timeout and report empty attempt ids forever."""
        from tpumr.mapred.tasktracker import make_map_locator
        events = [{"map_index": 0, "attempt_id": "a0",
                   "shuffle_addr": "127.0.0.1:7001",
                   "status": "SUCCEEDED"}]
        loc = make_map_locator(self._feed(events), None, poll_s=0.01,
                               timeout_s=5.0)
        assert loc(0).port == 7001
        loc.invalidate(0)
        # reports keep naming the real attempt while demoted
        assert loc.attempt_of(0) == "a0"
        t0 = time.time()
        assert loc(0).port == 7001          # falls back, does NOT block
        assert time.time() - t0 < 2.0
        # once the master withdraws it, the fallback dies with it and
        # the re-run's fresh event wins
        loc.invalidate(0)
        events.append({"map_index": 0, "attempt_id": "a0",
                       "shuffle_addr": "127.0.0.1:7001",
                       "status": "OBSOLETE"})
        events.append({"map_index": 0, "attempt_id": "a1",
                       "shuffle_addr": "127.0.0.1:7002",
                       "status": "SUCCEEDED"})
        assert loc(0).port == 7002
        assert loc.attempt_of(0) == "a1"

    def test_tombstone_for_uncached_attempt_is_inert(self):
        """A late joiner replaying SUCCEEDED→OBSOLETE→SUCCEEDED from
        cursor 0 must land on the re-run's address."""
        from tpumr.mapred.tasktracker import make_map_locator
        events = [
            {"map_index": 0, "attempt_id": "a0",
             "shuffle_addr": "127.0.0.1:7001", "status": "SUCCEEDED"},
            {"map_index": 0, "attempt_id": "a0",
             "shuffle_addr": "127.0.0.1:7001", "status": "OBSOLETE"},
            {"map_index": 0, "attempt_id": "a1",
             "shuffle_addr": "127.0.0.1:7002", "status": "SUCCEEDED"},
        ]
        loc = make_map_locator(self._feed(events), None, poll_s=0.01,
                               timeout_s=2.0)
        assert loc(0).port == 7002


# ------------------------------------------------------- fi determinism


class TestSeededFaultInjection:
    def setup_method(self):
        fi.reset()

    def _sequence(self, conf, n=64):
        out = []
        for _ in range(n):
            try:
                fi.maybe_fail("seeded.point", conf)
                out.append(0)
            except fi.InjectedFault:
                out.append(1)
        return out

    def test_same_seed_replays_bit_identically(self):
        conf = _conf(**{"tpumr.fi.seeded.point.probability": 0.5,
                        "tpumr.fi.seed": 1234})
        first = self._sequence(conf)
        fi.reset()   # fresh process-equivalent
        assert self._sequence(conf) == first
        assert 0 < sum(first) < 64   # actually probabilistic

    def test_different_seeds_diverge(self):
        a = _conf(**{"tpumr.fi.seeded.point.probability": 0.5,
                     "tpumr.fi.seed": 1})
        b = _conf(**{"tpumr.fi.seeded.point.probability": 0.5,
                     "tpumr.fi.seed": 2})
        sa = self._sequence(a)
        fi.reset()
        sb = self._sequence(b)
        assert sa != sb


# ------------------------------------------------- tracker heartbeat


def _bare_noderunner(interval_s=0.2):
    """A NodeRunner shell for heartbeat-loop tests — no daemon
    bring-up, just the fields the loop touches."""
    from tpumr.mapred.tasktracker import NodeRunner
    from tpumr.metrics.core import MetricsRegistry
    nr = object.__new__(NodeRunner)
    nr._stop = threading.Event()
    nr.heartbeat_s = interval_s
    nr.tracer = None                     # tracing off (the default)
    nr.master_unreachable = False
    nr._master_failures = 0
    nr._last_master_contact = time.monotonic()
    nr._lost_master_backoff_max_s = 15.0
    nr._mreg = MetricsRegistry("t")
    return nr


class TestHeartbeatErrorBackoff:
    def test_lost_master_backs_off_and_honors_stop(self):
        """Master-unreachable beats enter the lost-master state: capped
        jittered exponential backoff (never below one interval), the
        master_unreachable flag raised, retries forever, and _stop
        still interrupts the wait promptly."""
        nr = _bare_noderunner(interval_s=0.1)
        beats = []
        nr._heartbeat_once = lambda: (beats.append(time.time()),
                                      (_ for _ in ()).throw(
                                          ConnectionError("down")))
        t = threading.Thread(target=nr._heartbeat_loop, daemon=True)
        t.start()
        time.sleep(1.0)
        assert nr.master_unreachable, \
            "transport failure must raise the lost-master flag"
        nr._stop.set()
        t.join(timeout=1.0)
        assert not t.is_alive(), "stop must interrupt the backoff wait"
        assert len(beats) >= 2, "must keep retrying through the outage"
        gaps = [b - a for a, b in zip(beats, beats[1:])]
        # jittered exponential: every gap within [interval, cap], and
        # the SECOND retry gap is never shorter than half the first's
        # ceiling — it backs off rather than hammering a restarting
        # master at a fixed cadence
        assert all(0.09 <= g <= 15.0 for g in gaps), gaps
        assert nr._master_failures == len(beats)

    def test_application_rpc_error_keeps_cadence_and_charges_nothing(self):
        """An RPC-level error (the master answered, unhappily) is NOT a
        lost master: normal interval, no unreachable flag, no backoff."""
        from tpumr.ipc.rpc import RpcError
        nr = _bare_noderunner(interval_s=0.1)
        beats = []
        nr._heartbeat_once = lambda: (beats.append(time.time()),
                                      (_ for _ in ()).throw(
                                          RpcError("handler raised")))
        t = threading.Thread(target=nr._heartbeat_loop, daemon=True)
        t.start()
        time.sleep(0.55)
        nr._stop.set()
        t.join(timeout=1.0)
        assert not nr.master_unreachable
        assert nr._master_failures == 0
        assert len(beats) >= 3, "application errors keep the cadence"
        gaps = [b - a for a, b in zip(beats, beats[1:])]
        assert all(g < 0.25 for g in gaps), \
            f"no lost-master backoff for application errors (gaps={gaps})"


# ------------------------------------------------------------ end to end


class TestEndToEndChaos:
    def test_lost_map_output_recovers_without_failing_reduces(self):
        """Acceptance: tpumr.fi.shuffle.serve injects persistent fetch
        failures for one completed map's output (its tracker keeps
        heartbeating). The job must finish with byte-correct output:
        the map re-executes, reducers pick the new location up from
        OBSOLETE/refreshed completion events, no reduce attempt fails,
        and maps_reexecuted_fetch_failure == 1."""
        fi.reset()
        from tpumr.fs import FileSystem, get_filesystem
        from tpumr.mapred.job_client import JobClient
        from tpumr.mapred.mini_cluster import MiniMRCluster

        base = JobConf()
        # every serve of an ATTEMPT-0 map output fails, persistently —
        # the tracker itself stays healthy and heartbeating; the re-run
        # (attempt 1) serves fine wherever it lands
        base.set("tpumr.fi.shuffle.serve.a0.probability", 1.0)
        base.set("tpumr.shuffle.fetch.retries.per.source", 1)
        base.set("tpumr.shuffle.copy.backoff.ms", 10)
        base.set("tpumr.shuffle.copy.backoff.max.ms", 100)
        base.set("mapred.max.fetch.failures.per.map", 2)
        try:
            with MiniMRCluster(num_trackers=2, conf=base) as c:
                fs = get_filesystem("mem:///")
                fs.write_bytes("/ff/in.txt",
                               b"".join(b"w%02d x\n" % (i % 31)
                                        for i in range(3000)))
                conf = c.create_job_conf()
                conf.set_input_paths("mem:///ff/in.txt")
                conf.set_output_path("mem:///ff/out")
                conf.set("mapred.mapper.class",
                         "tpumr.mapred.lib.TokenCountMapper")
                conf.set("mapred.reducer.class",
                         "tpumr.examples.basic.LongSumReducer")
                conf.set("mapred.map.tasks", 1)
                conf.set_num_reduce_tasks(2)
                result = JobClient(conf).run_job(conf)
                assert result.successful, \
                    "job must survive the lost map output"
                out = b"".join(fs.read_bytes(st.path)
                               for st in fs.list_status("/ff/out")
                               if "part-" in str(st.path))
                counts = dict(line.split(b"\t")
                              for line in out.splitlines())
                assert counts[b"x"] == b"3000"
                assert counts[b"w00"] == b"97"     # 3000/31 → 97
                # the protocol ran: exactly one map re-executed, faults
                # were reported, and NO reduce attempt was failed
                snap = c.master.metrics.snapshot()["jobtracker"]
                assert snap["maps_reexecuted_fetch_failure"] == 1
                assert snap["fetch_failures_reported"] >= 2
                jip = c.master.jobs[str(result.job_id)]
                for tip in jip.reduces:
                    assert tip.failures == 0
                    assert not [s for s in tip.attempts.values()
                                if s.state == TaskState.FAILED]
                # the lost attempt itself was burned, once
                assert sum(t.failures for t in jip.maps) == 1
                assert fi.fired("shuffle.serve.a0") >= 1
        finally:
            fi.reset()
            FileSystem.clear_cache()
