"""Pipeline restart survival (PR 11 chaos leg).

THE acceptance e2e: a master SIGKILL mid-pipeline (upstream stage done,
downstream in flight) followed by a restart with recovery on must
finish the pipeline with byte-identical final output and WITHOUT
re-running the completed upstream stage — its node keeps the original
pre-restart job id, adopted terminal from history, while the in-flight
downstream stage re-binds to its job-recovery alias.

Runs both handoff modes: the dfs-staged chain, and the streamed chain
(where the post-restart downstream maps land on the committed part-file
fallback whenever the old master's handoff feed died with it — the
artifact-of-record stance: the stream is an optimization, DFS is the
truth).
"""

import json
import os
import time

from tpumr.fs import FileSystem, get_filesystem
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.mini_cluster import MiniMRCluster
from tpumr.pipeline import JobGraph, PipelineClient

PIPELINE_TRACE_OUT = "/tmp/tpumr-pipeline-trace.json"


def _cluster_conf(tmp_path):
    conf = JobConf()
    conf.set("tpumr.history.dir", str(tmp_path / "history"))
    conf.set("mapred.jobtracker.restart.recover", True)
    conf.set("mapred.jobtracker.restart.recovery.grace.ms", 500)
    conf.set("tpumr.heartbeat.interval.ms", 50)
    conf.set("tpumr.tracker.expiry.ms", 60_000)
    conf.set("tpumr.rpc.client.retries", 2)
    conf.set("tpumr.rpc.client.backoff.ms", 50)
    conf.set("mapred.reduce.slowstart.completed.maps", 0.0)
    conf.set("mapred.speculative.execution", False)
    return conf


def _write_words(fs, path, lines=2500):
    fs.write_bytes(path, b"".join(b"w%02d x\n" % (i % 17)
                                  for i in range(lines)))


def _read_parts(fs, outdir):
    return b"".join(fs.read_bytes(st.path)
                    for st in sorted(fs.list_status(outdir),
                                     key=lambda s: str(s.path))
                    if "part-" in str(st.path))


def _chain_graph(name, inpath, middir, outdir, stream):
    g = JobGraph(name)
    g.node("count", {
        "mapred.input.dir": inpath,
        "mapred.output.dir": middir,
        "mapred.mapper.class": "tpumr.mapred.lib.TokenCountMapper",
        "mapred.reducer.class": "tpumr.examples.basic.LongSumReducer",
        "mapred.reduce.tasks": 2,
        "mapred.map.tasks": 4,
        "mapred.output.format.class":
            "tpumr.mapred.output_formats.SequenceFileOutputFormat",
    })
    emit = {
        "mapred.output.dir": outdir,
        "mapred.mapper.class": "tpumr.mapred.api.IdentityMapper",
        "mapred.reduce.tasks": 0,
    }
    if not stream:
        emit["mapred.input.format.class"] = \
            "tpumr.mapred.input_formats.SequenceFileInputFormat"
    g.node("emit", emit)
    g.edge("count", "emit", stream=stream)
    return g


def _poll_status(running, deadline_s=60.0):
    """Status poll that rides out the restart window."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            return running.status()
        except Exception:  # noqa: BLE001 — master restarting
            time.sleep(0.05)
    raise TimeoutError("master never answered a pipeline status poll")


def _wait_node(running, node, state, deadline_s=90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        st = _poll_status(running)
        if st["nodes"][node]["state"] == state:
            return st
        if st["state"] in ("FAILED", "KILLED"):
            raise AssertionError(f"pipeline died early: {st}")
        time.sleep(0.02)
    raise TimeoutError(f"node {node} never reached {state}")


def _wait_terminal(running, deadline_s=120.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        st = _poll_status(running)
        if st["state"] in ("SUCCEEDED", "FAILED", "KILLED"):
            return st
        time.sleep(0.05)
    raise TimeoutError("pipeline never finished")


def _kill_and_restart_master(cluster):
    """Abrupt master death (no finalization, no goodbye) + restart on
    the same address with recovery on."""
    host, port = cluster.master.address
    cluster.master.stop()
    m2 = None
    for _ in range(200):
        try:
            m2 = JobMaster(cluster.conf, host=host, port=port).start()
            break
        except OSError:
            time.sleep(0.05)
    assert m2 is not None, "could not rebind the master port"
    cluster.master = m2
    return m2


class TestPipelineRestartChaos:
    def teardown_method(self):
        FileSystem.clear_cache()

    def _control(self, tmp_path, stream):
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_cluster_conf(tmp_path
                                              / "control")) as c:
            fs = get_filesystem("mem:///")
            _write_words(fs, "/ctl/in.txt")
            g = _chain_graph("control", "mem:///ctl/in.txt",
                             "mem:///ctl/mid", "mem:///ctl/out", stream)
            st = PipelineClient(c.create_job_conf()).submit(g) \
                .wait_for_completion(timeout=120)
            assert st["state"] == "SUCCEEDED", st
            return _read_parts(fs, "/ctl/out")

    def _run_chaos(self, tmp_path, stream):
        control = self._control(tmp_path, stream)
        with MiniMRCluster(num_trackers=2, tpu_slots=0,
                           conf=_cluster_conf(tmp_path)) as c:
            fs = get_filesystem("mem:///")
            _write_words(fs, "/pr/in.txt")
            g = _chain_graph("chaos", "mem:///pr/in.txt",
                             "mem:///pr/mid", "mem:///pr/out", stream)
            if not stream:
                # traced leg: the merged end-to-end pipeline trace is
                # the CI artifact (stage jobs share the pipeline trace)
                g.conf["tpumr.trace.enabled"] = True
                g.conf["tpumr.trace.dir"] = str(tmp_path / "traces")
            client = PipelineClient(c.create_job_conf())
            running = client.submit(g)
            pid = running.pipeline_id
            # kill once the upstream stage SETTLED (its output is
            # committed, the downstream stage is submitted or about to
            # be — mid-pipeline by construction)
            st = _wait_node(running, "count", "SUCCEEDED")
            count_job = st["nodes"]["count"]["job_id"]
            m2 = _kill_and_restart_master(c)
            st = _wait_terminal(running)
            assert st["state"] == "SUCCEEDED", st
            # byte-identical final output vs the undisturbed chain
            out = _read_parts(fs, "/pr/out")
            assert out == control, "post-restart output must be " \
                                   "byte-identical"
            # the completed upstream stage was adopted, NEVER re-run:
            # same single job id as before the kill, no resubmission
            assert st["nodes"]["count"]["jobs"] == [count_job], st
            snap = m2.metrics.snapshot()["jobtracker"]
            assert snap.get("pipelines_recovered", 0) == 1
            # pipeline identity is stable across the restart
            assert m2.get_pipeline_status(pid)["state"] == "SUCCEEDED"
            return m2, pid

    def test_master_killed_mid_pipeline_dfs_chain(self, tmp_path):
        m2, pid = self._run_chaos(tmp_path, stream=False)
        # export the merged pipeline trace (CI artifact): the recovered
        # pipeline keeps its trace id, so the file spans both masters
        from tpumr.core import tracing
        trace = m2.get_pipeline_trace(pid)
        assert trace["spans"], "traced pipeline must have spans"
        chrome = tracing.to_chrome_trace(trace["spans"])
        with open(PIPELINE_TRACE_OUT, "w") as f:
            json.dump(chrome, f)
        assert os.path.getsize(PIPELINE_TRACE_OUT) > 0

    def test_master_killed_mid_pipeline_streamed_chain(self, tmp_path):
        self._run_chaos(tmp_path, stream=True)
