"""Streaming tier tests ≈ contrib streaming's TestStreaming*: script
mappers/reducers over stdin/stdout, the stderr reporter protocol, and the
conf-to-environment export."""

import sys

from tpumr.fs import get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.streaming import setup_stream_job

PY = sys.executable

WC_MAPPER = (f"{PY} -c \"import sys\n"
             "for line in sys.stdin:\n"
             "    parts = line.rstrip().split('\\t', 1)\n"
             "    text = parts[1] if len(parts) > 1 else parts[0]\n"
             "    for w in text.split():\n"
             "        print(w + '\\t1')\n"
             "sys.stderr.write('reporter:counter:WC,MAP_LINES,1\\n')\"")

WC_REDUCER = (f"{PY} -c \"import sys\n"
              "cur, total = None, 0\n"
              "for line in sys.stdin:\n"
              "    k, v = line.rstrip().split('\\t')\n"
              "    if k != cur:\n"
              "        if cur is not None:\n"
              "            print(cur + '\\t' + str(total))\n"
              "        cur, total = k, 0\n"
              "    total += int(v)\n"
              "if cur is not None:\n"
              "    print(cur + '\\t' + str(total))\"")


def _read_output(fs, out_dir):
    merged = {}
    for st in fs.list_files(out_dir):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, _, v = line.partition("\t")
                merged[k] = v
    return merged


def test_streaming_wordcount():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/stream/in.txt", b"x y x\nz y x\n" * 5)
    conf = JobConf()
    conf.set_input_paths("mem:///stream/in.txt")
    conf.set_output_path("mem:///stream/out")
    conf.set_num_reduce_tasks(1)
    setup_stream_job(conf, mapper=WC_MAPPER, reducer=WC_REDUCER)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert _read_output(fs, "mem:///stream/out") == \
        {"x": "15", "y": "10", "z": "5"}
    # stderr reporter protocol reached real counters (one per map task)
    assert result.counters.value("WC", "MAP_LINES") >= 1


def test_streaming_cat_identity_and_env():
    """/bin/cat as mapper (the canonical streaming smoke test) + conf keys
    exported to the child environment with dots -> underscores."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/cat/in.txt", b"one\ntwo\n")
    conf = JobConf()
    conf.set_input_paths("mem:///cat/in.txt")
    conf.set_output_path("mem:///cat/out")
    conf.set_num_reduce_tasks(0)
    env_mapper = (f"{PY} -c \"import sys, os\n"
                  "for line in sys.stdin:\n"
                  "    sys.stdout.write(line)\n"
                  "print('jobname\\t' + os.environ['mapred_job_name'])\"")
    conf.set_job_name("envcheck")
    setup_stream_job(conf, mapper=env_mapper)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    out = _read_output(fs, "mem:///cat/out")
    assert out["jobname"] == "envcheck"
    assert "one" in out  # cat passthrough (value lands in the key column)


def test_streaming_failing_child_fails_task():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/sf/in.txt", b"a\n")
    conf = JobConf()
    conf.set_input_paths("mem:///sf/in.txt")
    conf.set_output_path("mem:///sf/out")
    conf.set_num_reduce_tasks(0)
    setup_stream_job(conf, mapper=f"{PY} -c \"import sys; sys.exit(7)\"")
    import pytest
    with pytest.raises(RuntimeError, match="rc=7"):
        JobClient(conf).run_job(conf)


def test_streaming_combiner():
    """Subprocess combiner runs per spill and pre-aggregates map output."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/comb/in.txt", b"k k k\nk k k\n" * 10)
    conf = JobConf()
    conf.set_input_paths("mem:///comb/in.txt")
    conf.set_output_path("mem:///comb/out")
    conf.set_num_reduce_tasks(1)
    setup_stream_job(conf, mapper=WC_MAPPER, reducer=WC_REDUCER,
                     combiner=WC_REDUCER)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert _read_output(fs, "mem:///comb/out") == {"k": "60"}
    # combiner actually folded records before the reduce
    from tpumr.core.counters import TaskCounter
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.COMBINE_INPUT_RECORDS) == 60
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.COMBINE_OUTPUT_RECORDS) == 1


# ------------------------------------------------------------- typed-bytes

TB_MAPPER = (f"{PY} -c \"import sys\n"
             "sys.path[:0] = {path!r}\n"
             "from tpumr.streaming.typedbytes import read_pairs, write_pair\n"
             "for k, v in read_pairs(sys.stdin.buffer):\n"
             "    v = v.encode() if isinstance(v, str) else v\n"
             "    write_pair(sys.stdout.buffer, v, bytes([0]) + v + b'\\\\n' + v)\n"
             "sys.stdout.buffer.flush()\"")

TB_REDUCER = (f"{PY} -c \"import sys\n"
              "sys.path[:0] = {path!r}\n"
              "from tpumr.streaming.typedbytes import read_pairs, write_pair\n"
              "for k, v in read_pairs(sys.stdin.buffer):\n"
              "    write_pair(sys.stdout.buffer, k, v)\n"
              "sys.stdout.buffer.flush()\"")


def test_typedbytes_roundtrip_all_types():
    """Codec roundtrip ≈ typedbytes/TestTypedBytesInput: every supported
    type, including byte strings with embedded NUL/TAB/NL."""
    import io as _io

    from tpumr.streaming.typedbytes import read_typed, write_typed

    values = [
        b"",
        b"embedded\x00nul\ttab\nnewline\xff\xfe",
        True, False,
        0, -1, 2**31 - 1, -(2**31), 2**31, -(2**63),  # INT edge + LONG
        3.5, -0.0,
        "unicode é中",
        (1, "two", b"\x00three"),          # VECTOR
        [b"\n", [1, 2], "nested"],          # LIST (nested)
        {b"k\x00": b"v\n", "n": 1},        # MAP
    ]
    buf = _io.BytesIO()
    for v in values:
        write_typed(buf, v)
    buf.seek(0)
    out = [read_typed(buf) for _ in values]
    assert out == values
    import pytest as _pytest
    with _pytest.raises(EOFError):
        read_typed(buf)


def test_typedbytes_wire_format_is_reference_compatible():
    """Byte-level check against Type.java codes so reference typed-bytes
    tools interoperate: code byte + big-endian payloads."""
    import io as _io
    import struct

    from tpumr.streaming.typedbytes import write_typed

    def enc(v):
        b = _io.BytesIO()
        write_typed(b, v)
        return b.getvalue()

    assert enc(b"ab") == b"\x00" + struct.pack(">i", 2) + b"ab"
    assert enc(True) == b"\x02\x01"
    assert enc(7) == b"\x03" + struct.pack(">i", 7)
    assert enc(2**40) == b"\x04" + struct.pack(">q", 2**40)
    assert enc(1.5) == b"\x06" + struct.pack(">d", 1.5)
    assert enc("hi") == b"\x07" + struct.pack(">i", 2) + b"hi"
    assert enc([1]) == b"\x09" + enc(1) + b"\xff"


def test_typedbytes_streaming_job_binary_safe(tmp_path):
    """End-to-end -io typedbytes job: values with embedded \\n and \\0
    survive the child pipes byte-for-byte (the exact records the line
    protocol cannot carry). Output via SequenceFile stays binary-safe."""
    from tpumr.io import sequencefile
    from tpumr.mapred.output_formats import SequenceFileOutputFormat
    import sys as _sys

    fs = get_filesystem("mem:///")
    fs.write_bytes("/tb/in.txt", b"r1\nr2\nr3\n")
    conf = JobConf()
    conf.set_input_paths("mem:///tb/in.txt")
    conf.set_output_path("mem:///tb/out")
    conf.set_num_reduce_tasks(1)
    conf.set_output_format(SequenceFileOutputFormat)
    path = list(_sys.path)
    setup_stream_job(conf,
                     mapper=TB_MAPPER.replace("{path!r}", repr(path)),
                     reducer=TB_REDUCER.replace("{path!r}", repr(path)),
                     io="typedbytes")
    result = JobClient(conf).run_job(conf)
    assert result.successful

    recs = {}
    for st in fs.list_files("mem:///tb/out"):
        if st.path.name.startswith("part-"):
            with fs.open(st.path) as f:
                for k, v in sequencefile.Reader(f):
                    recs[k] = v
    expected = {f"r{i}".encode():
                b"\x00" + f"r{i}".encode() + b"\n" + f"r{i}".encode()
                for i in (1, 2, 3)}
    assert recs == expected


def test_typedbytes_protocol_error_fails_task(tmp_path):
    """A child that emits a dangling key (truncated pair) must FAIL the
    task — not hang the reader thread or silently drop output."""
    import sys as _sys

    import pytest as _pytest

    fs = get_filesystem("mem:///")
    fs.write_bytes("/tberr/in.txt", b"a\n")
    conf = JobConf()
    conf.set_input_paths("mem:///tberr/in.txt")
    conf.set_output_path("mem:///tberr/out")
    conf.set_num_reduce_tasks(0)
    conf.set("mapred.map.max.attempts", 1)
    bad_mapper = (f"{PY} -c \"import sys\n"
                  f"sys.path[:0] = {list(_sys.path)!r}\n"
                  "from tpumr.streaming.typedbytes import write_typed\n"
                  "write_typed(sys.stdout.buffer, b'lone-key')\n"
                  "sys.stdout.buffer.flush()\"")
    setup_stream_job(conf, mapper=bad_mapper, io="typedbytes")
    with _pytest.raises(RuntimeError):
        JobClient(conf).run_job(conf)
