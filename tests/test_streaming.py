"""Streaming tier tests ≈ contrib streaming's TestStreaming*: script
mappers/reducers over stdin/stdout, the stderr reporter protocol, and the
conf-to-environment export."""

import sys

from tpumr.fs import get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.streaming import setup_stream_job

PY = sys.executable

WC_MAPPER = (f"{PY} -c \"import sys\n"
             "for line in sys.stdin:\n"
             "    parts = line.rstrip().split('\\t', 1)\n"
             "    text = parts[1] if len(parts) > 1 else parts[0]\n"
             "    for w in text.split():\n"
             "        print(w + '\\t1')\n"
             "sys.stderr.write('reporter:counter:WC,MAP_LINES,1\\n')\"")

WC_REDUCER = (f"{PY} -c \"import sys\n"
              "cur, total = None, 0\n"
              "for line in sys.stdin:\n"
              "    k, v = line.rstrip().split('\\t')\n"
              "    if k != cur:\n"
              "        if cur is not None:\n"
              "            print(cur + '\\t' + str(total))\n"
              "        cur, total = k, 0\n"
              "    total += int(v)\n"
              "if cur is not None:\n"
              "    print(cur + '\\t' + str(total))\"")


def _read_output(fs, out_dir):
    merged = {}
    for st in fs.list_files(out_dir):
        if st.path.name.startswith("part-"):
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, _, v = line.partition("\t")
                merged[k] = v
    return merged


def test_streaming_wordcount():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/stream/in.txt", b"x y x\nz y x\n" * 5)
    conf = JobConf()
    conf.set_input_paths("mem:///stream/in.txt")
    conf.set_output_path("mem:///stream/out")
    conf.set_num_reduce_tasks(1)
    setup_stream_job(conf, mapper=WC_MAPPER, reducer=WC_REDUCER)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert _read_output(fs, "mem:///stream/out") == \
        {"x": "15", "y": "10", "z": "5"}
    # stderr reporter protocol reached real counters (one per map task)
    assert result.counters.value("WC", "MAP_LINES") >= 1


def test_streaming_cat_identity_and_env():
    """/bin/cat as mapper (the canonical streaming smoke test) + conf keys
    exported to the child environment with dots -> underscores."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/cat/in.txt", b"one\ntwo\n")
    conf = JobConf()
    conf.set_input_paths("mem:///cat/in.txt")
    conf.set_output_path("mem:///cat/out")
    conf.set_num_reduce_tasks(0)
    env_mapper = (f"{PY} -c \"import sys, os\n"
                  "for line in sys.stdin:\n"
                  "    sys.stdout.write(line)\n"
                  "print('jobname\\t' + os.environ['mapred_job_name'])\"")
    conf.set_job_name("envcheck")
    setup_stream_job(conf, mapper=env_mapper)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    out = _read_output(fs, "mem:///cat/out")
    assert out["jobname"] == "envcheck"
    assert "one" in out  # cat passthrough (value lands in the key column)


def test_streaming_failing_child_fails_task():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/sf/in.txt", b"a\n")
    conf = JobConf()
    conf.set_input_paths("mem:///sf/in.txt")
    conf.set_output_path("mem:///sf/out")
    conf.set_num_reduce_tasks(0)
    setup_stream_job(conf, mapper=f"{PY} -c \"import sys; sys.exit(7)\"")
    import pytest
    with pytest.raises(RuntimeError, match="rc=7"):
        JobClient(conf).run_job(conf)


def test_streaming_combiner():
    """Subprocess combiner runs per spill and pre-aggregates map output."""
    fs = get_filesystem("mem:///")
    fs.write_bytes("/comb/in.txt", b"k k k\nk k k\n" * 10)
    conf = JobConf()
    conf.set_input_paths("mem:///comb/in.txt")
    conf.set_output_path("mem:///comb/out")
    conf.set_num_reduce_tasks(1)
    setup_stream_job(conf, mapper=WC_MAPPER, reducer=WC_REDUCER,
                     combiner=WC_REDUCER)
    result = JobClient(conf).run_job(conf)
    assert result.successful
    assert _read_output(fs, "mem:///comb/out") == {"k": "60"}
    # combiner actually folded records before the reduce
    from tpumr.core.counters import TaskCounter
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.COMBINE_INPUT_RECORDS) == 60
    assert result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                 TaskCounter.COMBINE_OUTPUT_RECORDS) == 1
