"""Fair + capacity schedulers against fakes ≈ the reference's contrib
scheduler tests (TestFairScheduler / TestCapacityScheduler drive the
scheduler through the TaskTrackerManager seam; SURVEY.md §2.4). Ours are
additionally TPU-aware — asserted explicitly."""

from tpumr.contrib.capacity import CapacityScheduler
from tpumr.contrib.fairscheduler import FairScheduler, pool_of
from tpumr.mapred.ids import JobID
from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.jobconf import JobConf

from test_scheduler import FakeManager, make_job, tracker_status


def make_fair(jobs, n_trackers=1, **conf_kv):
    sched = FairScheduler()
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched.configure(conf)
    sched.set_manager(FakeManager(jobs, n_trackers))
    return sched


def make_capacity(jobs, n_trackers=1, **conf_kv):
    sched = CapacityScheduler()
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched.configure(conf)
    sched.set_manager(FakeManager(jobs, n_trackers))
    return sched


def make_pool_job(pool, job_num, n_maps=8, kernel=False, n_reduces=0):
    conf = {"mapred.reduce.tasks": n_reduces,
            "mapred.fairscheduler.pool": pool,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    if kernel:
        conf["tpumr.map.kernel"] = "kmeans-assign"
    splits = [{"locations": []} for _ in range(n_maps)]
    return JobInProgress(JobID("test", job_num), conf, splits)


def make_queue_job(queue, job_num, n_maps=8, kernel=False):
    conf = {"mapred.reduce.tasks": 0,
            "mapred.job.queue.name": queue,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    if kernel:
        conf["tpumr.map.kernel"] = "kmeans-assign"
    splits = [{"locations": []} for _ in range(n_maps)]
    return JobInProgress(JobID("test", job_num), conf, splits)


class TestFairScheduler:
    def test_pool_from_conf_or_user(self):
        a = make_pool_job("analytics", 1)
        assert pool_of(a) == "analytics"
        b = make_job(job_num=2)
        b.conf["user.name"] = "erin"
        assert pool_of(b) == "erin"

    def test_starved_pool_gets_slots_first(self):
        # pool A hogs: job1 (earlier) in A, job2 in B; equal weights →
        # assignments must alternate between pools, not drain FIFO
        j1 = make_pool_job("A", 1, n_maps=4)
        j2 = make_pool_job("B", 2, n_maps=4)
        sched = make_fair([j1, j2])
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        assert len(tasks) == 4
        pools = [str(t.attempt_id.task.job) for t in tasks]
        # 2 slots each, interleaved — pure FIFO would give all 4 to job1
        assert pools.count(str(j1.job_id)) == 2
        assert pools.count(str(j2.job_id)) == 2

    def test_weights_skew_shares(self):
        j1 = make_pool_job("heavy", 1, n_maps=8)
        j2 = make_pool_job("light", 2, n_maps=8)
        sched = make_fair([j1, j2],
                          **{"tpumr.fairscheduler.pool.heavy.weight": 3.0})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        assert by_job.count(str(j1.job_id)) == 3
        assert by_job.count(str(j2.job_id)) == 1

    def test_min_share_beats_weight(self):
        j1 = make_pool_job("big", 1, n_maps=8)
        j2 = make_pool_job("guaranteed", 2, n_maps=8)
        sched = make_fair(
            [j1, j2],
            **{"tpumr.fairscheduler.pool.big.weight": 100.0,
               "tpumr.fairscheduler.pool.guaranteed.minmaps": 2})
        tasks = sched.assign_tasks(tracker_status(cpu=2, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        # guaranteed pool is below min share → first slot goes there even
        # though big's weight dwarfs it
        assert by_job.count(str(j2.job_id)) >= 1

    def test_tpu_pass_respects_fair_order_and_kernel_gate(self):
        j1 = make_pool_job("A", 1, n_maps=4, kernel=False)
        j2 = make_pool_job("B", 2, n_maps=4, kernel=True)
        sched = make_fair([j1, j2])
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=1, reduce=0))
        assert len(tasks) == 1
        t = tasks[0]
        assert str(t.attempt_id.task.job) == str(j2.job_id)
        assert t.run_on_tpu and t.tpu_device_id == 0


class TestCapacityScheduler:
    def test_underserved_queue_first(self):
        j1 = make_queue_job("prod", 1, n_maps=8)
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j1, j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        assert by_job.count(str(j1.job_id)) == 3
        assert by_job.count(str(j2.job_id)) == 1

    def test_elasticity_when_other_queue_idle(self):
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        assert len(tasks) == 4  # adhoc takes the whole cluster while idle

    def test_max_capacity_ceiling(self):
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25,
               "tpumr.capacity.adhoc.max-capacity": 50})
        # tracker claims 2 adhoc maps already running cluster-wide
        j2._pending_maps -= {0, 1}  # simulate 2 assigned
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        # ceiling = 50% of 4 slots = 2 running → no more
        assert len(tasks) == 0

    def test_unknown_queue_falls_back_to_default(self):
        j = make_queue_job("nonexistent", 1, n_maps=2)
        sched = make_capacity(
            [j], **{"tpumr.capacity.queues": "default,prod",
                    "tpumr.capacity.prod.capacity": 50,
                    "tpumr.capacity.default.capacity": 50})
        tasks = sched.assign_tasks(tracker_status(cpu=2, tpu=0, reduce=0))
        assert len(tasks) == 2

    def test_tpu_aware(self):
        j = make_queue_job("prod", 1, n_maps=4, kernel=True)
        sched = make_capacity(
            [j], **{"tpumr.capacity.queues": "prod",
                    "tpumr.capacity.prod.capacity": 100})
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=1, reduce=0))
        assert len(tasks) == 1 and tasks[0].run_on_tpu


class TestReducePass:
    def test_fair_minmaps_does_not_leak_into_reduce_order(self):
        # prod has a huge map min-share; its reduces must NOT preempt
        # other pools' reduces (one reduce per heartbeat → check who wins)
        j1 = make_pool_job("prod", 1, n_maps=0, n_reduces=4)
        j2 = make_pool_job("other", 2, n_maps=0, n_reduces=4)
        # make prod busier in the reduce dimension
        j1._pending_reduces -= {0, 1}
        sched = make_fair(
            [j1, j2],
            **{"tpumr.fairscheduler.pool.prod.minmaps": 100})
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=0, reduce=1))
        assert len(tasks) == 1
        assert str(tasks[0].attempt_id.task.job) == str(j2.job_id)

    def test_capacity_reduce_uses_reduce_slot_pool(self):
        # 50% max-capacity against 8 reduce slots = ceiling 4, so a queue
        # with 2 running reduces must still get a reduce (the bug was
        # computing the ceiling against the 4 map slots → 2 >= 2 → starved)
        conf = {"mapred.reduce.tasks": 4,
                "mapred.job.queue.name": "adhoc",
                "mapred.reduce.slowstart.completed.maps": 0.0}
        j = JobInProgress(JobID("test", 1), conf,
                          [])
        j._pending_reduces -= {0, 1}  # 2 reduces already running
        sched = make_capacity(
            [j],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25,
               "tpumr.capacity.adhoc.max-capacity": 50})

        class WideManager(FakeManager):
            def total_slots(self):
                return {"cpu": 4, "tpu": 0, "reduce": 8}

        sched.set_manager(WideManager([j]))
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=0, reduce=1))
        assert len(tasks) == 1 and not tasks[0].is_map

    def test_capacity_unknown_queue_is_last_not_privileged(self):
        known = make_queue_job("prod", 1, n_maps=4)
        stray = make_queue_job("typo", 2, n_maps=4)
        sched = make_capacity(
            [stray, known],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=1, tpu=0, reduce=0))
        # the single slot goes to the configured queue, not the stray job
        assert len(tasks) == 1
        assert str(tasks[0].attempt_id.task.job) == str(known.job_id)


class TestPluggability:
    def test_jobmaster_loads_contrib_scheduler(self):
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("mapred.jobtracker.taskScheduler",
                 "tpumr.contrib.fairscheduler.FairScheduler")
        jm = JobMaster(conf)
        try:
            assert isinstance(jm.scheduler, FairScheduler)
        finally:
            jm.stop()
