"""Fair + capacity schedulers against fakes ≈ the reference's contrib
scheduler tests (TestFairScheduler / TestCapacityScheduler drive the
scheduler through the TaskTrackerManager seam; SURVEY.md §2.4). Ours are
additionally TPU-aware — asserted explicitly."""

from tpumr.contrib.capacity import CapacityScheduler
from tpumr.contrib.fairscheduler import FairScheduler, pool_of
from tpumr.mapred.ids import JobID
from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.jobconf import JobConf

from test_scheduler import FakeManager, make_job, tracker_status


def make_fair(jobs, n_trackers=1, **conf_kv):
    sched = FairScheduler()
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched.configure(conf)
    sched.set_manager(FakeManager(jobs, n_trackers))
    return sched


def make_capacity(jobs, n_trackers=1, **conf_kv):
    sched = CapacityScheduler()
    conf = JobConf()
    for k, v in conf_kv.items():
        conf.set(k, v)
    sched.configure(conf)
    sched.set_manager(FakeManager(jobs, n_trackers))
    return sched


def make_pool_job(pool, job_num, n_maps=8, kernel=False, n_reduces=0):
    conf = {"mapred.reduce.tasks": n_reduces,
            "mapred.fairscheduler.pool": pool,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    if kernel:
        conf["tpumr.map.kernel"] = "kmeans-assign"
    splits = [{"locations": []} for _ in range(n_maps)]
    return JobInProgress(JobID("test", job_num), conf, splits)


def make_queue_job(queue, job_num, n_maps=8, kernel=False):
    conf = {"mapred.reduce.tasks": 0,
            "mapred.job.queue.name": queue,
            "mapred.reduce.slowstart.completed.maps": 0.0}
    if kernel:
        conf["tpumr.map.kernel"] = "kmeans-assign"
    splits = [{"locations": []} for _ in range(n_maps)]
    return JobInProgress(JobID("test", job_num), conf, splits)


class TestFairScheduler:
    def test_pool_from_conf_or_user(self):
        a = make_pool_job("analytics", 1)
        assert pool_of(a) == "analytics"
        b = make_job(job_num=2)
        b.conf["user.name"] = "erin"
        assert pool_of(b) == "erin"

    def test_starved_pool_gets_slots_first(self):
        # pool A hogs: job1 (earlier) in A, job2 in B; equal weights →
        # assignments must alternate between pools, not drain FIFO
        j1 = make_pool_job("A", 1, n_maps=4)
        j2 = make_pool_job("B", 2, n_maps=4)
        sched = make_fair([j1, j2])
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        assert len(tasks) == 4
        pools = [str(t.attempt_id.task.job) for t in tasks]
        # 2 slots each, interleaved — pure FIFO would give all 4 to job1
        assert pools.count(str(j1.job_id)) == 2
        assert pools.count(str(j2.job_id)) == 2

    def test_weights_skew_shares(self):
        j1 = make_pool_job("heavy", 1, n_maps=8)
        j2 = make_pool_job("light", 2, n_maps=8)
        sched = make_fair([j1, j2],
                          **{"tpumr.fairscheduler.pool.heavy.weight": 3.0})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        assert by_job.count(str(j1.job_id)) == 3
        assert by_job.count(str(j2.job_id)) == 1

    def test_min_share_beats_weight(self):
        j1 = make_pool_job("big", 1, n_maps=8)
        j2 = make_pool_job("guaranteed", 2, n_maps=8)
        sched = make_fair(
            [j1, j2],
            **{"tpumr.fairscheduler.pool.big.weight": 100.0,
               "tpumr.fairscheduler.pool.guaranteed.minmaps": 2})
        tasks = sched.assign_tasks(tracker_status(cpu=2, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        # guaranteed pool is below min share → first slot goes there even
        # though big's weight dwarfs it
        assert by_job.count(str(j2.job_id)) >= 1

    def test_tpu_pass_respects_fair_order_and_kernel_gate(self):
        j1 = make_pool_job("A", 1, n_maps=4, kernel=False)
        j2 = make_pool_job("B", 2, n_maps=4, kernel=True)
        sched = make_fair([j1, j2])
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=1, reduce=0))
        assert len(tasks) == 1
        t = tasks[0]
        assert str(t.attempt_id.task.job) == str(j2.job_id)
        assert t.run_on_tpu and t.tpu_device_id == 0


class TestCapacityScheduler:
    def test_underserved_queue_first(self):
        j1 = make_queue_job("prod", 1, n_maps=8)
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j1, j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        by_job = [str(t.attempt_id.task.job) for t in tasks]
        assert by_job.count(str(j1.job_id)) == 3
        assert by_job.count(str(j2.job_id)) == 1

    def test_elasticity_when_other_queue_idle(self):
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        assert len(tasks) == 4  # adhoc takes the whole cluster while idle

    def test_max_capacity_ceiling(self):
        j2 = make_queue_job("adhoc", 2, n_maps=8)
        sched = make_capacity(
            [j2],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25,
               "tpumr.capacity.adhoc.max-capacity": 50})
        # tracker claims 2 adhoc maps already running cluster-wide
        j2._pending_maps -= {0, 1}  # simulate 2 assigned
        tasks = sched.assign_tasks(tracker_status(cpu=4, tpu=0, reduce=0))
        # ceiling = 50% of 4 slots = 2 running → no more
        assert len(tasks) == 0

    def test_unknown_queue_falls_back_to_default(self):
        j = make_queue_job("nonexistent", 1, n_maps=2)
        sched = make_capacity(
            [j], **{"tpumr.capacity.queues": "default,prod",
                    "tpumr.capacity.prod.capacity": 50,
                    "tpumr.capacity.default.capacity": 50})
        tasks = sched.assign_tasks(tracker_status(cpu=2, tpu=0, reduce=0))
        assert len(tasks) == 2

    def test_tpu_aware(self):
        j = make_queue_job("prod", 1, n_maps=4, kernel=True)
        sched = make_capacity(
            [j], **{"tpumr.capacity.queues": "prod",
                    "tpumr.capacity.prod.capacity": 100})
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=1, reduce=0))
        assert len(tasks) == 1 and tasks[0].run_on_tpu


class TestReducePass:
    def test_fair_minmaps_does_not_leak_into_reduce_order(self):
        # prod has a huge map min-share; its reduces must NOT preempt
        # other pools' reduces (one reduce per heartbeat → check who wins)
        j1 = make_pool_job("prod", 1, n_maps=0, n_reduces=4)
        j2 = make_pool_job("other", 2, n_maps=0, n_reduces=4)
        # make prod busier in the reduce dimension
        j1._pending_reduces -= {0, 1}
        sched = make_fair(
            [j1, j2],
            **{"tpumr.fairscheduler.pool.prod.minmaps": 100})
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=0, reduce=1))
        assert len(tasks) == 1
        assert str(tasks[0].attempt_id.task.job) == str(j2.job_id)

    def test_capacity_reduce_uses_reduce_slot_pool(self):
        # 50% max-capacity against 8 reduce slots = ceiling 4, so a queue
        # with 2 running reduces must still get a reduce (the bug was
        # computing the ceiling against the 4 map slots → 2 >= 2 → starved)
        conf = {"mapred.reduce.tasks": 4,
                "mapred.job.queue.name": "adhoc",
                "mapred.reduce.slowstart.completed.maps": 0.0}
        j = JobInProgress(JobID("test", 1), conf,
                          [])
        j._pending_reduces -= {0, 1}  # 2 reduces already running
        sched = make_capacity(
            [j],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25,
               "tpumr.capacity.adhoc.max-capacity": 50})

        class WideManager(FakeManager):
            def total_slots(self):
                return {"cpu": 4, "tpu": 0, "reduce": 8}

        sched.set_manager(WideManager([j]))
        tasks = sched.assign_tasks(tracker_status(cpu=0, tpu=0, reduce=1))
        assert len(tasks) == 1 and not tasks[0].is_map

    def test_capacity_unknown_queue_is_last_not_privileged(self):
        known = make_queue_job("prod", 1, n_maps=4)
        stray = make_queue_job("typo", 2, n_maps=4)
        sched = make_capacity(
            [stray, known],
            **{"tpumr.capacity.queues": "prod,adhoc",
               "tpumr.capacity.prod.capacity": 75,
               "tpumr.capacity.adhoc.capacity": 25})
        tasks = sched.assign_tasks(tracker_status(cpu=1, tpu=0, reduce=0))
        # the single slot goes to the configured queue, not the stray job
        assert len(tasks) == 1
        assert str(tasks[0].attempt_id.task.job) == str(known.job_id)


class TestPluggability:
    def test_jobmaster_loads_contrib_scheduler(self):
        from tpumr.mapred.jobtracker import JobMaster
        conf = JobConf()
        conf.set("mapred.jobtracker.taskScheduler",
                 "tpumr.contrib.fairscheduler.FairScheduler")
        jm = JobMaster(conf)
        try:
            assert isinstance(jm.scheduler, FairScheduler)
        finally:
            jm.stop()


class TestFairPreemption:
    """≈ FairScheduler.preemptTasksIfNecessary: a pool starved below its min
    share beyond the timeout reclaims slots by killing the NEWEST running
    maps of over-share pools (killed, not failed — no attempt budget spent).
    Deterministic: time injected, no daemons."""

    def _run_maps(self, sched, job, n, start_base):
        """Assign n maps to the hog job and back-date their start times so
        victim ordering (newest first) is deterministic."""
        import time as _time
        from tpumr.mapred.task import TaskState, TaskStatus
        tasks = []
        for i in range(n):
            t = job.obtain_new_map_task("host0", run_on_tpu=False)
            assert t is not None
            st = TaskStatus(attempt_id=t.attempt_id, is_map=True,
                            state=TaskState.RUNNING,
                            start_time=start_base + i)
            job.update_task_status(st, "h:0")
            tasks.append(t)
        return tasks

    def _make(self, hog, starved, timeout_ms=1000):
        return make_fair(
            [hog, starved],
            **{"tpumr.fairscheduler.preemption": True,
               "tpumr.fairscheduler.preemption.timeout.ms": timeout_ms,
               "tpumr.fairscheduler.preemption.interval.ms": 0,
               "tpumr.fairscheduler.pool.gold.minmaps": 2})

    def test_starved_pool_preempts_newest_after_timeout(self):
        import time as _time
        hog = make_pool_job("bulk", 1, n_maps=6)
        starved = make_pool_job("gold", 2, n_maps=4)
        sched = self._make(hog, starved)
        hog_tasks = self._run_maps(sched, hog, 4, start_base=1000.0)

        now = _time.time()
        sched._preempt_if_starved(now=now)          # starts the clock
        assert not any(hog.should_kill_attempt(str(t.attempt_id))
                       for t in hog_tasks)          # not yet: timeout unmet
        sched._preempt_if_starved(now=now + 2.0)    # past 1s timeout
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        # deficit = min share (2) - usage (0) → two newest victims
        assert len(marked) == 2
        newest_two = {str(t.attempt_id) for t in hog_tasks[-2:]}
        assert {str(t.attempt_id) for t in marked} == newest_two

    def test_preemption_never_breaches_victims_own_min_share(self):
        import time as _time
        hog = make_pool_job("bulk", 1, n_maps=6)
        starved = make_pool_job("gold", 2, n_maps=4)
        sched = make_fair(
            [hog, starved],
            **{"tpumr.fairscheduler.preemption": True,
               "tpumr.fairscheduler.preemption.timeout.ms": 1000,
               "tpumr.fairscheduler.preemption.interval.ms": 0,
               "tpumr.fairscheduler.pool.gold.minmaps": 4,
               "tpumr.fairscheduler.pool.bulk.minmaps": 3})
        hog_tasks = self._run_maps(sched, hog, 4, start_base=1000.0)
        now = _time.time()
        sched._preempt_if_starved(now=now)
        sched._preempt_if_starved(now=now + 2.0)
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        # bulk runs 4 with min share 3: only ONE is preemptable even though
        # gold's deficit is 4
        assert len(marked) == 1
        # repeated checks while the kill is in flight must NOT erode the
        # victim pool below ITS min share (in-flight counts as surplus
        # already spent)
        sched._preempt_if_starved(now=now + 4.0)
        sched._preempt_if_starved(now=now + 6.0)
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        assert len(marked) == 1

    def test_starvation_clock_resets_when_pool_empties(self):
        """A pool that stops running jobs while starved must not keep a
        stale clock — a later job in it has to re-serve the full timeout."""
        import time as _time
        hog = make_pool_job("bulk", 1, n_maps=6)
        starved = make_pool_job("gold", 2, n_maps=4)
        sched = self._make(hog, starved)
        hog_tasks = self._run_maps(sched, hog, 4, start_base=1000.0)
        now = _time.time()
        sched._preempt_if_starved(now=now)             # clock starts
        # gold's job leaves the running set (finished/killed)
        sched.set_manager(FakeManager([hog]))
        sched._preempt_if_starved(now=now + 0.5)       # clock dropped
        # a NEW gold job appears much later
        gold2 = make_pool_job("gold", 3, n_maps=4)
        sched.set_manager(FakeManager([hog, gold2]))
        sched._preempt_if_starved(now=now + 10.0)      # first sighting
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        assert marked == []                            # timeout not served
        sched._preempt_if_starved(now=now + 12.0)      # 2s > 1s timeout
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        assert len(marked) == 2

    def test_lost_tracker_clears_preempt_marks(self):
        """A preempt-marked attempt on a lost tracker must not linger as a
        phantom in-flight kill suppressing future preemption."""
        import time as _time
        hog = make_pool_job("bulk", 1, n_maps=6)
        starved = make_pool_job("gold", 2, n_maps=4)
        sched = self._make(hog, starved)
        hog_tasks = self._run_maps(sched, hog, 4, start_base=1000.0)
        now = _time.time()
        sched._preempt_if_starved(now=now)
        sched._preempt_if_starved(now=now + 2.0)
        marked = [str(t.attempt_id) for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        assert len(marked) == 2
        hog.requeue_lost_attempts(marked)  # tracker died before kills landed
        assert hog.preempt_pending() == set()

    def test_in_flight_kills_not_double_counted(self):
        import time as _time
        hog = make_pool_job("bulk", 1, n_maps=6)
        starved = make_pool_job("gold", 2, n_maps=4)
        sched = self._make(hog, starved)
        hog_tasks = self._run_maps(sched, hog, 4, start_base=1000.0)
        now = _time.time()
        sched._preempt_if_starved(now=now)
        sched._preempt_if_starved(now=now + 2.0)
        sched._preempt_if_starved(now=now + 4.0)   # kills still in flight
        marked = [t for t in hog_tasks
                  if hog.should_kill_attempt(str(t.attempt_id))]
        assert len(marked) == 2  # no extra victims while kills in flight

    def test_killed_preempted_attempt_requeues_without_failure(self):
        import time as _time
        from tpumr.mapred.task import TaskState, TaskStatus
        hog = make_pool_job("bulk", 1, n_maps=2)
        starved = make_pool_job("gold", 2, n_maps=2)
        sched = self._make(hog, starved)
        [t] = self._run_maps(sched, hog, 1, start_base=1000.0)
        now = _time.time()
        sched._preempt_if_starved(now=now)
        sched._preempt_if_starved(now=now + 2.0)
        aid = str(t.attempt_id)
        assert hog.should_kill_attempt(aid)
        pending_before = hog.pending_map_count()
        hog.update_task_status(TaskStatus(
            attempt_id=t.attempt_id, is_map=True, state=TaskState.KILLED,
            start_time=1000.0, finish_time=now), "h:0")
        assert hog.pending_map_count() == pending_before + 1  # requeued
        assert hog.maps[t.partition].failures == 0            # no budget
        assert not hog.preempt_pending()                      # mark cleared


class TestCapacityMemoryMatching:
    """≈ CapacityTaskScheduler memory matching: trackers report available
    memory; jobs declaring more than a tracker has left are skipped there
    (not failed), and assignment consumes the budget within a heartbeat."""

    def _mem_job(self, job_num, map_mb, n_maps=4):
        conf = {"mapred.reduce.tasks": 0,
                "mapred.job.queue.name": "default",
                "mapred.job.map.memory.mb": map_mb,
                "mapred.reduce.slowstart.completed.maps": 0.0}
        splits = [{"locations": []} for _ in range(n_maps)]
        return JobInProgress(JobID("test", job_num), conf, splits)

    def test_high_memory_job_skips_small_tracker(self):
        big = self._mem_job(1, map_mb=4000)
        small = self._mem_job(2, map_mb=500)
        sched = make_capacity([big, small])
        tts = tracker_status(cpu=2, tpu=0, reduce=0)
        tts["available_memory_mb"] = 1200
        tasks = sched.assign_tasks(tts)
        # both slots go to the small job; the 4 GB job never lands here
        assert len(tasks) == 2
        assert all(str(t.attempt_id.task.job) == str(small.job_id)
                   for t in tasks)
        assert all(t.memory_mb == 500 for t in tasks)

    def test_memory_budget_consumed_within_heartbeat(self):
        job = self._mem_job(1, map_mb=700)
        sched = make_capacity([job])
        tts = tracker_status(cpu=3, tpu=0, reduce=0)
        tts["available_memory_mb"] = 1500
        tasks = sched.assign_tasks(tts)
        assert len(tasks) == 2  # 700+700 fits, third (2100) would not

    def test_unlimited_when_tracker_reports_none(self):
        job = self._mem_job(1, map_mb=100_000)
        sched = make_capacity([job])
        tasks = sched.assign_tasks(tracker_status(cpu=2, tpu=0, reduce=0))
        assert len(tasks) == 2  # no memory report = matching off


def test_priority_orders_within_pool_and_queue():
    """Within one pool (fair) a HIGH job drains before an
    earlier-submitted NORMAL one; the capacity scheduler honors
    priority only when supports-priority is enabled (the reference's
    opt-in, default off)."""
    for make, kv in ((make_fair, {}),
                     (make_capacity,
                      {"tpumr.capacity.supports-priority": True})):
        j1 = make_pool_job("p", 1, n_maps=2)
        j2 = make_pool_job("p", 2, n_maps=2)
        j2.priority = "HIGH"
        sched = make([j1, j2], **kv)
        order = [str(t.attempt_id.task.job)
                 for t in sched.assign_tasks(tracker_status(cpu=4, tpu=0))
                 if t.is_map]
        assert order[:2] == ["job_test_0002"] * 2, (make.__name__, order)


def test_capacity_priority_off_by_default():
    """Without supports-priority, within-queue order stays submit time
    (reference default: mapred.capacity-scheduler...supports-priority
    = false)."""
    j1 = make_pool_job("p", 1, n_maps=2)
    j2 = make_pool_job("p", 2, n_maps=2)
    j2.priority = "HIGH"
    sched = make_capacity([j1, j2])
    order = [str(t.attempt_id.task.job)
             for t in sched.assign_tasks(tracker_status(cpu=4, tpu=0))
             if t.is_map]
    assert order[:2] == ["job_test_0001"] * 2, order
