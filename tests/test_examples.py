"""Examples tier ≈ the reference's src/examples inventory (SURVEY.md §2.4):
terasort family, sort, secondarysort, join, sleep, randomwriter."""

import numpy as np

from tpumr.cli import main as cli_main
from tpumr.fs import get_filesystem
from tpumr.io import sequencefile


def _read_seq_parts(fs, out_dir):
    recs = []
    for st in sorted(fs.list_files(out_dir), key=lambda s: str(s.path)):
        if not st.path.name.startswith("part"):
            continue
        with fs.open(st.path) as f:
            recs.extend(sequencefile.Reader(f))
    return recs


class TestTeraSort:
    def test_teragen_terasort_teravalidate(self, capsys):
        fs = get_filesystem("mem:///")
        assert cli_main(["examples", "teragen", "1000", "mem:///ts/gen",
                         "-m", "3"]) == 0
        recs = _read_seq_parts(fs, "/ts/gen")
        assert len(recs) == 1000
        assert all(len(k) == 10 and len(v) == 90 for k, v in recs)
        # deterministic row ids present
        rows = sorted(v[:10] for _, v in recs)
        assert rows[0] == b"0000000000" and rows[-1] == b"0000000999"

        assert cli_main(["examples", "terasort", "mem:///ts/gen",
                         "mem:///ts/sorted", "-r", "3"]) == 0
        out = _read_seq_parts(fs, "/ts/sorted")
        assert len(out) == 1000
        keys = [k for k, _ in out]
        assert keys == sorted(keys), "parts concatenated must be sorted"

        assert cli_main(["examples", "teravalidate", "mem:///ts/sorted",
                         "mem:///ts/report"]) == 0
        assert "globally sorted" in capsys.readouterr().out

    def test_teravalidate_catches_misorder(self, capsys):
        fs = get_filesystem("mem:///")
        # two part files with an inverted cross-part boundary
        for name, keys in (("part-00000", [b"zzz", b"aaa"]),
                           ("part-00001", [b"mmm"])):
            with fs.create(f"/tv/bad/{name}") as f:
                w = sequencefile.Writer(f)
                for k in keys:
                    w.append(k, b"x")
                w.close()
        assert cli_main(["examples", "teravalidate", "mem:///tv/bad",
                         "mem:///tv/report"]) == 1
        assert "FAILED" in capsys.readouterr().out


class TestSortAndRandomWriter:
    def test_randomwriter_then_total_order_sort(self):
        fs = get_filesystem("mem:///")
        assert cli_main(["examples", "randomwriter", "mem:///rw/data",
                         "-m", "2", "--bytes-per-map", "20000"]) == 0
        inp = _read_seq_parts(fs, "/rw/data")
        assert sum(len(k) + len(v) for k, v in inp) >= 40000
        assert cli_main(["examples", "sort", "mem:///rw/data",
                         "mem:///rw/sorted", "-r", "2",
                         "--total-order"]) == 0
        out = _read_seq_parts(fs, "/rw/sorted")
        assert len(out) == len(inp)
        keys = [k for k, _ in out]
        assert keys == sorted(keys)


class TestSecondarySort:
    def test_values_sorted_within_group(self):
        fs = get_filesystem("mem:///")
        rng = np.random.default_rng(5)
        lines = []
        for first in (3, 1, 2):
            for second in rng.permutation(20):
                lines.append(f"{first} {int(second)}")
        rng.shuffle(lines)
        fs.write_bytes("/ss/in.txt", ("\n".join(lines) + "\n").encode())
        assert cli_main(["examples", "secondarysort", "mem:///ss/in.txt",
                         "mem:///ss/out"]) == 0
        text = fs.read_bytes("/ss/out/part-00000").decode()
        got = {}
        for line in text.splitlines():
            k, _, v = line.partition("\t")
            got[int(k)] = v
        assert sorted(got) == [1, 2, 3]
        for v in got.values():
            import ast
            seconds = ast.literal_eval(v)
            assert seconds == sorted(seconds), "secondary order violated"


class TestJoin:
    def test_inner_and_outer(self):
        fs = get_filesystem("mem:///")
        fs.write_bytes("/j/left.txt",
                       b"k1\tL|ankara\nk2\tL|oslo\nk3\tL|lima\n")
        fs.write_bytes("/j/right.txt",
                       b"k1\tR|tr\nk3\tR|pe\nk4\tR|xx\n")
        assert cli_main(["examples", "join", "mem:///j/left.txt",
                         "mem:///j/right.txt", "mem:///j/inner"]) == 0
        text = fs.read_bytes("/j/inner/part-00000").decode()
        rows = dict(line.split("\t", 1) for line in text.splitlines())
        assert rows == {"k1": "ankara\ttr", "k3": "lima\tpe"}
        assert cli_main(["examples", "join", "mem:///j/left.txt",
                         "mem:///j/right.txt", "mem:///j/outer",
                         "--outer"]) == 0
        text = fs.read_bytes("/j/outer/part-00000").decode()
        assert len(text.splitlines()) == 4  # k1 k2 k3 k4


class TestSleep:
    def test_sleep_runs(self):
        assert cli_main(["examples", "sleep", "-m", "2", "-r", "1",
                         "--map-ms", "1", "--reduce-ms", "1"]) == 0


class TestVectorizedValidate:
    def test_batch_order_check_matches_per_record(self):
        """map_record_batch must reproduce exact Python-bytes ordering —
        including prefix keys, trailing-NUL keys, and embedded NULs
        (the cases padded comparisons classically get wrong)."""
        from tpumr.examples.terasort import TeraValidateMapper
        from tpumr.io.recordbatch import RecordBatch
        from tpumr.mapred.api import OutputCollector
        from tpumr.mapred.jobconf import JobConf

        cases = [
            [b"a", b"ab", b"b"],                        # sorted, prefixes
            [b"ab", b"a"],                              # prefix inversion
            [b"ab", b"ab\x00"],                         # trailing NUL asc
            [b"ab\x00", b"ab"],                         # trailing NUL inv
            [b"a\x00b", b"a\x00a"],                     # embedded NUL inv
            [b"a\x00a", b"a\x00b"],                     # embedded NUL asc
            [b"x" * 10, b"x" * 9 + b"y", b"z"],         # fixed width
            [b"k", b"k", b"k"],                         # all equal
            [b"", b"", b""],                            # all empty keys
        ]
        for keys in cases:
            expect = sum(1 for i in range(1, len(keys))
                         if keys[i] < keys[i - 1])
            batch = RecordBatch.from_pairs([(k, b"v") for k in keys])
            m = TeraValidateMapper()
            m.configure(JobConf())
            got = []
            m.map_record_batch(batch, OutputCollector(
                lambda k, v: got.append((k, v))), None)
            m.close()
            ordinal, (first, last, errors) = got[0]
            assert errors == expect, (keys, errors, expect)
            assert first == keys[0] and last == keys[-1]
