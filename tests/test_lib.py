"""mapred.lib helpers ≈ the reference's lib/ test coverage
(TestKeyFieldHelper, TestChainMapReduce, TestMultipleInputs,
TestMultipleOutputs, aggregate tests)."""

import numpy as np
import pytest

from tpumr.fs import get_filesystem
from tpumr.mapred import JobConf, Mapper, Reducer, run_job
from tpumr.mapred.lib import (ChainMapper, ChainReducer,
                              FieldSelectionMapReduce, InverseMapper,
                              KeyFieldBasedComparator, MultipleInputs,
                              MultipleOutputs, RegexMapper,
                              TokenCountMapper, ValueAggregatorCombiner,
                              ValueAggregatorReducer)


class SumReducer(Reducer):
    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))


def _read(fs, path):
    return dict(l.split("\t", 1) for l in
                fs.read_bytes(path).decode().splitlines())


def test_token_count_and_regex_mappers():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib1/in.txt", b"aa bb aa\ncc aa\n")
    conf = JobConf()
    conf.set_input_paths("mem:///lib1/in.txt")
    conf.set_output_path("mem:///lib1/out")
    conf.set_mapper_class(TokenCountMapper)
    conf.set_reducer_class(SumReducer)
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    assert _read(fs, "mem:///lib1/out/part-00000") == {
        "aa": "3", "bb": "1", "cc": "1"}

    conf = JobConf()
    conf.set_input_paths("mem:///lib1/in.txt")
    conf.set_output_path("mem:///lib1/out2")
    conf.set_mapper_class(RegexMapper)
    conf.set("mapred.mapper.regex", r"[abc]{2}")
    conf.set_reducer_class(SumReducer)
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    assert _read(fs, "mem:///lib1/out2/part-00000") == {
        "aa": "3", "bb": "1", "cc": "1"}


def test_field_selection():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib2/in.txt",
                   b"u1\tWA\t10\tx\nu2\tOR\t20\ty\nu1\tWA\t30\tz\n")
    conf = JobConf()
    conf.set_input_paths("mem:///lib2/in.txt")
    conf.set_output_path("mem:///lib2/out")
    conf.set_mapper_class(FieldSelectionMapReduce)
    conf.set_reducer_class(FieldSelectionMapReduce)
    conf.set("mapred.text.key.value.fields.spec", "0,1:2-")
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    lines = sorted(fs.read_bytes("mem:///lib2/out/part-00000")
                   .decode().splitlines())
    assert lines == ["u1\tWA\t10\tx", "u1\tWA\t30\tz", "u2\tOR\t20\ty"]


def test_key_field_based_comparator():
    from tpumr.io.writable import serialize
    conf = JobConf()
    conf.set("mapred.text.key.comparator.options", "-k2,2nr -k1,1")
    cmp_ = KeyFieldBasedComparator(conf)
    keys = ["b\t2", "a\t10", "c\t10", "a\t1"]
    got = sorted(keys, key=lambda k: cmp_.sort_key(serialize(k)))
    # field 2 numeric DESC, then field 1 ASC
    assert got == ["a\t10", "c\t10", "b\t2", "a\t1"]

    # sort(1) semantics: -k2 (no end) = field 2 through END of key
    conf2 = JobConf()
    conf2.set("mapred.text.key.comparator.options", "-k2")
    open_end = KeyFieldBasedComparator(conf2)
    ks = ["a\t5\ty", "b\t5\tx"]
    got = sorted(ks, key=lambda k: open_end.sort_key(serialize(k)))
    assert got == ["b\t5\tx", "a\t5\ty"]  # tie on f2 broken by f3

    # char offsets: explicit unsupported error, never silently wrong
    conf3 = JobConf()
    conf3.set("mapred.text.key.comparator.options", "-k1.3,1.5")
    with pytest.raises(ValueError, match="char offsets"):
        KeyFieldBasedComparator(conf3)

    # end-to-end: job sorted by the comparator
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib3/in.txt", b"b\t2\na\t10\nc\t10\na\t1\n")

    class LineKeyMapper(Mapper):
        def map(self, key, value, output, reporter):
            v = value if isinstance(value, str) else value.decode()
            output.collect(v, 1)

    conf = JobConf()
    conf.set_input_paths("mem:///lib3/in.txt")
    conf.set_output_path("mem:///lib3/out")
    conf.set_mapper_class(LineKeyMapper)
    conf.set_output_key_comparator_class(KeyFieldBasedComparator)
    conf.set("mapred.text.key.comparator.options", "-k2,2nr -k1,1")
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    order = [l.split("\t")[0] + "\t" + l.split("\t")[1] for l in
             fs.read_bytes("mem:///lib3/out/part-00000")
             .decode().splitlines()]
    assert order == ["a\t10", "c\t10", "b\t2", "a\t1"]




class SplitMapper(Mapper):
    def map(self, key, value, output, reporter):
        v = value if isinstance(value, str) else value.decode()
        a, b = v.split()
        output.collect(a, int(b))


class DoubleMapper(Mapper):
    def map(self, key, value, output, reporter):
        output.collect(key, value * 2)


class UpperMapper(Mapper):
    def map(self, key, value, output, reporter):
        output.collect(str(key).upper(), value)


class CsvMapper(Mapper):
    def map(self, key, value, output, reporter):
        v = value if isinstance(value, str) else value.decode()
        k, n = v.split(",")
        output.collect(k, int(n))


class TsvMapper(Mapper):
    def map(self, key, value, output, reporter):
        v = value if isinstance(value, str) else value.decode()
        k, n = v.split("\t")
        output.collect(k, int(n))

def test_chain_mapper_and_reducer():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib4/in.txt", b"x 1\ny 2\n")

    conf = JobConf()
    conf.set_input_paths("mem:///lib4/in.txt")
    conf.set_output_path("mem:///lib4/out")
    ChainMapper.add_mapper(conf, SplitMapper)
    ChainMapper.add_mapper(conf, DoubleMapper)   # [MAP+]
    ChainReducer.set_reducer(conf, SumReducer)
    ChainReducer.add_mapper(conf, UpperMapper)   # [REDUCE MAP*]
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    assert _read(fs, "mem:///lib4/out/part-00000") == {"X": "2", "Y": "4"}


def test_multiple_inputs_routes_by_path():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib5/csv/a.txt", b"k,1\nk,2\n")
    fs.write_bytes("/lib5/tsv/b.txt", b"k\t3\n")

    conf = JobConf()
    conf.set_output_path("mem:///lib5/out")
    MultipleInputs.add_input_path(conf, "mem:///lib5/csv", CsvMapper)
    MultipleInputs.add_input_path(conf, "mem:///lib5/tsv", TsvMapper)
    conf.set_reducer_class(SumReducer)
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    assert _read(fs, "mem:///lib5/out/part-00000") == {"k": "6"}


def test_multiple_outputs_side_files_follow_commit():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib6/in.txt", b"good 1\nbad 2\ngood 3\n")

    class Router(Mapper):
        def configure(self, conf):
            self._conf = conf
            self._mo = None

        def map(self, key, value, output, reporter):
            if self._mo is None:
                self._mo = MultipleOutputs(self._conf)
            v = value if isinstance(value, str) else value.decode()
            tag, n = v.split()
            if tag == "bad":
                self._mo.collector("rejected").collect(tag, n)
            else:
                output.collect(tag, int(n))

        def close(self):
            if self._mo is not None:
                self._mo.close()

    conf = JobConf()
    conf.set_input_paths("mem:///lib6/in.txt")
    conf.set_output_path("mem:///lib6/out")
    conf.set_mapper_class(Router)
    conf.set_num_reduce_tasks(0)
    assert run_job(conf).successful
    names = {str(s.path.name) for s in fs.list_status("/lib6/out")}
    assert "rejected-00000" in names, names
    assert fs.read_bytes("mem:///lib6/out/rejected-00000") == b"bad\t2\n"
    main = fs.read_bytes("mem:///lib6/out/part-00000").decode()
    assert sorted(main.splitlines()) == ["good\t1", "good\t3"]

    for bad_name in ("../escape", "part"):
        with pytest.raises(ValueError, match="bad MultipleOutputs"):
            MultipleOutputs(conf).collector(bad_name)

    # map-side named outputs in a job WITH reducers commit too
    conf = JobConf()
    conf.set_input_paths("mem:///lib6/in.txt")
    conf.set_output_path("mem:///lib6/out2")
    conf.set_mapper_class(Router)
    conf.set_reducer_class(SumReducer)

    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    assert fs.read_bytes("mem:///lib6/out2/rejected-00000") == b"bad\t2\n"
    assert _read(fs, "mem:///lib6/out2/part-00000") == {"good": "4"}


def test_key_field_based_partitioner():
    """api.KeyFieldBasedPartitioner: records sharing the leading fields
    land in the same partition regardless of trailing fields."""
    from tpumr.mapred.api import KeyFieldBasedPartitioner
    p = KeyFieldBasedPartitioner(num_fields=2)
    a = p.get_partition("u1\tWA\textra1", None, 16)
    b = p.get_partition("u1\tWA\textra2", None, 16)
    c = p.get_partition("u2\tOR\textra1", None, 16)
    assert a == b
    assert 0 <= a < 16 and 0 <= c < 16
    # and distinct prefixes spread (not a constant function)
    parts = {p.get_partition(f"u{i}\tX", None, 64) for i in range(40)}
    assert len(parts) > 8


def test_aggregate_framework():
    fs = get_filesystem("mem:///")
    fs.write_bytes("/lib7/in.txt", b"apple 3\npear 5\napple 4\n")

    class Emit(Mapper):
        def map(self, key, value, output, reporter):
            v = value if isinstance(value, str) else value.decode()
            word, n = v.split()
            output.collect(f"LongValueSum:{word}", int(n))
            output.collect(f"LongValueMax:max-{word}", int(n))
            output.collect("UniqValueCount:words", word)
            output.collect("ValueHistogram:lens", len(word))

    conf = JobConf()
    conf.set_input_paths("mem:///lib7/in.txt")
    conf.set_output_path("mem:///lib7/out")
    conf.set_mapper_class(Emit)
    conf.set_reducer_class(ValueAggregatorReducer)
    conf.set_combiner_class(ValueAggregatorCombiner)
    conf.set_num_reduce_tasks(1)
    assert run_job(conf).successful
    got = _read(fs, "mem:///lib7/out/part-00000")
    assert got["apple"] == "7" and got["pear"] == "5"
    assert got["max-apple"] == "4"
    assert got["words"] == "2"
    assert got["lens"] == "4:1;5:2"  # pear(4)x1, apple(5)x2


def test_streaming_reducer_aggregate(tmp_path):
    import stat
    mapper = tmp_path / "map.py"
    mapper.write_text(
        "#!/usr/bin/env python3\nimport sys\n"
        "for line in sys.stdin:\n"
        "    w = line.split()[0]\n"
        "    print(f'LongValueSum:{w}\\t1')\n")
    mapper.chmod(mapper.stat().st_mode | stat.S_IXUSR)
    src = tmp_path / "in.txt"
    src.write_text("dog x\ncat y\ndog z\n")
    from tpumr.cli import main as cli_main
    out = tmp_path / "out"
    assert cli_main(["streaming", "-input", f"file://{src}",
                     "-output", f"file://{out}",
                     "-mapper", f"python3 {mapper}",
                     "-reducer", "aggregate"]) == 0
    got = dict(l.split("\t") for l in
               (out / "part-00000").read_text().splitlines())
    assert got == {"dog": "2", "cat": "1"}
