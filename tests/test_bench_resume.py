"""Unit tests for bench.py's --resume planning machinery.

The resume predicates decide whether hour-long completed phases are
kept or re-measured, and whether device rows can be silently relabeled
across backends/scales — load-bearing enough for the artifact the
driver captures that they get direct coverage here (the end-to-end
flows are driven by the bench itself; these pin the predicate
semantics against row-key / PHASES drift).
"""

from __future__ import annotations

import importlib.util
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    return mod


def _clean_prior(bench, names=None, device_rows=True):
    """A prior artifact where every named phase completed cleanly."""
    prior: dict = {}
    for name, _, _, _ in bench.PHASES:
        if names is not None and name not in names:
            continue
        prior[f"phase_{name}_s"] = 10.0
        if device_rows and name in bench.DEVICE_SENTINEL:
            prior[bench.DEVICE_SENTINEL[name]] = 1.5
        prior[name.rstrip("s") + "_x" if name == "codecs"
              else name + "_something"] = 1
    return prior


# ------------------------------------------------------------ ownership


def test_every_sentinel_owned_by_its_phase(bench):
    """DEVICE_SENTINEL and phase_owns must agree, or invalidation
    leaves a sentinel behind and resume skips a half-invalidated
    phase."""
    for name, key in bench.DEVICE_SENTINEL.items():
        assert bench.phase_owns(name, key), (name, key)
        # ...and no OTHER phase owns it
        for other, _, _, _ in bench.PHASES:
            if other != name:
                assert not bench.phase_owns(other, key), (other, key)


def test_terasort_pair_ownership_disjoint(bench):
    assert bench.phase_owns("terasort", "terasort_host_job_s")
    assert bench.phase_owns("terasort", "terasort_device_cold_job_s")
    assert not bench.phase_owns(
        "terasort", "terasort_device_fresh_process_cached_s")
    assert bench.phase_owns(
        "terasort_fresh", "terasort_device_fresh_process_cached_s")
    assert not bench.phase_owns("terasort_fresh", "terasort_host_job_s")


def test_kmeans_does_not_own_kernel_rows(bench):
    assert not bench.phase_owns("kmeans", "kernel_kmeans_mrec_per_s")
    assert bench.phase_owns("kernels", "kernel_kmeans_mrec_per_s")
    assert bench.phase_owns("codecs", "codec_tlz_text_ratio")


# ------------------------------------------------------------ phase_done


def test_phase_done_requires_timing_and_no_marker(bench):
    assert not bench.phase_done({}, "pi", "optional", tpu_ok=True)
    prior = {"phase_pi_s": 5.0, "pi_tpu_job_s": 0.4}
    assert bench.phase_done(prior, "pi", "optional", tpu_ok=True)
    prior["bench_pi"] = "failed: phase exited rc=3"
    assert not bench.phase_done(prior, "pi", "optional", tpu_ok=True)


def test_phase_done_missing_device_rows_reruns_when_tpu_back(bench):
    """A phase that completed host-only under a wedge re-runs once the
    device is back — but counts as done while it is still down."""
    prior = {"phase_pi_s": 5.0}          # no pi_tpu_job_s captured
    assert not bench.phase_done(prior, "pi", "optional", tpu_ok=True)
    assert bench.phase_done(prior, "pi", "optional", tpu_ok=False)
    # marker-string sentinel values read as not-captured too
    prior["pi_tpu_job_s"] = "skipped: tpu unavailable"
    assert not bench.phase_done(prior, "pi", "optional", tpu_ok=True)


# ----------------------------------------------------------- plan_resume


def test_plan_rerun_only_failed_phase(bench):
    prior = _clean_prior(bench)
    prior["bench_wordcount"] = "failed: phase timeout 900s"
    rows = dict(prior)
    rerun, forced, invalidated = bench.plan_resume(
        prior, tpu_ok=True, resume=True, rows=rows)
    assert rerun == {"wordcount"}
    assert forced == set()
    assert "bench_wordcount" in invalidated
    assert "phase_wordcount_s" not in rows
    # untouched phases keep their rows
    assert "phase_pi_s" in rows


def test_plan_pairs_terasort_with_fresh_when_device_up(bench):
    prior = _clean_prior(bench)
    prior["bench_terasort_fresh"] = "failed: phase exited rc=3"
    rows = dict(prior)
    rerun, forced, invalidated = bench.plan_resume(
        prior, tpu_ok=True, resume=True, rows=rows)
    assert rerun == {"terasort", "terasort_fresh"}
    assert forced == {"terasort"}        # dragged in only by the pair
    # terasort's prior device rows were invalidated but preserved for
    # the mid-loop-device-loss restore path
    assert "terasort_device_job_s" in invalidated
    assert "terasort_device_job_s" not in rows


def test_plan_no_pairing_while_device_down(bench):
    """With the tunnel down, terasort_fresh is unfixable anyway —
    terasort's good device rows must NOT be sacrificed."""
    prior = _clean_prior(bench)
    prior["bench_terasort_fresh"] = "skipped: tpu unavailable"
    rows = dict(prior)
    rerun, forced, _ = bench.plan_resume(
        prior, tpu_ok=False, resume=True, rows=rows)
    assert "terasort" not in rerun
    assert forced == set()
    assert "terasort_device_job_s" in rows


def test_plan_fresh_run_reruns_everything(bench):
    rows: dict = {}
    rerun, forced, invalidated = bench.plan_resume(
        {}, tpu_ok=True, resume=False, rows=rows)
    assert rerun == {name for name, _, _, _ in bench.PHASES}
    assert invalidated == {}


# -------------------------------------------------------- resume_context


def test_resume_context_prefers_stamp(bench):
    prior = {"bench_context": {"backend": "tpu", "small": False}}
    assert bench.resume_context(prior) == {"backend": "tpu",
                                           "small": False}
    assert "bench_context" not in prior   # consumed


def test_resume_context_synthesizes_for_legacy_artifacts(bench):
    prior = {"backend_probe": {"backend": "cpu"},
             "kmeans_n_points": 2_000_000}
    ctx = bench.resume_context(prior)
    assert (ctx["backend"], ctx["small"]) == ("cpu", True)
    prior = {"backend_probe": {"backend": "tpu"},
             "kmeans_n_points": 100_000_000}
    ctx = bench.resume_context(prior)
    assert (ctx["backend"], ctx["small"]) == ("tpu", False)


def test_phase_done_host_measured_phase_reruns_when_tpu_back(bench):
    """wordcount has no device-only row key; the per-phase backend
    stamp is what forces its re-measure after a host-only wedge run."""
    prior = {"phase_wordcount_s": 3.0, "wordcount_job_s": 60.0,
             "wordcount_mb_per_s": 3.5, "phase_wordcount_backend": "cpu"}
    assert not bench.phase_done(prior, "wordcount", "optional",
                                tpu_ok=True, backend="tpu")
    assert bench.phase_done(prior, "wordcount", "optional",
                            tpu_ok=False, backend="tpu")
    # a cpu-REQUESTED run legitimately measures on cpu: stamp matches
    assert bench.phase_done(prior, "wordcount", "optional",
                            tpu_ok=True, backend="cpu")
    prior["phase_wordcount_backend"] = "tpu"
    assert bench.phase_done(prior, "wordcount", "optional",
                            tpu_ok=True, backend="tpu")


def test_plan_invalidates_backend_stamp_too(bench):
    prior = {"phase_wordcount_s": 3.0, "wordcount_job_s": 60.0,
             "phase_wordcount_backend": "cpu"}
    rows = dict(prior)
    rerun, _, invalidated = bench.plan_resume(
        prior, tpu_ok=True, resume=True, rows=rows, backend="tpu")
    assert "wordcount" in rerun
    assert "phase_wordcount_backend" in invalidated
    assert "phase_wordcount_backend" not in rows


def test_resume_context_includes_local_host_for_legacy(bench):
    import platform
    ctx = bench.resume_context({"backend_probe": {"backend": "cpu"},
                                "kmeans_n_points": 2_000_000})
    assert ctx["host"] == platform.node()


def test_resume_context_unknown_scale_never_matches(bench):
    """kmeans never ran: scale is unknowable and must mismatch BOTH
    scales (forcing a full re-measure), not default to the current
    run's."""
    ctx = bench.resume_context({"backend_probe": {"backend": "cpu"}})
    assert ctx["small"] not in (True, False)


class TestStallWatchdog:
    """run_phase_subprocess's wedge watchdog: a zero-CPU no-progress
    child dies early with a 'stalled' marker; a CPU-busy child is left
    alone. Popen is stubbed so no real phase (or device) is involved."""

    def _run(self, bench, monkeypatch, tmp_path, child_code, window="6",
             timeout_s=120):
        import subprocess as sp
        real = sp.Popen

        def stub(cmd, **kw):
            # env passes through: the progress-file liveness test's
            # child reads TPUMR_DEVICE_PROGRESS_FILE from it — dropping
            # env made that child crash instantly and the test vacuous
            return real([sys.executable, "-c", child_code],
                        **{k: v for k, v in kw.items()
                           if k in ("stdout", "start_new_session",
                                    "env")})
        monkeypatch.setattr(bench.subprocess, "Popen", stub)
        monkeypatch.setenv("BENCH_SHARED_DIR", str(tmp_path))
        monkeypatch.setenv("BENCH_STALL_WINDOW_S", window)
        rows: dict = {}
        ok = bench.run_phase_subprocess("kernels", timeout_s, rows,
                                        stall_watch=True)
        return ok, rows

    def test_zero_cpu_child_killed_as_stalled(self, bench, monkeypatch,
                                              tmp_path):
        import time
        t0 = time.time()
        ok, rows = self._run(bench, monkeypatch, tmp_path,
                             "import time; time.sleep(600)")
        assert not ok
        assert "stalled" in rows["bench_kernels"]
        assert time.time() - t0 < 60

    def test_busy_child_not_flagged(self, bench, monkeypatch, tmp_path):
        ok, rows = self._run(
            bench, monkeypatch, tmp_path,
            "import time\nt=time.time()\nwhile time.time()-t<9: pass")
        # child ran to completion (exits rc=0 without PHASE_ROWS -> not
        # ok, but crucially NOT the stalled marker)
        assert "stalled" not in rows.get("bench_kernels", "")

    def test_progress_file_counts_as_liveness(self, bench, monkeypatch,
                                              tmp_path):
        # sleeper that ticks the progress file stays alive past the
        # window, then exits on its own
        code = (
            "import os, time\n"
            "p = os.environ['TPUMR_DEVICE_PROGRESS_FILE']\n"
            "for _ in range(4):\n"
            "    open(p, 'w').write('tick')\n"
            "    time.sleep(2.5)\n")
        ok, rows = self._run(bench, monkeypatch, tmp_path, code)
        assert "stalled" not in rows.get("bench_kernels", "")

    def test_tree_cpu_covers_detached_descendants(self, bench):
        import subprocess as sp
        import time
        # grandchild in its OWN session burns CPU; the tree scan must
        # still see it (pgroup scans would not)
        child = sp.Popen([sys.executable, "-c", (
            "import subprocess, sys, time\n"
            "p = subprocess.Popen([sys.executable, '-c', "
            "'t=__import__(\"time\");e=t.time()+4\\n"
            "while t.time()<e: pass'], start_new_session=True)\n"
            "p.wait()\n")], start_new_session=True)
        try:
            time.sleep(2.0)
            cpu = bench._tree_cpu_s(child.pid)
            assert cpu > 0.5, f"descendant CPU invisible: {cpu}"
        finally:
            child.kill()
            child.wait()


class TestArchiveMarkers:
    def test_wedged_rerun_cannot_mask_good_archive_rows(self, bench,
                                                        tmp_path,
                                                        monkeypatch):
        import json, os
        monkeypatch.setenv("TPUMR_BENCH_ROUND", "97")
        monkeypatch.setattr(bench.os.path, "dirname",
                            bench.os.path.dirname)
        # write via the real helper into the repo misc dir, then clean
        bench._archive_device_capture(
            {"phase_kernels_s": 30.0,
             "kernel_matmul_bf16_onchip_s": 0.001})
        bench._archive_device_capture(
            {"bench_kernels": "skipped: tpu unavailable"})
        path = os.path.join(os.path.dirname(bench.__file__)
                            if hasattr(bench, "__file__") else ".",
                            "misc", "bench_device_r97.json")
        try:
            d = json.load(open(path))
        finally:
            os.unlink(path)
        assert "bench_kernels" not in d, d
        assert d["kernel_matmul_bf16_onchip_s"] == 0.001
