"""Pressure tests beyond toy sizes (VERDICT r1 weak #4): multi-spill maps
with combiner-at-spill, the k-way merge over many spill files, and a
many-map × many-reduce shuffle — the paths that only show their bugs
under volume. Marked slow; sized to stay under ~2 minutes total."""

import collections
import random

import pytest

from tpumr.core.counters import TaskCounter
from tpumr.fs import get_filesystem
from tpumr.mapred.job_client import JobClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.mini_cluster import MiniMRCluster

pytestmark = pytest.mark.slow


class WcMapper:
    def configure(self, conf):
        pass

    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)

    def close(self):
        pass


class SumReducer:
    def configure(self, conf):
        pass

    def reduce(self, key, values, output, reporter):
        output.collect(key, sum(values))

    def close(self):
        pass


def _read_counts(fs, out_dir):
    out = {}
    parts = 0
    for st in fs.list_files(out_dir):
        if st.path.name.startswith("part-"):
            parts += 1
            for line in fs.read_bytes(st.path).decode().splitlines():
                k, v = line.split("\t")
                out[k] = int(v)
    return out, parts


def test_multi_spill_combiner_merge_under_pressure(tmp_path):
    """~24 MB through maps capped at io.sort.mb=1: dozens of spills per
    map, the combiner running at EVERY spill, and the final k-way merge
    over all of them — output must still be exact."""
    rng = random.Random(42)
    words = [f"word{i:04d}" for i in range(500)]
    lines = []
    for _ in range(340_000):
        lines.append(" ".join(rng.choice(words) for _ in range(8)))
    data = ("\n".join(lines) + "\n").encode()
    assert len(data) > 20 * 1024 * 1024
    expected = collections.Counter(
        w for line in lines for w in line.split())

    src = tmp_path / "pressure.txt"
    src.write_bytes(data)
    conf = JobConf()
    conf.set_input_paths(f"file://{src}")
    conf.set_output_path(f"file://{tmp_path}/out")
    conf.set_class("mapred.mapper.class", WcMapper)
    conf.set_class("mapred.reducer.class", SumReducer)
    conf.set_class("mapred.combiner.class", SumReducer)
    conf.set("io.sort.mb", 1)                # force frequent spills
    conf.set("io.sort.spill.percent", 0.8)
    conf.set("mapred.map.tasks", 3)
    conf.set_num_reduce_tasks(3)

    result = JobClient(conf).run_job(conf)
    assert result.successful

    fs = get_filesystem(f"file://{tmp_path}/out")
    counts, parts = _read_counts(fs, f"file://{tmp_path}/out")
    assert parts == 3
    assert counts == dict(expected)

    spilled = result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                    TaskCounter.SPILLED_RECORDS)
    map_out = sum(expected.values())
    # combiner at spill: many spills happened AND combine ran hard
    assert spilled > map_out * 0.5, (spilled, map_out)
    combined_in = result.counters.value(TaskCounter.FRAMEWORK_GROUP,
                                        TaskCounter.COMBINE_INPUT_RECORDS)
    assert combined_in >= map_out * 0.9, (combined_in, map_out)


def test_many_maps_many_reduces_shuffle(tmp_path):
    """40 maps × 6 reduces over a mini-cluster: 240 shuffle segments
    fetched over tracker RPC; every record must arrive exactly once and
    keys must land in their hash partition."""
    fs = get_filesystem("mem:///")
    n_keys = 4000
    data = "".join(f"k{i % n_keys:05d}\n" for i in range(40_000))
    fs.write_bytes("/scale/in.txt", data.encode())

    with MiniMRCluster(num_trackers=2, cpu_slots=3, tpu_slots=0) as cluster:
        conf = cluster.create_job_conf()
        conf.set_input_paths("mem:///scale/in.txt")
        conf.set_output_path("mem:///scale/out")
        conf.set_class("mapred.mapper.class", WcMapper)
        conf.set_class("mapred.reducer.class", SumReducer)
        conf.set("mapred.map.tasks", 40)
        conf.set("mapred.min.split.size", 1)
        conf.set_num_reduce_tasks(6)
        result = JobClient(conf).run_job(conf)
        assert result.successful
        assert result.num_maps >= 30, result.num_maps

    counts, parts = _read_counts(fs, "mem:///scale/out")
    assert parts == 6
    # every key counted exactly (10 occurrences each), nothing lost or
    # double-fetched across the 240 segments
    assert len(counts) == n_keys
    assert all(v == 10 for v in counts.values()), \
        {k: v for k, v in counts.items() if v != 10}


def test_heartbeat_cost_independent_of_finished_task_history():
    """SURVEY §3.2: the reference recomputes per-backend mean runtimes by
    rescanning ALL TaskReports on every heartbeat (O(jobs × tasks)); this
    framework keeps running sums, so assign_tasks cost must NOT grow with
    a job's finished-task history. Measured as a ratio so machine speed
    doesn't matter: 40x more finished tasks must not make heartbeats
    meaningfully slower (the reference's rescan would be ~40x)."""
    import time as _time

    from test_scheduler import make_job, make_scheduler, tracker_status
    from tpumr.mapred.task import TaskState, TaskStatus

    def build_jobs(finished_per_job, jobs=8, pending=4):
        out = []
        for j in range(jobs):
            job = make_job(n_maps=finished_per_job + pending, kernel=True,
                           job_num=j + 1)
            for i in range(finished_per_job):
                task = job.obtain_new_map_task("host0",
                                               run_on_tpu=(i % 2 == 0),
                                               tpu_device_id=0)
                assert task is not None
                st = TaskStatus(attempt_id=task.attempt_id, is_map=True,
                                state=TaskState.SUCCEEDED,
                                run_on_tpu=task.run_on_tpu,
                                start_time=0.0, finish_time=0.5)
                job.update_task_status(st, "h:0")
            out.append(job)
        return out

    def mean_heartbeat_s(jobs, rounds=150):
        sched = make_scheduler(jobs, n_trackers=4)
        # full pools so every heartbeat does the complete profiling scan
        # but can't actually assign (pending stays stable across rounds)
        tts = tracker_status(cpu=3, tpu=1, run_cpu=3, run_tpu=1,
                             devices=[False])
        t0 = _time.time()
        for _ in range(rounds):
            sched.assign_tasks(dict(tts))
        return (_time.time() - t0) / rounds

    small = mean_heartbeat_s(build_jobs(50))
    big = mean_heartbeat_s(build_jobs(2000))
    assert big / max(small, 1e-9) < 5.0, (
        f"heartbeat cost grew with finished-task history: "
        f"{small * 1e6:.0f}us -> {big * 1e6:.0f}us")
